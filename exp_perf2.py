"""Round 2: zero-copy offset Pallas kernel vs fixed-slab lower bound."""
import functools, time, sys
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS, D, FRAC, ITERS = 3_000_000, 1000, 0.1, 20
M = int(ROWS * FRAC)
TILE = 2048
MT = M // TILE * TILE  # batch rows, tile-aligned

key = jax.random.PRNGKey(0)
kx, kw, kn = jax.random.split(key, 3)

@jax.jit
def gen():
    X = jax.random.normal(kx, (ROWS, D), jnp.bfloat16)
    w_true = jax.random.uniform(kw, (D,), jnp.float32, -1.0, 1.0)
    y = X.astype(jnp.float32) @ w_true + 0.1 * jax.random.normal(kn, (ROWS,), jnp.float32)
    return X, y

X, y = jax.block_until_ready(gen())
w0 = jnp.zeros((D,), jnp.float32)
print("data ready", file=sys.stderr)


def ls_sums(Xb, yb, w):
    margins = Xb.astype(jnp.float32) @ w
    r = margins - yb
    g = r.astype(Xb.dtype) @ Xb
    return g.astype(jnp.float32), 0.5 * jnp.sum(r * r)


def step_fixed(w, X, y, i):
    Xb, yb = X[:MT], y[:MT]  # static slice: no copy
    g, l = ls_sums(Xb, yb, w)
    return w - 0.5 / jnp.sqrt(i.astype(jnp.float32)) * g / MT, l / MT


PADL = 128


def _kernel(start_ref, x_ref, y_ref, w_ref, acc_ref):
    i = pl.program_id(0)
    Xt = x_ref[:]
    W = w_ref[:]
    margins = jnp.dot(Xt, W.astype(Xt.dtype), preferred_element_type=jnp.float32)[:, 0:1]
    r = margins - y_ref[:]
    C = jnp.concatenate([r, 0.5 * r * r] + [jnp.zeros_like(r)] * 6, axis=1)
    G = jax.lax.dot_general(
        C.astype(Xt.dtype), Xt, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == 0)
    def _():
        acc_ref[:] = G

    @pl.when(i > 0)
    def _():
        acc_ref[:] = acc_ref[:] + G


def pallas_offset_sums(X, y, w, start_tile):
    n, d = X.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(MT // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, d), lambda i, s: (s[0] + i, 0)),
            pl.BlockSpec((TILE, 1), lambda i, s: (s[0] + i, 0)),
            pl.BlockSpec((d, PADL), lambda i, s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((8, d), lambda i, s: (0, 0)),
    )
    Wp = jnp.zeros((d, PADL), jnp.float32).at[:, 0].set(w)
    acc = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((8, d), jnp.float32),
    )(jnp.asarray([start_tile], jnp.int32), X, y.reshape(-1, 1), Wp)
    return acc[0], acc[1]


def step_pallas_offset(w, X, y, i):
    k = jax.random.fold_in(jax.random.PRNGKey(42), i)
    start_tile = jax.random.randint(k, (), 0, (X.shape[0] - MT) // TILE)
    g, _ = pallas_offset_sums(X, y, w, start_tile)
    return w - 0.5 / jnp.sqrt(i.astype(jnp.float32)) * g / MT, jnp.float32(0)


def run(name, step, reads=2):
    f = jax.jit(step)
    try:
        t0 = time.perf_counter()
        jax.block_until_ready(f(w0, X, y, jnp.asarray(1, jnp.int32)))
        print(f"{name}: compile {time.perf_counter()-t0:.1f}s", file=sys.stderr)
        w = w0
        t0 = time.perf_counter()
        for i in range(1, ITERS + 1):
            w, l = f(w, X, y, jnp.asarray(i, jnp.int32))
        jax.block_until_ready(w)
        dt = (time.perf_counter() - t0) / ITERS
        gbps = MT * D * 2 * reads / dt / 1e9
        print(f"{name}: {dt*1e3:.2f} ms/iter  (~{gbps:.0f} GB/s @ {reads} X-reads)", file=sys.stderr)
        return dt
    except Exception as e:
        print(f"{name}: FAILED {type(e).__name__}: {str(e)[:400]}", file=sys.stderr)
        return None


# correctness check of the pallas kernel vs reference
gk, _ = jax.jit(pallas_offset_sums)(X, y, jnp.ones((D,), jnp.float32), 3)
Xb = X[3 * TILE : 3 * TILE + MT]
yb = y[3 * TILE : 3 * TILE + MT]
gr, _ = ls_sums(Xb, yb, jnp.ones((D,), jnp.float32))
err = float(jnp.max(jnp.abs(gk - gr)) / (jnp.max(jnp.abs(gr)) + 1e-9))
print(f"pallas correctness rel err: {err:.2e}", file=sys.stderr)

run("fixed-slab (lower bound)", step_fixed)
run("pallas zero-copy offset", step_pallas_offset, reads=1)
