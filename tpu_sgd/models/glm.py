"""Generalized linear model harness.

Reference parity: [U] mllib/regression/GeneralizedLinearAlgorithm.scala
(SURVEY.md §2 #5, §1 L5).  Owns exactly what the reference's harness owns:
input validation, feature-count discovery, intercept handling (bias appended
as the LAST column, parity with ``MLUtils.appendBias``), calling
``optimizer.optimize``, splitting the intercept back out, and
``create_model``.  Models own prediction; training always flows through
``run``.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from tpu_sgd.models.labeled_point import LabeledPoint, to_arrays
from tpu_sgd.ops.sparse import append_bias_auto, is_sparse, row_matrix_bcoo
from tpu_sgd.optimize.optimizer import Optimizer

DatasetLike = Union[Tuple, Iterable[LabeledPoint]]


def _as_arrays(data: DatasetLike) -> Tuple[np.ndarray, np.ndarray]:
    if isinstance(data, tuple) and len(data) == 2:
        X, y = data
        if is_sparse(X):  # BCOO features pass through undensified
            return X, np.asarray(y)
        return np.asarray(X), np.asarray(y)
    return to_arrays(data)


class GeneralizedLinearModel:
    """Weights + intercept + prediction rule (abstract ``predict_point``)."""

    def __init__(self, weights, intercept: float = 0.0):
        self.weights = jnp.asarray(weights)
        self.intercept = float(intercept)

    def _margin(self, X):
        if not is_sparse(X):
            import jax.core
            from tpu_sgd.ops.bucketed import DEFAULT_BUCKETS

            if (not isinstance(X, jax.core.Tracer)
                    and not isinstance(self.weights, jax.core.Tracer)
                    and np.ndim(X) == 2
                    and 0 < np.shape(X)[0] <= DEFAULT_BUCKETS[-1]):
                # Canonical shape-bucketed margin program (ops/bucketed.py):
                # pads the row count to a fixed bucket set and reuses one
                # compiled program per bucket, so ad-hoc predict and the
                # serving endpoint score the same batch through the SAME
                # executable — bitwise-identical dense predictions, and no
                # per-batch-size recompiles.  Tracers (a user's jit/vmap/
                # grad around predict, over the input OR the weights) stay
                # on the pure-jnp path below — the host-side pad cannot
                # trace.
                from tpu_sgd.ops.bucketed import bucketed_matvec

                return jnp.asarray(
                    bucketed_matvec(X, self.weights, self.intercept)
                )
            # tracers, empty input, and beyond-max-bucket batches (the
            # training-scale case) stay pure device: one eager matmul at
            # the natural shape, no host round-trip
            X = jnp.asarray(X)
        return X @ self.weights + self.intercept

    def predict_margin(self, X):
        """Raw margin(s) ``x.w + b`` for a single vector or a batch; always
        returns a batch-shaped result (a single vector yields shape (1,))."""
        import jax.core

        if is_sparse(X):
            return self._margin(row_matrix_bcoo(X))
        if isinstance(X, jax.core.Tracer):
            return self._margin(jnp.atleast_2d(X))
        if np.ndim(X) == 1:
            # a single row is tiny: shape it host-side for the bucketed
            # path (2-D inputs pass through untouched — _margin decides
            # device vs host by batch size without materializing)
            return self._margin(np.atleast_2d(np.asarray(X)))
        return self._margin(X)

    def predict_point(self, margin):
        raise NotImplementedError

    def predict(self, X):
        """Predict for one feature vector or a batch (parity with the
        reference's ``predict(Vector)`` / ``predict(RDD[Vector])``); accepts
        dense arrays or sparse (BCOO) features."""
        single = np.ndim(X) == 1  # attribute-based: no device transfer
        out = self.predict_point(self.predict_margin(X))
        return out[0] if single else out

    def predict_streamed(self, X, batch_rows: int = 1_000_000) -> np.ndarray:
        """Chunked prediction for host-resident matrices beyond device HBM
        — the analogue of the reference's ``predict(RDD[Vector])`` scoring
        partitions executor-side ([U] GeneralizedLinearModel, SURVEY.md §2
        #5): each chunk is transferred, scored on device, and materialized
        back to host memory before the next chunk moves, so peak device
        memory is one ``batch_rows`` block regardless of ``len(X)``."""
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive, got {batch_rows}")
        if not is_sparse(X):  # BCOO chunks by row slicing, undensified
            X = np.asarray(X)
        if X.ndim == 1:
            return np.asarray(self.predict(X))
        outs = [
            np.asarray(self.predict(X[s:s + batch_rows]))
            for s in range(0, X.shape[0], batch_rows)
        ]
        return (
            np.concatenate(outs) if outs
            else np.zeros((0,), np.float32)
        )

    def __repr__(self):
        return (
            f"{type(self).__name__}(numFeatures={self.weights.shape[-1]}, "
            f"intercept={self.intercept})"
        )


class GeneralizedLinearAlgorithm:
    """Shared training harness; subclasses provide optimizer + create_model."""

    #: subclasses set an Optimizer instance
    optimizer: Optimizer = None

    def __init__(self):
        self.add_intercept = False
        self.validate_data = True
        self.num_features = -1
        self.use_feature_scaling = False
        self.schedule = "auto"

    # -- fluent config, parity with the reference's setters ----------------
    def set_intercept(self, flag: bool):
        self.add_intercept = bool(flag)
        return self

    def set_validate_data(self, flag: bool):
        self.validate_data = bool(flag)
        return self

    def set_feature_scaling(self, flag: bool):
        """Scale features to unit column std before optimizing, then map the
        weights back to original space — the reference harness's hidden
        ``useFeatureScaling`` pass ([U] GeneralizedLinearAlgorithm.run, which
        its LBFGS-backed classifier switches on to condition the Hessian
        approximation).  Deliberate deviation: the reference hard-enables
        this for ``LogisticRegressionWithLBFGS``; here it is opt-in on every
        family so round-2 trajectories stay bit-identical, and because with
        ``reg_param > 0`` scaling changes the optimum (regularization is
        applied in scaled space, reference behavior)."""
        self.use_feature_scaling = bool(flag)
        return self

    def set_num_features(self, n: int):
        self.num_features = int(n)
        return self

    def set_schedule(self, mode: str):
        """Execution-schedule policy (``tpu_sgd/plan.py`` — the scheduler
        analogue of the reference's DAGScheduler + ``cache()``, SURVEY.md
        §2 #16).  ``"auto"`` (default): when no manual schedule flag is
        set on the optimizer, ``run`` probes (shape, dtype, gradient
        family, sampling, free device memory) and picks the measured-best
        schedule, logging one ``plan: ...`` line on the
        ``tpu_sgd.plan`` logger.  A schedule name
        (``resident_stock`` / ``resident_gram`` / ``partial_residency`` /
        ``host_streamed`` / ``streamed_virtual_gram``) forces that
        schedule (with a warning when the estimate says it loses).
        ``"off"``: never plan — the optimizer runs exactly as configured.
        Manual optimizer flags (``set_host_streaming``,
        ``set_sufficient_stats``, ``set_streamed_stats``) always win over
        ``"auto"``."""
        valid = ("auto", "off")
        from tpu_sgd.plan import SCHEDULES

        if mode not in valid + SCHEDULES:
            raise ValueError(
                f"schedule must be one of {valid + SCHEDULES}, got {mode!r}"
            )
        self.schedule = mode
        return self

    def _auto_plan(self, X, y) -> None:
        """Apply the execution planner per ``set_schedule``; called by
        ``run`` on the exact matrix the optimizer will see (post scaling
        and intercept append)."""
        if self.schedule == "off":
            return
        opt = self.optimizer
        manual = bool(
            getattr(opt, "host_streaming", False)
            or getattr(opt, "sufficient_stats", False)
            or getattr(opt, "streamed_stats", False)
        )
        # Flags set by a PREVIOUS plan (last_plan is not None) are the
        # planner's own and must not block re-planning for a new dataset;
        # the manual setters clear last_plan, so user-set flags — whenever
        # set, including after an auto-planned run — always win.
        if (self.schedule == "auto" and manual
                and getattr(opt, "last_plan", None) is None):
            return  # explicit optimizer flags win
        import numpy as np

        from tpu_sgd.plan import logger, plan_for, plan_quasi_newton
        from tpu_sgd.optimize.lbfgs import LBFGS as _LBFGS

        force = None if self.schedule == "auto" else self.schedule
        # Identically-shaped repeat runs (the streaming mode's thousands
        # of micro-batches) skip the probe + plan + log entirely.
        key = (np.shape(X), str(getattr(X, "dtype", "")), force,
               getattr(opt, "config", None), opt.mesh,
               getattr(opt, "max_num_iterations", None))
        if (getattr(opt, "last_plan", None) is not None
                and getattr(opt, "_plan_key", None) == key):
            return
        if isinstance(opt, _LBFGS):
            # quasi-Newton optimizers plan a narrower menu: stock
            # full-batch passes, the sufficient-stats substitution, or
            # (beyond HBM) the streamed-virtual-statistics schedule
            p = plan_quasi_newton(opt, X, y, force=force)
            if p is not None:
                p.apply_quasi_newton(opt)
        else:
            p = plan_for(opt, X, y, force=force)
            if p is not None:
                p.apply(opt)
        if p is not None:
            opt._plan_key = key
            logger.info(p.describe())
        elif getattr(opt, "last_plan", None) is not None:
            # Un-plannable input (sparse/BCOO, GramData, model mesh) after
            # a planned run: the PREVIOUS plan's schedule flags are the
            # planner's own and must not leak onto this dataset (e.g. a
            # stale host_streaming=True would crash a zero-flag user on
            # BCOO input) — reset to stock via the optimizers' own
            # clearing hook (one flag list, not three hand-rolled
            # copies).
            opt._clear_planned_schedule()  # flags AND plan-owned knobs
            opt.last_plan = None
            opt._plan_key = None
        if p is None and force is not None:
            raise ValueError(
                f"schedule={force!r} cannot be applied here: this "
                "optimizer/input is not planned (sparse/BCOO or GramData "
                "input, a 2-D data x model mesh, or an optimizer without "
                "schedules) — configure it directly with the optimizer "
                "setters instead"
            )

    # -- hooks -------------------------------------------------------------
    def create_model(self, weights, intercept) -> GeneralizedLinearModel:
        raise NotImplementedError

    def validators(self, X: np.ndarray, y: np.ndarray) -> None:
        """Input validation hook; classifier subclasses check label sets."""

    # -- training ----------------------------------------------------------
    def run(
        self,
        data: DatasetLike,
        initial_weights=None,
        initial_intercept: float = 0.0,
    ) -> GeneralizedLinearModel:
        X, y = _as_arrays(data)
        if X.shape[0] == 0:
            raise ValueError("empty input")
        if self.num_features < 0:
            self.num_features = X.shape[1]
        if self.validate_data:
            self.validators(X, y)
        if initial_weights is None:
            initial_weights = np.zeros((self._weight_dim(),), np.float32)
        w0 = np.asarray(initial_weights, np.float32)
        scaler = None
        if self.use_feature_scaling:
            # Fit BEFORE the bias column exists (the reference scales raw
            # features, then appends the bias to the scaled matrix); user
            # initial weights arrive in ORIGINAL space, so they move into
            # scaled space by the inverse map (w * std) — an improvement on
            # the reference, whose warm starts silently stay unscaled.
            # Flat stacked weights (the multinomial (K-1)*d layout) rescale
            # per d-sized block.
            from tpu_sgd.feature import StandardScaler

            scaler = StandardScaler(with_mean=False, with_std=True).fit(X)
            # host numpy input stays on host inside transform (the
            # device round-trip would triple the transfer); device and
            # sparse inputs keep their layout
            X = scaler.transform(X)
            d = int(np.asarray(scaler.std).shape[0])
            w0 = np.asarray(
                (w0.reshape(-1, d) * np.asarray(scaler.std)[None, :])
                .reshape(w0.shape),
                np.float32,
            )
        if self.add_intercept:
            # Bias appended as the LAST column ([U] MLUtils.appendBias;
            # SURVEY.md §3.1 intercept prepend/split).
            Xb = append_bias_auto(X)
            w0 = np.concatenate([w0, np.asarray([initial_intercept], np.float32)])
            self._auto_plan(Xb, y)
            weights = self.optimizer.optimize((Xb, y), w0)
            intercept = float(weights[-1])
            weights = weights[:-1]
        else:
            self._auto_plan(X, y)
            weights = self.optimizer.optimize((X, y), w0)
            intercept = 0.0
        if scaler is not None:
            # Same trick as the reference: transform() maps trained weights
            # back to original space (margin w'.(x/std) == (w'/std).x);
            # flat stacked (multinomial) weights go block-wise.
            d = int(np.asarray(scaler.std).shape[0])
            weights = scaler.transform(
                jnp.asarray(weights).reshape(-1, d)
            ).reshape(jnp.asarray(weights).shape)
        return self.create_model(weights, intercept)

    def _weight_dim(self) -> int:
        return self.num_features

    def run_warm(self, data: DatasetLike, model: Optional[GeneralizedLinearModel]):
        """Warm-started run used by the streaming mode (SURVEY.md §3.3):
        re-run the batch optimizer seeded with the latest weights AND
        intercept (improves on the reference, which re-seeds the intercept)."""
        if model is None:
            return self.run(data)
        return self.run(data, model.weights, model.intercept)
