"""Regression model families: Linear, Lasso, Ridge — all SGD-trained.

Reference parity: [U] mllib/regression/{LinearRegression,Lasso,
RidgeRegression}.scala (SURVEY.md §2 #6).  Each family is the GLM harness plus
a (Gradient, Updater) pair and the reference's defaults: step=1.0, iters=100,
frac=1.0; reg=0.0 for plain linear, 0.01 for Lasso/Ridge.
"""

from __future__ import annotations

from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import L1Updater, SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent


class LinearRegressionModel(GeneralizedLinearModel):
    """Prediction is the raw margin ``x.w + b``."""

    def predict_point(self, margin):
        return margin

    def save(self, path):
        from tpu_sgd.utils.persistence import save_glm_model

        save_glm_model(path, self)

    @classmethod
    def load(cls, path):
        from tpu_sgd.utils.persistence import load_glm_model

        return load_glm_model(path, cls)


class LassoModel(LinearRegressionModel):
    pass


class RidgeRegressionModel(LinearRegressionModel):
    pass


class _RegressionWithSGD(GeneralizedLinearAlgorithm):
    _gradient_cls = LeastSquaresGradient
    _updater_cls = SimpleUpdater
    _model_cls = LinearRegressionModel
    _default_reg = 0.0

    def __init__(
        self,
        step_size: float = 1.0,
        num_iterations: int = 100,
        reg_param: float = None,
        mini_batch_fraction: float = 1.0,
    ):
        super().__init__()
        if reg_param is None:
            reg_param = self._default_reg
        self.optimizer = (
            GradientDescent(self._gradient_cls(), self._updater_cls())
            .set_step_size(step_size)
            .set_num_iterations(num_iterations)
            .set_reg_param(reg_param)
            .set_mini_batch_fraction(mini_batch_fraction)
        )

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(
        cls,
        data,
        num_iterations: int = 100,
        step_size: float = 1.0,
        reg_param: float = None,
        mini_batch_fraction: float = 1.0,
        initial_weights=None,
        intercept: bool = False,
        mesh=None,
        sampling: str = None,
        host_streaming: bool = False,
        streaming_resident_rows: int = 0,
        sufficient_stats: bool = False,
        schedule: str = None,
    ):
        """Static train() parity with the reference's object methods.

        With no schedule-related arguments, the execution planner
        (``tpu_sgd/plan.py`` — the DAGScheduler/``cache()`` analogue,
        SURVEY.md §2 #16) probes (shape, dtype, gradient family, sampling,
        free device memory) and picks the measured-best schedule
        automatically, logging one ``plan: ...`` line; ``schedule=`` forces
        a named schedule ("resident_stock" / "resident_gram" /
        "partial_residency" / "host_streamed" / "streamed_virtual_gram")
        or disables planning ("off").

        ``mesh``, ``sampling`` and ``host_streaming`` are the TPU-side
        extensions: a device mesh for data parallelism, the mini-batch
        sampling strategy (see ``SGDConfig.sampling``), and host-resident
        streaming for datasets larger than device HBM —
        ``streaming_resident_rows`` additionally keeps that many leading
        rows on the device (partial residency; sliced sampling, single
        device) so most windows need no host transfer.
        ``sufficient_stats`` runs least-squares iterations from
        precomputed block-prefix Gram statistics (exact; ~20x on resident
        slabs — see ``GradientDescent.set_sufficient_stats``); it builds
        on the post-intercept-append matrix, so it composes with
        ``intercept=True``.  Manual flags always win over the planner.
        """
        alg = cls(step_size, num_iterations, reg_param, mini_batch_fraction)
        alg.set_intercept(intercept)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        if sampling is not None:
            alg.optimizer.set_sampling(sampling)
        if host_streaming:
            alg.optimizer.set_host_streaming(
                True, resident_rows=streaming_resident_rows
            )
        if sufficient_stats:
            alg.optimizer.set_sufficient_stats(True)
        if schedule is not None:
            alg.set_schedule(schedule)
        return alg.run(data, initial_weights)


class LinearRegressionWithSGD(_RegressionWithSGD):
    """Least squares, no regularization (config 1, BASELINE.json:7)."""

    @classmethod
    def train(cls, data, num_iterations: int = 100, step_size: float = 1.0,
              mini_batch_fraction: float = 1.0, initial_weights=None, **kw):
        """Reference static parity ([U] object LinearRegressionWithSGD,
        SURVEY.md §3.1): ``train(input, numIterations, stepSize,
        miniBatchFraction, initialWeights)`` — ``miniBatchFraction`` is
        the FOURTH positional (there is no regParam slot; the simple
        updater ignores regularization).  A ported reference call like
        ``train(data, 100, 1.0, 0.1)`` must mean frac=0.1, not a
        silently-ignored reg_param=0.1 with full-batch sampling.  The
        TPU-side extensions stay keyword-only."""
        return super().train(
            data, num_iterations, step_size,
            mini_batch_fraction=mini_batch_fraction,
            initial_weights=initial_weights, **kw)


class LassoWithSGD(_RegressionWithSGD):
    """Least squares + L1 prox updater."""

    _updater_cls = L1Updater
    _model_cls = LassoModel
    _default_reg = 0.01


class RidgeRegressionWithSGD(_RegressionWithSGD):
    """Least squares + squared-L2 updater."""

    _updater_cls = SquaredL2Updater
    _model_cls = RidgeRegressionModel
    _default_reg = 0.01


class LassoWithOWLQN(GeneralizedLinearAlgorithm):
    """Lasso via OWL-QN — the orthant-wise quasi-Newton upstream Spark uses
    (Breeze ``OWLQN``) where the SGD prox path only approximates: exact
    zeros on null coordinates, quasi-Newton convergence.  Same harness and
    model class as ``LassoWithSGD``.
    """

    _model_cls = LassoModel

    def __init__(self, reg_param: float = 0.01, max_num_iterations: int = 100):
        super().__init__()
        from tpu_sgd.optimize.owlqn import OWLQN

        self.optimizer = OWLQN(
            LeastSquaresGradient(),
            reg_param=reg_param,
            max_num_iterations=max_num_iterations,
        )

    def set_intercept(self, flag: bool):
        # The bias is the appended LAST column; upstream gives it zero L1
        # strength — exempt it so the intercept is never shrunk to 0.
        self.optimizer.set_penalize_intercept(not flag)
        return super().set_intercept(flag)

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(cls, data, reg_param: float = 0.01,
              max_num_iterations: int = 100, intercept: bool = False,
              sufficient_stats: bool = False):
        alg = cls(reg_param, max_num_iterations)
        alg.set_intercept(intercept)
        if sufficient_stats:
            alg.optimizer.set_sufficient_stats(True)
        return alg.run(data)


class LinearRegressionWithLBFGS(GeneralizedLinearAlgorithm):
    """Least squares via L-BFGS behind the same plugin boundary.

    TPU-side extension beyond the reference's SGD-only regression surface
    (upstream Spark's LBFGS optimizer, [U] mllib/optimization/LBFGS.scala
    SURVEY.md §2 #18, is only wired to logistic regression in mllib): the
    meshed CostFun + batched line search make quasi-Newton least squares a
    drop-in, and it is the natural pairing for ``set_feature_scaling`` —
    unit-variance columns condition the inverse-Hessian pairs.
    """

    _model_cls = LinearRegressionModel

    def __init__(self, reg_param: float = 0.0,
                 max_num_iterations: int = 100,
                 convergence_tol: float = 1e-6):
        super().__init__()
        from tpu_sgd.optimize.lbfgs import LBFGS

        self.optimizer = LBFGS(
            LeastSquaresGradient(),
            SquaredL2Updater(),
            reg_param=reg_param,
            max_num_iterations=max_num_iterations,
            convergence_tol=convergence_tol,
        )

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(cls, data, reg_param: float = 0.0,
              max_num_iterations: int = 100, intercept: bool = False,
              feature_scaling: bool = False, mesh=None,
              sufficient_stats: bool = False):
        alg = cls(reg_param, max_num_iterations)
        alg.set_intercept(intercept)
        alg.set_feature_scaling(feature_scaling)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        if sufficient_stats:
            alg.optimizer.set_sufficient_stats(True)
        return alg.run(data)


class LinearRegressionWithNormal(GeneralizedLinearAlgorithm):
    """Exact least squares via the one-pass normal-equations solver.

    TPU-side extension beyond the reference's SGD-only mllib surface
    (upstream Spark ships the equivalent as ``spark.ml``'s
    WeightedLeastSquares "normal" solver): on TPU a single Gram-matrix pass
    on the MXU is cheaper than iterating whenever ``d`` is modest.  Same
    harness, intercept handling, and model class as the SGD family;
    ``reg_param > 0`` gives exact ridge regression.
    """

    _model_cls = LinearRegressionModel

    def __init__(self, reg_param: float = 0.0):
        super().__init__()
        from tpu_sgd.optimize.normal import NormalEquations

        self.optimizer = NormalEquations(reg_param)

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(cls, data, reg_param: float = 0.0, intercept: bool = False,
              mesh=None):
        alg = cls(reg_param)
        alg.set_intercept(intercept)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        return alg.run(data)
