"""The dataset element type.

Reference parity: [U] mllib/regression/LabeledPoint.scala (SURVEY.md §2 #9):
``(label: Double, features: Vector)``.  The TPU-native dataset is columnar
``(X, y)`` arrays (SoA, MXU-friendly), but the record type is kept for API
parity and for row-wise loaders; ``to_arrays`` converts a collection of
points into the columnar form the optimizer consumes.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Tuple

import numpy as np


class LabeledPoint(NamedTuple):
    label: float
    features: np.ndarray

    @staticmethod
    def parse(s: str) -> "LabeledPoint":
        """Parse "(label,[f0,f1,...])" or "label f0 f1 ..." forms."""
        s = s.strip()
        if s.startswith("("):
            label_str, feat_str = s[1:-1].split(",", 1)
            feats = feat_str.strip().lstrip("[").rstrip("]")
            return LabeledPoint(
                float(label_str), np.fromstring(feats, sep=",", dtype=np.float32)
            )
        parts = s.split()
        return LabeledPoint(
            float(parts[0]), np.asarray([float(p) for p in parts[1:]], np.float32)
        )


def to_arrays(points: Iterable[LabeledPoint]) -> Tuple[np.ndarray, np.ndarray]:
    """Collection of LabeledPoints -> columnar ``(X, y)`` float32 arrays."""
    pts = list(points)
    if not pts:
        return np.zeros((0, 0), np.float32), np.zeros((0,), np.float32)
    X = np.stack([np.asarray(p.features, np.float32) for p in pts])
    y = np.asarray([p.label for p in pts], np.float32)
    return X, y
