"""The dataset element type.

Reference parity: [U] mllib/regression/LabeledPoint.scala (SURVEY.md §2 #9):
``(label: Double, features: Vector)``.  The TPU-native dataset is columnar
``(X, y)`` arrays (SoA, MXU-friendly), but the record type is kept for API
parity and for row-wise loaders; ``to_arrays`` converts a collection of
points into the columnar form the optimizer consumes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, NamedTuple, Tuple, Union

import numpy as np

if TYPE_CHECKING:  # pragma: no cover - typing only
    from tpu_sgd.linalg import DenseVector, SparseVector


class LabeledPoint(NamedTuple):
    label: float
    #: raw array, or a linalg Dense/SparseVector record (the reference's
    #: Vector trait); sparse records flow to BCOO via ``to_arrays``
    features: Union[np.ndarray, "DenseVector", "SparseVector"]

    @staticmethod
    def parse(s: str) -> "LabeledPoint":
        """Parse the reference's text forms ([U] LabeledPoint.parse):
        dense "(label,[f0,f1,...])" / "label f0 f1 ...", or sparse
        "(label,(size,[i0,i1,...],[v0,v1,...]))" — the latter yields a
        ``linalg.SparseVector`` feature record."""
        s = s.strip()
        if s.startswith("("):
            # the feature text is exactly Vectors.parse's input (dense
            # "[...]" or sparse "(size,[i],[v])"); dense stays a raw array
            # for backward compatibility
            from tpu_sgd.linalg import DenseVector, Vectors

            label_str, feat_str = s[1:-1].split(",", 1)
            feat_str = feat_str.strip()
            if feat_str.startswith(("[", "(")):
                feats = Vectors.parse(feat_str)
                if isinstance(feats, DenseVector):
                    feats = feats.to_array()
            else:  # bracket-less tuple form "(label,f0,f1,...)"
                feats = np.asarray(
                    [float(t) for t in feat_str.split(",") if t.strip()],
                    np.float32,
                )
            return LabeledPoint(float(label_str), feats)
        parts = s.split()
        return LabeledPoint(
            float(parts[0]), np.asarray([float(p) for p in parts[1:]], np.float32)
        )


def to_arrays(points: Iterable[LabeledPoint]) -> Tuple[np.ndarray, np.ndarray]:
    """Collection of LabeledPoints -> columnar ``(X, y)`` float32 form.

    Features may be raw arrays, ``linalg.DenseVector`` records, or
    ``linalg.SparseVector`` records — the reference's ``RDD[LabeledPoint]``
    carries SparseVectors for a9a/RCV1 ([U] mllib/regression/
    LabeledPoint.scala + Vectors.scala); those stay sparse here, returned
    as one BCOO matrix that flows through the undensified training path.
    """
    pts = list(points)
    if not pts:
        return np.zeros((0, 0), np.float32), np.zeros((0,), np.float32)
    y = np.asarray([p.label for p in pts], np.float32)
    from tpu_sgd.linalg import DenseVector, SparseVector

    if any(isinstance(p.features, SparseVector) for p in pts):
        # ANY sparse row makes the whole collection sparse (the reference's
        # RDD[LabeledPoint] mixes dense and sparse vectors freely); dense
        # rows contribute their nonzeros.  One CSR pass feeds the shared
        # csr_to_bcoo constructor (sorted/unique flags included).
        from tpu_sgd.ops.sparse import csr_to_bcoo

        cols_list, vals_list = [], []
        d = 0
        for p in pts:
            f = p.features
            if isinstance(f, SparseVector):
                order = np.argsort(f.indices)
                c = np.asarray(f.indices)[order].astype(np.int32)
                v = np.asarray(f.values)[order].astype(np.float32)
                d = max(d, f.size)
            else:
                arr = (
                    f.to_array()
                    if isinstance(f, DenseVector)
                    else np.asarray(f, np.float32)
                )
                c = np.nonzero(arr)[0].astype(np.int32)
                v = arr[c].astype(np.float32)
                d = max(d, arr.shape[0])
            cols_list.append(c)
            vals_list.append(v)
        indptr = np.concatenate(
            [[0], np.cumsum([len(c) for c in cols_list])]
        )
        cols = np.concatenate(cols_list)
        vals = np.concatenate(vals_list)
        return csr_to_bcoo((vals, cols, indptr), d), y
    X = np.stack([
        p.features.to_array()
        if isinstance(p.features, DenseVector)
        else np.asarray(p.features, np.float32)
        for p in pts
    ])
    return X, y
