"""Streaming (online) SGD over micro-batches.

Reference parity: [U] mllib/regression/StreamingLinearRegressionWithSGD.scala
and StreamingLinearAlgorithm.scala (SURVEY.md §2 #15, §3.3), plus
[U] mllib/classification/StreamingLogisticRegressionWithSGD.scala.  The
reference implements online learning by re-running the batch optimizer per
micro-batch, warm-started with the latest weights — there is no separate
online-SGD code path.  The TPU build reuses the batch step the same way
(config 5, BASELINE.json:11): a "DStream" is any iterator of ``(X, y)``
micro-batches, and ``train_on`` folds the model through it.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from tpu_sgd.models.classification import LogisticRegressionWithSGD
from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.models.regression import LinearRegressionWithSGD

Batch = Tuple[np.ndarray, np.ndarray]


class StreamingLinearAlgorithm:
    """Fold a GLM through a stream of micro-batches with warm restarts."""

    def __init__(self, algorithm: GeneralizedLinearAlgorithm):
        self.algorithm = algorithm
        self.model: Optional[GeneralizedLinearModel] = None
        self._batch_count = 0

    def latest_model(self) -> GeneralizedLinearModel:
        if self.model is None:
            raise RuntimeError(
                "Model must be initialized (set_initial_weights) or trained "
                "before use"
            )
        return self.model

    def set_initial_weights(self, weights, intercept: float = 0.0):
        self.model = self.algorithm.create_model(
            np.asarray(weights, np.float32), intercept
        )
        return self

    def train_on_batch(self, X, y) -> GeneralizedLinearModel:
        """One micro-batch update (the body of the reference's foreachRDD);
        accepts dense or sparse (BCOO) feature batches."""
        from tpu_sgd.ops.sparse import is_sparse

        if not is_sparse(X):
            X = np.asarray(X)
        if X.shape[0] == 0:  # reference skips empty RDDs
            return self.model
        self.model = self.algorithm.run_warm((X, np.asarray(y)), self.model)
        self._batch_count += 1
        return self.model

    def train_on(self, stream: Iterable[Batch]) -> GeneralizedLinearModel:
        """Consume an entire stream (parity with ``trainOn(DStream)``)."""
        for X, y in stream:
            self.train_on_batch(X, y)
        return self.model

    def predict_on(self, stream: Iterable[np.ndarray]) -> Iterator[np.ndarray]:
        """Lazily map prediction over a stream of feature batches, using the
        model snapshot current at consumption time (parity with
        ``predictOn``)."""
        for X in stream:
            yield np.asarray(self.latest_model().predict(X))

    def predict_on_values(
        self, stream: Iterable[Tuple[object, np.ndarray]]
    ) -> Iterator[Tuple[object, np.ndarray]]:
        """Keyed variant (parity with ``predictOnValues``)."""
        for key, X in stream:
            yield key, np.asarray(self.latest_model().predict(X))


class StreamingLinearRegressionWithSGD(StreamingLinearAlgorithm):
    def __init__(
        self,
        step_size: float = 0.1,
        num_iterations: int = 50,
        mini_batch_fraction: float = 1.0,
        reg_param: float = 0.0,
    ):
        super().__init__(
            LinearRegressionWithSGD(
                step_size, num_iterations, reg_param, mini_batch_fraction
            )
        )


class StreamingLogisticRegressionWithSGD(StreamingLinearAlgorithm):
    def __init__(
        self,
        step_size: float = 0.1,
        num_iterations: int = 50,
        mini_batch_fraction: float = 1.0,
        reg_param: float = 0.0,
    ):
        super().__init__(
            LogisticRegressionWithSGD(
                step_size, num_iterations, reg_param, mini_batch_fraction
            )
        )
