"""Streaming (online) SGD over micro-batches.

Reference parity: [U] mllib/regression/StreamingLinearRegressionWithSGD.scala
and StreamingLinearAlgorithm.scala (SURVEY.md §2 #15, §3.3), plus
[U] mllib/classification/StreamingLogisticRegressionWithSGD.scala.  The
reference implements online learning by re-running the batch optimizer per
micro-batch, warm-started with the latest weights — there is no separate
online-SGD code path.  The TPU build reuses the batch step the same way
(config 5, BASELINE.json:11): a "DStream" is any iterator of ``(X, y)``
micro-batches, and ``train_on`` folds the model through it.

Driver recovery (SURVEY.md §5.4c): the reference rides DStream
checkpointing — a restarted driver resumes from the latest model and
stream position.  The analogue here is ``set_checkpoint`` (persist the
latest model + batch index every K micro-batches through the shared
``CheckpointManager``) and ``resume_from`` (reconstruct the algorithm
mid-stream from the newest checkpoint); with a replayable stream the
resumed run reproduces the uninterrupted run's weights and loss history
exactly, because each micro-batch update is deterministic in
``(warm-start weights, batch)``.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from tpu_sgd.models.classification import LogisticRegressionWithSGD
from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.models.regression import LinearRegressionWithSGD

Batch = Tuple[np.ndarray, np.ndarray]


class StreamingLinearAlgorithm:
    """Fold a GLM through a stream of micro-batches with warm restarts."""

    def __init__(self, algorithm: GeneralizedLinearAlgorithm):
        self.algorithm = algorithm
        self.model: Optional[GeneralizedLinearModel] = None
        self._batch_count = 0
        self.loss_history: list = []
        self.checkpoint_manager = None
        self.checkpoint_every = 1
        self.checkpoint_history_tail = None
        self._resume_skip = 0
        self._model_update_listeners: list = []

    def latest_model(self) -> GeneralizedLinearModel:
        if self.model is None:
            raise RuntimeError(
                "Model must be initialized (set_initial_weights) or trained "
                "before use"
            )
        return self.model

    def set_initial_weights(self, weights, intercept: float = 0.0):
        self.model = self.algorithm.create_model(
            np.asarray(weights, np.float32), intercept
        )
        return self

    def set_checkpoint(self, manager_or_directory, every: int = 1,
                       history_tail: int = None):
        """Persist (latest model, batch index, cumulative loss history)
        every ``every`` micro-batches — the DStream-checkpointing analogue
        (SURVEY.md §5.4c): kill the driver mid-stream and
        :meth:`resume_from` restarts from the newest checkpoint.  Accepts
        a ``CheckpointManager`` or a directory path.

        ``history_tail`` bounds the persisted loss history to its last N
        entries.  The default (None, full history) keeps resume BITWISE
        identical to the uninterrupted run — but re-serializes the whole
        unbounded history every checkpoint, which is O(N²) cumulative
        I/O over a long-lived stream; an UNBOUNDED stream with frequent
        checkpoints should set a tail (the resumed run's history then
        starts at the tail, weights still exact)."""
        import os

        from tpu_sgd.utils.checkpoint import CheckpointManager

        if isinstance(manager_or_directory, (str, os.PathLike)):
            manager_or_directory = CheckpointManager(
                str(manager_or_directory))
        self.checkpoint_manager = manager_or_directory
        self.checkpoint_every = max(1, int(every))
        if history_tail is not None and int(history_tail) < 1:
            raise ValueError(
                f"history_tail must be positive, got {history_tail}"
            )
        self.checkpoint_history_tail = (
            None if history_tail is None else int(history_tail))
        return self

    @classmethod
    def resume_from(cls, directory: str, every: int = 1, **init_kwargs):
        """Reconstruct a streaming algorithm mid-stream from the newest
        checkpoint in ``directory`` (written by :meth:`set_checkpoint`):
        latest model, batch index, and loss history are restored, and
        checkpointing continues into the same directory.  Construct with
        the SAME hyper-parameters as the interrupted run
        (``init_kwargs``) — they are not stored in the checkpoint.

        With a stream replayed from the beginning, the next
        :meth:`train_on` skips the already-consumed micro-batches and the
        run reproduces the uninterrupted weights/history exactly; a LIVE
        stream that only yields new batches should be consumed with
        ``train_on(stream, skip=0)``."""
        from tpu_sgd.utils.checkpoint import CheckpointManager

        import warnings

        self = cls(**init_kwargs)
        manager = CheckpointManager(directory)
        ck = manager.restore()
        if ck is None:
            raise FileNotFoundError(
                f"no checkpoint to resume from in {directory!r}"
            )
        if "intercept" not in ck["extras"]:
            raise ValueError(
                f"{directory!r} holds a non-streaming checkpoint "
                f"(config_key={ck['config_key']!r}); streaming resume "
                "needs one written by set_checkpoint"
            )
        expect_key = f"stream:{type(self.algorithm).__name__}"
        if ck["config_key"] != expect_key:
            warnings.warn(
                f"resuming a checkpoint written by {ck['config_key']!r} "
                f"with {expect_key!r} — construct the same streaming "
                "family/hyper-parameters as the interrupted run",
                RuntimeWarning,
                stacklevel=2,
            )
        self.set_checkpoint(manager, every=every)
        self.model = self.algorithm.create_model(
            ck["weights"], float(ck["extras"]["intercept"])
        )
        self._batch_count = int(ck["iteration"])
        self.loss_history = [float(v) for v in ck["loss_history"]]
        self._resume_skip = self._batch_count
        return self

    def add_model_update_listener(self, callback):
        """Register ``callback(model, batch_index)`` to fire after every
        micro-batch that updates the model — AFTER the checkpoint write
        for that batch (if any), so a listener that consumes the durable
        artifact (e.g. ``tpu_sgd.serve.ModelRegistry.on_model_update``)
        sees the published version.  Listener exceptions propagate to the
        training loop: a broken publisher should fail loudly, not train
        silently unpublished."""
        if not callable(callback):
            raise TypeError(f"callback must be callable, got {callback!r}")
        self._model_update_listeners.append(callback)
        return self

    def remove_model_update_listener(self, callback):
        self._model_update_listeners.remove(callback)
        return self

    def on_model_update(self):
        """Fire the registered model-update listeners with the current
        model and stream position."""
        for cb in self._model_update_listeners:
            cb(self.model, self._batch_count)

    def _maybe_checkpoint(self):
        if (self.checkpoint_manager is not None
                and self.model is not None
                and self._batch_count % self.checkpoint_every == 0):
            m = self.model
            self.checkpoint_manager.save(
                self._batch_count,  # = batches consumed (stream position)
                np.asarray(m.weights),
                0.0,
                np.asarray(
                    self.loss_history if self.checkpoint_history_tail
                    is None
                    else self.loss_history[-self.checkpoint_history_tail:],
                    np.float64,
                ),
                config_key=f"stream:{type(self.algorithm).__name__}",
                extras={
                    "intercept": np.asarray(m.intercept, np.float64),
                },
            )

    def train_on_batch(self, X, y) -> GeneralizedLinearModel:
        """One micro-batch update (the body of the reference's foreachRDD);
        accepts dense or sparse (BCOO) feature batches.  EVERY batch —
        including an empty one, whose update is skipped like the
        reference skips empty RDDs — advances ``_batch_count``, so the
        count is the STREAM POSITION and a resumed replay's skip stays
        aligned with the consumed prefix."""
        from tpu_sgd.ops.sparse import is_sparse

        if not is_sparse(X):
            X = np.asarray(X)
        if X.shape[0] == 0:  # reference skips empty RDDs (no update)
            self._batch_count += 1
            self._maybe_checkpoint()
            return self.model
        self.model = self.algorithm.run_warm((X, np.asarray(y)), self.model)
        self._batch_count += 1
        hist = getattr(self.algorithm.optimizer, "loss_history", None)
        if hist is not None and len(hist):
            self.loss_history.append(float(hist[-1]))
        self._maybe_checkpoint()
        self.on_model_update()
        return self.model

    def train_on(self, stream: Iterable[Batch],
                 skip: Optional[int] = None) -> GeneralizedLinearModel:
        """Consume an entire stream (parity with ``trainOn(DStream)``).

        ``skip``: leading micro-batches to drop before training — defaults
        to the number already consumed when this instance was resumed via
        :meth:`resume_from` (so a stream replayed from the beginning
        continues where the interrupted run stopped); pass ``0`` for a
        live stream that only yields new batches.  The resume skip is
        consumed by the first ``train_on`` call."""
        if skip is None:
            skip = self._resume_skip
        self._resume_skip = 0
        for i, (X, y) in enumerate(stream):
            if i < skip:
                continue
            self.train_on_batch(X, y)
        return self.model

    def predict_on(self, stream: Iterable[np.ndarray]) -> Iterator[np.ndarray]:
        """Lazily map prediction over a stream of feature batches, using the
        model snapshot current at consumption time (parity with
        ``predictOn``)."""
        for X in stream:
            yield np.asarray(self.latest_model().predict(X))

    def predict_on_values(
        self, stream: Iterable[Tuple[object, np.ndarray]]
    ) -> Iterator[Tuple[object, np.ndarray]]:
        """Keyed variant (parity with ``predictOnValues``)."""
        for key, X in stream:
            yield key, np.asarray(self.latest_model().predict(X))


class StreamingLinearRegressionWithSGD(StreamingLinearAlgorithm):
    def __init__(
        self,
        step_size: float = 0.1,
        num_iterations: int = 50,
        mini_batch_fraction: float = 1.0,
        reg_param: float = 0.0,
    ):
        super().__init__(
            LinearRegressionWithSGD(
                step_size, num_iterations, reg_param, mini_batch_fraction
            )
        )


class StreamingLogisticRegressionWithSGD(StreamingLinearAlgorithm):
    def __init__(
        self,
        step_size: float = 0.1,
        num_iterations: int = 50,
        mini_batch_fraction: float = 1.0,
        reg_param: float = 0.0,
    ):
        super().__init__(
            LogisticRegressionWithSGD(
                step_size, num_iterations, reg_param, mini_batch_fraction
            )
        )
