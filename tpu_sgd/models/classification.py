"""Classification model families: logistic regression and linear SVM.

Reference parity: [U] mllib/classification/{LogisticRegression,SVM}.scala
(SURVEY.md §2 #7-#8).  Reference defaults mirrored: both use step=1.0,
iters=100, reg=0.01, frac=1.0 and the squared-L2 updater; config 3
(BASELINE.json:9) swaps the SVM's updater for L1 via
``svm.optimizer.set_updater(L1Updater())``.  Prediction thresholds are
mutable and clearable exactly like the reference (``clear_threshold`` makes
``predict`` return raw scores).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.ops.gradients import HingeGradient, LogisticGradient
from tpu_sgd.ops.updaters import SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent


class _ThresholdedModel(GeneralizedLinearModel):
    _default_threshold = 0.5

    def __init__(self, weights, intercept: float = 0.0):
        super().__init__(weights, intercept)
        self.threshold = self._default_threshold

    def set_threshold(self, t: float):
        self.threshold = float(t)
        return self

    def clear_threshold(self):
        """After this, ``predict`` returns raw scores (reference parity)."""
        self.threshold = None
        return self

    def score(self, margin):
        raise NotImplementedError

    def predict_point(self, margin):
        s = self.score(margin)
        if self.threshold is None:
            return s
        return (s > self.threshold).astype(jnp.float32)


class LogisticRegressionModel(_ThresholdedModel):
    """Sigmoid score thresholded at 0.5 by default."""

    def score(self, margin):
        return jax.nn.sigmoid(margin)


class SVMModel(_ThresholdedModel):
    """Raw margin thresholded at 0.0 by default."""

    _default_threshold = 0.0

    def score(self, margin):
        return margin


def _save(model, path):
    from tpu_sgd.utils.persistence import save_glm_model

    save_glm_model(path, model)


def _load(cls, path):
    from tpu_sgd.utils.persistence import load_glm_model

    return load_glm_model(path, cls)


LogisticRegressionModel.save = _save
LogisticRegressionModel.load = classmethod(_load)
SVMModel.save = _save
SVMModel.load = classmethod(_load)


class _BinaryClassifierWithSGD(GeneralizedLinearAlgorithm):
    _gradient_cls = None
    _model_cls = None

    def __init__(
        self,
        step_size: float = 1.0,
        num_iterations: int = 100,
        reg_param: float = 0.01,
        mini_batch_fraction: float = 1.0,
    ):
        super().__init__()
        self.optimizer = (
            GradientDescent(self._gradient_cls(), SquaredL2Updater())
            .set_step_size(step_size)
            .set_num_iterations(num_iterations)
            .set_reg_param(reg_param)
            .set_mini_batch_fraction(mini_batch_fraction)
        )

    def validators(self, X, y):
        """Binary label validator ([U] DataValidators.binaryLabelValidator)."""
        bad = np.logical_and(y != 0.0, y != 1.0)
        if bad.any():
            raise ValueError(
                "Classification labels should be 0 or 1; found "
                f"{np.unique(np.asarray(y)[bad])[:5]}"
            )

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(
        cls,
        data,
        num_iterations: int = 100,
        step_size: float = 1.0,
        reg_param: float = 0.01,
        mini_batch_fraction: float = 1.0,
        initial_weights=None,
        intercept: bool = False,
        updater=None,
        mesh=None,
        sampling: str = None,
        host_streaming: bool = False,
        streaming_resident_rows: int = 0,
        schedule: str = None,
    ):
        alg = cls(step_size, num_iterations, reg_param, mini_batch_fraction)
        alg.set_intercept(intercept)
        if updater is not None:
            alg.optimizer.set_updater(updater)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        if sampling is not None:
            alg.optimizer.set_sampling(sampling)
        if host_streaming:
            alg.optimizer.set_host_streaming(
                True, resident_rows=streaming_resident_rows
            )
        if schedule is not None:
            # execution-schedule policy (tpu_sgd/plan.py): "auto" is the
            # default; a schedule name forces it, "off" disables planning
            alg.set_schedule(schedule)
        return alg.run(data, initial_weights)


class LogisticRegressionWithSGD(_BinaryClassifierWithSGD):
    """Binary logistic regression via SGD (config 2, BASELINE.json:8)."""

    _gradient_cls = LogisticGradient
    _model_cls = LogisticRegressionModel

    @classmethod
    def train(cls, data, num_iterations: int = 100, step_size: float = 1.0,
              mini_batch_fraction: float = 1.0, initial_weights=None,
              reg_param: float = 0.0, **kw):
        """Reference static parity ([U] object LogisticRegressionWithSGD):
        ``train(input, numIterations, stepSize, miniBatchFraction[,
        initialWeights])`` — ``miniBatchFraction`` is the FOURTH
        positional and the STATIC trains UNREGULARIZED (the reference's
        companion object hardcodes regParam 0.0; the class constructor
        keeps the 0.01 class default).  ``reg_param`` and the TPU-side
        extensions are keyword-only here.  (``SVMWithSGD.train`` keeps
        the base signature: the reference's SVM static takes regParam as
        its own fourth positional.)"""
        return super().train(
            data, num_iterations, step_size, reg_param=reg_param,
            mini_batch_fraction=mini_batch_fraction,
            initial_weights=initial_weights, **kw)


class SVMWithSGD(_BinaryClassifierWithSGD):
    """Linear SVM via hinge-loss SGD (config 3, BASELINE.json:9)."""

    _gradient_cls = HingeGradient
    _model_cls = SVMModel


class MultinomialLogisticRegressionModel(GeneralizedLinearModel):
    """K-class logistic model over a flat ``(K-1)*D`` weight vector with
    pivot class 0 (reference parity: ``LogisticRegressionModel`` with
    ``numClasses > 2``, [U] mllib/classification/LogisticRegression.scala).
    The intercept per class lives as the last per-class weight when trained
    with ``intercept=True`` (bias column convention)."""

    def __init__(self, weights, intercept: float = 0.0, num_classes: int = 2,
                 num_features: int = None, has_intercept_column: bool = False):
        super().__init__(weights, intercept)
        self.num_classes = int(num_classes)
        if num_features is None:
            num_features = self.weights.shape[-1] // (self.num_classes - 1)
        self.num_features = int(num_features)
        #: True when trained with a folded-in bias column; recorded
        #: explicitly so predict never guesses from input width.
        self.has_intercept_column = bool(has_intercept_column)

    def _check_width(self, width: int) -> None:
        expect = self.num_features - (1 if self.has_intercept_column else 0)
        if width != expect:
            raise ValueError(
                f"expected {expect}-feature input, got {width}"
            )

    def predict_dense_bucketed(self, X, buckets=None) -> np.ndarray:
        """The SINGLE home of the dense multinomial decision path —
        validation, bias column, per-class margins through the shared
        bucketed program (ops/bucketed.py), host-side pivot argmax
        (ops/gradients.py).  ``model.predict`` and the serving engine
        both route here, which is what makes serving results identical
        to ad-hoc prediction; the engine passes its own ``buckets``."""
        import jax.numpy as jnp

        from tpu_sgd.ops.gradients import pivot_class_host
        from tpu_sgd.ops.bucketed import DEFAULT_BUCKETS, bucketed_matvec

        X = np.atleast_2d(np.asarray(X))  # batch-shaped: (d,) scores as (1,)
        self._check_width(int(X.shape[-1]))
        if self.has_intercept_column:
            from tpu_sgd.utils.mlutils import append_bias

            X = append_bias(X)
        K = self.num_classes
        W = jnp.asarray(self.weights).reshape(K - 1, X.shape[-1])
        margins = bucketed_matvec(
            X, W.T, 0.0, DEFAULT_BUCKETS if buckets is None else buckets
        )
        return pivot_class_host(margins)

    def predict(self, X):
        import jax.core
        import jax.numpy as jnp

        from tpu_sgd.ops.gradients import MultinomialLogisticGradient
        from tpu_sgd.ops.sparse import (append_bias_auto, is_sparse,
                                        row_matrix_bcoo)

        sparse = is_sparse(X)
        tracer = (isinstance(X, jax.core.Tracer)
                  or isinstance(self.weights, jax.core.Tracer))
        if not sparse and tracer:
            X = jnp.asarray(X)
        single = (X.ndim if sparse or tracer else np.ndim(X)) == 1
        if sparse or tracer:
            # sparse batches and tracers (user jit/vmap/grad around
            # predict, over the input OR the weights) take the pure-jnp
            # rule; the bucketed host path below cannot trace
            Xb = row_matrix_bcoo(X) if sparse else jnp.atleast_2d(X)
            self._check_width(int(Xb.shape[-1]))
            if self.has_intercept_column:
                if sparse:
                    Xb = append_bias_auto(Xb)
                else:  # traced dense: append the bias column in-trace
                    # graftlint: disable=shape-trap -- tracer-only branch (guarded above): fuses into the user's jit, no eager compile
                    Xb = jnp.concatenate(
                        [Xb, jnp.ones((Xb.shape[0], 1), Xb.dtype)], axis=1
                    )
            g = MultinomialLogisticGradient(self.num_classes)
            out = g.predict_class(Xb, self.weights)
        else:
            # concrete dense input: stay host-side (the bucketed program
            # pads in numpy; a device round-trip here is pure waste)
            out = jnp.asarray(
                self.predict_dense_bucketed(np.atleast_2d(np.asarray(X)))
            )
        return out[0] if single else out


MultinomialLogisticRegressionModel.save = _save
MultinomialLogisticRegressionModel.load = classmethod(_load)


class LogisticRegressionWithLBFGS(GeneralizedLinearAlgorithm):
    """Logistic regression via L-BFGS, binary or multinomial.

    Reference parity: [U] mllib/classification/LogisticRegression.scala's
    ``LogisticRegressionWithLBFGS`` — same user API as the SGD variant, with
    the L-BFGS optimizer (SURVEY.md §2 #18) behind the same boundary and
    ``set_num_classes(K)`` switching to the multinomial gradient (pivot
    class 0, ``(K-1)*D`` weights), as the reference's does.
    """

    def __init__(
        self,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_num_iterations: int = 100,
        reg_param: float = 0.0,
    ):
        super().__init__()
        from tpu_sgd.optimize.lbfgs import LBFGS

        self.num_classes = 2
        self.optimizer = LBFGS(
            LogisticGradient(),
            SquaredL2Updater(),
            num_corrections=num_corrections,
            convergence_tol=convergence_tol,
            max_num_iterations=max_num_iterations,
            reg_param=reg_param,
        )

    def set_num_classes(self, k: int):
        from tpu_sgd.ops.gradients import MultinomialLogisticGradient

        if k < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = int(k)
        if k == 2:
            self.optimizer.set_gradient(LogisticGradient())
        else:
            self.optimizer.set_gradient(MultinomialLogisticGradient(k))
        return self

    def validators(self, X, y):
        yv = np.asarray(y)
        bad = (yv < 0) | (yv >= self.num_classes) | (yv != np.floor(yv))
        if bad.any():
            raise ValueError(
                f"Classification labels should be integers in [0, "
                f"{self.num_classes}); found {np.unique(yv[bad])[:5]}"
            )

    def _weight_dim(self) -> int:
        if self.num_classes == 2:
            return self.num_features
        return (self.num_classes - 1) * self.num_features

    def run(self, data, initial_weights=None, initial_intercept: float = 0.0):
        if self.num_classes > 2 and self.add_intercept:
            # The bias-column trick gives each class its own intercept as the
            # last per-class weight; the harness's scalar split doesn't apply.
            X, y = data if isinstance(data, tuple) else (None, None)
            if X is None:
                from tpu_sgd.models.labeled_point import to_arrays

                X, y = to_arrays(data)
            from tpu_sgd.ops.sparse import append_bias_auto, is_sparse

            if not is_sparse(X):
                X = np.asarray(X)
            if X.shape[0] == 0:
                raise ValueError("empty input")
            d = X.shape[1]
            scaler = None
            if self.use_feature_scaling:
                # Same scale->train->rescale pass as the harness ([U] GLA.run
                # useFeatureScaling), applied before the bias column so each
                # class's intercept slot stays unscaled.
                from tpu_sgd.feature import StandardScaler

                scaler = StandardScaler(with_mean=False, with_std=True).fit(X)
                X = scaler.transform(X)  # host input stays on host
            X = append_bias_auto(X)
            K = self.num_classes
            if initial_weights is None:
                w0 = np.zeros(((K - 1), d), np.float32)
                has_bias_slots = False
            else:
                # Accept BOTH layouts: (K-1)*d (fresh weights, bias slots
                # added here) and (K-1)*(d+1) (a trained intercept model's
                # own weights — the warm-start/continuation contract:
                # run_warm passes model.weights straight back in).
                w0 = np.asarray(initial_weights, np.float32)
                if w0.size == (K - 1) * (d + 1):
                    w0 = w0.reshape(K - 1, d + 1)
                    has_bias_slots = True
                elif w0.size == (K - 1) * d:
                    w0 = w0.reshape(K - 1, d)
                    has_bias_slots = False
                else:
                    raise ValueError(
                        f"initial_weights has size {w0.size} but expected "
                        f"{(K - 1) * d} ((num_classes-1) * num_features) "
                        f"or {(K - 1) * (d + 1)} (with per-class bias "
                        "slots, e.g. a trained intercept model's weights)"
                    )
            if scaler is not None:
                # User initial weights arrive in original space; the inverse
                # of the weight-rescale below moves them into scaled space
                # (feature slots only — bias slots are unscaled).
                std = np.asarray(scaler.std)
                if has_bias_slots:
                    w0 = w0.copy()
                    w0[:, :d] = w0[:, :d] * std[None, :]
                else:
                    w0 = np.asarray(w0 * std[None, :], np.float32)
            if not has_bias_slots:
                bias0 = np.full((K - 1, 1), float(initial_intercept),
                                np.float32)
                w0 = np.concatenate([w0, bias0], axis=1)
            w0 = np.asarray(w0, np.float32).reshape(-1)
            if self.validate_data:
                self.validators(X, y)
            # the schedule contract holds on this branch too: zero-flag
            # runs auto-plan, set_schedule forces or raises — exactly as
            # the harness path does
            self._auto_plan(X, np.asarray(y))
            weights = self.optimizer.optimize((X, np.asarray(y)), w0)
            if scaler is not None:
                W = np.array(weights, np.float32).reshape(K - 1, d + 1)
                W[:, :d] = W[:, :d] * np.asarray(scaler.factor)[None, :]
                weights = W.reshape(-1)
            return MultinomialLogisticRegressionModel(
                weights, 0.0, self.num_classes, X.shape[1],
                has_intercept_column=True,
            )
        return super().run(data, initial_weights, initial_intercept)

    def create_model(self, weights, intercept):
        if self.num_classes > 2:
            return MultinomialLogisticRegressionModel(
                weights, intercept, self.num_classes, self.num_features
            )
        return LogisticRegressionModel(weights, intercept)

    @classmethod
    def train(cls, data, max_num_iterations: int = 100, reg_param: float = 0.0,
              initial_weights=None, intercept: bool = False,
              num_classes: int = 2, mesh=None):
        alg = cls(max_num_iterations=max_num_iterations, reg_param=reg_param)
        alg.set_intercept(intercept)
        alg.set_num_classes(num_classes)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        return alg.run(data, initial_weights)
