"""Classification model families: logistic regression and linear SVM.

Reference parity: [U] mllib/classification/{LogisticRegression,SVM}.scala
(SURVEY.md §2 #7-#8).  Reference defaults mirrored: both use step=1.0,
iters=100, reg=0.01, frac=1.0 and the squared-L2 updater; config 3
(BASELINE.json:9) swaps the SVM's updater for L1 via
``svm.optimizer.set_updater(L1Updater())``.  Prediction thresholds are
mutable and clearable exactly like the reference (``clear_threshold`` makes
``predict`` return raw scores).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.ops.gradients import HingeGradient, LogisticGradient
from tpu_sgd.ops.updaters import SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent


class _ThresholdedModel(GeneralizedLinearModel):
    _default_threshold = 0.5

    def __init__(self, weights, intercept: float = 0.0):
        super().__init__(weights, intercept)
        self.threshold = self._default_threshold

    def set_threshold(self, t: float):
        self.threshold = float(t)
        return self

    def clear_threshold(self):
        """After this, ``predict`` returns raw scores (reference parity)."""
        self.threshold = None
        return self

    def score(self, margin):
        raise NotImplementedError

    def predict_point(self, margin):
        s = self.score(margin)
        if self.threshold is None:
            return s
        return (s > self.threshold).astype(jnp.float32)


class LogisticRegressionModel(_ThresholdedModel):
    """Sigmoid score thresholded at 0.5 by default."""

    def score(self, margin):
        return jax.nn.sigmoid(margin)


class SVMModel(_ThresholdedModel):
    """Raw margin thresholded at 0.0 by default."""

    _default_threshold = 0.0

    def score(self, margin):
        return margin


def _save(model, path):
    from tpu_sgd.utils.persistence import save_glm_model

    save_glm_model(path, model)


def _load(cls, path):
    from tpu_sgd.utils.persistence import load_glm_model

    return load_glm_model(path, cls)


LogisticRegressionModel.save = _save
LogisticRegressionModel.load = classmethod(_load)
SVMModel.save = _save
SVMModel.load = classmethod(_load)


class _BinaryClassifierWithSGD(GeneralizedLinearAlgorithm):
    _gradient_cls = None
    _model_cls = None

    def __init__(
        self,
        step_size: float = 1.0,
        num_iterations: int = 100,
        reg_param: float = 0.01,
        mini_batch_fraction: float = 1.0,
    ):
        super().__init__()
        self.optimizer = (
            GradientDescent(self._gradient_cls(), SquaredL2Updater())
            .set_step_size(step_size)
            .set_num_iterations(num_iterations)
            .set_reg_param(reg_param)
            .set_mini_batch_fraction(mini_batch_fraction)
        )

    def validators(self, X, y):
        """Binary label validator ([U] DataValidators.binaryLabelValidator)."""
        bad = np.logical_and(y != 0.0, y != 1.0)
        if bad.any():
            raise ValueError(
                "Classification labels should be 0 or 1; found "
                f"{np.unique(np.asarray(y)[bad])[:5]}"
            )

    def create_model(self, weights, intercept):
        return self._model_cls(weights, intercept)

    @classmethod
    def train(
        cls,
        data,
        num_iterations: int = 100,
        step_size: float = 1.0,
        reg_param: float = 0.01,
        mini_batch_fraction: float = 1.0,
        initial_weights=None,
        intercept: bool = False,
        updater=None,
        mesh=None,
    ):
        alg = cls(step_size, num_iterations, reg_param, mini_batch_fraction)
        alg.set_intercept(intercept)
        if updater is not None:
            alg.optimizer.set_updater(updater)
        if mesh is not None:
            alg.optimizer.set_mesh(mesh)
        return alg.run(data, initial_weights)


class LogisticRegressionWithSGD(_BinaryClassifierWithSGD):
    """Binary logistic regression via SGD (config 2, BASELINE.json:8)."""

    _gradient_cls = LogisticGradient
    _model_cls = LogisticRegressionModel


class SVMWithSGD(_BinaryClassifierWithSGD):
    """Linear SVM via hinge-loss SGD (config 3, BASELINE.json:9)."""

    _gradient_cls = HingeGradient
    _model_cls = SVMModel


class LogisticRegressionWithLBFGS(GeneralizedLinearAlgorithm):
    """Binary logistic regression via L-BFGS.

    Reference parity: [U] mllib/classification/LogisticRegression.scala's
    ``LogisticRegressionWithLBFGS`` — same user API as the SGD variant, with
    the L-BFGS optimizer (SURVEY.md §2 #18) behind the same boundary.
    """

    def __init__(
        self,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_num_iterations: int = 100,
        reg_param: float = 0.0,
    ):
        super().__init__()
        from tpu_sgd.optimize.lbfgs import LBFGS

        self.optimizer = LBFGS(
            LogisticGradient(),
            SquaredL2Updater(),
            num_corrections=num_corrections,
            convergence_tol=convergence_tol,
            max_num_iterations=max_num_iterations,
            reg_param=reg_param,
        )

    def validators(self, X, y):
        bad = np.logical_and(y != 0.0, y != 1.0)
        if bad.any():
            raise ValueError(
                "Classification labels should be 0 or 1; found "
                f"{np.unique(np.asarray(y)[bad])[:5]}"
            )

    def create_model(self, weights, intercept):
        return LogisticRegressionModel(weights, intercept)

    @classmethod
    def train(cls, data, max_num_iterations: int = 100, reg_param: float = 0.0,
              initial_weights=None, intercept: bool = False):
        alg = cls(max_num_iterations=max_num_iterations, reg_param=reg_param)
        alg.set_intercept(intercept)
        return alg.run(data, initial_weights)
