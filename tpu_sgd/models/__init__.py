from tpu_sgd.models.labeled_point import LabeledPoint, to_arrays
from tpu_sgd.models.glm import GeneralizedLinearAlgorithm, GeneralizedLinearModel
from tpu_sgd.models.regression import (
    LassoModel,
    LassoWithOWLQN,
    LassoWithSGD,
    LinearRegressionModel,
    LinearRegressionWithLBFGS,
    LinearRegressionWithNormal,
    LinearRegressionWithSGD,
    RidgeRegressionModel,
    RidgeRegressionWithSGD,
)
from tpu_sgd.models.classification import (
    LogisticRegressionModel,
    LogisticRegressionWithLBFGS,
    LogisticRegressionWithSGD,
    MultinomialLogisticRegressionModel,
    SVMModel,
    SVMWithSGD,
)
from tpu_sgd.models.streaming import (
    StreamingLinearAlgorithm,
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)

__all__ = [
    "LabeledPoint",
    "to_arrays",
    "GeneralizedLinearAlgorithm",
    "GeneralizedLinearModel",
    "LinearRegressionModel",
    "LinearRegressionWithLBFGS",
    "LinearRegressionWithNormal",
    "LinearRegressionWithSGD",
    "LassoModel",
    "LassoWithOWLQN",
    "LassoWithSGD",
    "RidgeRegressionModel",
    "RidgeRegressionWithSGD",
    "LogisticRegressionModel",
    "LogisticRegressionWithSGD",
    "LogisticRegressionWithLBFGS",
    "MultinomialLogisticRegressionModel",
    "SVMModel",
    "SVMWithSGD",
    "StreamingLinearAlgorithm",
    "StreamingLinearRegressionWithSGD",
    "StreamingLogisticRegressionWithSGD",
]
