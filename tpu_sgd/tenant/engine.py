"""Tenant-aware bucketed predict: mixed-tenant batches, one dispatch.

The tenant counterpart of ``serve/engine.py``'s :class:`PredictEngine`,
with the same discipline and one extra input: each request row carries a
tenant id, and the batch scores against that tenant's slab row via the
gathered-matvec program (``ops.bucketed.bucketed_gather_matvec``) — the
slot vector and the slab are TRACED arguments, so dispatch and compile
counts are independent of how many tenants appear in the batch (tests
pin this across M ∈ {1, 16, 256}).

Exactness split, deliberately explicit:

* a UNIFORM batch (every row the same tenant — the M=1 slab and the
  common per-tenant micro-batch) gathers that tenant's host row and
  routes through the canonical :func:`bucketed_matvec` — literally the
  same compiled program ``model.predict`` and the single-model
  ``PredictEngine`` run, hence bitwise-identical to them;
* a MIXED batch runs the gathered einsum program — same math, a
  different XLA reduction, so ~1 ulp vs the uniform path.  Both are
  exactly one device dispatch.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from tpu_sgd.obs.spans import event as obs_event
from tpu_sgd.obs.spans import span
from tpu_sgd.ops.bucketed import (DEFAULT_BUCKETS, bucket_for,
                                  bucketed_gather_matvec, bucketed_matvec,
                                  bucketed_multi_matvec, program_cache_size,
                                  slab_program_cache_size)
from tpu_sgd.tenant.slab import row_set_program_cache_size


class TenantPredictEngine:
    """Score ``(tenant_id, features)`` batches against a tenant store's
    slab.  Stateless with respect to residency: admission-on-miss and
    hot reloads happen inside the store; the engine only snapshots and
    dispatches."""

    def __init__(self, store, buckets: Tuple[int, ...] = DEFAULT_BUCKETS):
        self.store = store
        self.buckets = tuple(buckets)
        self.call_count = 0
        self.dispatch_count = 0
        self.uniform_count = 0
        self.mixed_count = 0

    @property
    def compile_count(self) -> int:
        """Every compiled program a tenant predict can reach: the shared
        single-model matvec cache (uniform path), the slab gather/all
        cache, and the slab's row-set (hot reload) cache."""
        return (program_cache_size() + slab_program_cache_size()
                + row_set_program_cache_size())

    def bucket_for(self, n: int) -> int:
        return bucket_for(n, self.buckets)

    def predict_batch(self, tenant_ids, X) -> np.ndarray:
        """Margin/score for each row of ``X`` under its own tenant's
        model — ONE device dispatch regardless of how many distinct
        tenants the batch mixes.  Emits a ``tenant.predict`` event per
        distinct tenant (staleness attr feeds the per-tenant series)."""
        tids = np.asarray(tenant_ids, np.int64).reshape(-1)
        Xh = np.asarray(X)
        if Xh.ndim != 2 or Xh.shape[0] != tids.shape[0]:
            raise ValueError(
                f"X must be (n, d) with one tenant id per row, got "
                f"X{Xh.shape} for {tids.shape[0]} ids")
        self.call_count += 1
        uniq = np.unique(tids)
        act = self.store.activation
        with span("tenant.batch") as sp:
            if len(uniq) == 1:
                # uniform batch: the canonical single-model program on
                # the gathered host row — bitwise the PredictEngine
                # path.  Bounded retry: a concurrent eviction storm can
                # race the row out between admission and read
                for attempt in range(5):
                    try:
                        w, b = self.store.slab.host_row(int(uniq[0]))
                        break
                    except KeyError:
                        self.store.slots_for(uniq)  # admit from disk
                else:
                    raise KeyError(int(uniq[0]))
                out = bucketed_matvec(Xh, w, b, self.buckets, activation=act)
                self.uniform_count += 1
            else:
                slots, W, b = self.store.slots_for(tids)
                out = bucketed_gather_matvec(Xh, slots, W, b, self.buckets,
                                             activation=act)
                self.mixed_count += 1
            self.dispatch_count += 1
            sp.set(rows=int(Xh.shape[0]), tenants=int(len(uniq)),
                   padded=self.bucket_for(int(Xh.shape[0])))
        for t in uniq:
            obs_event("tenant.predict", tenant=int(t),
                      staleness_s=self.store.staleness_s(int(t)))
        return out

    def predict_all(self, X):
        """Score every row of ``X`` against EVERY resident tenant in one
        dispatch — the shadow/canary multi-model batch (residents = the
        admitted registry versions).  Returns ``(scores, tenant_ids)``
        with ``scores[r, j]`` = row ``r`` under ``tenant_ids[j]``."""
        ids, slots, W, b = self.store.slab.snapshot_resident()
        if not ids:
            raise ValueError("predict_all on an empty slab")
        self.call_count += 1
        with span("tenant.batch") as sp:
            full = bucketed_multi_matvec(np.asarray(X), W, b, self.buckets,
                                         activation=self.store.activation)
            # column-select the resident slots host-side: the program is
            # keyed on capacity alone, so admitting one more version
            # never recompiles
            scores = np.asarray(full)[:, slots]
            self.dispatch_count += 1
            sp.set(rows=int(np.asarray(X).shape[0]), tenants=len(ids))
        return scores, np.asarray(ids, np.int64)
