"""Tenant model store: durable per-tenant checkpoints under one slab.

The persistence half of the tenant plane, layered on the SAME machinery
single-model serving already trusts:

* every tenant owns a ``CheckpointManager`` directory
  (``<root>/tenant_<id>/`` — numbered, atomically-renamed,
  content-checksummed npz files), written by :meth:`publish` — the
  per-tenant retraining trickle's sink;
* residency is lazy: a request for a non-resident tenant loads its
  newest checkpoint (corrupt versions raise at restore — the CRC rides
  the file) and admits it into the :class:`~tpu_sgd.tenant.slab.
  WeightSlab`, evicting the LRU tenant when full;
* a publish to a RESIDENT tenant hot-swaps its one row in place —
  neighbors unscored, nothing recompiled;
* the slab itself checkpoints as one frame (:meth:`save_state` /
  :meth:`restore_state`): the packed weight matrix plus the residency
  map ride a ``CheckpointManager`` entry, sealed with the io-plane CRC
  (``tpu_sgd/io/integrity.py`` — site ``tenant.slab``) so a
  bit-flipped slab restore is a typed :class:`IntegrityError`, never
  silently-wrong predictions for every tenant at once;
* the shadow/canary special case (:meth:`admit_versions`): M = the
  registry VERSIONS of one model — several checkpoint versions packed
  as slab rows and scored per dispatch
  (``TenantPredictEngine.predict_all``).

Obs events (``tenant.admit`` / ``tenant.evict`` / ``tenant.swap``,
fanned per tenant by ``obs.timeseries.EVENT_FANOUT``) and counters ride
every residency transition; the opt-in ``SlabThrashDetector``
(``obs/detect.py``) turns eviction churn into a typed alert.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from tpu_sgd.obs.counters import inc as obs_inc
from tpu_sgd.obs.spans import event as obs_event
from tpu_sgd.tenant.slab import SlabFullError, WeightSlab
from tpu_sgd.utils.checkpoint import CheckpointManager

logger = logging.getLogger("tpu_sgd.tenant.store")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the lazy
#: per-tenant manager cache is shared between serving threads (miss
#: loads) and publishers; the slab has its own internal lock.
GRAFTLINT_LOCKS = {
    "TenantModelStore": {
        "_managers": "_lock",
        "_publish_locks": "_lock",
        "_state_seq": "_lock",
    },
}


class TenantMissingError(RuntimeError):
    """No checkpoint exists for this tenant — it was never published."""


class TenantModelStore:
    """Durable multi-tenant model store over one device-resident slab.

    ``activation`` fixes the GLM family every tenant of this store
    shares (``None`` = margin/regression, ``"sigmoid"`` = logistic
    score) — one family per store keeps the slab's compiled programs
    shared across all tenants; run a second store for a second family.
    """

    def __init__(self, directory: str, *, capacity: int, d: int,
                 activation: Optional[str] = None, keep: int = 4):
        if activation not in (None, "sigmoid"):
            raise ValueError(
                f"activation must be None or 'sigmoid', got {activation!r}")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.slab = WeightSlab(capacity, d)
        self.activation = activation
        self.keep = int(keep)
        self._lock = threading.Lock()
        self._managers: Dict[int, CheckpointManager] = {}
        #: per-tenant publish serialization: two concurrent publishers
        #: of the SAME tenant would both compute version = latest+1 and
        #: collide on the checkpoint's tmp filename (publishes to
        #: DIFFERENT tenants stay fully concurrent)
        self._publish_locks: Dict[int, threading.Lock] = {}
        self._state_seq = 0

    # -- internals ---------------------------------------------------------
    def _manager(self, tenant_id: int) -> CheckpointManager:
        tid = int(tenant_id)
        with self._lock:
            m = self._managers.get(tid)
            if m is None:
                m = self._managers[tid] = CheckpointManager(
                    os.path.join(self.directory, f"tenant_{tid}"),
                    keep=self.keep)
            return m

    def _publish_lock(self, tenant_id: int) -> threading.Lock:
        with self._lock:
            lk = self._publish_locks.get(tenant_id)
            if lk is None:
                lk = self._publish_locks[tenant_id] = threading.Lock()
            return lk

    def _emit(self, kind: str, tenant: int) -> None:
        obs_inc(f"tenant.{kind}")
        obs_event(f"tenant.{kind}", tenant=int(tenant))

    # -- training side -----------------------------------------------------
    def publish(self, tenant_id: int, weights, intercept: float = 0.0) -> int:
        """Durably publish one tenant's new model (one checkpoint write)
        and — when the tenant is resident — hot-swap its slab row in
        place.  Returns the new version number.  The per-tenant
        retraining trickle calls this continuously under live traffic."""
        tid = int(tenant_id)
        m = self._manager(tid)
        with self._publish_lock(tid):
            version = (m.latest_version() or 0) + 1
            m.save(version, np.asarray(weights, np.float32), 0.0, [],
                   config_key=f"tenant-{tid}",
                   extras={"intercept": np.float32(intercept)})
            if self.slab.slot_of(tid) is not None:
                _, _, kind = self.slab.put(tid, weights, intercept,
                                           version=version)
                self._emit("swap" if kind == "swapped" else "admit", tid)
        return version

    # -- residency ---------------------------------------------------------
    def load(self, tenant_id: int) -> int:
        """Admit (or refresh) ``tenant_id`` from its newest checkpoint;
        returns the loaded version.  Raises :class:`TenantMissingError`
        when the tenant has no checkpoints; a corrupt newest checkpoint
        raises whatever ``CheckpointManager.restore_version`` raises
        (incl. ``IntegrityError``) — residency never swallows it."""
        tid = int(tenant_id)
        m = self._manager(tid)
        # the whole scan-restore-put under the tenant's publish lock: a
        # concurrent publish(tid) bumps the checkpoint AND the slab row
        # between an unserialized load's restore and its put, and the
        # load would then overwrite the newer slab row with the older
        # checkpoint — a silent version regression served until the
        # next swap (Eraser-confirmed on the publish-storm workload,
        # ISSUE 19).  Loads of DIFFERENT tenants stay fully concurrent.
        with self._publish_lock(tid):
            last_err: Optional[BaseException] = None
            for _ in range(3):
                latest = m.latest_version()
                if latest is None:
                    raise TenantMissingError(
                        f"tenant {tid}: no published checkpoint under "
                        f"{self.directory!r}")
                try:
                    ck = m.restore_version(latest)
                    break
                except Exception as e:
                    # a concurrent publish can prune `latest` between
                    # the version scan and the read (keep=N retention);
                    # re-scan and retry — a persistent failure (e.g. a
                    # corrupt newest checkpoint) still raises after the
                    # bounded retries, never silently served
                    last_err = e
            else:
                raise last_err
            _, evicted, kind = self.slab.put(
                tid, ck["weights"],
                float(ck["extras"].get("intercept", 0.0)), version=latest)
            self._emit("swap" if kind == "swapped" else "admit", tid)
            if evicted is not None:
                self._emit("evict", evicted)
        return latest

    # alias: the hot-reload spelling (reload tenant i; neighbors untouched)
    reload = load

    def slots_for(self, tenant_ids):
        """The serving resolve: tenants -> ``(slots, W, b)`` snapshot,
        admitting non-resident tenants from disk on miss.  Bounded
        retries guard against admission thrash (a burst whose distinct
        tenant count exceeds capacity cannot be scored in one batch —
        :class:`SlabFullError` instead of livelock)."""
        for _ in range(5):
            try:
                return self.slab.snapshot_for(tenant_ids)
            except KeyError as e:
                (missing,) = e.args
                for tid in sorted(missing):
                    self.load(tid)
        raise SlabFullError(
            f"slab thrash: {self.slab.capacity} slots cannot hold this "
            "batch's distinct tenants; raise capacity "
            "(plan.choose_slab_capacity) or shrink the batch")

    def admit_versions(self, manager_or_directory,
                       versions: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
        """The multi-model (shadow/canary) special case: pack several
        checkpoint VERSIONS of one model registry stream as slab rows,
        keyed by version number — ``TenantPredictEngine.predict_all``
        then scores a batch against every admitted version in one
        dispatch.  ``versions=None`` admits all of them (newest last,
        so the newest is the hottest row).  Returns the version ids
        admitted."""
        m = manager_or_directory
        if isinstance(m, (str, os.PathLike)):
            m = CheckpointManager(str(m))
        vs = list(versions) if versions is not None else list(m.versions())
        for v in vs:
            ck = m.restore_version(int(v))
            _, evicted, kind = self.slab.put(
                int(v), ck["weights"],
                float(ck["extras"].get("intercept", 0.0)), version=int(v))
            self._emit("swap" if kind == "swapped" else "admit", int(v))
            if evicted is not None:
                self._emit("evict", evicted)
        return tuple(int(v) for v in vs)

    def staleness_s(self, tenant_id: int) -> float:
        return self.slab.staleness_s(tenant_id)

    # -- slab state checkpointing ------------------------------------------
    def save_state(self, manager: CheckpointManager) -> int:
        """Checkpoint the WHOLE slab (weights + residency map) as one
        CRC-sealed frame through the standard checkpoint machinery: the
        npz content checksum covers every entry, and an io-plane seal
        over the packed arrays (site ``tenant.slab``) is stored
        alongside so :meth:`restore_state` re-verifies the slab bytes
        end-to-end.  Returns the state version written."""
        from tpu_sgd.io.integrity import seal

        st = self.slab.state()
        crc = seal(st["weights"], st["intercepts"], st["tenant_ids"],
                   st["slots"], st["versions"])
        with self._lock:
            self._state_seq += 1
            seq = self._state_seq
        manager.save(
            seq, st["weights"], 0.0, [], config_key="tenant-slab",
            extras={
                "slab_intercepts": st["intercepts"],
                "slab_tenant_ids": st["tenant_ids"],
                "slab_slots": st["slots"],
                "slab_versions": st["versions"],
                "slab_crc": np.int64(-1 if crc is None else crc),
            })
        return seq

    def restore_state(self, manager: CheckpointManager,
                      version: Optional[int] = None) -> int:
        """Restore a :meth:`save_state` frame into the slab, verifying
        the io-plane seal first (``IntegrityError`` on mismatch — a
        corrupt slab restore must fail loudly, not mis-serve every
        tenant).  Returns the state version restored."""
        from tpu_sgd.io.integrity import verify

        v = version if version is not None else manager.latest_version()
        if v is None:
            raise TenantMissingError(
                f"no slab state checkpoint under {manager.directory!r}")
        ck = manager.restore_version(int(v))
        ex = ck["extras"]
        st = {
            "weights": ck["weights"],
            "intercepts": ex["slab_intercepts"],
            "tenant_ids": ex["slab_tenant_ids"],
            "slots": ex["slab_slots"],
            "versions": ex["slab_versions"],
        }
        crc = int(ex["slab_crc"])
        if crc >= 0:
            verify("tenant.slab", crc, st["weights"], st["intercepts"],
                   st["tenant_ids"], st["slots"], st["versions"])
        self.slab.load_state(st)
        with self._lock:
            self._state_seq = max(self._state_seq, int(v))
        return int(v)

    # -- ops ---------------------------------------------------------------
    def healthz(self) -> dict:
        with self._lock:
            n_mgr = len(self._managers)
        return {
            "slab": self.slab.ledger_snapshot(),
            "tenant_dirs_open": n_mgr,
            "activation": self.activation,
        }
