"""Multi-tenant serving facade: lanes + admission control, tenant-keyed.

A :class:`TenantServer` is the tenant-plane analogue of
``serve.Server``: the SAME :class:`~tpu_sgd.serve.batcher.MicroBatcher`
(lanes, deadline admission, shedding, displacement, burst admission)
in front of a :class:`~tpu_sgd.tenant.engine.TenantPredictEngine`.

The batcher coalesces rows from MANY tenants into one flush, so the
tenant id must ride the row itself: it is packed as float32 COLUMN 0 of
a ``(1 + d)``-wide request row (exact for ids below 2**24 — enforced at
submit), and the flush callback splits ids from features before the
gathered dispatch.  The batcher, ``stack_rows``, and every admission
rule stay untouched — multi-tenant coalescing costs one column, not a
second request type.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from tpu_sgd.obs import timeseries as obs_timeseries
from tpu_sgd.ops.bucketed import DEFAULT_BUCKETS
from tpu_sgd.serve.batcher import MicroBatcher
from tpu_sgd.tenant.engine import TenantPredictEngine

#: tenant ids must stay exact through the float32 feature row
_MAX_TENANT_ID = 1 << 24


def _check_tid(tenant_id: int) -> np.float32:
    tid = int(tenant_id)
    if not (0 <= tid < _MAX_TENANT_ID):
        raise ValueError(
            f"tenant_id must be in [0, 2**24) to ride a float32 row "
            f"exactly, got {tid}")
    return np.float32(tid)


class TenantServer:
    """Micro-batched multi-tenant predict endpoint over one slab."""

    def __init__(self, store, *, buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
                 max_batch: int = 128, max_latency_s: float = 0.005,
                 max_queue: int = 1024, metrics=None, event_log=None,
                 shed_utilization=None):
        self.store = store
        self.engine = TenantPredictEngine(store, buckets)
        if metrics is None and event_log is not None:
            # same wiring as serve.Server: a listener event log buys the
            # per-batch latency records the lane_p99_s SLO metric reads
            from tpu_sgd.serve.metrics import ServingMetrics

            metrics = ServingMetrics(listener=event_log)
        self.metrics = metrics
        self.batcher = MicroBatcher(
            self._predict_batch,
            max_batch=max_batch,
            max_latency_s=max_latency_s,
            max_queue=max_queue,
            metrics=metrics,
            padded_size_fn=lambda n: self.engine.bucket_for(n),
            shed_utilization=shed_utilization,
        )

    # -- flush side --------------------------------------------------------
    def _predict_batch(self, X):
        """Split the composite rows the batcher coalesced: column 0 is
        the tenant id (exact float32 integers), the rest the features."""
        Xh = np.asarray(X)
        tids = Xh[:, 0].astype(np.int64)
        return self.engine.predict_batch(tids, Xh[:, 1:])

    # -- client side -------------------------------------------------------
    def submit(self, tenant_id: int, x, lane: str = "interactive",
               deadline_s: Optional[float] = None):
        """Enqueue one ``(tenant_id, features)`` request; resolves to
        that tenant's score for the row.  Admission raises/answers
        exactly like the single-model server (typed ``Overloaded``)."""
        xb = np.asarray(x, np.float32).reshape(-1)
        row = np.concatenate(([_check_tid(tenant_id)], xb))
        return self.batcher.submit(row, lane=lane, deadline_s=deadline_s)

    def submit_burst(self, tenant_ids, X, lane: str = "interactive",
                     deadline_s: Optional[float] = None):
        """Admit a whole ``(tenant_ids, X)`` burst under one lock round
        (``MicroBatcher.submit_burst``); returns one future per row."""
        Xh = np.asarray(X, np.float32)
        tids = np.asarray(tenant_ids).reshape(-1)
        if Xh.ndim != 2 or Xh.shape[0] != tids.shape[0]:
            raise ValueError(
                f"X must be (n, d) with one tenant id per row, got "
                f"X{Xh.shape} for {tids.shape[0]} ids")
        col = np.empty((len(tids), 1), np.float32)
        for i, t in enumerate(tids):
            col[i, 0] = _check_tid(t)
        rows = np.concatenate([col, Xh], axis=1)
        return self.batcher.submit_burst(list(rows), lane=lane,
                                         deadline_s=deadline_s)

    def predict(self, tenant_id: int, x, timeout: Optional[float] = None,
                *, lane: str = "interactive",
                deadline_s: Optional[float] = None):
        return self.submit(tenant_id, x, lane=lane,
                           deadline_s=deadline_s).result(timeout)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.batcher.start()
        return self

    def stop(self, drain: bool = True):
        self.batcher.stop(drain=drain)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- ops ---------------------------------------------------------------
    def healthz(self) -> dict:
        """Tenant-plane ops probe: slab residency/eviction ledger, the
        admission-cost ledger, engine dispatch counters, and the
        per-tenant obs windows (``tenant.*`` series)."""
        return {
            "serving": self.batcher._thread is not None,
            "queue_depth": self.batcher.queue_depth,
            "batch_count": self.batcher.batch_count,
            "lanes": self.batcher.lane_snapshot(),
            "admission": self.batcher.admission_snapshot(),
            "slab": self.store.slab.ledger_snapshot(),
            "engine": {
                "calls": self.engine.call_count,
                "dispatches": self.engine.dispatch_count,
                "uniform": self.engine.uniform_count,
                "mixed": self.engine.mixed_count,
                "compiles": self.engine.compile_count,
            },
            "windows": obs_timeseries.snapshot(prefix="tenant", last=8),
        }
