"""Multi-tenant model store and serving plane (ROADMAP item 4).

Thousands of tenants, each owning a small GLM, served from ONE
device-resident ``(capacity, d)`` weight slab:

* :class:`WeightSlab` — LRU-admitted rows, in-place hot reload, exact
  admission/eviction ledger (``tenant/slab.py``);
* :class:`TenantModelStore` — per-tenant ``CheckpointManager``
  durability, admission-on-miss, CRC-sealed whole-slab checkpoints,
  and the shadow/canary multi-version special case
  (``tenant/store.py``);
* :class:`TenantPredictEngine` — mixed-tenant batches scored by ONE
  gathered-matvec dispatch; uniform batches take the canonical
  single-model program for the bitwise contract (``tenant/engine.py``);
* :class:`TenantServer` — the lanes/admission micro-batcher fronting
  it, tenant id riding each row as a float32 column
  (``tenant/serve.py``).

The organizing rule (ADVICE.md): pack tenants into one slab; gather,
don't recompile — dispatch and compile counts are independent of
tenant count by construction, because tenant identity only ever enters
compiled programs as a traced index vector.
"""

from tpu_sgd.tenant.engine import TenantPredictEngine
from tpu_sgd.tenant.serve import TenantServer
from tpu_sgd.tenant.slab import (SlabFullError, WeightSlab,
                                 row_set_program_cache_size)
from tpu_sgd.tenant.store import TenantMissingError, TenantModelStore

__all__ = [
    "SlabFullError",
    "TenantMissingError",
    "TenantModelStore",
    "TenantPredictEngine",
    "TenantServer",
    "WeightSlab",
    "row_set_program_cache_size",
]
