"""Device-resident weight slab: M tenants' models as ONE ``(C, d)`` array.

The multi-tenant store's core trade (ROADMAP item 4): thousands of
tenants each own a small GLM, and M separate device arrays would mean M
host->device transfers, M gather-less dispatch paths, and — fatally,
under the shape-trap discipline of ``ops/bucketed.py`` — a compiled
program per tenant.  Instead every resident tenant occupies one ROW of
a fixed-capacity slab; scoring gathers rows by a traced slot vector
(``ops.bucketed.bucketed_gather_matvec``), so the executable count
depends on the slab's SHAPE (capacity x width), never on which — or
how many — tenants are resident.

Residency is LRU: admitting a tenant into a full slab evicts the
least-recently-served one (its checkpoints remain on disk; the next
request for it re-admits).  A hot reload swaps ONE row in place through
a cached jit row-set program — the neighbors' rows, the LRU order, and
every compiled program are untouched, which is what makes a per-tenant
retraining trickle cheap under live traffic.

Thread contract: admissions, swaps, and snapshot reads serialize on one
lock; the device arrays are immutable jax values REPLACED under that
lock, so a predict path that snapshotted ``(slots, W, b)`` keeps a
consistent view even if a swap lands mid-dispatch (the atomic-reference
idiom of ``serve/registry.py``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the host
#: mirrors, the device references, the LRU map, and the ledger are
#: shared between serving threads (snapshot reads) and admission /
#: hot-reload callers — every touch holds the lock.  The device refs
#: are ``:w`` (atomic-reference swap: a reader that copied them out
#: under the lock keeps a consistent immutable view).
GRAFTLINT_LOCKS = {
    "WeightSlab": {
        "_host_w": "_lock",
        "_host_b": "_lock",
        "_dev_w": "_lock:w",
        "_dev_b": "_lock:w",
        "_lru": "_lock",
        "_free": "_lock",
        "_published_at": "_lock",
        "_versions": "_lock",
        "ledger": "_lock",
        "evictions": "_lock",
    },
}

#: compiled row-set programs (hot reload), keyed by
#: (capacity, d, dtype) — the slot index and the row are traced
#: arguments, so ONE program swaps any row of any tenant forever
_ROW_SET_PROGRAMS: dict = {}

#: memo-key contract (graftlint memo-key rule): the factory receives
#: the fully-formed key tuple; its only reads come out of the key
GRAFTLINT_MEMO = {"_ROW_SET_PROGRAMS": ("key",)}


def row_set_program_cache_size() -> int:
    return len(_ROW_SET_PROGRAMS)


def _row_set_program(key):
    fn = _ROW_SET_PROGRAMS.get(key)
    if fn is None:
        import jax

        # slot is a traced int32 scalar: one compiled scatter per slab
        # SHAPE, reused for every slot / tenant / reload forever
        fn = jax.jit(lambda W, b, slot, row, bi: (
            W.at[slot].set(row), b.at[slot].set(bi)))
        _ROW_SET_PROGRAMS[key] = fn
    return fn


class SlabFullError(RuntimeError):
    """Admission thrash: the working set churned a just-admitted tenant
    out before it could be served — capacity is too small for the
    concurrency (``plan.choose_slab_capacity`` sizes it)."""


class WeightSlab:
    """Fixed-capacity ``(C, d)`` device slab + ``(C,)`` intercepts with
    LRU admission/eviction and in-place per-row hot swap.

    Tenant ids are integers (they ride serving rows as a float32
    column — exact below 2**24; ``tpu_sgd/tenant/serve.py``).  The
    eviction ledger (``ledger`` counts + the ``evictions`` log) is
    exact by construction — tests pin it.
    """

    def __init__(self, capacity: int, d: int, dtype=np.float32):
        if capacity < 1 or d < 1:
            raise ValueError(
                f"capacity and d must be >= 1, got ({capacity}, {d})")
        import jax.numpy as jnp

        self.capacity = int(capacity)
        self.d = int(d)
        #: immutable after construction — safe to read lock-free
        self.dtype = np.dtype(dtype)
        self._lock = threading.Lock()
        self._host_w = np.zeros((self.capacity, self.d), self.dtype)
        self._host_b = np.zeros((self.capacity,), np.float32)
        self._dev_w = jnp.asarray(self._host_w)
        self._dev_b = jnp.asarray(self._host_b)
        #: tenant_id -> slot, insertion order = recency (last = hottest)
        self._lru: "OrderedDict[int, int]" = OrderedDict()
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._published_at: Dict[int, float] = {}
        self._versions: Dict[int, int] = {}
        #: exact admission/eviction ledger (tests pin it): ``admitted``
        #: = tenants brought into the slab, ``evicted`` = LRU victims,
        #: ``swapped`` = in-place hot reloads of a resident row,
        #: ``hits``/``misses`` = per-tenant residency outcomes of
        #: serving lookups
        self.ledger: Dict[str, int] = {
            "admitted": 0, "evicted": 0, "swapped": 0,
            "hits": 0, "misses": 0,
        }
        #: ordered eviction log: (evicted_tenant, slot, admitted_tenant)
        self.evictions: List[Tuple[int, int, int]] = []

    # -- residency ---------------------------------------------------------
    @property
    def resident_count(self) -> int:
        with self._lock:
            return len(self._lru)

    def resident(self) -> Tuple[int, ...]:
        """Tenant ids currently resident, coldest first."""
        with self._lock:
            return tuple(self._lru)

    def slot_of(self, tenant_id: int) -> Optional[int]:
        with self._lock:
            return self._lru.get(int(tenant_id))

    # -- admission / hot reload --------------------------------------------
    def put(self, tenant_id: int, weights, intercept: float = 0.0,
            version: int = 0):
        """Admit ``tenant_id`` (evicting the LRU tenant when full) or —
        when already resident — hot-swap its row IN PLACE: one cached
        row-set dispatch, neighbors' rows and every compiled program
        untouched.  Returns ``(slot, evicted_tenant_or_None,
        "admitted"|"swapped")``."""
        import jax.numpy as jnp

        tid = int(tenant_id)
        row = np.asarray(weights, self.dtype).reshape(self.d)
        bi = np.float32(intercept)
        with self._lock:
            evicted: Optional[int] = None
            slot = self._lru.get(tid)
            if slot is not None:
                kind = "swapped"
                self._lru.move_to_end(tid)
                self.ledger["swapped"] += 1
            else:
                kind = "admitted"
                if self._free:
                    slot = self._free.pop()
                else:
                    evicted, slot = self._lru.popitem(last=False)
                    self._versions.pop(evicted, None)
                    self._published_at.pop(evicted, None)
                    self.ledger["evicted"] += 1
                    self.evictions.append((evicted, slot, tid))
                self._lru[tid] = slot
                self.ledger["admitted"] += 1
            self._host_w[slot] = row
            self._host_b[slot] = bi
            fn = _row_set_program(
                (self.capacity, self.d, str(self._host_w.dtype)))
            self._dev_w, self._dev_b = fn(
                self._dev_w, self._dev_b, np.int32(slot),
                jnp.asarray(row), jnp.asarray(bi))
            self._versions[tid] = int(version)
            self._published_at[tid] = time.time()
        return slot, evicted, kind

    # -- serving reads -----------------------------------------------------
    def snapshot_for(self, tenant_ids):
        """Resolve a request batch's tenants to slab slots and return a
        CONSISTENT ``(slots, W, b)`` view: the slot vector plus the
        device arrays as of one locked instant (immutable values — a
        concurrent swap replaces the references, never the snapshot).
        Touches LRU recency for every distinct tenant.  Raises
        ``KeyError`` carrying the set of non-resident tenants (the
        store's admission-on-miss hook)."""
        tids = np.asarray(tenant_ids).astype(np.int64, copy=False)
        uniq = {int(t) for t in np.unique(tids)}
        with self._lock:
            missing = {t for t in uniq if t not in self._lru}
            if missing:
                self.ledger["misses"] += len(missing)
                self.ledger["hits"] += len(uniq) - len(missing)
                raise KeyError(missing)
            self.ledger["hits"] += len(uniq)
            for t in uniq:
                self._lru.move_to_end(t)
            lru = self._lru
            slots = np.fromiter((lru[int(t)] for t in tids), np.int32,
                                count=len(tids))
            return slots, self._dev_w, self._dev_b

    def host_row(self, tenant_id: int) -> Tuple[np.ndarray, float]:
        """One tenant's ``(weights, intercept)`` from the host mirror —
        the uniform-batch path scores it through the canonical
        ``bucketed_matvec`` program for the single-model bitwise
        contract.  Raises ``KeyError`` when not resident."""
        tid = int(tenant_id)
        with self._lock:
            slot = self._lru[tid]  # KeyError -> store admits and retries
            return self._host_w[slot].copy(), float(self._host_b[slot])

    def snapshot_resident(self):
        """``(tenant_ids, slots, W, b)`` for the multi-model / all-
        versions batch (``bucketed_multi_matvec``): every resident
        tenant's column, coldest first."""
        with self._lock:
            ids = tuple(self._lru)
            slots = np.fromiter((self._lru[t] for t in ids), np.int32,
                                count=len(ids))
            return ids, slots, self._dev_w, self._dev_b

    def staleness_s(self, tenant_id: int) -> float:
        """Seconds since this tenant's row was last published into the
        slab (admit or swap); ``inf`` when not resident."""
        with self._lock:
            t = self._published_at.get(int(tenant_id))
        return float("inf") if t is None else max(0.0, time.time() - t)

    def version_of(self, tenant_id: int) -> Optional[int]:
        with self._lock:
            return self._versions.get(int(tenant_id))

    # -- checkpoint state --------------------------------------------------
    def state(self) -> dict:
        """Host snapshot of the whole slab for checkpointing: the weight
        matrix, intercepts, and the residency map as parallel arrays
        (coldest first, so a restore rebuilds the same LRU order)."""
        with self._lock:
            ids = np.asarray(list(self._lru), np.int64)
            slots = np.asarray([self._lru[int(t)] for t in ids], np.int32)
            return {
                "weights": self._host_w.copy(),
                "intercepts": self._host_b.copy(),
                "tenant_ids": ids,
                "slots": slots,
                "versions": np.asarray(
                    [self._versions.get(int(t), 0) for t in ids], np.int64),
            }

    def load_state(self, state: dict) -> None:
        """Restore a :meth:`state` snapshot (same capacity/width)."""
        import jax.numpy as jnp

        w = np.asarray(state["weights"], self.dtype)
        b = np.asarray(state["intercepts"], np.float32)
        if w.shape != (self.capacity, self.d):
            raise ValueError(
                f"slab state shape {w.shape} != ({self.capacity}, {self.d})")
        ids = np.asarray(state["tenant_ids"], np.int64)
        slots = np.asarray(state["slots"], np.int32)
        versions = np.asarray(state["versions"], np.int64)
        with self._lock:
            self._host_w = w.copy()
            self._host_b = b.copy()
            self._dev_w = jnp.asarray(self._host_w)
            self._dev_b = jnp.asarray(self._host_b)
            self._lru = OrderedDict(
                (int(t), int(s)) for t, s in zip(ids, slots))
            used = set(int(s) for s in slots)
            self._free = [s for s in range(self.capacity - 1, -1, -1)
                          if s not in used]
            now = time.time()
            self._published_at = {int(t): now for t in ids}
            self._versions = {int(t): int(v)
                              for t, v in zip(ids, versions)}

    def ledger_snapshot(self) -> dict:
        with self._lock:
            return {**self.ledger, "resident": len(self._lru),
                    "capacity": self.capacity}
