"""Configuration for the TPU-native SGD framework.

Mirrors the reference's two-tier config system (SURVEY.md §5.6): Spark exposes
builder-style setters on the optimizer/algorithm (``setStepSize``,
``setNumIterations``, ``setRegParam``, ``setMiniBatchFraction``,
``setConvergenceTol``) with defaults step=1.0, iters=100, frac=1.0, reg=0.0,
convTol=0.001.  Here the same knobs live in a frozen dataclass; the fluent
setters on :class:`~tpu_sgd.optimize.gradient_descent.GradientDescent` return
updated copies of it.

Reference parity: [U] mllib/optimization/GradientDescent.scala (defaults set in
the class constructor; see SURVEY.md §2 #2, §5.6).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SGDConfig:
    """Hyper-parameters of mini-batch SGD, with the reference's defaults.

    Attributes:
      step_size: initial step size; decays as ``step_size / sqrt(iter)``
        inside the updaters (parity with Spark's ``Updater.compute``).
      num_iterations: number of outer SGD iterations.
      reg_param: regularization strength handed to the updater.
      mini_batch_fraction: Bernoulli sampling fraction per iteration
        (parity with ``data.sample(false, frac, 42 + i)``).
      convergence_tol: early-exit tolerance on the relative weight delta,
        ``||w_new - w_old|| < tol * max(||w_new||, 1)``.
      seed: base RNG seed; iteration ``i`` folds in ``seed + i`` (the
        distributional analogue of Spark's per-iteration seed ``42 + i``).
      sampling: mini-batch sampling strategy when ``mini_batch_fraction < 1``.
        ``"bernoulli"`` (default) is exact reference parity — a per-example
        Bernoulli mask, normalized by the realized count; it computes the
        full-dataset matvec with masked coefficients.  ``"indexed"`` is a
        TPU fast path: gather a fixed-size batch of ``round(frac * n)`` rows
        sampled with replacement, touching only ``frac`` of HBM per
        iteration — distributionally equivalent for SGD, ~1/frac less
        memory traffic.  ``"sliced"`` is the HBM-optimal fast path: a
        contiguous row window of ``round(frac * n)`` rows at a per-iteration
        random offset — sequential DMA instead of a random gather (several
        times faster again), and zero-copy under ``PallasGradient``.  Sliced
        sampling is statistically sound when row order carries no signal
        (shuffled or i.i.d.-generated datasets); shuffle once beforehand if
        your rows are ordered.
    """

    step_size: float = 1.0
    num_iterations: int = 100
    reg_param: float = 0.0
    mini_batch_fraction: float = 1.0
    convergence_tol: float = 0.001
    seed: int = 42
    sampling: str = "bernoulli"

    def __post_init__(self):
        # the same range checks the fluent setters enforce: direct
        # construction and replace() must not smuggle in values that
        # silently train wrong (frac=0 samples empty batches forever)
        if self.sampling not in ("bernoulli", "indexed", "sliced"):
            raise ValueError(
                "sampling must be 'bernoulli', 'indexed' or 'sliced', "
                f"got {self.sampling!r}"
            )
        if not (0.0 < self.mini_batch_fraction <= 1.0):
            raise ValueError(
                "mini_batch_fraction must be in (0, 1], got "
                f"{self.mini_batch_fraction}"
            )
        if self.num_iterations < 1:
            raise ValueError(
                f"num_iterations must be >= 1, got {self.num_iterations}"
            )
        if self.step_size <= 0.0:
            raise ValueError(
                f"step_size must be positive, got {self.step_size}"
            )
        if self.reg_param < 0.0:
            raise ValueError(
                f"reg_param must be >= 0, got {self.reg_param}"
            )
        if not (0.0 <= self.convergence_tol <= 1.0):
            raise ValueError(
                "convergence_tol must be in [0, 1], got "
                f"{self.convergence_tol}"
            )

    def replace(self, **kwargs) -> "SGDConfig":
        return dataclasses.replace(self, **kwargs)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Shape of the device mesh the optimizer runs over.

    The reference's only parallelism axis is data parallelism (SURVEY.md §2
    parallelism ledger); ``model`` is the optional feature-sharding hook for
    very wide weight vectors (SURVEY.md §2 ledger, TP row).
    """

    data: int = 1
    model: int = 1

    def build(self, devices=None):
        """Materialize the ``jax.sharding.Mesh`` this config describes
        (``devices`` defaults to all visible devices)."""
        from tpu_sgd.parallel.mesh import make_mesh

        return make_mesh(n_data=self.data, n_model=self.model,
                         devices=devices)

    @property
    def n_devices(self) -> int:
        return self.data * self.model


def _default_shed_utilization():
    # interactive deliberately absent: the premium lane sheds only at
    # queue-full-with-no-victim (serve/batcher.py documents the order)
    return {"batch": 0.75, "shadow": 0.50}


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Serving-plane admission knobs — the control plane's actuation
    surface (ROADMAP item 1).

    ``shed_utilization`` maps lane -> queue-utilization fraction at
    which NEW arrivals to that lane are shed.  Historically these were
    the ``DEFAULT_SHED_UTILIZATION`` module constants in
    ``serve/batcher.py``, which a controller could only monkey-patch;
    now a batcher built with ``shed_utilization=None`` reads the
    PROCESS config here at construction, and a RUNNING batcher is
    actuated through ``MicroBatcher.set_shed_utilization`` — no
    constant ever needs patching.
    """

    shed_utilization: dict = dataclasses.field(
        default_factory=_default_shed_utilization)

    def __post_init__(self):
        for lane, thr in self.shed_utilization.items():
            if not (0.0 < float(thr) <= 1.0):
                raise ValueError(
                    f"shed_utilization[{lane!r}] must be in (0, 1], "
                    f"got {thr}")

    def replace(self, **kwargs) -> "ServingConfig":
        return dataclasses.replace(self, **kwargs)


_SERVING_CONFIG = ServingConfig()


def serving_config() -> ServingConfig:
    """The process-wide serving config new batchers default to."""
    return _SERVING_CONFIG


def set_serving_config(cfg: ServingConfig) -> ServingConfig:
    """Install a new process-wide serving config (returns the previous
    one, for scoped restore in tests).  Affects batchers constructed
    AFTER the call; running ones are actuated via their own
    ``set_shed_utilization``."""
    global _SERVING_CONFIG
    if not isinstance(cfg, ServingConfig):
        raise TypeError(f"expected ServingConfig, got {type(cfg).__name__}")
    prev = _SERVING_CONFIG
    _SERVING_CONFIG = cfg
    return prev
