"""Execution planning: ``train()`` picks the measured-best schedule itself.

The reference's user never chooses data placement: ``train()`` runs, and
Spark's scheduler plus ``cache()`` own where partitions live and how the
work is staged ([U] core/.../scheduler/DAGScheduler.scala — SURVEY.md §2
#16; the north star keeps the user API unchanged, BASELINE.json:5).
Rounds 2–3 left this framework with SIX measured execution schedules but
made the user compose them from flags (``sampling`` + ``sufficient_stats``
+ ``host_streaming`` + ``streaming_resident_rows`` + block size) — only
``bench.py`` knew the ladder.  This module is the scheduler analogue: probe
``(n, d, dtype, gradient family, sampling, free HBM)``, pick the schedule
the round-3 hardware measurements say is fastest, and configure the
optimizer — so a zero-flag ``train()`` call lands on the right schedule
and an explicit ``schedule=...`` override is honored with a warning when
the estimate says it will lose.

Schedules (measured figures: BASELINE.md "Measured results", TPU v5 lite):

=====================  ====================================================
``resident_stock``     data fits in HBM; fused two-pass iterations at the
                       two-HBM-read bandwidth floor (1.64 ms/iter on the
                       3M×1000 bf16 slab)
``resident_gram``      + least squares with sliced/full-batch sampling:
                       block-prefix sufficient statistics, exact
                       trajectory, 0.036–0.123 ms/iter (19–45×)
``partial_residency``  just beyond HBM, sliced sampling, single device:
                       leading rows resident, windows inside the prefix
                       cost no transfer (~2.4× the plain streamed rate
                       here)
``host_streamed``      anything host-resident: double-buffered per-
                       iteration batch transfer (feed-bandwidth-bound);
                       on a single device the planner also picks the
                       fused-step count K (``choose_superstep``) so one
                       compiled K-step scan amortizes the per-iteration
                       dispatch tax (README "Fused stepping")
``streamed_virtual_gram``  least squares beyond HBM, sliced/full-batch:
                       ONE streaming pass builds on-device statistics,
                       then iterations touch no rows (0.026 ms/iter
                       post-build on the true 10M×1000).  Uses ALIGNED
                       (block-floored) windows — a sampling deviation
                       (harmless on shuffled rows, not on sorted/grouped
                       data) that the plan's ``reason`` states loudly.
=====================  ====================================================

The quasi-Newton optimizers (LBFGS/OWL-QN) plan a narrower menu through
:func:`plan_quasi_newton` (``QN_SCHEDULES``): stock full-batch passes,
the sufficient-statistics substitution (least squares — resident or
streamed-virtual, meshed via per-shard totals), and — round 5 — the
``host_streamed`` chunked-CostFun schedule for NON-least-squares losses
beyond HBM (``optimize/streamed_costfun.py``), closing the reference's
any-size-any-loss CostFun contract.

The cost model's constants are calibrated to the round-3 hardware captures
(``BENCH_LAST_TPU.json``); they steer *decision boundaries*, not perf
claims, and every number the decision used is recorded in
``Plan.estimates`` for inspection.  Decisions are deliberately
conservative for small problems: the one-time statistics build only pays
for itself past ``build_amortize_iters`` iterations (measured ~1000–1900
at 3M×1000), so tiny workloads keep the stock path and its bitwise
round-2 trajectories.  :meth:`CostModel.calibrate` re-measures the two
environment-sensitive rates (~2 s) for deployments off the calibrated
tunnel environment.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import warnings
from typing import Optional

logger = logging.getLogger("tpu_sgd.plan")

#: the five schedules `plan` chooses among (resident_gram covers both the
#: exact and aligned variants via Plan.aligned)
SCHEDULES = (
    "resident_stock",
    "resident_gram",
    "partial_residency",
    "host_streamed",
    "streamed_virtual_gram",
)


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Decision-boundary constants, calibrated to the round-3 hardware
    captures (BASELINE.md / BENCH_LAST_TPU.json).  Override any of them
    (e.g. ``host_feed_gb_s`` for a pod-local host whose DMA feed is
    ~100–1000× this environment's 0.03–0.16 GB/s tunnel)."""

    #: effective HBM read bandwidth (measured: 1.64 ms/iter for the 1.2 GB
    #: two-read window on the 3M×1000 bf16 slab)
    hbm_gb_s: float = 730.0
    #: f32 HIGHEST-precision matmul throughput for the statistics build
    mxu_f32_flops: float = 2.0e13
    #: fixed build cost: compile + launches of the one-time statistics pass
    build_overhead_s: float = 1.2
    #: per-iteration fixed cost of the gram schedule beyond its HBM traffic
    #: (loop bookkeeping; measured residual at 0.08 ms/iter total)
    gram_iter_overhead_s: float = 5.0e-5
    #: host->device feed bandwidth for streaming schedules (measured
    #: through this environment's tunnel: 0.03–0.163 GB/s; pod-local PCIe
    #: is ~10–100 GB/s — override for real deployments)
    host_feed_gb_s: float = 0.15
    #: fallback device memory when the backend reports no memory stats
    hbm_bytes: float = 16.0e9
    #: fraction of free device memory the planner will commit
    hbm_safety: float = 0.80
    #: minimum fraction of iterations that must avoid transfer for partial
    #: residency to be chosen over plain streaming
    min_resident_gain: float = 0.05
    #: fixed host cost of ONE streamed-SGD iteration dispatch (batch
    #: ``device_put``s + program launch + readback bookkeeping) — the
    #: per-iteration tax the superstep executor amortizes K-fold.
    #: Fitted from BENCH_SUPERSTEP.json's slope difference between the
    #: K=1 and K=8 drivers on this harness (slope_K1 - slope_K8 scaled
    #: by 8/7 = implied_dispatch_overhead_s; recalibrated for the
    #: resident-driver round at 2.3 ms — the earlier 1.4 ms capture was
    #: a quieter ambient state, the same run-to-run band
    #: BENCH_SUPERSTEP.json's basis string warns about); like
    #: ``host_feed_gb_s`` it is environment-bound — pod-local hosts
    #: dispatch ~10× faster
    dispatch_overhead_s: float = 2.3e-3
    #: target ceiling for the residual dispatch tax under fusion:
    #: choose_superstep picks the smallest K with
    #: ``dispatch_overhead_s / K <= frac * per-iteration wall``
    superstep_dispatch_frac: float = 0.05
    #: gradient all-reduce link rate for the compressed-wire decision
    #: (choose_wire_compress).  ICI within a slice is far faster, but
    #: the rate that matters for the wires this planner can choose to
    #: compress is the slowest link the update crosses — DCN / host
    #: tunnel class; like host_feed_gb_s it is environment-bound
    allreduce_gb_s: float = 10.0
    #: fixed per-step cost of the compress/decompress stages (host
    #: top-k selection + segment scatter-add dispatch); compression
    #: pays only when the predicted wire-byte saving dominates this
    compress_overhead_s: float = 2.0e-4
    #: top-k fraction the planner proposes when compression pays; 1%
    #: of coordinates = ~50x fewer physical bytes (value + int32 index
    #: per entry), the SparCML operating point
    wire_compress_frac: float = 0.01
    #: density (nnz / dim) at which the sharded store's SparCML
    #: pairwise segment merge switches to a dense accumulator
    #: (``io.sparse_wire.merge_sparse_segments``; arXiv:1802.08021's
    #: representation crossover): a sparse merge costs O(nnz log nnz)
    #: per pair and only re-pays while the union stays sparse — past
    #: this density the O(dim) dense scatter-add is strictly cheaper
    sparse_merge_density: float = 0.25
    #: set by :meth:`calibrate` — raw probe readings plus which probes
    #: were rejected and fell back to the persisted defaults; excluded
    #: from equality/repr (two models with the same rates ARE the same
    #: model however they were obtained)
    calibration_report: Optional[dict] = dataclasses.field(
        default=None, compare=False, repr=False)

    @classmethod
    def calibrate(cls, device=None, copy_mb: float = 256.0,
                  feed_mb: float = 64.0, **overrides):
        """Measure THIS environment's two planner-critical rates and
        return a :class:`CostModel` carrying them (~2 s; everything else
        keeps the defaults unless overridden).

        The persisted defaults are single-environment calibrations of a
        tunnel-attached TPU v5 lite (0.15 GB/s feed!); on a pod-local
        host every streaming decision boundary shifts ~100×, so a
        deployment that cares about the boundaries should probe once:

        * ``hbm_gb_s`` — effective on-device bandwidth from the SLOPE
          between two trip counts of one compiled read+write loop over a
          ``copy_mb`` buffer, so the per-call tax (launch + readback,
          ~65–130 ms through a remote tunnel) cancels out.  The trip
          count is a TRACED argument — a constant bound lets XLA unroll
          and fold the whole loop into one fused pass (measured on the
          axon tunnel: a constant-200 loop reported ~700,000 GB/s) —
          and each timing ends with a 1-element device→host readback,
          which cannot return before the work is done even where
          ``block_until_ready`` is unreliable (experimental remote
          platforms).
        * ``host_feed_gb_s`` — the same two-point slope over two
          ``device_put`` sizes (``feed_mb`` and a quarter of it), each
          synced by readback, cancelling the per-transfer round trip.

        Either probe falls back to the persisted default (and keeps the
        other's measurement) if its slope comes out non-positive or the
        implied rate lands outside a physical-plausibility window
        (1–20,000 GB/s for HBM, 0.001–1,000 GB/s for host feed) — a
        wedged tunnel or an elided program must not poison the cost
        model with a garbage rate.
        """
        import time

        import jax
        import jax.numpy as jnp
        import numpy as np

        if device is None:
            device = jax.devices()[0]

        n_elems = max(1024, int(copy_mb * 1e6 // 4))
        x = jnp.zeros((n_elems,), jnp.float32, device=device)

        @jax.jit
        def many_passes(a, n):
            return jax.lax.fori_loop(0, n, lambda i, v: v + 1.0, a)

        def timed_passes(loops):
            t0 = time.perf_counter()
            r = many_passes(x, jnp.int32(loops))
            np.asarray(r[:1])  # readback: forces true completion
            return time.perf_counter() - t0

        def accept(raw, slope_s, window, default, label):
            """ONE rejection policy for both probes: a rate outside its
            physical-plausibility window (collapsed, elided, clamped, or
            noise-dominated measurement) falls back to the persisted
            default with a warning.  Returns ``(rate, fell_back)``."""
            fell_back = not (window[0] <= raw <= window[1])
            if fell_back:
                logger.warning(
                    "calibrate: %s probe rejected (implied %.6g GB/s, "
                    "slope %.2e s); keeping the persisted default "
                    "%.6g GB/s", label, raw, slope_s, default)
            return (default if fell_back else raw), fell_back

        lo, hi = 50, 200
        timed_passes(2)  # compile + warm (dynamic bound: one program)
        dt_lo, dt_hi = timed_passes(lo), timed_passes(hi)
        hbm_slope = dt_hi - dt_lo
        hbm_raw = ((hi - lo) * 2.0 * n_elems * 4.0 / hbm_slope / 1e9
                   if hbm_slope > 1e-5 else 0.0)
        # no real memory system exceeds ~20 TB/s
        hbm_gb_s, hbm_fell_back = accept(
            hbm_raw, hbm_slope, (1.0, 20_000.0), cls.hbm_gb_s, "HBM")

        n_feed = max(1024, int(feed_mb * 1e6 // 4))
        h_lo = np.zeros((max(1024, n_feed // 4),), np.float32)
        h_hi = np.zeros((n_feed,), np.float32)

        def timed_put(h):
            t0 = time.perf_counter()
            y = jax.device_put(h, device)
            np.asarray(y[:1])  # readback: forces arrival
            return time.perf_counter() - t0

        timed_put(h_lo)  # warm the transfer path + both buffer sizes'
        timed_put(h_hi)  # device allocations before timing either
        slope = timed_put(h_hi) - timed_put(h_lo)
        nbytes_delta = h_hi.nbytes - h_lo.nbytes
        # Trust the slope only when h_lo escaped its 1024-element clamp
        # (feed_mb >= ~0.017): a partially-clamped pair leaves a few-KB
        # byte delta whose jitter-dominated slope can land inside the
        # plausibility window as a garbage rate.
        unclamped = n_feed // 4 >= 1024
        feed_raw = (nbytes_delta / slope / 1e9
                    if slope > 1e-5 and unclamped else 0.0)
        feed_gb_s, feed_fell_back = accept(
            feed_raw, slope, (1e-3, 1_000.0), cls.host_feed_gb_s,
            "host-feed")

        report = {"hbm_raw_gb_s": hbm_raw, "hbm_slope_s": hbm_slope,
                  "hbm_fell_back": hbm_fell_back,
                  "feed_raw_gb_s": feed_raw, "feed_slope_s": slope,
                  "feed_fell_back": feed_fell_back}
        # explicit overrides win, including over the measured fields
        # (a user may probe one rate while pinning the other)
        return cls(**{"hbm_gb_s": hbm_gb_s, "host_feed_gb_s": feed_gb_s,
                      "calibration_report": report, **overrides})


DEFAULT_COST_MODEL = CostModel()


def device_budget(device=None, cost_model: CostModel = DEFAULT_COST_MODEL):
    """``(free_bytes, source)`` for the target device — probed from
    ``device.memory_stats()`` when the backend reports it (TPU does),
    otherwise the cost model's fallback.  ``source`` says which."""
    import jax

    if device is None:
        try:
            device = jax.devices()[0]
        except Exception:  # backend init failure: fall back
            return cost_model.hbm_bytes * cost_model.hbm_safety, "fallback"
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("bytes_limit"):
        free = stats["bytes_limit"] - stats.get("bytes_in_use", 0)
        return max(0.0, free * cost_model.hbm_safety), "memory_stats"
    return cost_model.hbm_bytes * cost_model.hbm_safety, "fallback"


@dataclasses.dataclass(frozen=True)
class Plan:
    """A chosen execution schedule plus the estimates that chose it.

    ``apply(optimizer)`` configures a ``GradientDescent`` accordingly and
    returns it; ``describe()`` is the one-line human explanation that
    ``train()`` logs."""

    schedule: str
    reason: str
    block_rows: Optional[int] = None
    batch_rows: Optional[int] = None
    aligned: bool = False
    resident_rows: int = 0
    #: chunked-gather driver iterations per outer step (gram schedules;
    #: None = the per-iteration driver — the default until the hardware
    #: decomposition capture settles the win)
    chunk_iters: Optional[int] = None
    #: ingest-pipeline knobs for the streaming schedules (tpu_sgd/io):
    #: wire_dtype stays None — the bf16 wire is a documented opt-in, the
    #: planner never silently rounds the user's inputs; prefetch_depth=2
    #: is the double buffer whose 2× staging footprint
    #: choose_streamed_build budgets for
    wire_dtype: Optional[str] = None
    prefetch_depth: int = 2
    #: fused-step count for the host_streamed schedule (README "Fused
    #: stepping"): K iterations per compiled dispatch, the K-batch
    #: superchunk staged double-buffered like every other chunk
    #: (choose_superstep budgets 2× its footprint); 1 = the
    #: per-iteration driver
    superstep: int = 1
    #: device-residency cadence for the host_streamed full-batch feed
    #: (README "Device-resident training"): C >= 2 moves the whole run
    #: into one compiled while_loop with host callbacks every C
    #: supersteps (choose_residency — resident only when the cadence
    #: window holds at least 2 supersteps); 0 = the per-superstep
    #: host-dispatched driver
    residency: int = 0
    #: compressed gradient wire for the meshed host_streamed schedule
    #: (README "Compressed wire"): "topk:<frac>" when
    #: choose_wire_compress says the per-step all-reduce bytes dominate
    #: the compress cost, else None.  NOTE the compressed wire changes
    #: the UPDATE RULE (top-k + error feedback — convergent at matched
    #: final loss, not bitwise), so the planner proposes it only where
    #: a real multi-shard all-reduce exists; user wire_compress wins
    wire_compress: Optional[str] = None
    #: async replica-worker count for the bounded-staleness driver
    #: (``tpu_sgd/replica``; README "Async replicas"): how many
    #: ``ReplicaDriver`` workers the cost model says this workload can
    #: keep busy (``choose_replicas``; 0 = stay synchronous), stamped
    #: on every plan :func:`plan` returns (also in
    #: ``estimates["replicas"]``).  NOT a schedule the planner
    #: auto-applies — ``tau > 0`` changes the update rule (matched
    #: final loss, not matched trajectory), so going async is always
    #: the USER's call; this field is the sizing advice they read when
    #: they make it
    replicas: int = 0
    #: store-shard count for the async store's apply plane
    #: (``tpu_sgd/replica/shard.py``; ``choose_store_shards``): how
    #: many per-shard apply pipelines the cost model says pay at this
    #: width (1 = unsharded).  Sizing advice with the same contract as
    #: :attr:`replicas` — the driver only shards when the user asks
    #: (``ReplicaDriver.set_store_shards``); also in
    #: ``estimates["store_shards"]``
    store_shards: int = 1
    estimates: dict = dataclasses.field(default_factory=dict)

    def describe(self) -> str:
        return f"plan: {self.schedule} — {self.reason}"

    def apply(self, optimizer):
        """Configure ``optimizer`` (a ``GradientDescent``) for this
        schedule.  Clears the schedule flags and plan-owned gram knobs
        first, so re-planning an optimizer between datasets never leaks
        the previous choice.  Attributes are assigned DIRECTLY, not
        through the fluent setters: the setters record USER intent
        (``_user_gram_opts``, ``last_plan`` invalidation) and the planner
        must not masquerade as the user — knob fields the user set via
        ``set_gram_options`` are preserved (user flags win)."""
        if self.schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {self.schedule!r}")
        apply_gram_knobs(optimizer, self)
        optimizer.host_streaming = self.schedule in (
            "partial_residency", "host_streamed")
        optimizer.streaming_resident_rows = (
            self.resident_rows if self.schedule == "partial_residency"
            else 0)
        optimizer.sufficient_stats = self.schedule == "resident_gram"
        optimizer.streamed_stats = self.schedule == "streamed_virtual_gram"
        optimizer.last_plan = self
        return optimizer

    def apply_quasi_newton(self, optimizer):
        """Configure an ``LBFGS``/``OWLQN`` optimizer per this plan — the
        quasi-Newton analogue of :meth:`apply`, kept HERE so schedule
        application has one home and callers (``models/glm.py``) cannot
        drift from it.  Same contract as :meth:`apply`: direct
        assignment, user-set knobs win, plan-owned fields always reset."""
        if self.schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {self.schedule!r}")
        optimizer.sufficient_stats = self.schedule == "resident_gram"
        optimizer.streamed_stats = self.schedule == "streamed_virtual_gram"
        optimizer.host_streaming = self.schedule == "host_streamed"
        if "stream_batch_rows" not in getattr(
                optimizer, "_user_gram_opts", frozenset()):
            optimizer.stream_batch_rows = (
                self.batch_rows if self.schedule == "host_streamed"
                else None)
        apply_gram_knobs(optimizer, self)
        optimizer.last_plan = self
        return optimizer


def apply_gram_knobs(optimizer, p: "Plan") -> None:
    """Write a plan's gram build knobs onto ``optimizer``, preserving any
    field the USER set via ``set_gram_options``/``set_streamed_stats``
    (recorded in ``_user_gram_opts``).  Plan-owned fields are always
    reset — a previous dataset's block size or streamed-build chunk cap
    must not leak into this build (the gram identity caches key on them).
    Shared by :meth:`Plan.apply` (GradientDescent) and
    :meth:`Plan.apply_quasi_newton` (LBFGS/OWL-QN)."""
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS

    user = getattr(optimizer, "_user_gram_opts", frozenset())
    if "block_rows" not in user:
        optimizer.gram_block_rows = p.block_rows or DEFAULT_BLOCK_ROWS
    if "batch_rows" not in user:
        # A host_streamed plan sizes batch_rows as the STREAM chunk (a
        # global, mesh-scaled row count owned by stream_batch_rows) —
        # writing it here would hand a later manual streamed-gram build
        # an absurd chunk cap sized for the wrong schedule.
        optimizer.gram_batch_rows = (
            None if p.schedule == "host_streamed" else p.batch_rows or None)
    if "aligned" not in user and hasattr(optimizer, "gram_aligned"):
        optimizer.gram_aligned = bool(p.aligned)
    if ("chunk_iters" not in user
            and hasattr(optimizer, "gram_chunk_iters")):
        optimizer.gram_chunk_iters = p.chunk_iters or None
    if ("wire_dtype" not in user
            and hasattr(optimizer, "ingest_wire_dtype")):
        optimizer.ingest_wire_dtype = p.wire_dtype
    if ("prefetch_depth" not in user
            and hasattr(optimizer, "ingest_prefetch_depth")):
        optimizer.ingest_prefetch_depth = int(p.prefetch_depth)
    if "superstep" not in user and hasattr(optimizer, "superstep"):
        optimizer.superstep = int(getattr(p, "superstep", 1) or 1)
    if ("residency" not in user
            and hasattr(optimizer, "resident_cadence")):
        optimizer.resident_cadence = int(getattr(p, "residency", 0) or 0)
    if ("wire_compress" not in user
            and hasattr(optimizer, "ingest_wire_compress")):
        optimizer.ingest_wire_compress = getattr(p, "wire_compress", None)


#: THE user-facing gram knob table: name -> (optimizer attribute,
#: requires-positive-int).  Shared by the setters' validate-then-apply
#: (`apply_user_gram_knobs`), `apply_gram_knobs`, and
#: `reset_plan_owned_gram_knobs`, so a new knob is wired in ONE place.
_GRAM_KNOBS = {
    "block_rows": ("gram_block_rows", True),
    "batch_rows": ("gram_batch_rows", True),
    "aligned": ("gram_aligned", False),
    "chunk_iters": ("gram_chunk_iters", True),
}


def apply_user_gram_knobs(optimizer, **knobs) -> None:
    """Validate-all-then-apply for USER-set gram knobs (the
    ``set_gram_options`` body, shared by GradientDescent and LBFGS): a
    bad LATER argument must not leave earlier knobs half-applied —
    mutated but unrecorded in ``_user_gram_opts`` with the plan cache
    intact.  Records every applied knob as user-owned and invalidates
    the repeat-run plan key (knobs are not a schedule choice, so
    ``last_plan`` survives and re-planning still runs)."""
    provided = {}
    for name, val in knobs.items():
        if val is None:
            continue
        attr, positive = _GRAM_KNOBS[name]
        if positive:
            if int(val) < 1:
                raise ValueError(f"{name} must be positive, got {val}")
            val = int(val)
        else:
            val = bool(val)
        provided[name] = (attr, val)
    for attr, val in provided.values():
        setattr(optimizer, attr, val)
    optimizer._user_gram_opts = optimizer._user_gram_opts | set(provided)
    optimizer._plan_key = None


def apply_user_ingest_options(optimizer, wire_dtype=None,
                              prefetch_depth=None, pipeline=None,
                              retry=None, wire_compress=None) -> None:
    """Validate-all-then-apply for USER-set ingest-pipeline knobs (the
    ``set_ingest_options`` body, shared by GradientDescent and LBFGS) —
    the ingest sibling of :func:`apply_user_gram_knobs`, with the same
    contract: a bad later argument leaves earlier knobs untouched, every
    applied knob is recorded user-owned in ``_user_gram_opts`` so the
    planner preserves it, and the repeat-run plan key invalidates.

    ``wire_dtype``: ``"bfloat16"`` (half the bytes on the host→device
    hop; see ``tpu_sgd/io/wire.py`` for when that is safe) or any
    floating dtype name; validated eagerly so a typo fails HERE, not
    mid-build.  ``prefetch_depth``: chunks staged ahead (0 = synchronous
    legacy feed, 2 = double buffer).  ``pipeline``: False reverts the
    streamed builds to the legacy sync loop (A/B debugging).
    ``retry``: a ``tpu_sgd.reliability.RetryPolicy`` healing transient
    host-feed faults on the host-streamed SGD path (``False`` clears a
    previously set policy); retries never change the sampled sequence,
    so results are unaffected.  ``wire_compress``: ``"topk:<frac>"``
    engages the compressed sparse gradient wire
    (``tpu_sgd/io/sparse_wire.py``; README "Compressed wire"),
    validated eagerly like ``wire_dtype``; ``False`` clears it."""
    from tpu_sgd.io import parse_wire_compress, resolve_wire_dtype

    provided = {}
    if wire_compress is not None:
        if wire_compress is False:
            provided["wire_compress"] = ("ingest_wire_compress", None)
        else:
            parse_wire_compress(wire_compress)  # validate, keep spec
            provided["wire_compress"] = ("ingest_wire_compress",
                                         str(wire_compress))
    if retry is not None:
        if retry is False:
            provided["retry"] = ("ingest_retry_policy", None)
        else:
            from tpu_sgd.reliability.retry import RetryPolicy

            if not isinstance(retry, RetryPolicy):
                raise TypeError(
                    f"retry must be a RetryPolicy or False, got "
                    f"{type(retry).__name__}"
                )
            provided["retry"] = ("ingest_retry_policy", retry)
    if wire_dtype is not None:
        resolve_wire_dtype(wire_dtype, "float32")  # validate, keep name
        provided["wire_dtype"] = ("ingest_wire_dtype", str(wire_dtype))
    if prefetch_depth is not None:
        if int(prefetch_depth) < 0:
            raise ValueError(
                f"prefetch_depth must be >= 0, got {prefetch_depth}"
            )
        provided["prefetch_depth"] = ("ingest_prefetch_depth",
                                      int(prefetch_depth))
    if pipeline is not None:
        provided["pipeline"] = ("ingest_pipeline", bool(pipeline))
    for attr, val in provided.values():
        setattr(optimizer, attr, val)
    optimizer._user_gram_opts = optimizer._user_gram_opts | set(provided)
    optimizer._plan_key = None


def reset_plan_owned_gram_knobs(optimizer) -> None:
    """The clearing counterpart of :func:`apply_gram_knobs`: restore
    every gram knob the USER did not set (``_user_gram_opts``) to its
    constructor default.  Called when a manual schedule setter takes the
    wheel after an auto-planned run — the previous plan's block size /
    chunk caps were sized for ITS dataset and budget, and a manual
    schedule on a different dataset must not inherit them (the same
    leak class as the host_streamed batch_rows fix, but via the
    manual-after-plan path)."""
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS

    user = getattr(optimizer, "_user_gram_opts", frozenset())
    if "block_rows" not in user:
        optimizer.gram_block_rows = DEFAULT_BLOCK_ROWS
    if "batch_rows" not in user:
        optimizer.gram_batch_rows = None
    if "aligned" not in user and hasattr(optimizer, "gram_aligned"):
        optimizer.gram_aligned = False
    if ("chunk_iters" not in user
            and hasattr(optimizer, "gram_chunk_iters")):
        optimizer.gram_chunk_iters = None
    if ("stream_batch_rows" not in user
            and hasattr(optimizer, "stream_batch_rows")):
        optimizer.stream_batch_rows = None
    if ("wire_dtype" not in user
            and hasattr(optimizer, "ingest_wire_dtype")):
        optimizer.ingest_wire_dtype = None
    if ("prefetch_depth" not in user
            and hasattr(optimizer, "ingest_prefetch_depth")):
        from tpu_sgd.io import DEFAULT_PREFETCH_DEPTH

        optimizer.ingest_prefetch_depth = DEFAULT_PREFETCH_DEPTH
    if "superstep" not in user and hasattr(optimizer, "superstep"):
        optimizer.superstep = 1
    if ("residency" not in user
            and hasattr(optimizer, "resident_cadence")):
        optimizer.resident_cadence = 0
    if ("wire_compress" not in user
            and hasattr(optimizer, "ingest_wire_compress")):
        optimizer.ingest_wire_compress = None


def _stack_bytes(n_local: int, block_rows: int, d: int) -> float:
    """Device bytes of the f32 block-prefix statistics at this block size
    (PG + Pb + Pyy + totals; see ops/gram.py memory note)."""
    nbf = max(1, n_local // block_rows)
    return (nbf + 2) * (d * d + d + 1) * 4.0


def choose_block_rows(n_local: int, d: int, stats_budget: float,
                      start: int = 4096) -> Optional[int]:
    """Smallest measured-good block size whose prefix stack fits the
    budget (doubling from the 4096 the round-3 captures liked; smaller
    blocks mean less edge traffic but a bigger stack).  None when no block
    size up to ``n_local`` fits — gram is then infeasible here."""
    B = min(max(1, start), max(1, n_local))
    while _stack_bytes(n_local, B, d) > stats_budget:
        if B >= n_local:
            return None
        B *= 2
    return B


def choose_streamed_build(n_local: int, d: int, itemsize: int,
                          budget: float, start: int = 4096):
    """``(block_rows, batch_rows)`` for a STREAMED statistics build whose
    whole device footprint fits ``budget`` — the prefix stack PLUS the
    in-flight host→device chunks that are co-resident during the build
    (``build_streamed`` defaults the chunk to 64 blocks, which at the
    large block sizes a tight stack budget forces can exceed the stack
    itself).  The stack gets ~2/3 of the budget; the chunk is capped to
    the remainder divided by TWO — the double-buffered ingest pipeline
    (``tpu_sgd/io``) stages chunk ``k+1`` while chunk ``k``'s kernel
    consumes its buffer, so two chunks are live at the peak (never above
    the builder's 64-block default).  Returns ``(None, None)`` when no
    split fits."""
    B = choose_block_rows(n_local, d, budget * 2.0 / 3.0, start=start)
    if B is None:
        return None, None
    chunk_budget = budget - _stack_bytes(n_local, B, d)
    rows = int(chunk_budget // max(1, 2 * (d * itemsize + 4)))
    if rows < B:  # cannot hold even one block alongside the stack
        return None, None
    return B, int(min(rows, 64 * B))


def choose_superstep(window_rows: int, d: int, itemsize: int,
                     iter_s: float, staging_budget: float,
                     cost_model: CostModel = DEFAULT_COST_MODEL,
                     cap: int = 64) -> int:
    """Fused-step count K for the host_streamed schedule, from the
    fixed-cost/slope fit (the GRAM_SCAN_EXPERIMENT / BENCH_SUPERSTEP
    methodology): every streamed iteration pays a fixed host dispatch
    tax ``dispatch_overhead_s`` on top of its ``iter_s`` transfer/
    compute slope, and fusing K steps into one program divides the tax
    by K.  Picks the smallest K that pushes the residual tax below
    ``superstep_dispatch_frac`` of the per-iteration wall — smallest,
    not largest, because K also multiplies the preemption latency and
    the staging footprint — then clamps to what the double-buffered
    K-batch superchunk (2× one superchunk live at the peak, the same
    2× rule ``choose_streamed_build`` applies) fits in
    ``staging_budget``, and to ``cap``.  Returns 1 when fusion cannot
    pay (tiny dispatch tax or no staging room)."""
    cm = cost_model
    batch_bytes = window_rows * (d * itemsize + 5.0)  # X + y(f32) + valid
    if math.isinf(staging_budget):
        # shared-batch feeds stage no superchunk at all (one transfer,
        # the scan reuses it): only the amortization target binds
        k_budget = int(cap)
    else:
        k_budget = int(staging_budget // max(1.0, 2.0 * batch_bytes))
    if k_budget < 2:
        return 1
    target = cm.superstep_dispatch_frac * max(iter_s, 1e-9)
    k_amortize = math.ceil(cm.dispatch_overhead_s / target)
    return int(max(1, min(cap, k_amortize, k_budget)))


def choose_wire_compress(dim: int, n_devices: int,
                         cost_model: CostModel = DEFAULT_COST_MODEL,
                         resident_cadence: int = 0) -> Optional[str]:
    """Compressed-wire decision for the per-step gradient all-reduce
    (README "Compressed wire"): compression pays ONLY when the
    predicted wire bytes dominate the compress/decompress cost.

    The per-step dense wire moves one ``(dim,)`` f32 update per shard
    (``dim * 4`` bytes at ``allreduce_gb_s``); top-k at
    ``wire_compress_frac`` shrinks that to ``2 * frac`` of the bytes
    (each surviving entry carries an int32 index beside its f32 value)
    at a fixed ``compress_overhead_s`` per step (host/device top-k
    selection + the segment scatter-add).  Returns ``"topk:<frac>"``
    when the byte-time saving exceeds the overhead, else None.

    ``resident_cadence`` lifts the old single-device gate (ISSUE 20):
    a lone device has no all-reduce wire, so the EF rule used to be
    strictly a user opt-in for A/B runs — and the resident driver
    REFUSED it anyway (the PR 9 deviation).  With EF carried in the
    resident while-loop ring, a plan may propose residency AND the
    compressed update together: under ``resident_cadence >= 2`` the
    top-k select runs in-trace inside the one fused body (no
    ``compress_overhead_s`` host hop — only one extra ``(dim,)`` pass
    at ``hbm_gb_s``), so the single-device proposal costs what that
    pass costs and buys scale-out-ready EF state: the run trains the
    exact update rule its meshed or replica twin ships, with the wire
    already matched-loss-validated.  The proposal still requires the
    kept segment to hold at least one entry (``frac * dim >= 1``) and
    the in-trace pass to fit the same ``compress_overhead_s`` budget
    the meshed rule charges.

    Deliberately conservative: the compressed wire CHANGES the update
    rule (top-k + error feedback — matched final loss, not matched
    trajectory), so the planner proposes it only where the cost model
    says the wire genuinely dominates (or, resident, where it rides
    free); borderline cases keep the dense wire and its bitwise
    contracts."""
    cm = cost_model
    if int(dim) < 2:
        return None
    frac = float(cm.wire_compress_frac)
    if int(n_devices) <= 1:
        if int(resident_cadence) < 2 or frac * dim < 1.0:
            return None
        select_s = dim * 4.0 / (cm.hbm_gb_s * 1e9)
        if select_s > cm.compress_overhead_s:
            return None
        return f"topk:{frac:g}"
    dense_s = dim * 4.0 / (cm.allreduce_gb_s * 1e9)
    saved_s = dense_s * (1.0 - 2.0 * frac)
    if saved_s <= cm.compress_overhead_s:
        return None
    return f"topk:{frac:g}"


#: fraction of a replica worker's per-push compute wall the SERIALIZED
#: store work (one apply dispatch + the update wire) may consume at the
#: chosen fleet size before the store becomes the bottleneck —
#: ``choose_replicas`` keeps the store at most half busy so push
#: arrivals queue on compute, not on each other
REPLICA_STORE_HEADROOM = 0.5


def choose_replicas(n: int, d: int, itemsize: int = 4,
                    n_devices: int = 1,
                    mini_batch_fraction: float = 1.0,
                    cost_model: CostModel = DEFAULT_COST_MODEL,
                    cap: int = 8, store_shards: int = 1) -> int:
    """Replica-worker count W for the async bounded-staleness driver
    (``tpu_sgd/replica``), from the existing cost model.

    The async fleet's structural bottleneck is the STORE: every
    accepted push costs one serialized apply — a program dispatch
    (``dispatch_overhead_s``) plus the update-shaped wire both ways
    (pulled weights + pushed contribution, ``2 * d * 4`` bytes at
    ``allreduce_gb_s``) — while the workers' shard gradients run
    concurrently (each a two-pass read of its sampled rows,
    ``2 * (n/W) * frac * d * itemsize / hbm_gb_s``).  W workers
    generate one push per per-shard compute wall, so the store's busy
    fraction is ``W * store_s / compute_s(W)`` and grows as W² (more
    pushers, each pushing sooner).  W is the LARGEST count — capped by
    ``n_devices`` and ``cap`` — that keeps the store under
    :data:`REPLICA_STORE_HEADROOM` busy; 0 when even W=2 saturates it
    (tiny workloads stay synchronous — the same "smallest that pays"
    honesty as ``choose_residency``'s crossover).

    Like :data:`Plan.replicas`, this is SIZING advice, not a schedule
    decision: ``tau > 0`` changes the update rule (matched final loss,
    not matched trajectory), so the async switch itself is always the
    user's.

    ``store_shards``: the store's apply-pipeline count
    (:func:`choose_store_shards`; ``tpu_sgd/replica/shard.py``).  A
    sharded store splits the per-push COMBINE across S pipelines, so
    only the update wire scales down by S — the one whole-vector apply
    dispatch stays serialized (the updater is not per-coordinate
    separable; ADVICE.md "Shard the apply, not the contract").  The
    pre-shard model charged the full wire to every push, silently
    understating the fleet a sharded store can feed."""
    cm = cost_model
    store_s = (cm.dispatch_overhead_s
               + 2.0 * d * 4.0
               / (max(1, int(store_shards)) * cm.allreduce_gb_s * 1e9))
    best = 0
    # an empty range when fewer than 2 devices: a single device cannot
    # place a fleet, whatever the cost model says
    for w in range(2, min(int(n_devices), int(cap)) + 1):
        rows_local = max(1.0, float(n) / w)
        compute_s = (2.0 * rows_local * mini_batch_fraction * d
                     * itemsize / (cm.hbm_gb_s * 1e9))
        if w * store_s <= REPLICA_STORE_HEADROOM * compute_s:
            best = w
    return best


def choose_store_shards(n: int, d: int, itemsize: int = 4,
                        n_devices: int = 1,
                        workers: int = 2,
                        mini_batch_fraction: float = 1.0,
                        cost_model: CostModel = DEFAULT_COST_MODEL,
                        cap: int = 8) -> int:
    """Store-shard count S for the sharded parameter store
    (``tpu_sgd/replica/shard.py``): the largest S — clamped by the
    device count and ``cap`` — whose per-shard pipeline keeps
    :data:`REPLICA_STORE_HEADROOM` headroom under a ``workers``-strong
    fleet's push arrival rate, subject to DISPATCH DOMINANCE: each
    added pipeline replicates the fixed apply-dispatch tax
    (``dispatch_overhead_s``), so splitting only pays while the
    per-shard share of the update wire (``2 * d * 4 / S`` bytes at
    ``allreduce_gb_s``) still dominates one dispatch.  Small models
    return 1 (unsharded — the wire never dominated); wide models
    return the largest S the clamps allow.  Sizing advice with the
    same contract as :func:`choose_replicas`: the driver only shards
    when the user asks (``ReplicaDriver.set_store_shards``)."""
    cm = cost_model
    w = max(2, int(workers))
    transfer_s = 2.0 * d * 4.0 / (cm.allreduce_gb_s * 1e9)
    rows_local = max(1.0, float(n) / w)
    compute_s = (2.0 * rows_local * mini_batch_fraction * d
                 * itemsize / (cm.hbm_gb_s * 1e9))
    best = 1
    for s in range(2, min(int(n_devices), int(cap)) + 1):
        if transfer_s / s < cm.dispatch_overhead_s:
            break  # dispatch dominance: the (s-1)-way split already
            # shrank the wire below one dispatch tax
        if (w * (cm.dispatch_overhead_s + transfer_s / s)
                <= REPLICA_STORE_HEADROOM * compute_s):
            best = s
    return best


def choose_residency(k: int, checkpoint_every: int = 10,
                     preempt_latency_iters: Optional[int] = None,
                     cap: int = 64) -> int:
    """Cadence C (in supersteps) for the device-resident whole-run
    driver — :func:`choose_superstep` extended past the dispatch axis:
    K fixed how many iterations one PROGRAM advances; C fixes how many
    supersteps run between HOST callbacks once the loop itself lives on
    device (``optimize/resident_driver.py``).

    The choice rule, and the resident-vs-superstep crossover it
    records: residency only pays when a cadence window holds at least
    **2 supersteps** — at C=1 the resident loop would call back to the
    host exactly as often as the superstep driver dispatches, paying
    the io_callback round trip where the superstep driver pays the
    (comparable, ``dispatch_overhead_s``-calibrated) dispatch tax, for
    no structural win; BENCH_RESIDENT.json measures the counts.  So C
    is the LARGEST window that respects the two host-side bounds, and 0
    (keep the superstep driver) when that window is smaller than 2:

    * **checkpoint cadence** — the window may not exceed
      ``checkpoint_every`` iterations, or cadence saves (replayed
      inside the window callback) would trail their legacy iterations
      by a whole window;
    * **preemption latency** — stop signals are polled once per window,
      so the window may not exceed the preemption-latency budget
      (defaults to ``checkpoint_every``, the same grace-window
      reasoning as ADVICE.md's K <= checkpoint_every rule).

    ``cap`` bounds C itself (supersteps per window) as a backstop; the
    ring buffer stages ``C*K`` steps of history, and its ROW bound
    comes from the budget above — ``C*K`` never exceeds
    ``min(checkpoint_every, preempt_latency_iters)`` iterations, the
    same staging-vs-cadence reasoning as ``choose_superstep``'s cap."""
    K = max(1, int(k))
    if K < 2:
        return 0  # residency rides the fused executor; no K, no ring
    budget_iters = min(
        max(1, int(checkpoint_every)),
        max(1, int(preempt_latency_iters))
        if preempt_latency_iters is not None else max(
            1, int(checkpoint_every)),
    )
    c = min(int(cap), budget_iters // K)
    return int(c) if c >= 2 else 0


def choose_slab_capacity(n_tenants: int, d: int, itemsize: int = 4,
                         free_hbm: Optional[float] = None,
                         working_set: Optional[int] = None,
                         hot_frac: float = 0.1,
                         cost_model: CostModel = DEFAULT_COST_MODEL,
                         cap: int = 65536) -> int:
    """Slab capacity C (resident tenant rows) for the multi-tenant
    model store (``tpu_sgd/tenant``): the smallest power of two holding
    the HOT working set, clamped to what HBM can carry.

    The decision axes, in order:

    * **working set, not tenant count** — a Zipf-shaped tenant
      population serves most traffic from a small head, and every
      resident row costs HBM whether or not it is ever gathered, so C
      targets ``working_set`` (explicit, from the operator's traffic
      knowledge) or ``hot_frac * n_tenants`` (the default 10% head)
      rather than all ``n_tenants``.  Misses are not failures — the
      store re-admits from checkpoint at disk latency — but each one
      evicts a neighbor, so an undersized slab thrashes (the opt-in
      ``SlabThrashDetector`` watches the evict/admit ratio live).
    * **power-of-two rounding (up)** — the slab's capacity is a
      compiled-program shape root (``ops/bucketed.py``'s slab-program
      keys): every distinct capacity is a fresh compile of the gather,
      multi-model, and row-set programs, so quantizing keeps a fleet
      of stores on a handful of executables.
    * **HBM clamp** — ``C * (d + 1) * itemsize`` (rows + intercepts)
      must fit the measured free budget under the cost model's
      ``hbm_safety`` fraction (``free_hbm=None`` probes
      :func:`device_budget`), leaving the rest for serving batches and
      any co-resident training run.  ``cap`` backstops the search.

    Same contract as :func:`choose_replicas`: sizing ADVICE, not a
    schedule decision — the caller constructs the store with the
    returned capacity (or their own number) explicitly."""
    m = max(1, int(n_tenants))
    target = (max(1, int(working_set)) if working_set is not None
              else max(1, int(round(hot_frac * m))))
    target = min(target, m)
    c = 1
    while c < target:
        c *= 2
    if free_hbm is None:
        free_hbm, _ = device_budget(cost_model=cost_model)
    row_bytes = (int(d) + 1) * int(itemsize)
    budget = cost_model.hbm_safety * float(free_hbm)
    while c > 1 and c * row_bytes > budget:
        c //= 2
    return int(min(c, int(cap)))


def _fmt_gb(b: float) -> str:
    return f"{b / 1e9:.2f} GB"


def plan(
    n: int,
    d: int,
    *,
    itemsize: int = 4,
    gram_able: bool = False,
    sampling: str = "bernoulli",
    mini_batch_fraction: float = 1.0,
    num_iterations: int = 100,
    n_devices: int = 1,
    free_hbm: Optional[float] = None,
    host_resident_ok: bool = True,
    cost_model: CostModel = DEFAULT_COST_MODEL,
    force: Optional[str] = None,
    checkpoint_every: int = 10,
) -> Plan:
    """Pick an execution schedule for an ``(n, d)`` dense dataset.

    Pure decision function — probing (device memory, dtype, gradient
    class) belongs to the caller; :func:`plan_for` does it for an
    optimizer + arrays.  Arguments:

    * ``itemsize`` — bytes per element of the training matrix (2 for
      bf16, 4 for f32).
    * ``gram_able`` — the gradient is exactly least squares (fixed-size
      sufficient statistics exist) AND the data is dense.
    * ``sampling`` / ``mini_batch_fraction`` — the USER's sampling
      semantics; the planner never changes them (gram requires sliced
      windows or full batch — under bernoulli/indexed sampling it simply
      does not qualify).
    * ``n_devices`` — data-mesh size; rows shard across it.
      ``streamed_virtual_gram`` composes with the mesh (per-shard virtual
      statistics streamed to each device); ``partial_residency`` is
      single-device only and reduces to ``host_streamed`` on a mesh.
    * ``free_hbm`` — plannable device bytes; defaults to
      :func:`device_budget`.
    * ``host_resident_ok`` — False when the data is already a committed
      device array (streaming schedules are then meaningless).
    * ``force`` — schedule name to apply regardless; the planner still
      runs its estimates and WARNS when the forced choice is estimated to
      lose (e.g. gram with ``build_amortize_iters > num_iterations``).
    * ``checkpoint_every`` — the optimizer's checkpoint cadence in
      iterations; bounds the device-residency window
      (:func:`choose_residency`) so cadence saves and preemption
      latency stay within one checkpoint interval.

    Returns a :class:`Plan`; ``plan.estimates`` records every number the
    decision used.
    """
    if force is not None and force not in SCHEDULES:
        raise ValueError(
            f"unknown schedule {force!r}; choose one of {SCHEDULES}"
        )
    cm = cost_model
    if free_hbm is None:
        free_hbm, budget_source = device_budget(cost_model=cm)
    else:
        budget_source = "caller"
    n_local = max(1, math.ceil(n / max(1, n_devices)))
    frac = float(mini_batch_fraction)
    full_batch = frac >= 1.0
    data_bytes_local = n_local * d * itemsize + n_local * 4.0  # + y
    fits = data_bytes_local <= free_hbm
    window_sliced = full_batch or sampling == "sliced"
    gram_eligible = bool(gram_able) and window_sliced

    est = {
        "n": int(n), "d": int(d), "itemsize": int(itemsize),
        "n_devices": int(n_devices), "n_local": int(n_local),
        "data_bytes_local": data_bytes_local,
        "free_hbm": float(free_hbm), "budget_source": budget_source,
        "fits_resident": bool(fits),
        "gram_eligible": gram_eligible,
        "sampling": sampling, "mini_batch_fraction": frac,
        "num_iterations": int(num_iterations),
    }

    # per-iteration walls of the candidate schedules (seconds)
    window_rows = n_local if full_batch else max(1, round(frac * n_local))
    stock_iter_s = 2.0 * window_rows * d * itemsize / (cm.hbm_gb_s * 1e9)
    est["stock_iter_s"] = stock_iter_s

    def _gram_terms(B: int, aligned: bool):
        edge_bytes = 0.0 if aligned else 2.0 * B * d * itemsize
        prefix_bytes = 2.0 * (d * d + d) * 4.0
        it = (cm.gram_iter_overhead_s
              + (edge_bytes + prefix_bytes) / (cm.hbm_gb_s * 1e9))
        build = (cm.build_overhead_s
                 + n_local * d * itemsize / (cm.hbm_gb_s * 1e9)
                 + 2.0 * n_local * d * d / cm.mxu_f32_flops)
        return it, build

    chosen: Optional[Plan] = None

    # ---- resident regime -------------------------------------------------
    if fits:
        if gram_eligible:
            B = choose_block_rows(n_local, d, free_hbm - data_bytes_local)
            if B is not None:
                gram_iter_s, build_s = _gram_terms(B, aligned=False)
                saving = stock_iter_s - gram_iter_s
                amortize = (math.inf if saving <= 0
                            else build_s / saving)
                est.update(block_rows=B, gram_iter_s=gram_iter_s,
                           gram_build_s=build_s,
                           build_amortize_iters=amortize)
                if amortize <= num_iterations:
                    chosen = Plan(
                        "resident_gram",
                        f"data ({_fmt_gb(data_bytes_local)}/device) fits "
                        f"HBM ({_fmt_gb(free_hbm)} free); least-squares "
                        f"{'full-batch' if full_batch else 'sliced'} "
                        f"windows run from block-prefix statistics "
                        f"(B={B}, exact mode; build amortizes in "
                        f"~{amortize:.0f} of {num_iterations} iters)",
                        block_rows=B, estimates=est,
                    )
                elif force == "resident_gram":
                    warnings.warn(
                        "forced resident_gram is estimated a NET LOSS "
                        f"here: the statistics build (~{build_s:.2f}s) "
                        f"amortizes in ~{amortize:.0f} iterations but the "
                        f"run is only {num_iterations}",
                        RuntimeWarning, stacklevel=3,
                    )
        if chosen is None:
            why = (
                f"data ({_fmt_gb(data_bytes_local)}/device) fits HBM "
                f"({_fmt_gb(free_hbm)} free)"
            )
            if gram_eligible and "build_amortize_iters" in est:
                why += (
                    "; statistics build would amortize in "
                    f"~{est['build_amortize_iters']:.0f} iters > "
                    f"{num_iterations} run length, so stock wins"
                )
            elif gram_able and not window_sliced:
                why += (
                    f"; sufficient stats need sliced windows or full "
                    f"batch (sampling={sampling!r} honored)"
                )
            chosen = Plan("resident_stock", why, estimates=est)

    # ---- beyond-HBM regime ----------------------------------------------
    if chosen is None:
        feed = cm.host_feed_gb_s * 1e9
        streamed_iter_s = window_rows * d * itemsize / feed
        est["streamed_iter_s"] = streamed_iter_s
        if gram_eligible:
            B, batch_rows = choose_streamed_build(n_local, d, itemsize,
                                                  free_hbm)
            if B is not None:
                gram_iter_s, _ = _gram_terms(B, aligned=True)
                build_s = (cm.build_overhead_s
                           + n_local * d * itemsize / feed)
                saving = streamed_iter_s - gram_iter_s
                amortize = (math.inf if saving <= 0
                            else build_s / saving)
                est.update(block_rows=B, batch_rows=batch_rows,
                           gram_iter_s=gram_iter_s,
                           gram_build_s=build_s,
                           build_amortize_iters=amortize,
                           stack_bytes=_stack_bytes(n_local, B, d),
                           # double-buffered ingest: two chunks live
                           staging_bytes=2.0 * batch_rows
                           * (d * itemsize + 4.0))
                if amortize <= num_iterations:
                    chosen = Plan(
                        "streamed_virtual_gram",
                        f"data ({_fmt_gb(data_bytes_local)}) exceeds HBM "
                        f"({_fmt_gb(free_hbm)} free) but its statistics "
                        f"({_fmt_gb(est['stack_bytes'])}, B={B}) fit "
                        "beside the build chunk: one streaming build "
                        f"pass (~{build_s:.0f}s at {cm.host_feed_gb_s} "
                        "GB/s), then iterations touch no rows.  NOTE: "
                        "uses ALIGNED (block-floored) windows — a "
                        "sampling deviation (fine on shuffled rows, not "
                        "on sorted/grouped data); pass "
                        "schedule='host_streamed' to keep exact windows",
                        block_rows=B, batch_rows=batch_rows,
                        aligned=True, estimates=est,
                    )
                elif force == "streamed_virtual_gram":
                    warnings.warn(
                        "forced streamed_virtual_gram is estimated a NET "
                        f"LOSS here: the streaming build (~{build_s:.0f}s) "
                        f"amortizes in ~{amortize:.0f} iterations but the "
                        f"run is only {num_iterations}",
                        RuntimeWarning, stacklevel=3,
                    )
        if chosen is None and (sampling == "sliced" and not full_batch
                               and n_devices == 1):
            m = max(1, round(frac * n_local))
            R = int((free_hbm - 4.0 * n_local) // (d * itemsize))
            p_resident = min(
                1.0, max(0.0, (R - m + 1) / max(n_local - m + 1, 1))
            )
            est.update(resident_rows=max(0, R),
                       resident_window_p=p_resident)
            if R >= m and p_resident >= cm.min_resident_gain:
                chosen = Plan(
                    "partial_residency",
                    f"data ({_fmt_gb(data_bytes_local)}) exceeds HBM "
                    f"({_fmt_gb(free_hbm)} free); keeping the leading "
                    f"{R} rows resident makes ~{p_resident:.0%} of "
                    "sliced windows transfer-free",
                    resident_rows=R, estimates=est,
                )
        if chosen is None:
            # superstep fusion: single-device only (the meshed feed now
            # fuses too, but through per-superstep host staging the
            # planner does not yet model), budgeted against the free
            # HBM a streamed schedule leaves idle — a quarter of it
            # caps the double-buffered superchunk staging; the shared
            # full-batch feed stages nothing (one transfer, the scan
            # reuses it), so only the amortization target binds there
            K = 1
            if n_devices == 1:
                # the shared full-batch feed transfers ONCE and then
                # iterates at the device rate, so its dispatch-tax
                # amortization is judged against stock_iter_s, not the
                # per-iteration feed slope (which it never pays after
                # the first transfer); it also stages no superchunk
                K = choose_superstep(
                    window_rows, d, itemsize,
                    stock_iter_s if full_batch else streamed_iter_s,
                    math.inf if full_batch else free_hbm * 0.25,
                    cost_model=cm)
            est["superstep"] = K
            # device residency: the run loop itself moves on device
            # when the feed is device-resident-data (full batch) and a
            # cadence window holds >= 2 supersteps (choose_residency's
            # crossover rule) — host hops drop from one per superstep
            # to one per window, and dispatches to one per run
            Cres = 0
            if n_devices == 1 and full_batch and K > 1:
                # under residency K no longer buys dispatch savings
                # (the whole run is one dispatch regardless) — shrink
                # it into the ADVICE K <= checkpoint_every rule, halved
                # so the cadence window holds >= 2 supersteps; the
                # shrink only sticks if residency actually engages —
                # when choose_residency still says 0 (a tight
                # checkpoint cadence), the dispatch tax IS the cost
                # model again and the unshrunk amortizing K wins
                K_res = max(2, min(K, max(1, int(checkpoint_every) // 2)))
                Cres = choose_residency(K_res, checkpoint_every)
                if Cres:
                    K = K_res
                    est["superstep"] = K
            est["residency"] = Cres
            # compressed gradient wire: where a real multi-shard
            # all-reduce exists and its bytes dominate the compress
            # cost — or, single-device, where the EF select rides the
            # RESIDENT body in-trace (ISSUE 20 lifted the PR 9 mutual
            # exclusion, so a plan may propose residency and the
            # compressed update together).  Matched-loss, not
            # matched-trajectory either way, so the proposal is loud
            # in the reason string
            wc = choose_wire_compress(d, n_devices, cost_model=cm,
                                      resident_cadence=Cres)
            est["wire_compress"] = wc
            fused_note = (
                f"; K={K} fused steps per dispatch amortize the "
                f"~{cm.dispatch_overhead_s * 1e3:.1f} ms/iter host "
                "dispatch tax" if K > 1 else "")
            if Cres:
                fused_note += (
                    f"; device-resident run loop (cadence {Cres} "
                    "supersteps/host hop — one dispatch per run)")
            if wc and n_devices > 1:
                fused_note += (
                    f"; compressed gradient wire ({wc}: top-k + error "
                    "feedback — matched final loss, NOT a bitwise "
                    "trajectory; pass wire_compress=False to keep the "
                    "dense all-reduce)")
            elif wc:
                fused_note += (
                    f"; compressed gradient wire ({wc}) riding the "
                    "resident body — the EF top-k selects in-trace "
                    "inside the one while-loop dispatch (ISSUE 20), "
                    "matched final loss, NOT a bitwise trajectory; "
                    "pass wire_compress=False to keep the dense "
                    "update")
            chosen = Plan(
                "host_streamed",
                f"data ({_fmt_gb(data_bytes_local)}) exceeds HBM "
                f"({_fmt_gb(free_hbm)} free); host-resident with "
                "double-buffered per-iteration batches "
                f"(~{streamed_iter_s:.2f}s/iter at {cm.host_feed_gb_s} "
                f"GB/s feed){fused_note}",
                superstep=K, residency=Cres, wire_compress=wc,
                estimates=est,
            )

    # async replica sizing advice (tpu_sgd/replica; README "Async
    # replicas"), stamped on EVERY returned plan: not a schedule choice
    # (τ>0 changes the update rule, so going async is the user's call),
    # just what the cost model says a fleet could be if they make it
    replicas = choose_replicas(n, d, itemsize, n_devices,
                               mini_batch_fraction=frac, cost_model=cm)
    # two-pass sizing: the single-apply fleet estimate feeds the shard
    # choice, then the replica advice is re-derived against the sharded
    # store (the fix for the stale single-apply model)
    store_shards = choose_store_shards(
        n, d, itemsize, n_devices, workers=max(2, replicas),
        mini_batch_fraction=frac, cost_model=cm)
    if store_shards > 1:
        replicas = choose_replicas(n, d, itemsize, n_devices,
                                   mini_batch_fraction=frac,
                                   cost_model=cm,
                                   store_shards=store_shards)
    est["replicas"] = replicas
    est["store_shards"] = store_shards

    if not host_resident_ok and chosen.schedule in (
            "partial_residency", "host_streamed", "streamed_virtual_gram"):
        chosen = Plan(
            "resident_stock",
            "data is already device-committed; streaming schedules do "
            "not apply (" + chosen.reason + ")",
            estimates=est,
        )

    if force is not None and force != chosen.schedule:
        forced = _forced_plan(
            force, chosen, est, fits=fits, free_hbm=free_hbm,
            data_bytes_local=data_bytes_local,
            per_dev=f"/device × {n_devices}" if n_devices > 1 else "",
            stacklevel=4,
            aligned=force == "streamed_virtual_gram",
            resident_rows=est.get("resident_rows", 0),
        )
        if force == "partial_residency" and not forced.resident_rows:
            if fits:
                raise ValueError(
                    "partial_residency cannot be forced here: the data "
                    f"({_fmt_gb(data_bytes_local)}/device) already fits "
                    "HBM — run resident, or shrink free_hbm to test the "
                    "beyond-HBM ladder"
                )
            raise ValueError(
                "partial_residency cannot be forced here: it needs "
                "sliced sampling with mini_batch_fraction < 1 on a "
                "single device, and at least one window of rows must "
                f"fit the budget (sampling={sampling!r}, frac={frac}, "
                f"n_devices={n_devices})"
            )
        return dataclasses.replace(forced, replicas=replicas,
                                   store_shards=store_shards)
    return dataclasses.replace(chosen, replicas=replicas,
                               store_shards=store_shards)


def _forced_plan(force, chosen, est, *, fits, free_hbm, data_bytes_local,
                 per_dev="", stacklevel=3, **plan_fields):
    """The forced-schedule contract, shared by :func:`plan` and
    :func:`plan_quasi_newton`'s ``_force_wrap``: warn when the forced
    schedule has no feasible statistics block size or exceeds the probed
    budget, then construct the forced :class:`Plan` recording what the
    planner would have picked instead."""
    if (force in ("resident_gram", "streamed_virtual_gram")
            and est.get("block_rows") is None):
        warnings.warn(
            f"forced {force} has NO feasible block size at this "
            f"budget ({_fmt_gb(free_hbm)} free vs O(d²) statistics); "
            "the build will run at the default block size and may "
            "exhaust device memory",
            RuntimeWarning, stacklevel=stacklevel,
        )
    if force.startswith("resident_") and not fits:
        warnings.warn(
            f"forced {force} commits {_fmt_gb(data_bytes_local)}"
            f"{per_dev} to a device with only {_fmt_gb(free_hbm)} in "
            "the probed budget — it does not fit and will likely "
            "exhaust device memory",
            RuntimeWarning, stacklevel=stacklevel,
        )
    return Plan(
        force,
        f"forced by caller (planner would pick {chosen.schedule}: "
        + chosen.reason + ")",
        block_rows=est.get("block_rows"),
        batch_rows=est.get("batch_rows"),
        estimates=est, **plan_fields,
    )


#: schedules a quasi-Newton optimizer can be forced onto
QN_SCHEDULES = ("resident_stock", "resident_gram", "host_streamed",
                "streamed_virtual_gram")


def plan_quasi_newton(optimizer, X, y,
                      cost_model: Optional[CostModel] = None,
                      free_hbm: Optional[float] = None,
                      force: Optional[str] = None) -> Optional[Plan]:
    """Schedule decision for the quasi-Newton optimizers (LBFGS/OWL-QN):
    enable the sufficient-statistics substitution when the one-time build
    amortizes inside ``max_num_iterations``, and pick the beyond-HBM
    execution otherwise.

    Each quasi-Newton iteration is several FULL-batch passes over ``X``
    (cost+gradient at the current and accepted points, plus the batched
    line-search sweep — ~4 row reads), so the break-even comes much
    earlier than for mini-batch SGD.  The menu:

    * least squares, fits HBM: ``resident_gram`` when the build
      amortizes, else ``resident_stock``;
    * least squares, beyond HBM: ``streamed_virtual_gram`` — one
      streaming build pass, then every cost/sweep is an O(d²)
      statistics read (single device: prefix stacks, the ``n % B`` tail
      dropped; meshed: per-shard O(d²) totals carries, EXACT);
    * any other loss, beyond HBM: ``host_streamed`` — the chunked
      treeAggregate CostFun (``optimize/streamed_costfun.py``), the
      literal analogue of the reference's any-size-any-loss CostFun
      ([U] mllib/optimization/LBFGS.scala, SURVEY.md §2 #18).

    Meshed optimizers (1-D data mesh) divide the HBM budget by the
    shard count exactly as the GD planner does; the statistics builds
    run per shard and combine to replicated totals.  ``force`` accepts
    any of ``QN_SCHEDULES``."""
    import numpy as np

    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS, GramData
    from tpu_sgd.ops.sparse import is_sparse
    from tpu_sgd.optimize.lbfgs import LBFGS

    if (not isinstance(optimizer, LBFGS) or is_sparse(X)
            or isinstance(X, GramData)):
        return None
    if force is not None and force not in QN_SCHEDULES:
        raise ValueError(
            f"schedule {force!r} does not exist behind a quasi-Newton "
            f"optimizer; choose one of {QN_SCHEDULES}"
        )
    n_devices = 1
    if optimizer.mesh is not None:
        from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS

        mesh_shape = optimizer.mesh.shape
        if DATA_AXIS not in mesh_shape or mesh_shape.get(MODEL_AXIS, 1) > 1:
            return None  # model-sharded: leave the user's config alone
        n_devices = int(mesh_shape[DATA_AXIS])
    shape = np.shape(X)
    if len(shape) != 2 or shape[0] == 0:
        return None
    n, d = (int(shape[0]), int(shape[1]))
    dt = np.dtype(getattr(X, "dtype", np.float32))
    itemsize = dt.itemsize if np.issubdtype(dt, np.inexact) else 4
    cm = cost_model or DEFAULT_COST_MODEL
    if free_hbm is None:
        free_hbm, budget_source = device_budget(cost_model=cm)
    else:
        budget_source = "caller"
    iters = int(optimizer.max_num_iterations)
    gram_able = type(optimizer.gradient) is LeastSquaresGradient
    n_local = max(1, math.ceil(n / n_devices))
    data_bytes_local = n_local * d * itemsize + n_local * 4.0
    fits = data_bytes_local <= free_hbm
    est = {
        "n": n, "d": d, "itemsize": int(itemsize),
        "n_devices": int(n_devices), "n_local": int(n_local),
        "data_bytes_local": data_bytes_local,
        "free_hbm": float(free_hbm), "budget_source": budget_source,
        "fits_resident": bool(fits), "gram_able": bool(gram_able),
        "max_num_iterations": iters,
    }
    per_dev = f"/device × {n_devices}" if n_devices > 1 else ""

    def _force_wrap(chosen):
        if force is None or force == chosen.schedule:
            return chosen
        return _forced_plan(
            force, chosen, est, fits=fits, free_hbm=free_hbm,
            data_bytes_local=data_bytes_local, per_dev=per_dev,
            stacklevel=5,
        )

    # ---- non-least-squares losses ---------------------------------------
    if not gram_able:
        if force in ("resident_gram", "streamed_virtual_gram"):
            raise ValueError(
                f"schedule {force!r} cannot apply: no fixed-size "
                "sufficient statistics exist for "
                f"{type(optimizer.gradient).__name__} (least squares "
                "only); choose resident_stock or host_streamed"
            )
        if fits:
            chosen = Plan(
                "resident_stock",
                f"data ({_fmt_gb(data_bytes_local)}{per_dev}) fits; "
                "stock full-batch passes (no fixed-size statistics "
                f"exist for {type(optimizer.gradient).__name__})",
                estimates=est,
            )
        else:
            # chunk sized so two in-flight buffers use <= half the
            # budget (the policy function is the evaluator's own)
            from tpu_sgd.optimize.streamed_costfun import (
                default_stream_batch_rows,
            )

            # per-DEVICE budget: the evaluator shards each chunk
            # n_devices ways, so the global chunk scales with the mesh
            batch_rows = default_stream_batch_rows(
                d, itemsize, chunk_bytes=free_hbm * 0.25 * n_devices)
            est["batch_rows"] = batch_rows
            chosen = Plan(
                "host_streamed",
                f"data ({_fmt_gb(data_bytes_local)}{per_dev}) exceeds "
                f"HBM ({_fmt_gb(free_hbm)} free) and "
                f"{type(optimizer.gradient).__name__} has no fixed-size "
                "statistics: every full-batch cost/sweep streams the "
                "rows through the device in "
                f"{batch_rows}-row chunks (the chunked treeAggregate "
                "CostFun — feed-bound, ~3 dataset reads per iteration)",
                batch_rows=batch_rows, estimates=est,
            )
        return _force_wrap(chosen)

    # ---- least squares, beyond HBM --------------------------------------
    if not fits:
        B, batch_rows = choose_streamed_build(n_local, d, itemsize,
                                              free_hbm)
        if B is None and n_devices > 1:
            # the meshed build carries O(d²) totals, not prefix stacks —
            # feasible whenever one chunk fits beside the (d, d) carry
            rows = int((free_hbm - 3 * d * d * 4.0)
                       // max(1, 2 * (d * itemsize + 4)))
            if rows >= 1:
                B, batch_rows = min(DEFAULT_BLOCK_ROWS, rows), rows
        if B is not None:
            est.update(block_rows=B, batch_rows=batch_rows,
                       stack_bytes=(_stack_bytes(n_local, B, d)
                                    if n_devices == 1 else 3 * d * d * 4.0))
            tail_note = (
                f"exact totals; the n_local % {B} tail rows are dropped"
                if n_devices == 1 else
                "EXACT totals — the meshed build keeps every row"
            )
            chosen = Plan(
                "streamed_virtual_gram",
                f"data ({_fmt_gb(data_bytes_local)}{per_dev}) exceeds "
                f"HBM ({_fmt_gb(free_hbm)} free) but its statistics "
                f"({_fmt_gb(est['stack_bytes'])}, B={B}) fit beside the "
                "build chunk: one streaming build pass"
                f"{' per shard' if n_devices > 1 else ''}, then every "
                "full-batch cost/sweep is an O(d²) statistics read "
                f"({tail_note})",
                block_rows=B, batch_rows=batch_rows, estimates=est,
            )
        else:
            chosen = Plan(
                "resident_stock",
                f"data ({_fmt_gb(data_bytes_local)}{per_dev}) exceeds "
                f"HBM ({_fmt_gb(free_hbm)} free) and so does its O(d²) "
                "statistics stack; no schedule fits this device",
                estimates=est,
            )
        return _force_wrap(chosen)

    # ---- least squares, resident ----------------------------------------
    if n_devices == 1:
        B = choose_block_rows(n_local, d, free_hbm - data_bytes_local)
    else:
        # the meshed substitution carries O(d²) TOTALS per shard, not a
        # prefix stack (build_sharded_total_stats) — feasible whenever
        # the tiny carry fits the headroom
        from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS as _DEF_B

        carry_bytes = 3 * d * d * 4.0
        B = (min(_DEF_B, n_local)
             if carry_bytes <= free_hbm - data_bytes_local else None)
    chosen = None
    if B is not None:
        # ~4 full row reads per iteration vs O(d^2) stats matvecs (the
        # 25-trial sweep's (T,d)x(d,d) matmul reads G once per chunk)
        stock_iter_s = 4.0 * n_local * d * itemsize / (cm.hbm_gb_s * 1e9)
        gram_iter_s = (cm.gram_iter_overhead_s
                       + 8.0 * d * d * 4.0 / (cm.hbm_gb_s * 1e9))
        build_s = (cm.build_overhead_s
                   + n_local * d * itemsize / (cm.hbm_gb_s * 1e9)
                   + 2.0 * n_local * d * d / cm.mxu_f32_flops)
        saving = stock_iter_s - gram_iter_s
        amortize = math.inf if saving <= 0 else build_s / saving
        est.update(block_rows=B, stock_iter_s=stock_iter_s,
                   gram_iter_s=gram_iter_s, gram_build_s=build_s,
                   build_amortize_iters=amortize)
        if amortize <= iters:
            chosen = Plan(
                "resident_gram",
                f"quasi-Newton least squares on a resident "
                f"({_fmt_gb(data_bytes_local)}{per_dev}) dataset: "
                f"full-batch cost/sweep from statistics (B={B}; build "
                f"amortizes in ~{amortize:.0f} of {iters} iterations"
                + ("; per-shard totals combine over the mesh"
                   if n_devices > 1 else "") + ")",
                block_rows=B, estimates=est,
            )
        elif force == "resident_gram":
            warnings.warn(
                "forced resident_gram is estimated a NET LOSS here: the "
                f"statistics build (~{build_s:.2f}s) amortizes in "
                f"~{amortize:.0f} iterations but max_num_iterations is "
                f"{iters}",
                RuntimeWarning, stacklevel=3,
            )
    if chosen is None:
        why = (f"data ({_fmt_gb(data_bytes_local)}{per_dev}) fits; "
               "stock full-batch passes")
        if "build_amortize_iters" in est:
            why += (
                f" (statistics build would amortize in "
                f"~{est['build_amortize_iters']:.0f} iters > {iters})"
            )
        chosen = Plan("resident_stock", why, estimates=est)
    return _force_wrap(chosen)


def plan_for(optimizer, X, y, cost_model: Optional[CostModel] = None,
             force: Optional[str] = None) -> Optional[Plan]:
    """Probe ``(optimizer, X, y)`` and :func:`plan` for it.

    Returns None (no planning) when the input is sparse (BCOO trains
    resident by construction) or the optimizer is not a
    ``GradientDescent``.  The caller applies/logs the returned plan."""
    import numpy as np

    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.sparse import is_sparse
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    if not isinstance(optimizer, GradientDescent) or is_sparse(X):
        return None
    from tpu_sgd.ops.gram import GramData

    if isinstance(X, GramData):
        return None  # statistics-first input: the schedule is the input
    shape = np.shape(X)
    if len(shape) != 2 or shape[0] == 0:
        return None
    n, d = shape
    dt = np.dtype(getattr(X, "dtype", np.float32))
    itemsize = (dt.itemsize if np.issubdtype(dt, np.inexact)
                else 4)  # int/bool features coerce to f32 in optimize()
    cfg = optimizer.config
    mesh = optimizer.mesh
    n_devices = 1
    if mesh is not None:
        from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS

        if DATA_AXIS not in mesh.shape:
            return None  # model-only mesh: resident by construction
        if mesh.shape.get(MODEL_AXIS, 1) > 1:
            # 2-D (data x model) mesh: every streaming schedule needs a
            # 1-D data mesh, so there is nothing to plan — leave the
            # advanced-mesh configuration exactly as the user set it
            return None
        n_devices = int(mesh.shape[DATA_AXIS])  # rows shard over 'data'
    import jax

    host_resident_ok = not isinstance(X, jax.Array)
    return plan(
        int(n), int(d),
        itemsize=int(itemsize),
        gram_able=type(optimizer.gradient) is LeastSquaresGradient,
        sampling=cfg.sampling,
        mini_batch_fraction=cfg.mini_batch_fraction,
        num_iterations=cfg.num_iterations,
        n_devices=n_devices,
        host_resident_ok=host_resident_ok,
        cost_model=cost_model or DEFAULT_COST_MODEL,
        force=force,
        checkpoint_every=int(getattr(optimizer, "checkpoint_every", 10)),
    )
