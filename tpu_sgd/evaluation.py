"""Model evaluation metrics.

Reference parity: [U] mllib/evaluation/{RegressionMetrics,
BinaryClassificationMetrics,MulticlassMetrics}.scala — the metrics surface
the reference's users score every trained GLM with (SURVEY.md §2 #6-#8
models produce the score/label pairs these consume).

TPU-first design: the reference computes curve metrics with a combineByKey
over score bins and a driver-side scan; here the whole ROC/PR construction
is ONE jitted program — sort by score (descending), cumulative-sum the
positive/negative indicators, collapse tied scores to their group tail with
a reverse ``lax.cummin``, and integrate with a fused trapezoid.  Duplicate
curve points from ties contribute zero width, so the integral needs no
dynamic-shape dedup — static shapes end to end, MXU-free but fully fused.
The confusion matrix is a single on-device scatter-add.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Regression
# ---------------------------------------------------------------------------


@jax.jit
def _regression_stats(pred, obs):
    err = pred - obs
    n = pred.shape[0]
    mse = jnp.mean(err * err)
    mae = jnp.mean(jnp.abs(err))
    obs_mean = jnp.mean(obs)
    ss_tot = jnp.sum((obs - obs_mean) ** 2)
    ss_err = jnp.sum(err * err)
    # [U] RegressionMetrics.explainedVariance: sum((pred - mean(obs))^2)/n.
    explained = jnp.sum((pred - obs_mean) ** 2) / n
    r2 = 1.0 - ss_err / ss_tot
    return mse, mae, explained, r2


class RegressionMetrics:
    """Error metrics over ``(prediction, observation)`` arrays.

    Mirrors [U] RegressionMetrics: ``mean_squared_error``,
    ``root_mean_squared_error``, ``mean_absolute_error``, ``r2``,
    ``explained_variance`` — computed in one fused device pass.
    """

    def __init__(self, predictions, observations):
        pred = jnp.asarray(predictions, jnp.float32).reshape(-1)
        obs = jnp.asarray(observations, jnp.float32).reshape(-1)
        if pred.shape != obs.shape:
            raise ValueError(
                f"predictions {pred.shape} vs observations {obs.shape}"
            )
        if pred.shape[0] == 0:
            raise ValueError("empty input")
        mse, mae, explained, r2 = _regression_stats(pred, obs)
        self.mean_squared_error = float(mse)
        self.root_mean_squared_error = float(np.sqrt(self.mean_squared_error))
        self.mean_absolute_error = float(mae)
        self.explained_variance = float(explained)
        self.r2 = float(r2)


# ---------------------------------------------------------------------------
# Binary classification
# ---------------------------------------------------------------------------


@jax.jit
def _binary_curves(scores, labels):
    """Sorted-cumulative sufficient statistics for every threshold.

    Returns per-position (score, cumTP, cumFP) where positions inside a tied
    score group all carry the group-TAIL cumulative counts — the semantics of
    the reference's per-distinct-threshold grouping, with static shapes.
    """
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    s = scores[order]
    pos = labels[order]
    # integer count accumulation: float32 cumsum silently stops
    # incrementing at 2^24 examples of one class (counts are exact in
    # int32 to 2^31; downstream ratios cast to f32 after)
    pos_i = (pos > 0.5).astype(jnp.int32)
    cum_tp = jnp.cumsum(pos_i).astype(jnp.float32)
    cum_fp = jnp.cumsum(1 - pos_i).astype(jnp.float32)
    idx = jnp.arange(n)
    boundary = jnp.concatenate([s[1:] != s[:-1], jnp.array([True])])
    group_end = jax.lax.cummin(
        jnp.where(boundary, idx, n - 1), axis=0, reverse=True
    )
    return s, cum_tp[group_end], cum_fp[group_end], boundary


@jax.jit
def _trapezoid(x, y):
    return jnp.sum((x[1:] - x[:-1]) * (y[1:] + y[:-1]) * 0.5)


class BinaryClassificationMetrics:
    """ROC / PR metrics over ``(score, label)`` arrays with 0/1 labels.

    Mirrors [U] BinaryClassificationMetrics: ``area_under_roc``,
    ``area_under_pr``, ``roc()``, ``pr()``, ``thresholds()``,
    ``precision_by_threshold()``, ``recall_by_threshold()``,
    ``f_measure_by_threshold(beta)``; ``num_bins`` downsamples the curves
    (every ``ceil(groups/num_bins)``-th distinct threshold, group tails kept)
    the way the reference's binning trades resolution for size.
    """

    def __init__(self, scores, labels, num_bins: int = 0):
        scores = jnp.asarray(scores, jnp.float32).reshape(-1)
        labels = jnp.asarray(labels, jnp.float32).reshape(-1)
        if scores.shape != labels.shape:
            raise ValueError(f"scores {scores.shape} vs labels {labels.shape}")
        if scores.shape[0] == 0:
            raise ValueError("empty input")
        if num_bins < 0:
            raise ValueError(f"num_bins must be >= 0, got {num_bins}")
        lv = np.asarray(labels)
        bad = (lv != 0.0) & (lv != 1.0)
        if bad.any():
            # LIBSVM files commonly carry -1/+1: cum_fp would count each
            # negative as 2 and num_pos as pos-neg, making every curve
            # and AUC silently wrong
            raise ValueError(
                "labels must be 0/1; found "
                f"{np.unique(lv[bad])[:5]} (map -1/+1 labels first, "
                "e.g. y = (y > 0).astype('float32'))"
            )
        s, cum_tp, cum_fp, boundary = _binary_curves(scores, labels)
        self._num_pos = float(cum_tp[-1])
        self._num_neg = float(cum_fp[-1])
        if self._num_pos == 0 or self._num_neg == 0:
            raise ValueError(
                "labels must contain both classes "
                f"(pos={self._num_pos}, neg={self._num_neg})"
            )
        # AUCs integrate the full per-position curve on device: tied
        # positions duplicate their group-tail point, adding zero area.
        tpr = cum_tp / self._num_pos
        fpr = cum_fp / self._num_neg
        prec = cum_tp / jnp.maximum(cum_tp + cum_fp, 1.0)
        zero = jnp.zeros((1,), jnp.float32)
        one = jnp.ones((1,), jnp.float32)
        self.area_under_roc = float(
            _trapezoid(
                # graftlint: disable=shape-trap -- one-shot metrics construction: one compile per dataset size, not a hot path
                jnp.concatenate([zero, fpr]), jnp.concatenate([zero, tpr])
            )
        )
        # The reference anchors PR at (0, precision of the top group).
        self.area_under_pr = float(
            _trapezoid(
                # graftlint: disable=shape-trap -- one-shot metrics construction: one compile per dataset size, not a hot path
                jnp.concatenate([zero, tpr]),
                # graftlint: disable=shape-trap -- one-shot metrics construction: one compile per dataset size, not a hot path
                jnp.concatenate([prec[:1], prec]),
            )
        )
        # Curve getters work on the distinct-threshold (group-tail) points,
        # materialized host-side once.
        b = np.asarray(boundary)
        self._thresholds = np.asarray(s)[b]
        self._tp = np.asarray(cum_tp)[b]
        self._fp = np.asarray(cum_fp)[b]
        if num_bins > 0 and self._thresholds.size > num_bins:
            stride = int(np.ceil(self._thresholds.size / num_bins))
            keep = np.zeros(self._thresholds.size, bool)
            keep[stride - 1 :: stride] = True
            keep[-1] = True  # always keep the all-predicted-positive tail
            self._thresholds = self._thresholds[keep]
            self._tp = self._tp[keep]
            self._fp = self._fp[keep]

    def thresholds(self) -> np.ndarray:
        return self._thresholds.copy()

    def roc(self) -> np.ndarray:
        """(FPR, TPR) points with the reference's (0,0) and (1,1) anchors."""
        fpr = self._fp / self._num_neg
        tpr = self._tp / self._num_pos
        pts = np.stack([fpr, tpr], axis=1)
        return np.concatenate([[[0.0, 0.0]], pts, [[1.0, 1.0]]])

    def pr(self) -> np.ndarray:
        """(recall, precision) points anchored at (0, first precision)."""
        recall = self._tp / self._num_pos
        precision = self._tp / np.maximum(self._tp + self._fp, 1.0)
        pts = np.stack([recall, precision], axis=1)
        return np.concatenate([[[0.0, pts[0, 1]]], pts])

    def precision_by_threshold(self) -> np.ndarray:
        p = self._tp / np.maximum(self._tp + self._fp, 1.0)
        return np.stack([self._thresholds, p], axis=1)

    def recall_by_threshold(self) -> np.ndarray:
        return np.stack([self._thresholds, self._tp / self._num_pos], axis=1)

    def f_measure_by_threshold(self, beta: float = 1.0) -> np.ndarray:
        p = self._tp / np.maximum(self._tp + self._fp, 1.0)
        r = self._tp / self._num_pos
        b2 = beta * beta
        denom = np.maximum(b2 * p + r, 1e-38)
        f = (1 + b2) * p * r / denom
        return np.stack([self._thresholds, f], axis=1)


# ---------------------------------------------------------------------------
# Multiclass
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2,))
def _confusion(pred, obs, k):
    flat = obs.astype(jnp.int32) * k + pred.astype(jnp.int32)
    # int32 cells: float32 scatter-adds stop counting at 2^24 per cell
    return (
        jnp.zeros((k * k,), jnp.int32)
        .at[flat]
        .add(1, mode="drop")
        .reshape(k, k)
        .astype(jnp.float32)
    )


class MulticlassMetrics:
    """Confusion-matrix metrics over ``(prediction, label)`` arrays.

    Mirrors [U] MulticlassMetrics: ``confusion_matrix`` (rows = true label,
    columns = prediction, like the reference), ``accuracy``,
    per-label ``precision/recall/f_measure``, and the label-frequency
    ``weighted_*`` aggregates.
    """

    def __init__(self, predictions, labels, num_classes: int = 0):
        pred = np.asarray(predictions).reshape(-1)
        obs = np.asarray(labels).reshape(-1)
        if pred.shape != obs.shape:
            raise ValueError(f"predictions {pred.shape} vs labels {obs.shape}")
        if pred.shape[0] == 0:
            raise ValueError("empty input")
        k = int(num_classes) if num_classes > 0 else int(
            max(pred.max(), obs.max())
        ) + 1
        bad = ((pred < 0) | (pred >= k) | (obs < 0) | (obs >= k)
               | (pred != np.floor(pred)) | (obs != np.floor(obs)))
        if bad.any():
            # Silent scatter-drop would deflate accuracy while _n still
            # counts the sample; the reference includes every observed
            # label, so out-of-range input is a caller error here.
            raise ValueError(
                f"labels/predictions must be integers in [0, {k}); found "
                f"{np.unique(np.concatenate([pred[bad], obs[bad]]))[:5]}"
            )
        self.num_classes = k
        self.confusion_matrix = np.asarray(
            _confusion(jnp.asarray(pred), jnp.asarray(obs), k)
        )
        self._n = float(pred.shape[0])

    @property
    def labels(self) -> np.ndarray:
        return np.arange(self.num_classes, dtype=np.float64)

    @property
    def accuracy(self) -> float:
        return float(np.trace(self.confusion_matrix) / self._n)

    def precision(self, label) -> float:
        i = int(label)
        col = self.confusion_matrix[:, i].sum()
        return float(self.confusion_matrix[i, i] / col) if col else 0.0

    def recall(self, label) -> float:
        i = int(label)
        row = self.confusion_matrix[i, :].sum()
        return float(self.confusion_matrix[i, i] / row) if row else 0.0

    def f_measure(self, label, beta: float = 1.0) -> float:
        p, r = self.precision(label), self.recall(label)
        b2 = beta * beta
        return (1 + b2) * p * r / (b2 * p + r) if (p + r) else 0.0

    def _weighted(self, per_label) -> float:
        w = self.confusion_matrix.sum(axis=1) / self._n
        return float(sum(w[i] * per_label(i) for i in range(self.num_classes)))

    @property
    def weighted_precision(self) -> float:
        return self._weighted(self.precision)

    @property
    def weighted_recall(self) -> float:
        return self._weighted(self.recall)

    def weighted_f_measure(self, beta: float = 1.0) -> float:
        return self._weighted(lambda i: self.f_measure(i, beta))
