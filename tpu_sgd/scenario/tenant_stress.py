"""Multi-tenant serving stress scenario (ISSUE 18, ROADMAP item 4).

The tenant-plane sibling of ``scenario/harness.py``: one run drives a
:class:`~tpu_sgd.tenant.TenantServer` the way production would —

1. **Zipf traffic over thousands of tenants** — request tenant ids draw
   from a Zipf-shaped popularity curve, so a small head of hot tenants
   dominates while a long cold tail forces admission-on-miss; the slab
   is sized (``plan.choose_slab_capacity`` reasoning) to hold the head,
   NOT the population.
2. **A continuous retraining trickle** — a background thread publishes
   fresh weights for hot tenants the whole run (``tenant.swap`` hot
   reloads landing under live traffic, the arXiv 1505.04956 async-
   update pattern at per-tenant granularity).
3. **Chaos phases** — a slab-EVICTION storm (a rotating sweep of cold
   tenants forced resident, churning the LRU well past capacity) and a
   RELOAD storm (rapid-fire publishes to the hottest tenants) run
   concurrently with the traffic's storm phase.
4. **The SLO gate** — same contract as the flagship scenario: the one
   JSONL trace feeds ``obs.report --slo`` and its exit code is ours.
   Gated: zero dropped / zero transport errors (the loadgen's
   conservation ledger), answered volume, the retrain trickle actually
   reached serving (``tenant.swap``), the eviction storm actually
   churned (``tenant.evict``), the opt-in ``SlabThrashDetector``
   tripped a typed alert, tenant batches traced, and a loose
   interactive p99 (2-core CI walls are weather; BENCH_SERVE.json
   carries the tight numbers).

Deterministic by construction: traffic schedule, Zipf draws, trickle
and chaos orders all derive from ``seed``.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

import numpy as np

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — the chaos/trickle threads share only a stop Event and
#: their own tally dicts, read after join() (a happens-before edge).
GRAFTLINT_LOCKS: dict = {}

P99_BOUND_S = {"smoke": 2.0, "full": 1.0}


def build_tenant_slos(mode: str = "smoke",
                      violate: Optional[str] = None) -> dict:
    """The tenant scenario's declarative SLO document (``obs.report``
    format); ``violate`` breaks one named SLO so CI can prove the gate
    fails a bad run (the harness's own convention)."""
    slos = [
        {"name": "tenant-interactive-p99", "metric": "lane_p99_s",
         "lane": "interactive", "max": P99_BOUND_S[mode]},
        {"name": "zero-dropped", "metric": "counter",
         "counter": "scenario.dropped", "max": 0},
        {"name": "zero-transport-errors", "metric": "counter",
         "counter": "scenario.errors", "max": 0},
        {"name": "answered-volume", "metric": "counter",
         "counter": "scenario.answered", "min": 50},
        {"name": "tenant-batches-traced", "metric": "span_count",
         "span": "tenant.batch", "min": 1},
        # the retraining trickle really reached serving: hot reloads of
        # RESIDENT rows landed under traffic
        {"name": "retrain-trickle-served", "metric": "counter",
         "counter": "tenant.swap", "min": 5},
        # the eviction storm really churned the LRU past capacity
        {"name": "eviction-storm-churned", "metric": "counter",
         "counter": "tenant.evict", "min": 10},
        # ...and the opt-in detector turned the churn into a typed alert
        {"name": "alert-slab-thrash", "metric": "alert_count",
         "rule": "slab-thrash", "min": 1},
    ]
    if violate is not None:
        matched = [s for s in slos if s["name"] == violate]
        if not matched:
            raise ValueError(
                f"--violate {violate!r}: no such SLO "
                f"(have {[s['name'] for s in slos]})")
        s = matched[0]
        if "max" in s:
            s["max"] = -1.0
        else:
            s["min"] = 10 ** 9
    return {"slos": slos}


def _zipf_tenants(rng, n_tenants: int, size: int, a: float = 1.2):
    """``size`` tenant ids drawn Zipf(a)-shaped over ``[0, n_tenants)``
    via an explicit normalized pmf — bounded support by construction
    (``rng.zipf`` is unbounded), deterministic in the generator."""
    ranks = np.arange(1, n_tenants + 1, dtype=np.float64)
    p = ranks ** -a
    p /= p.sum()
    return rng.choice(n_tenants, size=size, p=p)


def run_tenant_scenario(
    seed: int = 0,
    *,
    smoke: bool = True,
    out_dir: Optional[str] = None,
    violate: Optional[str] = None,
    verbose: bool = True,
) -> int:
    """Run the multi-tenant stress scenario; returns the SLO gate's
    exit code (the ``obs.report`` contract — 0 pass, 1 violation)."""
    from tpu_sgd import obs
    from tpu_sgd.obs import report as obs_report
    from tpu_sgd.obs.detect import SlabThrashDetector, default_detectors
    from tpu_sgd.scenario.loadgen import (OpenLoopLoadGen, Phase,
                                          TrafficSpec)
    from tpu_sgd.tenant import TenantModelStore, TenantServer
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import JsonLinesEventLog

    mode = "smoke" if smoke else "full"
    slo_doc = build_tenant_slos(mode, violate=violate)
    # -- scale knobs -------------------------------------------------------
    d = 16 if smoke else 32
    n_tenants = 300 if smoke else 4000
    capacity = 64 if smoke else 256      # holds the Zipf head only
    phases = ([Phase("warm", 0.6, 200), Phase("storm", 1.5, 800),
               Phase("cool", 0.6, 200)] if smoke else
              [Phase("warm", 2.0, 500), Phase("storm", 5.0, 3000),
               Phase("cool", 2.0, 500)])

    def say(msg: str):
        if verbose:
            print(f"[tenant-scenario seed={seed} mode={mode}] {msg}",
                  flush=True)

    owned_tmp = None
    if out_dir is None:
        owned_tmp = tempfile.TemporaryDirectory()
        out_dir = owned_tmp.name
    os.makedirs(out_dir, exist_ok=True)
    trace = os.path.join(out_dir, "tenant_trace.jsonl")
    if os.path.exists(trace):
        os.truncate(trace, 0)

    event_log = JsonLinesEventLog(trace)
    # the default live plane PLUS the opt-in slab detector — min_admits
    # lowered to the eviction storm's realistic per-window admission
    # rate (each admission pays a checkpoint restore from disk)
    obs.enable(event_log, detect=True, window_s=0.25,
               detectors=default_detectors()
               + [SlabThrashDetector(min_admits=8)])
    try:
        rng0 = np.random.default_rng(seed)
        store_dir = os.path.join(out_dir, "tenants")
        store = TenantModelStore(store_dir, capacity=capacity, d=d,
                                 keep=2)
        # every tenant gets an initial published model (its durable
        # checkpoint — cold tenants restore from here on admission)
        base = rng0.normal(size=(n_tenants, d)).astype(np.float32)
        for t in range(n_tenants):
            store.publish(t, base[t], intercept=0.01 * (t % 7))
        say(f"published {n_tenants} tenants under {store_dir}")

        srv = TenantServer(store, max_batch=32, max_latency_s=0.004,
                           max_queue=256, event_log=event_log)

        # pre-drawn request schedule: Zipf tenant per request, features
        # from a small pool (the generator thread never pays assembly)
        pool = rng0.normal(size=(256, d)).astype(np.float32)
        zipf_ids = _zipf_tenants(rng0, n_tenants, 8192)
        hot = np.unique(zipf_ids[:capacity * 4])[:max(8, capacity // 4)]
        cold_base = n_tenants - max(2 * capacity, 16)

        # warm the slab with the Zipf head and the compiled programs
        # with every bucket shape, so the measured run never pays XLA
        # compile on the serving path (a real endpoint warms at deploy)
        store.slots_for(np.unique(zipf_ids[:512])[:capacity])
        for b in srv.engine.buckets:
            ids = np.resize(np.unique(zipf_ids[:64])[:8], b)
            srv.engine.predict_batch(ids, pool[:1].repeat(b, 0))
            srv.engine.predict_batch(np.full(b, int(ids[0])),
                                     pool[:1].repeat(b, 0))
        compiles_warm = srv.engine.compile_count

        # -- background trickle + chaos ------------------------------------
        stop = threading.Event()
        tallies = {"trickle": 0, "evict_sweep": 0, "reload_storm": 0}

        def trickle():
            # the continuous per-tenant retraining trickle: fresh
            # weights for Zipf-hot tenants land all run long
            rng = np.random.default_rng(seed + 11)
            while not stop.is_set():
                tid = int(hot[rng.integers(len(hot))])
                store.publish(tid, rng.normal(size=d).astype(np.float32))
                tallies["trickle"] += 1
                time.sleep(0.01)

        def eviction_storm():
            # chaos: force a rotating window of COLD tenants resident,
            # churning the LRU well past capacity (the SlabThrash
            # detector's feed)
            rng = np.random.default_rng(seed + 23)
            i = 0
            while not stop.is_set():
                tid = cold_base + (i % max(2 * capacity, 16))
                store.load(int(tid))
                tallies["evict_sweep"] += 1
                i += 1
                if i % 8 == 0:
                    time.sleep(0.001 + 0.004 * rng.random())

        def reload_storm():
            # chaos: rapid-fire publishes to the HOTTEST tenants — a
            # reload storm under live traffic (hot swaps, no evictions)
            rng = np.random.default_rng(seed + 31)
            while not stop.is_set():
                for tid in hot[:8]:
                    if stop.is_set():
                        break
                    store.publish(int(tid),
                                  rng.normal(size=d).astype(np.float32))
                    tallies["reload_storm"] += 1
                time.sleep(0.005)

        # -- traffic -------------------------------------------------------
        mix = [
            TrafficSpec("tenant-interactive", "interactive", 0.70,
                        deadline_s=0.5),
            TrafficSpec("tenant-batch", "batch", 0.30),
        ]

        def route(spec: TrafficSpec, i: int, rng):
            tid = int(zipf_ids[i % len(zipf_ids)])
            row = pool[i % len(pool)]
            if spec.name == "tenant-interactive":
                return srv.submit(tid, row, lane=spec.lane,
                                  deadline_s=spec.deadline_s)
            return srv.submit(tid, row, lane=spec.lane)

        gen = OpenLoopLoadGen(route, mix, phases, seed=seed + 1)
        threads = [threading.Thread(target=f, name=f"tenant-{f.__name__}",
                                    daemon=True)
                   for f in (trickle, eviction_storm, reload_storm)]

        t_run = time.perf_counter()
        with srv:
            for t in threads:
                t.start()
            load_report = gen.run()
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
                assert not t.is_alive(), f"{t.name} hung"
            healthz = srv.healthz()
        wall_s = time.perf_counter() - t_run

        # -- client ledger -> trace counters (the SLO inputs) --------------
        totals = load_report["totals"]
        obs.inc("scenario.answered", totals["answered"])
        obs.inc("scenario.rejected",
                totals["rejected"] + totals["displaced"])
        obs.inc("scenario.errors", totals["errored"])
        obs.inc("scenario.dropped", totals["dropped"])

        ledger = healthz["slab"]
        say(f"load: {json.dumps(totals)} over {wall_s:.1f}s; "
            f"slab: {json.dumps(ledger)}; chaos: {json.dumps(tallies)}")
        say(f"engine: {json.dumps(healthz['engine'])} "
            f"(compiles warm={compiles_warm})")

        # structural invariants, asserted here so a failure names the
        # subsystem, not just the SLO
        assert totals["submitted"] == (
            totals["answered"] + totals["rejected"] + totals["displaced"]
            + totals["errored"] + totals["dropped"]), (
            f"ledger does not conserve: {totals}")
        assert ledger["evicted"] >= 10, (
            f"eviction storm never churned the slab: {ledger}")
        assert ledger["swapped"] >= 5, (
            f"retrain trickle/reload storm never hot-swapped: {ledger}")
        # the shape-trap contract under chaos: serving paid ZERO
        # compiles after warm-up, across evictions, reloads, and every
        # tenant mix the storm produced
        assert srv.engine.compile_count == compiles_warm, (
            f"serving compiled under chaos: {compiles_warm} -> "
            f"{srv.engine.compile_count}")

        summary = {"seed": seed, "mode": mode, "wall_s": wall_s,
                   "n_tenants": n_tenants, "capacity": capacity,
                   "totals": totals, "lanes": load_report["lanes"],
                   "phases": load_report["phases"], "slab": ledger,
                   "chaos": tallies, "healthz": healthz}
        with open(os.path.join(out_dir, "tenant_summary.json"),
                  "w") as f:
            json.dump(summary, f, indent=2, default=str)
    finally:
        obs.disable()
        event_log.close()

    slo_path = os.path.join(out_dir, "tenant_slo.json")
    with open(slo_path, "w") as f:
        json.dump(slo_doc, f, indent=2)
    chrome = os.path.join(out_dir, "tenant_trace.chrome.json")
    rc = obs_report.main([trace, "--slo", slo_path, "--chrome", chrome])
    if owned_tmp is not None:
        owned_tmp.cleanup()
    return rc
