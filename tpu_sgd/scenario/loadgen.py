"""Open-loop load generator for the production scenario harness.

Open-loop on purpose: arrivals follow the offered schedule regardless
of how the endpoint is coping — a closed loop (submit, wait, submit)
self-throttles exactly when the system saturates, which hides the
overload behavior this harness exists to measure.  The generator is the
same credit-paced design as ``bench_serving.py``: credits accrue at the
phase's offered rate, a bounded burst cap sheds arrivals the GENERATOR
fell behind on (a GIL stall must not compound into a thundering herd
that measures the generator, not the server), and a small sleep between
bursts keeps the flush thread scheduled.

Every submission is accounted for exactly once — the zero-silent-drops
ledger the scenario SLO gate audits:

* ``answered``   — the future resolved with a prediction;
* ``rejected``   — a typed :class:`~tpu_sgd.serve.Overloaded` raised at
  submit (queue_full / deadline / shed);
* ``displaced``  — admitted, then evicted for a higher-priority arrival
  (the future resolved with a typed ``Overloaded``);
* ``errored``    — the future resolved with any OTHER exception;
* ``dropped``    — the future never resolved within the drain timeout:
  the one bucket that must stay at ZERO.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from tpu_sgd.serve.batcher import Overloaded

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the tally
#: ledger is mutated by the generator thread (submit-side outcomes) AND
#: by future done-callbacks running on the serving flush threads
#: (completion-side outcomes) — every touch holds the lock.
GRAFTLINT_LOCKS = {
    "OpenLoopLoadGen": {
        "_tallies": "_lock",
    },
}


class TrafficSpec(NamedTuple):
    """One traffic class of the mix: a name for the ledger, the serving
    lane it rides, its share of arrivals, and the per-request deadline
    budget (None = no deadline).  The harness maps ``name`` to a
    concrete (server, row kind) in its submit callable."""

    name: str
    lane: str
    weight: float
    deadline_s: Optional[float] = None


class Phase(NamedTuple):
    """One segment of the open-loop schedule (e.g. warm / burst / cool)."""

    name: str
    duration_s: float
    offered_rps: float


class OpenLoopLoadGen:
    """See module docstring.  ``submit(spec, i, rng)`` is the harness's
    routing callable: it must return a ``concurrent.futures.Future`` or
    raise (``Overloaded`` = typed rejection, anything else = error)."""

    def __init__(
        self,
        submit: Callable,
        mix: Sequence[TrafficSpec],
        phases: Sequence[Phase],
        *,
        seed: int = 0,
        tick_s: float = 0.002,
        drain_timeout_s: float = 60.0,
    ):
        if not mix:
            raise ValueError("traffic mix must not be empty")
        total = sum(s.weight for s in mix)
        if total <= 0:
            raise ValueError("traffic mix weights must sum positive")
        self.submit = submit
        self.mix = list(mix)
        self._weights = [s.weight / total for s in mix]
        self.phases = list(phases)
        self.seed = int(seed)
        self.tick_s = float(tick_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._tallies: Dict[str, Dict[str, object]] = {}
        self._futures: List = []

    # -- ledger ------------------------------------------------------------
    def _tally_locked(self, name: str) -> dict:
        t = self._tallies.get(name)
        if t is None:
            t = self._tallies[name] = {
                "submitted": 0, "answered": 0, "rejected": 0,
                "displaced": 0, "errored": 0, "dropped": 0,
                "latencies": [],
            }
        return t

    def _on_done(self, fut, name: str, t_submit: float) -> None:
        # runs on the serving flush thread (or inline when already done)
        err = fut.exception()
        with self._lock:
            t = self._tally_locked(name)
            if err is None:
                t["answered"] += 1
                t["latencies"].append(time.perf_counter() - t_submit)
            elif isinstance(err, Overloaded):
                t["displaced"] += 1  # admitted, then typed-evicted
            else:
                t["errored"] += 1

    # -- the open loop -----------------------------------------------------
    def run(self) -> dict:
        """Drive every phase, drain, and return the report (see
        :meth:`report`)."""
        rng = np.random.default_rng(self.seed)
        n_specs = len(self.mix)
        per_phase: Dict[str, dict] = {}
        for phase in self.phases:
            stats = {"offered": 0, "rejected": 0}
            max_credit = max(phase.offered_rps * 0.05, 1.0)
            t_start = time.perf_counter()
            deadline = t_start + phase.duration_s
            t_last = t_start
            credit = 0.0
            i = 0
            while True:
                time.sleep(self.tick_s)
                now = time.perf_counter()
                if now >= deadline:
                    break
                credit = min(
                    credit + (now - t_last) * phase.offered_rps, max_credit)
                t_last = now
                while credit >= 1.0:
                    credit -= 1.0
                    spec = self.mix[int(rng.choice(n_specs,
                                                   p=self._weights))]
                    stats["offered"] += 1
                    t_sub = time.perf_counter()
                    try:
                        fut = self.submit(spec, i, rng)
                    except Overloaded:
                        stats["rejected"] += 1
                        with self._lock:
                            self._tally_locked(spec.name)["submitted"] += 1
                            self._tally_locked(spec.name)["rejected"] += 1
                    except Exception:
                        with self._lock:
                            self._tally_locked(spec.name)["submitted"] += 1
                            self._tally_locked(spec.name)["errored"] += 1
                    else:
                        with self._lock:
                            self._tally_locked(spec.name)["submitted"] += 1
                            self._futures.append(fut)
                        fut.add_done_callback(
                            lambda f, n=spec.name, t=t_sub:
                            self._on_done(f, n, t))
                    i += 1
            per_phase[phase.name] = stats
        self._drain()
        rep = self.report()
        rep["phases"] = per_phase
        return rep

    def _drain(self) -> None:
        """Wait for every outstanding future to resolve; whatever does
        not inside the budget is a DROP (the invariant violation this
        harness exists to catch, not an error to hide)."""
        with self._lock:
            futures = list(self._futures)
        deadline = time.monotonic() + self.drain_timeout_s
        for fut in futures:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                fut.exception(timeout=remaining)  # outcome via _on_done
            except (TimeoutError, _FutureTimeout):
                break
            except Exception:
                pass  # CancelledError etc.: the callback tallied it
        # done-callbacks fire after result-waiters wake; give the flush
        # threads a moment to finish writing the ledger.  Keyed on
        # futures actually DONE (not on submitted-minus-rejected): a
        # genuinely dropped future must cost the drain timeout above,
        # not another full settle window here
        t_wait = time.monotonic() + 5.0
        while time.monotonic() < t_wait:
            done = sum(1 for f in futures if f.done())
            with self._lock:
                settled = sum(
                    t["answered"] + t["displaced"] + t["errored"]
                    for t in self._tallies.values())
            if settled >= done:
                break
            time.sleep(0.005)

    # -- results -----------------------------------------------------------
    def report(self) -> dict:
        """Per-traffic-class ledger + per-lane rollup.  ``dropped`` is
        computed by conservation: submitted minus every accounted
        outcome — a future that simply never resolved."""
        from tpu_sgd.serve.metrics import nearest_rank

        with self._lock:
            tallies = {k: dict(v) for k, v in self._tallies.items()}
        by_lane: Dict[str, dict] = {}
        classes = {}
        for spec in self.mix:
            t = tallies.get(spec.name)
            if t is None:
                continue
            t["dropped"] = (t["submitted"] - t["answered"] - t["rejected"]
                            - t["displaced"] - t["errored"])
            lats = sorted(t.pop("latencies"))
            t["p50_s"] = nearest_rank(lats, 50)
            t["p99_s"] = nearest_rank(lats, 99)
            classes[spec.name] = t
            lane = by_lane.setdefault(
                spec.lane, {"submitted": 0, "answered": 0, "rejected": 0,
                            "displaced": 0, "errored": 0, "dropped": 0})
            for k in lane:
                lane[k] += t[k]
        totals = {k: sum(lane[k] for lane in by_lane.values())
                  for k in ("submitted", "answered", "rejected",
                            "displaced", "errored", "dropped")}
        return {"classes": classes, "lanes": by_lane, "totals": totals}
