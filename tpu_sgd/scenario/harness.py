"""The flagship "heavy traffic" production scenario (ROADMAP item 1).

One run exercises the whole circulatory system at once:

1. **Continuous retraining** — a :class:`~tpu_sgd.replica.ReplicaDriver`
   fleet (bounded staleness, compressed top-k pushes, ONE standby
   store under the HA supervisor — ``tpu_sgd/replica/ha.py``) trains
   round after round on a DRIFTING stream (each round regenerates
   labels from drifted true weights), checkpointing on a cadence
   through one ``CheckpointManager``.  During one round a worker is
   KILLED by an armed ``replica.push`` failpoint and rejoins under the
   driver's seeded rejoin policy; during a LATER round the PRIMARY
   STORE is killed mid-round and the supervisor promotes the standby
   under live traffic — the SLO gate requires >= 1 failover, a bounded
   ``replica.failover`` span, the failover detector's typed alert, and
   (as ever) zero dropped requests.  The store-kill round is ALSO the
   corruption round (ISSUE 15): ``corrupt_prob`` damages delta-log
   records on the replication hop the whole round, so the standby
   being promoted is one whose replica stream healed through its
   consume-site checksums — gated by the ``integrity-*`` SLOs
   (corruption detected, zero unhealed, integrity alert fired).
2. **Live serving under admission control** — three endpoints serve
   while the fleet retrains underneath them: a hot-reloading dense
   endpoint (interactive + shadow lanes, per-request deadlines), a
   hot-reloading sparse-BCOO endpoint (batch lane), and a static
   multinomial endpoint (batch lane).  The registry-backed servers
   auto-reload each fresh checkpoint.
3. **An overload burst** — the open-loop schedule includes a burst
   phase offered well above serving capacity, so shedding, deadline
   rejection, and displacement actually fire (a scenario that never
   saturates proves nothing about overload).
4. **The SLO gate** — the run's one JSONL trace (listener events,
   spans, counters) feeds ``python -m tpu_sgd.obs.report --slo``; the
   report's exit code is the harness exit code.  The gate asserts:
   per-lane p99 bounds, a bounded interactive-lane shed fraction,
   served-weight staleness (the reload/save join), ZERO dropped
   requests (every submission answered or typed-rejected — audited by
   the loadgen's conservation ledger), >= 2 hot reloads, and the
   worker kill/rejoin.

Deterministic by construction: the arrival schedule, traffic mix, data
drift, and fault schedule all derive from ``seed``.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

import numpy as np

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — the harness's only cross-thread state is the retrain
#: result list, appended once by the retrain thread and read only
#: after ``join()`` (a happens-before edge, no lock needed).
GRAFTLINT_LOCKS: dict = {}

#: declared SLO bounds, by mode.  The p99 bound is deliberately loose
#: for ``smoke`` (a 2-core CI host runs XLA compiles and replica
#: training under the serving GIL — wall clocks there are weather);
#: the bench (BENCH_SERVE.json) carries the tight quiet-host numbers.
P99_BOUND_S = {"smoke": 1.5, "full": 1.0}
#: the interactive lane may shed under the deliberate burst, but must
#: stay MOSTLY served.  The shed fraction is a COUNT ratio but its
#: denominator is serving capacity, which on a timeshared CI box is
#: the same weather the p99 bound ducks: with the whole tier-1 suite
#: loading both cores (and ISSUE 13's longer kill round retraining
#: under the serving GIL), the burst legitimately sheds past 0.5
#: while shadow/batch still absorb ~100% — so ``smoke`` gets
#: headroom and the ISSUE 12 production bound of 0.5 stays on the
#: full-size run.
INTERACTIVE_SHED_MAX = {"smoke": 0.7, "full": 0.5}
STALENESS_MAX_S = 60.0


def build_slos(mode: str = "smoke", violate: Optional[str] = None) -> dict:
    """The scenario's declarative SLO document (``obs.report`` format).

    ``violate`` deliberately breaks one named SLO (an impossible bound)
    so CI can assert the gate actually FAILS a bad run — a gate only
    ever seen passing is a gate nobody has tested."""
    slos = [
        {"name": "interactive-p99", "metric": "lane_p99_s",
         "lane": "interactive", "max": P99_BOUND_S[mode]},
        {"name": "serve-sheds-bounded", "metric": "lane_shed_fraction",
         "lane": "interactive", "max": INTERACTIVE_SHED_MAX[mode]},
        {"name": "zero-dropped", "metric": "counter",
         "counter": "scenario.dropped", "max": 0},
        {"name": "zero-transport-errors", "metric": "counter",
         "counter": "scenario.errors", "max": 0},
        {"name": "answered-volume", "metric": "counter",
         "counter": "scenario.answered", "min": 50},
        {"name": "hot-reloads", "metric": "counter",
         "counter": "scenario.reloads", "min": 2},
        {"name": "worker-rejoined", "metric": "counter",
         "counter": "scenario.rejoins", "min": 1},
        {"name": "fresh-weights", "metric": "staleness_s",
         "max": STALENESS_MAX_S},
        {"name": "serve-batches-traced", "metric": "span_count",
         "span": "serve.batch", "min": 1},
        # ISSUE 13: the live detectors really detected — the burst
        # phase must trip the shed-rate rule and the mid-round worker
        # kill the replica-straggler rule, both as typed obs_alert
        # records on this run's one trace
        {"name": "alert-shed-rate", "metric": "alert_count",
         "rule": "shed-rate", "min": 1},
        {"name": "alert-straggler", "metric": "alert_count",
         "rule": "replica-straggler", "min": 1},
        # ISSUE 14: the store-kill round really failed over (the
        # promotion span is the downtime surface — its bound is wall
        # clock, so it gets the same CI-weather headroom as the p99),
        # and the failover detector emitted its typed alert
        {"name": "store-failover", "metric": "span_count",
         "span": "replica.failover", "min": 1},
        {"name": "failover-downtime", "metric": "span_max_s",
         "span": "replica.failover",
         "max": (30.0 if mode == "smoke" else 10.0)},
        {"name": "alert-failover", "metric": "alert_count",
         "rule": "failover", "min": 1},
        # ISSUE 15: the store-kill round runs under active delta-log
        # corruption — the standby's consume-site checksums must have
        # DETECTED frames (corruption really injected), every one must
        # have healed (the unhealed counter stays zero — the retrain
        # thread's own success is the ground truth), and the integrity
        # detector must have turned the frames into a typed alert
        {"name": "integrity-corruption-detected", "metric": "counter",
         "counter": "integrity.corrupt", "min": 1},
        {"name": "integrity-zero-unhealed", "metric": "counter",
         "counter": "integrity.unhealed", "max": 0},
        {"name": "alert-integrity", "metric": "alert_count",
         "rule": "integrity", "min": 1},
    ]
    if violate is not None:
        matched = [s for s in slos if s["name"] == violate]
        if not matched:
            raise ValueError(
                f"--violate {violate!r}: no such SLO "
                f"(have {[s['name'] for s in slos]})")
        s = matched[0]
        # an impossible bound in whichever direction the SLO points
        if "max" in s:
            s["max"] = -1.0
        else:
            s["min"] = 10 ** 9
    return {"slos": slos}


def _drift_data(seed: int, round_index: int, n: int, d: int):
    """Round ``round_index`` of the drifting stream: labels regenerate
    from true weights that rotate a little every round — the live
    retraining actually has something to chase."""
    rng = np.random.default_rng(seed)
    w_base = rng.normal(size=d).astype(np.float32)
    w_drift = rng.normal(size=d).astype(np.float32)
    theta = 0.15 * round_index
    w_true = (np.cos(theta) * w_base + np.sin(theta) * w_drift).astype(
        np.float32)
    rng_r = np.random.default_rng((seed << 8) + round_index)
    X = rng_r.normal(size=(n, d)).astype(np.float32)
    y = (X @ w_true + 0.01 * rng_r.normal(size=n)).astype(np.float32)
    return X, y


def run_scenario(
    seed: int = 0,
    *,
    smoke: bool = True,
    out_dir: Optional[str] = None,
    violate: Optional[str] = None,
    verbose: bool = True,
) -> int:
    """Run the full scenario; returns the SLO gate's exit code (0 = all
    SLOs PASS, 1 = violation, 2 = usage error — the ``obs.report``
    contract).  ``out_dir`` keeps the trace/SLO/Chrome artifacts (a
    temp dir is used and discarded otherwise)."""
    from tpu_sgd import obs
    from tpu_sgd.models import (LinearRegressionModel,
                                MultinomialLogisticRegressionModel)
    from tpu_sgd.obs import report as obs_report
    from tpu_sgd.reliability import (RetryPolicy, corrupt_prob, fail_nth,
                                     inject_faults)
    from tpu_sgd.reliability.failpoints import triggers as fp_triggers
    from tpu_sgd.replica import ReplicaDriver
    from tpu_sgd.scenario.loadgen import (OpenLoopLoadGen, Phase,
                                          TrafficSpec)
    from tpu_sgd.serve import ModelRegistry, Server
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import JsonLinesEventLog

    mode = "smoke" if smoke else "full"
    # a typo'd --violate must fail BEFORE the run, not after paying it
    slo_doc = build_slos(mode, violate=violate)
    # -- scale knobs -------------------------------------------------------
    d = 16
    n_rows = 512
    workers = 3
    tau = 2
    wire = "topk:0.25"
    iters_per_round = 20 if smoke else 40
    rounds = 3 if smoke else 4          # round 0 seeds, 1.. run live
    ckpt_every = 5
    kill_round = 1        # a WORKER dies and rejoins in this round
    store_kill_round = 2  # the PRIMARY STORE dies in this round
    phases = ([Phase("warm", 0.8, 250), Phase("burst", 1.5, 4000),
               Phase("cool", 0.8, 250)] if smoke else
              [Phase("warm", 2.0, 400), Phase("burst", 4.0, 6000),
               Phase("cool", 2.0, 400)])

    def say(msg: str):
        if verbose:
            print(f"[scenario seed={seed} mode={mode}] {msg}", flush=True)

    owned_tmp = None
    if out_dir is None:
        owned_tmp = tempfile.TemporaryDirectory()
        out_dir = owned_tmp.name
    os.makedirs(out_dir, exist_ok=True)
    trace = os.path.join(out_dir, "scenario_trace.jsonl")
    if os.path.exists(trace):
        os.truncate(trace, 0)  # a rerun must not concatenate traces
    flight_path = os.path.join(out_dir, "flightrec.jsonl")
    if os.path.exists(flight_path):
        os.remove(flight_path)  # a stale dump must not pass this run's check
    ckpt_dir = os.path.join(out_dir, "ckpt")

    from tpu_sgd.obs.detect import StragglerDetector, default_detectors

    event_log = JsonLinesEventLog(trace)
    # ONE stream: listener events + spans + counters, with the ISSUE 13
    # live plane armed — 0.1s windows, the default detector set with
    # ONE tuning: the straggler threshold drops to 5 fleet steps.  The
    # rule is cumulative over fleet progress (load-invariant), and at
    # tau=2 x 3 workers the SSP progress bound caps a LIVE worker's lag
    # at ~(workers-1)*tau = 4 peer steps — 5 is the smallest threshold
    # only a dead worker can reach, which keeps detection inside the
    # 0.5s rejoin window even when ambient load (a full CI suite on 2
    # cores) slows the fleet to a crawl.  Flight recorder teed over the
    # same sink, dumping on every alert transition.
    detectors = ([d for d in default_detectors()
                  if d.rule != "replica-straggler"]
                 + [StragglerDetector(min_fleet_steps=5)])
    obs.enable(event_log, detect=True, window_s=0.1,
               detectors=detectors, flightrec=flight_path)
    try:
        manager = CheckpointManager(ckpt_dir, keep=64)

        # rounds resume from the shared checkpoint directory, so budgets
        # are CUMULATIVE; the kill round (and everything after, to keep
        # the budgets monotone) gets extra runway — the rejoin races the
        # surviving workers' remaining work, and a round that ends
        # before the seeded backoff comes due would never rejoin.  The
        # bonus is sized for the ISSUE 13 straggler detector: the
        # victim stays dead for the full 0.5s rejoin backoff, so the
        # survivors need enough budget to keep stepping PAST it (the
        # cumulative rule needs 5 fleet steps during the dead period;
        # the rejoin needs the round still running when the backoff
        # expires — ~200 versions covers a quiet host's rate)
        kill_bonus = 200 if smoke else 240

        def _budget(round_index: int) -> int:
            return (iters_per_round * (round_index + 1)
                    + (kill_bonus if round_index >= kill_round else 0))

        def make_driver(round_index: int) -> ReplicaDriver:
            return (ReplicaDriver()
                    .set_num_iterations(_budget(round_index))
                    .set_step_size(0.1).set_mini_batch_fraction(1.0)
                    .set_convergence_tol(0.0).set_reg_param(0.01)
                    .set_seed(seed + 7).set_workers(workers)
                    .set_staleness(tau).set_wire_compress(wire)
                    # ONE standby: every round runs the HA store (ISSUE
                    # 14) — rounds resume across epochs through the
                    # shared checkpoint directory's (epoch, version)
                    # ordering; the store-kill round promotes it live
                    .set_standbys(1)
                    .set_checkpoint(manager, every=ckpt_every)
                    # jitter=0: the killed worker's dead period is a
                    # deterministic 0.5s EVERY run, not a lucky draw —
                    # the straggler-alert SLO gates on the fleet
                    # accumulating its 5 steps inside that window
                    .set_rejoin(RetryPolicy(max_attempts=5,
                                            base_backoff_s=0.5,
                                            jitter=0.0,
                                            seed=seed + 43)))

        # -- round 0: seed the first servable versions ---------------------
        w0 = np.zeros(d, np.float32)
        make_driver(0).optimize_with_history(
            _drift_data(seed, 0, n_rows, d), w0)
        assert manager.versions(), "round 0 wrote no checkpoints"
        say(f"round 0 trained: versions {manager.versions()}")

        # -- serving tier --------------------------------------------------
        registry = ModelRegistry(
            manager, lambda w, b: LinearRegressionModel(w, b))
        rng0 = np.random.default_rng(seed)
        live = Server(registry=registry, max_batch=32, max_latency_s=0.004,
                      max_queue=64, event_log=event_log,
                      reload_interval_s=0.05)
        sparse_srv = Server(registry=registry, max_batch=32,
                            max_latency_s=0.01, max_queue=64,
                            event_log=event_log, reload_interval_s=0.05)
        n_classes = 4
        multi_model = MultinomialLogisticRegressionModel(
            rng0.normal(size=(n_classes - 1) * d).astype(np.float32), 0.0,
            num_classes=n_classes, num_features=d)
        multi_srv = Server(multi_model, max_batch=32, max_latency_s=0.01,
                           max_queue=64, event_log=event_log)

        # request pools (pre-built so the generator thread never pays
        # row assembly on the submit path)
        dense_rows = rng0.normal(size=(256, d)).astype(np.float32)
        from jax.experimental.sparse import BCOO
        import jax.numpy as jnp

        sparse_rows = []
        for i in range(64):
            row = np.where(rng0.random(d) < 0.25,
                           rng0.normal(size=d), 0.0).astype(np.float32)
            row[0] = 1.0  # never all-zero: keep nse stable-ish
            sparse_rows.append(BCOO.fromdense(jnp.asarray(row)))

        # warm the dense bucket programs so the measured run never pays
        # XLA compile on the serving path (a real endpoint warms at
        # deploy); the sparse/multinomial kernels warm on first use in
        # the tolerant batch lane
        model0 = registry.model()
        for b in live.engine.buckets:
            live.engine.predict_batch(model0, dense_rows[:1].repeat(b, 0))

        # -- the retraining loop (background) ------------------------------
        retrain_result: dict = {}

        def retrain():
            try:
                rejoins = 0
                failovers = 0
                for r in range(1, rounds):
                    drv = make_driver(r)
                    data = _drift_data(seed, r, n_rows, d)
                    if r == kill_round:
                        # one-shot kill mid-round: the nth push of this
                        # round dies, the worker deregisters, and the
                        # driver rejoins it with seeded backoff.  The
                        # standby drains this whole round's delta log,
                        # so the log wire runs under corrupt_prob here
                        # too (ISSUE 15) — every damaged record is
                        # detected by the consume-site checksum and
                        # healed by re-reading the intact retained copy
                        with inject_faults({
                                "replica.push": fail_nth(
                                    iters_per_round // 2),
                                "replica.log.record": corrupt_prob(
                                    0.05, seed=seed + 87)}):
                            drv.optimize_with_history(data, w0)
                            corruptions = fp_triggers(
                                "replica.log.record")
                        retrain_result["corruptions_healed"] = \
                            retrain_result.get("corruptions_healed",
                                               0) + corruptions
                        members = drv.last_membership_snapshot
                        rejoins += sum(max(0, m["joins"] - 1)
                                       for m in members.values())
                    elif r == store_kill_round:
                        # the PRIMARY STORE dies a few versions into
                        # this round's fresh work (the listener fires
                        # per applied version, so the kill lands at a
                        # deterministic version offset regardless of
                        # host load) and the supervisor promotes the
                        # standby under live serving traffic.  The SAME
                        # round is the CORRUPTION round (ISSUE 15):
                        # corrupt_prob silently damages delta-log
                        # records on the replication hop, the standby's
                        # consume-site checksum detects each one and
                        # heals by re-reading the intact retained
                        # record — so the store being promoted under
                        # traffic is one whose replica stream was under
                        # active corruption the whole time (gated by
                        # the integrity-* SLOs)
                        start_v = manager.latest_version() or 0

                        class _KillStoreAt:
                            def __init__(self):
                                self.done = False

                            def on_run_start(self, c): ...

                            def on_run_end(self, ev): ...

                            def on_iteration(self, ev):
                                if (not self.done
                                        and ev.iteration >= start_v + 8):
                                    self.done = True
                                    drv.kill_primary()

                        drv.set_listener(_KillStoreAt())
                        with inject_faults({
                                "replica.log.record": corrupt_prob(
                                    0.35, seed=seed + 88)}):
                            drv.optimize_with_history(data, w0)
                            corruptions = fp_triggers(
                                "replica.log.record")
                        failovers += drv.last_failover_snapshot[
                            "failovers"]
                        retrain_result["corruptions_healed"] = \
                            retrain_result.get("corruptions_healed",
                                               0) + corruptions
                    else:
                        drv.optimize_with_history(data, w0)
                    # the reload CADENCE: the auto-reload scan catches
                    # mid-round checkpoints under traffic; this explicit
                    # end-of-round reload guarantees every round's final
                    # version reaches serving even when the load phase
                    # ends before the round does
                    live.reload()
                    say(f"round {r} retrained to version "
                        f"{manager.latest_version()}, serving "
                        f"version {registry.current_version}")
                retrain_result["rejoins"] = rejoins
                retrain_result["failovers"] = failovers
            except BaseException as e:  # surfaced after join
                retrain_result["error"] = e

        # -- traffic -------------------------------------------------------
        mix = [
            TrafficSpec("dense-interactive", "interactive", 0.60,
                        deadline_s=0.25),
            TrafficSpec("dense-shadow", "shadow", 0.15),
            TrafficSpec("sparse-batch", "batch", 0.15),
            TrafficSpec("multinomial-batch", "batch", 0.10),
        ]

        def route(spec: TrafficSpec, i: int, rng):
            if spec.name == "dense-interactive":
                return live.submit(dense_rows[i % len(dense_rows)],
                                   lane=spec.lane,
                                   deadline_s=spec.deadline_s)
            if spec.name == "dense-shadow":
                return live.submit(dense_rows[(i * 7) % len(dense_rows)],
                                   lane=spec.lane)
            if spec.name == "sparse-batch":
                return sparse_srv.submit(sparse_rows[i % len(sparse_rows)],
                                         lane=spec.lane)
            return multi_srv.submit(dense_rows[(i * 3) % len(dense_rows)],
                                    lane=spec.lane)

        gen = OpenLoopLoadGen(route, mix, phases, seed=seed + 1)

        t_run = time.perf_counter()
        retrain_thread = threading.Thread(target=retrain,
                                          name="scenario-retrain",
                                          daemon=True)
        with live, sparse_srv, multi_srv:
            retrain_thread.start()
            load_report = gen.run()
            retrain_thread.join(timeout=600.0)
            assert not retrain_thread.is_alive(), "retraining hung"
            healthz = live.healthz()
        wall_s = time.perf_counter() - t_run

        if "error" in retrain_result:
            raise AssertionError(
                "retraining failed under live traffic"
            ) from retrain_result["error"]

        # -- client-side ledger -> trace counters (the SLO inputs) ---------
        totals = load_report["totals"]
        hot_reloads = registry.reload_count - 1  # first swap = initial load
        rejoins = retrain_result.get("rejoins", 0)
        failovers = retrain_result.get("failovers", 0)
        obs.inc("scenario.answered", totals["answered"])
        obs.inc("scenario.rejected",
                totals["rejected"] + totals["displaced"])
        obs.inc("scenario.errors", totals["errored"])
        obs.inc("scenario.dropped", totals["dropped"])
        obs.inc("scenario.reloads", hot_reloads)
        obs.inc("scenario.rejoins", rejoins)
        obs.inc("scenario.failovers", failovers)

        say(f"load: {json.dumps(totals)} over {wall_s:.1f}s; "
            f"hot_reloads={hot_reloads} rejoins={rejoins} "
            f"failovers={failovers} breaker={healthz.get('breaker')}")
        say(f"lanes: {json.dumps(load_report['lanes'])}")

        # structural invariants the SLO file also gates on — asserted
        # here too so a failure names the subsystem, not just the SLO
        assert totals["submitted"] == (
            totals["answered"] + totals["rejected"] + totals["displaced"]
            + totals["errored"] + totals["dropped"]), (
            f"ledger does not conserve: {totals}")
        assert hot_reloads >= 2, (
            f"serving saw only {hot_reloads} hot reload(s); the live "
            "retraining never reached the endpoint")

        summary = {"seed": seed, "mode": mode, "wall_s": wall_s,
                   "totals": totals, "lanes": load_report["lanes"],
                   "classes": load_report["classes"],
                   "phases": load_report["phases"],
                   "hot_reloads": hot_reloads, "rejoins": rejoins,
                   "failovers": failovers, "healthz": healthz}
        with open(os.path.join(out_dir, "scenario_summary.json"),
                  "w") as f:
            json.dump(summary, f, indent=2, default=str)
    finally:
        # flushes the trailing detector window, then the final counter
        # snapshot (the alert SLOs need both evaluated before teardown)
        obs.disable()
        event_log.close()

    # -- flight record: dumped on the detector trips, schema-valid ---------
    assert os.path.exists(flight_path), (
        "the detectors tripped (or must have) but no flight record "
        f"was dumped at {flight_path}")
    frec = JsonLinesEventLog.read(flight_path)
    assert frec and frec[0]["kind"] == "flightrec_meta", (
        "flight record missing its meta header")
    assert {"obs_window"} & {r["kind"] for r in frec}, (
        "flight record carries no window snapshots")

    # -- the SLO gate: obs.report's exit code IS ours ----------------------
    slo_path = os.path.join(out_dir, "scenario_slo.json")
    with open(slo_path, "w") as f:
        json.dump(slo_doc, f, indent=2)
    chrome = os.path.join(out_dir, "scenario_trace.chrome.json")
    rc = obs_report.main([trace, "--slo", slo_path, "--chrome", chrome])
    if owned_tmp is not None:
        owned_tmp.cleanup()
    return rc
