"""tpu_sgd.scenario: the production scenario harness (ROADMAP item 1).

The subsystems — async replica training (``tpu_sgd/replica``), hot
reload (``serve.ModelRegistry``), the admission-controlled micro-batcher
(``serve.batcher``), chaos failpoints, and the SLO-verdict trace report
(``obs.report``) — run here *as one system*: an open-loop load
generator (:mod:`tpu_sgd.scenario.loadgen`) drives mixed
dense/sparse/multinomial traffic across priority lanes, including a
deliberate overload burst, while a replica fleet retrains on a drifting
stream (compressed pushes, a worker killed and rejoined mid-run) and
the serving tier hot-reloads the fleet's checkpoints on a cadence
(:mod:`tpu_sgd.scenario.harness`).

The whole run is gated by declarative SLOs evaluated over the run's own
trace by ``python -m tpu_sgd.obs.report`` — per-lane p99, bounded
interactive-lane shed fraction, served-weight staleness, zero dropped
requests, the structural reload/rejoin counts — and the report's exit
code IS the harness exit code (``scripts/scenario_live.py``).
"""

from __future__ import annotations

from tpu_sgd.scenario.harness import build_slos, run_scenario
from tpu_sgd.scenario.loadgen import OpenLoopLoadGen, Phase, TrafficSpec
from tpu_sgd.scenario.tenant_stress import (build_tenant_slos,
                                            run_tenant_scenario)

__all__ = [
    "OpenLoopLoadGen",
    "Phase",
    "TrafficSpec",
    "build_slos",
    "build_tenant_slos",
    "run_scenario",
    "run_tenant_scenario",
]
