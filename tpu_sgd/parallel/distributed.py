"""Multi-host distributed runtime helpers.

Reference parity: SURVEY.md §5.8 — the reference's communication backend is
Netty RPC + TorrentBroadcast + shuffle-based treeAggregate across executor
JVMs.  The TPU-native backend is the JAX distributed runtime: within a slice
``lax.psum`` compiles to hardware ICI all-reduce; across hosts/slices the
SAME ``psum`` over a multi-host mesh rides DCN after
``jax.distributed.initialize`` — no code change in the optimizer, only a
bigger mesh.  These helpers wrap that bring-up so a cluster launch is:

    initialize_distributed(coordinator, num_processes, process_id)
    mesh = global_data_mesh()
    LinearRegressionWithSGD.train((X_local, y_local), mesh=mesh)

Single-process usage needs none of this (jax.devices() already sees the
local chips).
"""

from __future__ import annotations

from typing import Optional

import jax

from tpu_sgd.parallel.mesh import data_mesh, make_mesh


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Bring up the JAX distributed runtime (idempotent).

    On TPU pods the arguments are auto-detected from the environment; on
    other platforms pass them explicitly.  The DCN transport underneath is
    the functional replacement for the reference's Netty RPC fabric.
    """
    if jax.distributed.is_initialized():
        # TRUE no-op, not error-message matching: once any computation
        # has run, a second initialize() raises a message ("must be
        # called before any JAX calls...") that matching would re-raise
        # — breaking the idempotent contract exactly when a second
        # entry point defensively re-initializes mid-job
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)


def global_data_mesh():
    """1-D data mesh over every device in the job (all hosts).

    ``jax.devices()`` is global after ``initialize_distributed``; collectives
    over this mesh use ICI within each slice and DCN across slices.
    """
    return data_mesh(devices=jax.devices())


def global_mesh_2d(n_model: int = 1):
    """(data, model) mesh over every device in the job.

    Raises when ``n_model`` does not divide the device count — silently
    idling remainder devices would hide lost parallelism.
    """
    devs = jax.devices()
    if len(devs) % n_model:
        raise ValueError(
            f"n_model={n_model} does not divide the {len(devs)}-device job; "
            "choose a divisor or idle devices explicitly via make_mesh"
        )
    return make_mesh(n_data=len(devs) // n_model, n_model=n_model, devices=devs)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()
