"""Data-parallel SGD: shard the example axis, psum the gradient sums.

This is the TPU-native replacement for the reference's entire L1-L2 stack
(SURVEY.md §3.5): where Spark runs ``sample().treeAggregate(depth=2)`` through
shuffle files, task serialization and a driver hop every iteration, here the
batch lives sharded across cores, the weights live replicated, and
``lax.psum`` combines per-shard ``(grad_sum, loss_sum, count)`` in hardware
over ICI.  Broadcast of updated weights is free: the all-reduced update is
applied identically on every core (deterministic replication replaces
TorrentBroadcast, SURVEY.md §5.8).

Uneven example counts are handled by zero-padding each shard and carrying a
``valid`` row mask folded into the mini-batch mask — the analogue of Spark's
arbitrary-size partitions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.updaters import Updater
from tpu_sgd.parallel.mesh import DATA_AXIS, shard_map_fn, superchunk_specs

Array = jax.Array


def pad_to_multiple(
    X: np.ndarray, y: np.ndarray, n_shards: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Zero-pad rows so ``n`` divides evenly; returns (X, y, valid mask)."""
    n = X.shape[0]
    rem = (-n) % n_shards
    valid = np.ones((n + rem,), dtype=bool)
    if rem:
        X = np.concatenate([X, np.zeros((rem,) + X.shape[1:], X.dtype)], axis=0)
        y = np.concatenate([y, np.zeros((rem,), y.dtype)], axis=0)
        valid[n:] = False
    return X, y, valid


def shard_dataset(mesh: Mesh, X, y) -> Tuple[Array, Array, Optional[Array]]:
    """Place ``(X, y)`` sharded over the 'data' axis of ``mesh``.

    Returns device arrays plus a ``valid`` mask (None when no padding was
    needed).  This is the one host->device transfer of the whole run — the
    analogue of the reference's initial ``RDD.cache()`` materialization.

    Multi-host jobs (``jax.process_count() > 1`` after
    ``initialize_distributed``): ``X``/``y`` are each process's LOCAL rows —
    the analogue of each Spark executor reading its own input splits
    (SURVEY.md §3.4) — and the global sharded arrays are assembled without
    any cross-host data movement; only gradient psums ride DCN.
    """
    Xh = np.asarray(X)
    yh = np.asarray(y)
    if jax.process_count() > 1:
        return _shard_dataset_multihost(mesh, Xh, yh)
    n_shards = mesh.shape[DATA_AXIS]
    n = Xh.shape[0]
    Xh, yh, validh = pad_to_multiple(Xh, yh, n_shards)
    row_sharding = NamedSharding(mesh, P(DATA_AXIS))
    Xd = jax.device_put(Xh, NamedSharding(mesh, P(DATA_AXIS, None)))
    yd = jax.device_put(yh, row_sharding)
    if n == Xh.shape[0]:
        return Xd, yd, None
    vd = jax.device_put(validh, row_sharding)
    return Xd, yd, vd


def _shard_dataset_multihost(mesh: Mesh, Xh, yh):
    """Assemble globally-sharded arrays from per-process local rows.

    Each process contributes its rows via
    ``make_array_from_process_local_data`` — no host ever holds (or sends)
    another host's shard.  Per-process row counts may be uneven (the
    analogue of Spark's arbitrary-size input splits): a process allgather
    agrees on one common padded per-process length, so every process infers
    the SAME global shape; padding rows are masked out via the ``valid``
    mask.  Equal, locally-aligned splits need no padding and return
    ``valid=None`` like the single-process path, keeping the no-mask fast
    paths (incl. gram DP) available.
    """
    from jax.experimental import multihost_utils

    local_shards = dict(mesh.local_mesh.shape).get(DATA_AXIS, 1)
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray(Xh.shape[0]))
    )
    target = int(counts.max())
    target += (-target) % local_shards
    n = Xh.shape[0]
    pad = target - n
    valid = np.ones((target,), dtype=bool)
    if pad:
        Xh = np.concatenate(
            [Xh, np.zeros((pad,) + Xh.shape[1:], Xh.dtype)], axis=0
        )
        yh = np.concatenate([yh, np.zeros((pad,), yh.dtype)], axis=0)
        valid[n:] = False
    row_sharding = NamedSharding(mesh, P(DATA_AXIS))
    Xd = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(DATA_AXIS, None)), Xh
    )
    yd = jax.make_array_from_process_local_data(row_sharding, yh)
    if int(counts.min()) == target:
        # every process arrived equal AND locally aligned — no padding
        # anywhere, so return valid=None like the single-process path and
        # keep the no-mask fast paths (incl. gram DP) available; the
        # decision is identical on every process (counts is allgathered)
        return Xd, yd, None
    vd = jax.make_array_from_process_local_data(row_sharding, valid)
    return Xd, yd, vd


def dp_step_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    with_valid: bool,
):
    """Build the jitted shard_map'ed SINGLE-step function — the shared
    wiring for every observed/streamed mesh path (one source of truth for
    the step's in/out specs)."""
    from tpu_sgd.optimize.gradient_descent import make_step

    step = make_step(gradient, updater, config, axis_name=DATA_AXIS)
    if with_valid:
        body = step
        in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS), P(), P(),
                    P(DATA_AXIS))
    else:
        body = lambda w, X, y, i, r: step(w, X, y, i, r, None)
        in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS), P(), P())
    return jax.jit(
        shard_map_fn(mesh, body, in_specs, (P(), P(), P(), P()))
    )


#: replicated per-step ys of one fused superstep — (weights, loss, reg,
#: count, delta_norm, weight_norm), each stacked (K, ...); the psums
#: inside make_step leave every leaf identical on all shards
_SUPERSTEP_YS_SPECS = (P(), P(), P(), P(), P(), P())


def dp_superstep_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
):
    """Build the jitted shard_map'ed K-fused superstep over PER-STEP
    batches — ``make_superstep`` with the ICI all-reduce, consuming a
    row-sharded ``(K, rows, d)`` superchunk (``superchunk_specs``).

    This is what lifts the meshed host-streamed feed's old
    per-iteration-driver restriction: one sharded superchunk transfer
    plus ONE sharded program dispatch advance K iterations on every
    core, with the same per-step math and psum combines as the meshed
    per-iteration ``dp_step_fn`` (same-program contracts bitwise; vs
    the per-iteration driver the usual cross-program reassociation
    tolerance — see ``make_superstep``)."""
    from tpu_sgd.optimize.gradient_descent import make_superstep

    sstep = make_superstep(gradient, updater, config, axis_name=DATA_AXIS)
    in_specs = (P(), P(), P()) + superchunk_specs()
    return jax.jit(shard_map_fn(
        mesh, sstep, in_specs, (P(), _SUPERSTEP_YS_SPECS)))


def dp_shared_superstep_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    k: int,
    mesh: Mesh,
    with_valid: bool,
):
    """Build the jitted shard_map'ed K-fused superstep over ONE shared
    sharded batch — ``make_shared_batch_superstep`` with the ICI
    all-reduce: the meshed observed (listener/checkpoint) stepwise
    driver and the meshed streamed full-batch feed fuse K iterations
    per dispatch over data that moved once (``shard_dataset`` / the
    one-time streamed transfer)."""
    from tpu_sgd.optimize.gradient_descent import (
        make_shared_batch_superstep,
    )

    sstep = make_shared_batch_superstep(gradient, updater, config, k,
                                        axis_name=DATA_AXIS)
    if with_valid:
        body = sstep
        in_specs = (P(), P(), P(), P(DATA_AXIS, None), P(DATA_AXIS),
                    P(DATA_AXIS))
    else:
        body = lambda w, rv, i0, X, y: sstep(w, rv, i0, X, y, None)
        in_specs = (P(), P(), P(), P(DATA_AXIS, None), P(DATA_AXIS))
    return jax.jit(shard_map_fn(
        mesh, body, in_specs, (P(), _SUPERSTEP_YS_SPECS)))


#: per-shard error-feedback state of the compressed gradient wire: one
#: (dim,) accumulator per shard, globally a (n_shards, dim) array
#: sharded over 'data' — state, like the weights, but NOT replicated
#: (each shard's accumulator holds ITS dropped mass)
_EF_SPEC = P(DATA_AXIS, None)


def dp_compressed_step_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    mesh: Mesh,
    with_valid: bool,
):
    """Jitted shard_map'ed single step over the COMPRESSED gradient
    wire (``make_compressed_step`` with the 'data' axis): the gradient
    all-reduce ships top-k ``(values, indices)`` segments with per-shard
    error-feedback state instead of a dense ``(d,)`` psum — README
    "Compressed wire".  Signature: ``fn(w, ef, X, y, i, reg_val[,
    valid]) -> (new_w, new_ef, loss, new_reg, count)`` where ``ef`` is
    the ``(n_shards, dim)`` sharded accumulator."""
    from tpu_sgd.optimize.gradient_descent import make_compressed_step

    step = make_compressed_step(gradient, updater, config, topk_frac,
                                axis_name=DATA_AXIS)

    def body(w, ef, X, y, i, rv, valid=None):
        new_w, new_ef, loss, new_rv, c = step(w, ef[0], X, y, i, rv,
                                              valid)
        return new_w, new_ef[None], loss, new_rv, c

    in_specs = (P(), _EF_SPEC, P(DATA_AXIS, None), P(DATA_AXIS), P(),
                P())
    if with_valid:
        in_specs = in_specs + (P(DATA_AXIS),)
    return jax.jit(shard_map_fn(
        mesh, body, in_specs, (P(), _EF_SPEC, P(), P(), P())))


def dp_compressed_superstep_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    mesh: Mesh,
):
    """:func:`dp_superstep_fn` over the compressed wire: K fused
    compressed steps per dispatch, the per-shard EF accumulator carried
    in the scan and the per-step post-update accumulators returned as a
    ``(K, n_shards, dim)`` ys leaf (iteration-exact EF for
    mid-superstep checkpoints).  ``fn(w, ef, reg_val, i0, Xs, ys,
    valids) -> (w, ef, (*step_ys, efs))``."""
    from tpu_sgd.optimize.gradient_descent import (
        make_compressed_superstep,
    )

    sstep = make_compressed_superstep(gradient, updater, config,
                                      topk_frac, axis_name=DATA_AXIS)

    def body(w, ef, rv, i0, Xs, ys, valids):
        new_w, new_ef, out = sstep(w, ef[0], rv, i0, Xs, ys, valids)
        return new_w, new_ef[None], out[:6] + (out[6][:, None, :],)

    in_specs = (P(), _EF_SPEC, P(), P()) + superchunk_specs()
    out_specs = (P(), _EF_SPEC,
                 _SUPERSTEP_YS_SPECS + (P(None, DATA_AXIS, None),))
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def dp_compressed_shared_superstep_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    k: int,
    mesh: Mesh,
    with_valid: bool,
):
    """:func:`dp_shared_superstep_fn` over the compressed wire (one
    shared sharded batch, K fused compressed steps; same EF
    carry-and-ys contract as :func:`dp_compressed_superstep_fn`)."""
    from tpu_sgd.optimize.gradient_descent import (
        make_compressed_shared_superstep,
    )

    sstep = make_compressed_shared_superstep(
        gradient, updater, config, topk_frac, k, axis_name=DATA_AXIS)

    def body(w, ef, rv, i0, X, y, valid=None):
        new_w, new_ef, out = sstep(w, ef[0], rv, i0, X, y, valid)
        return new_w, new_ef[None], out[:6] + (out[6][:, None, :],)

    in_specs = (P(), _EF_SPEC, P(), P(), P(DATA_AXIS, None),
                P(DATA_AXIS))
    if with_valid:
        in_specs = in_specs + (P(DATA_AXIS),)
    out_specs = (P(), _EF_SPEC,
                 _SUPERSTEP_YS_SPECS + (P(None, DATA_AXIS, None),))
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def dp_run_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    with_valid: bool,
):
    """Build the jitted shard_map'ed full-loop runner.

    The inner body is *the same* ``make_run`` used single-device, with
    ``axis_name='data'`` turning its combines into ICI all-reduces — one
    compiled XLA program for the entire optimization across all cores.
    """
    from tpu_sgd.optimize.gradient_descent import make_run

    run = make_run(gradient, updater, config, axis_name=DATA_AXIS)
    if with_valid:
        body = lambda w, X, y, v: run(w, X, y, v)
        in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS))
    else:
        body = lambda w, X, y: run(w, X, y, None)
        in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS))
    out_specs = (P(), P(), P())
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def dp_optimize(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    initial_weights,
    X,
    y,
):
    """Shard, run, return ``(weights, loss_history, n_recorded)``."""
    Xd, yd, valid = shard_dataset(mesh, X, y)
    w0 = jnp.asarray(initial_weights)
    fn = dp_run_fn(gradient, updater, config, mesh, valid is not None)
    if valid is not None:
        return fn(w0, Xd, yd, valid)
    return fn(w0, Xd, yd)
