"""Data-parallel training on sparse (BCOO) features.

Reference parity: Spark trains sparse ``RDD[LabeledPoint]`` DISTRIBUTED —
each executor holds its partitions' sparse rows and ``treeAggregate``
combines per-partition gradient sums ([U] mllib/optimization/
GradientDescent.scala over sparse Vectors, SURVEY.md §2 #10/#13).  The
single-device BCOO path (tpu_sgd/ops/sparse.py) alone would cap the
framework below the reference's distributed-sparse capability.

The obstacle to sharding a BCOO directly is that a row range's nse varies
by shard, and ``shard_map`` needs one static local shape.  The layout here
makes nse uniform *by construction*:

  1. rows are split into ``n_shards`` contiguous equal blocks (row-padded
     like the dense path, with a ``valid`` mask);
  2. each block's entries are rebased to LOCAL row indices and padded to
     the max per-shard nse with null entries — value 0.0 at (row 0, col 0),
     which contribute exactly 0 to both matvecs;
  3. the per-shard blocks are concatenated into flat component arrays
     (``data``, ``indices``) sharded over the 'data' axis, and the
     shard_map body reassembles its LOCAL block into a BCOO of static shape
     ``(rows_local, d)``.

From there the body is *the same* ``make_run`` the dense mesh path uses —
the sparse gather/segment lowering per shard, one ``lax.psum`` of
``(grad_sum, loss_sum, count)`` over ICI per iteration.
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.sparse import host_entries
from tpu_sgd.ops.updaters import Updater
from tpu_sgd.parallel.mesh import DATA_AXIS, shard_map_fn

Array = jax.Array


def _layout_blocks(rows, cols, vals, n_shards: int, rows_local: int,
                   nse_local: int):
    """Scatter sorted entries into ``(n_shards, nse_local)`` equal-nse
    blocks with LOCAL row indices; unfilled slots stay null entries
    (0.0 at local (0, 0))."""
    shard_of = rows // rows_local
    local_row = (rows % rows_local).astype(np.int32)
    counts = np.bincount(shard_of, minlength=n_shards)
    data_h = np.zeros((n_shards, nse_local), vals.dtype)
    idx_h = np.zeros((n_shards, nse_local, 2), np.int32)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot = np.arange(rows.shape[0]) - offsets[shard_of]
    data_h[shard_of, slot] = vals
    idx_h[shard_of, slot, 0] = local_row
    idx_h[shard_of, slot, 1] = cols
    return data_h, idx_h


def shard_bcoo(mesh: Mesh, X, y) -> Tuple[Array, Array, Array, Array, int, int]:
    """Lay a BCOO matrix out for ``shard_map`` over the 'data' axis.

    Returns ``(data, indices, y, valid, rows_local, d)`` where the arrays
    are device-sharded so each core sees one equal-nse block with local row
    indices (see module docstring); ``valid`` is None when the row count
    divides evenly (the dense path's mask-free fast path).  This is the one
    host->device transfer of the run — the sparse analogue of
    ``shard_dataset``.

    Multi-host jobs: ``X``/``y`` are each process's LOCAL sparse rows (the
    analogue of each executor reading its own input splits); processes
    agree on a common per-shard ``(rows_local, nse_local)`` via allgather
    and assemble the global arrays without moving any row cross-host.
    """
    if jax.process_count() > 1:
        return _shard_bcoo_multihost(mesh, X, y)
    n_shards = mesh.shape[DATA_AXIS]
    n, d = X.shape
    rows_local = -(-n // n_shards)  # ceil: same contiguous blocks as the
    n_padded = rows_local * n_shards  # dense path's pad_to_multiple
    yh = np.zeros((n_padded,), np.asarray(y).dtype)
    yh[:n] = np.asarray(y)
    valid = np.zeros((n_padded,), bool)
    valid[:n] = True

    rows, cols, vals = host_entries(X)
    nse_local = max(
        1, int(np.bincount(rows // rows_local, minlength=n_shards).max())
    )
    data_h, idx_h = _layout_blocks(
        rows, cols, vals, n_shards, rows_local, nse_local
    )

    entry_sharding = NamedSharding(mesh, P(DATA_AXIS))
    data_d = jax.device_put(data_h.reshape(-1), entry_sharding)
    idx_d = jax.device_put(
        idx_h.reshape(-1, 2), NamedSharding(mesh, P(DATA_AXIS, None))
    )
    y_d = jax.device_put(yh, entry_sharding)
    valid_d = (
        None if n == n_padded else jax.device_put(valid, entry_sharding)
    )
    return data_d, idx_d, y_d, valid_d, rows_local, int(d)


def _shard_bcoo_multihost(mesh: Mesh, X, y):
    """Assemble globally-sharded BCOO component arrays from per-process
    local sparse rows (the sparse twin of ``_shard_dataset_multihost``).

    Processes allgather their ``(row count, per-shard max nse, d)`` so
    every process infers the SAME global shapes — common padded per-process
    row count, common per-shard nse — then contribute their local blocks
    via ``make_array_from_process_local_data``; no host ever holds another
    host's rows, and only gradient psums ride DCN at train time.  The
    validity mask is always on (per-process padding differs).
    """
    from jax.experimental import multihost_utils

    local_shards = dict(mesh.local_mesh.shape).get(DATA_AXIS, 1)
    n, d_local = X.shape
    rows, cols, vals = host_entries(X)

    # agree on (padded per-process rows, per-shard nse, d)
    counts0 = np.asarray(multihost_utils.process_allgather(np.asarray(n)))
    target = int(counts0.max())
    target += (-target) % local_shards
    rows_local = target // local_shards
    local_max_nse = int(
        np.bincount(rows // rows_local, minlength=local_shards).max()
    ) if rows.size else 0
    nse_all = np.asarray(
        multihost_utils.process_allgather(np.asarray(local_max_nse))
    )
    nse_local = max(1, int(nse_all.max()))
    d_all = np.asarray(
        multihost_utils.process_allgather(np.asarray(d_local))
    )
    if int(d_all.min()) != int(d_all.max()):
        # resolving by max would silently misalign everything built from
        # the LOCAL width (w0 length, the appended bias column) — each
        # process would trace a different program, which in multi-host
        # JAX is a distributed hang, not a clean error.  Make the user
        # pin num_features at load time instead.
        raise ValueError(
            "processes disagree on the feature count "
            f"({sorted(int(v) for v in set(d_all.tolist()))}); pass an "
            "explicit num_features to the loader so every process "
            "builds the same dimensionality"
        )
    d = int(d_all.max())

    data_h, idx_h = _layout_blocks(
        rows, cols, vals, local_shards, rows_local, nse_local
    )
    yh = np.zeros((target,), np.asarray(y).dtype)
    yh[:n] = np.asarray(y)
    valid = np.zeros((target,), bool)
    valid[:n] = True

    entry_sharding = NamedSharding(mesh, P(DATA_AXIS))
    data_d = jax.make_array_from_process_local_data(
        entry_sharding, data_h.reshape(-1)
    )
    idx_d = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(DATA_AXIS, None)), idx_h.reshape(-1, 2)
    )
    y_d = jax.make_array_from_process_local_data(entry_sharding, yh)
    valid_d = jax.make_array_from_process_local_data(entry_sharding, valid)
    return data_d, idx_d, y_d, valid_d, rows_local, d


def local_bcoo(data: Array, indices: Array, rows_local: int, d: int):
    """Reassemble one shard's component arrays into its local BCOO block
    (static shape; called inside the shard_map body)."""
    from jax.experimental.sparse import BCOO

    return BCOO((data, indices), shape=(rows_local, d))


def sparse_dp_step_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    rows_local: int,
    d: int,
    with_valid: bool,
):
    """Jitted shard_map'ed SINGLE-step function over sharded BCOO
    components — the sparse twin of ``dp_step_fn``, used by the observed
    (listener / checkpoint) path."""
    from tpu_sgd.optimize.gradient_descent import make_step

    step = make_step(gradient, updater, config, axis_name=DATA_AXIS)

    def local(w, X, y, i, reg_val, valid=None):
        return step(w, local_bcoo(X[0], X[1], rows_local, d), y, i, reg_val,
                    valid)

    # X arrives as the (data, idx) component tuple, matching the stepwise
    # caller's ``step(w, X, y, ...)`` signature for dense X
    # ``local`` defaults valid=None, so it serves both arities directly
    x_spec = (P(DATA_AXIS), P(DATA_AXIS, None))
    in_specs = (P(), x_spec, P(DATA_AXIS), P(), P())
    if with_valid:
        in_specs = in_specs + (P(DATA_AXIS),)
    return jax.jit(
        shard_map_fn(mesh, local, in_specs, (P(), P(), P(), P()))
    )


def sparse_dp_run_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    rows_local: int,
    d: int,
    with_valid: bool,
):
    """Jitted shard_map'ed full-loop runner over sharded BCOO components —
    the sparse twin of ``dp_run_fn`` (same ``make_run``, same psum)."""
    from tpu_sgd.optimize.gradient_descent import make_run

    run = make_run(gradient, updater, config, axis_name=DATA_AXIS)

    def local(w, data, idx, y, valid=None):
        return run(w, local_bcoo(data, idx, rows_local, d), y, valid)

    # ``local`` defaults valid=None, so it serves both arities directly
    in_specs = (P(), P(DATA_AXIS), P(DATA_AXIS, None), P(DATA_AXIS))
    if with_valid:
        in_specs = in_specs + (P(DATA_AXIS),)
    return jax.jit(shard_map_fn(mesh, local, in_specs, (P(), P(), P())))
