"""Device-mesh construction helpers.

The reference's distribution substrate is Spark executors + Netty RPC
(SURVEY.md §1 L1-L2); the TPU-native substrate is a ``jax.sharding.Mesh``
whose collectives ride ICI within a slice and DCN across hosts
(SURVEY.md §5.8).  The canonical mesh for this framework is 1-D over the
example axis (``'data'``), with an optional second ``'model'`` axis for
feature sharding of very wide weight vectors (SURVEY.md §2 parallelism
ledger).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"


def superchunk_specs():
    """PartitionSpecs of one fused K-step *superchunk* ``(Xs, ys,
    valids)`` with shapes ``(K, rows, d)`` / ``(K, rows)`` / ``(K,
    rows)``: the STEP axis is replicated (every shard runs all K fused
    steps), the ROW axis shards over 'data'.  THE one definition shared
    by the meshed superstep builder (``parallel/data_parallel.py``) and
    the streamed feed's superchunk transfer (``optimize/streamed.py``),
    so the program's in_specs and the host-side ``device_put`` sharding
    cannot drift."""
    P = PartitionSpec
    return (P(None, DATA_AXIS, None), P(None, DATA_AXIS),
            P(None, DATA_AXIS))


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh; defaults to all devices on 'data'."""
    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    n = n_data * n_model
    if n > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:n]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all devices on the 'data' axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def has_model_axis(mesh) -> bool:
    """True when the mesh shards the FEATURE axis (a 2-D (data, model)
    mesh with a non-trivial 'model' dimension) — the one predicate every
    mesh-kind routing decision shares."""
    return mesh is not None and dict(mesh.shape).get(MODEL_AXIS, 1) > 1


def as_data_mesh(mesh):
    """The 1-D data view of a mesh: a data-only mesh passes through;
    TRIVIAL (size-1) extra axes are flattened away — the canonical
    ``make_mesh``/``MeshConfig`` shape is 2-D with ``model=1``, and the
    data-only builders must accept it rather than raise; a genuinely
    sharded extra axis raises the builders' NotImplementedError."""
    if mesh is None or set(mesh.shape) == {DATA_AXIS}:
        return mesh
    extra = {k: v for k, v in dict(mesh.shape).items() if k != DATA_AXIS}
    if DATA_AXIS in dict(mesh.shape) and all(v == 1 for v in extra.values()):
        import numpy as np
        from jax.sharding import Mesh

        return Mesh(np.asarray(mesh.devices).reshape(-1), (DATA_AXIS,))
    raise NotImplementedError(
        f"this operation composes with a 1-D '{DATA_AXIS}' mesh; "
        f"got axes {tuple(mesh.shape)}"
    )


def shard_map_fn(mesh, fn, in_specs, out_specs, check_vma=False):
    """Version-tolerant shard_map wrapper (jax.shard_map vs experimental)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.6

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)
