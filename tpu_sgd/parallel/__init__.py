from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS, data_mesh, make_mesh
from tpu_sgd.parallel.data_parallel import dp_optimize, shard_dataset
from tpu_sgd.parallel.distributed import (
    global_data_mesh,
    global_mesh_2d,
    initialize_distributed,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_mesh",
    "make_mesh",
    "dp_optimize",
    "shard_dataset",
    "initialize_distributed",
    "global_data_mesh",
    "global_mesh_2d",
]
