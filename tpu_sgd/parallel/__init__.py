from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS, data_mesh, make_mesh
from tpu_sgd.parallel.data_parallel import dp_optimize, shard_dataset

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_mesh",
    "make_mesh",
    "dp_optimize",
    "shard_dataset",
]
