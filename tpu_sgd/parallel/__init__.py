from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS, data_mesh, make_mesh
from tpu_sgd.parallel.data_parallel import dp_optimize, shard_dataset
from tpu_sgd.parallel.distributed import (
    global_data_mesh,
    global_mesh_2d,
    initialize_distributed,
)
from tpu_sgd.parallel.sparse_parallel import shard_bcoo, sparse_dp_run_fn

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_mesh",
    "make_mesh",
    "dp_optimize",
    "shard_dataset",
    "shard_bcoo",
    "sparse_dp_run_fn",
    "initialize_distributed",
    "global_data_mesh",
    "global_mesh_2d",
]
