"""Data-parallel sufficient-statistics (Gram) execution.

Composes `tpu_sgd/ops/gram.py` with the 1-D data mesh: each shard owns the
block-prefix Gram statistics of its LOCAL rows (built in one shard_map
pass over the already-sharded dataset — the same one-time ``cache()``
moment as ``shard_dataset``), and the unchanged ``make_run`` body then
executes per-shard window gradients from those statistics with the usual
``lax.psum`` combine over ICI.  Sampling semantics are identical to the
stock DP sliced path (per-shard window starts from the axis-folded key),
so the trajectory matches the stock mesh run the way the single-device
gram path matches the single-device run.

Config-4 frame (SURVEY.md, `BASELINE.json:10`): the north star names
"8-way data-parallel all-reduce" — this module is what makes the ~20×
sufficient-stats schedule (BASELINE.md round 3) available in exactly that
shape.

Restriction: the row count must divide the data axis (no padding).  The
gram fast path normalizes windows by the full window length, while padded
datasets carry a ``valid`` mask whose realized counts differ — rather
than silently change normalization, non-divisible inputs fall back to the
stock mesh path (the optimizer handles this automatically).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gram import (DEFAULT_BLOCK_ROWS, GramData,
                              GramLeastSquaresGradient)
from tpu_sgd.ops.updaters import Updater
from tpu_sgd.parallel.mesh import (DATA_AXIS, as_data_mesh,
                                   shard_map_fn)

#: leading shard axis + per-element rank of each GramData stats leaf
_STATS_SPECS = (
    P(DATA_AXIS, None, None, None),  # PG      (k, nbf+1, d, d)
    P(DATA_AXIS, None, None),        # Pb      (k, nbf+1, d)
    P(DATA_AXIS, None),              # Pyy     (k, nbf+1)
    P(DATA_AXIS, None, None),        # G_tot   (k, d, d)
    P(DATA_AXIS, None),              # b_tot   (k, d)
    P(DATA_AXIS,),                   # yy_tot  (k,)
)


def build_sharded_gram_stats(mesh, Xd, yd, block_rows: int = DEFAULT_BLOCK_ROWS):
    """Per-shard block-prefix statistics for an already-sharded dataset.

    ``Xd``/``yd`` come from ``shard_dataset`` with no padding (``valid is
    None``).  Returns ``(stats_tuple, block_rows_local)`` where each stats
    leaf carries a leading shard axis, sharded over 'data' — ready to pass
    straight into :func:`dp_gram_run_fn`.
    """
    k = mesh.shape[DATA_AXIS]
    n_local = Xd.shape[0] // k
    B = max(1, min(int(block_rows), n_local))
    # f64 data keeps f64 statistics, matching the single-device build()
    # default (prefix-difference cancellation would amplify a silent f32
    # downgrade relative to the stock f64 mesh path).
    sd = GramLeastSquaresGradient._resolve_stats_dtype(Xd.dtype, None)
    fn = _stats_builder(mesh, B, jnp.dtype(sd).name)
    return fn(Xd, yd), B


@functools.lru_cache(maxsize=8)
def _stats_builder(mesh, B, stats_dtype_name):
    """Jitted per-shard stats builder, memoized per (mesh, block size,
    stats dtype) so repeated builds on fresh same-shape datasets retrace
    nothing (the jit itself caches per input shape/dtype)."""
    sd = jnp.dtype(stats_dtype_name)

    def body(Xl, yl):
        stats = GramLeastSquaresGradient._precompute(
            Xl, yl, B=B, stats_dtype=sd
        )
        return tuple(s[None] for s in stats)

    return jax.jit(shard_map_fn(
        mesh, body, (P(DATA_AXIS, None), P(DATA_AXIS)), _STATS_SPECS
    ))


def dp_gram_run_fn(
    updater: Updater,
    config: SGDConfig,
    mesh,
    block_rows: int,
    aligned: bool = False,
):
    """Jitted shard_map'ed full-loop runner over per-shard Gram stats.

    Same ``make_run`` body as ``dp_run_fn``, driven by an unbound
    :class:`GramLeastSquaresGradient` executor (least-squares semantics);
    each shard reconstructs its local ``GramData`` from the stacked stats
    leaves, so the accelerated window path runs per shard and only the
    (grad, loss, count) psums ride the ICI.  ``aligned`` floors per-shard
    window starts to block boundaries (edge corrections skipped — the
    documented sampling deviation; see ``set_gram_options``)."""
    from tpu_sgd.optimize.gradient_descent import make_run

    run = make_run(GramLeastSquaresGradient(aligned=aligned), updater,
                   config, axis_name=DATA_AXIS)

    def body(w, Xl, yl, PG, Pb, Pyy, Gt, bt, yyt):
        gd = GramData(Xl, PG[0], Pb[0], Pyy[0], Gt[0], bt[0], yyt[0],
                      block_rows)
        return run(w, gd, yl, None)

    in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS)) + _STATS_SPECS
    out_specs = (P(), P(), P())
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def build_streamed_sharded_gram_stats(mesh, Xh, yh, block_rows: int = DEFAULT_BLOCK_ROWS,
                                      batch_rows=None, resume_dir=None,
                                      wire_dtype=None, prefetch_depth=2,
                                      pipeline=True):
    """Per-shard VIRTUAL statistics from HOST-resident rows — the
    beyond-HBM statistics build composed with the data mesh (config 4's
    literal "8-way data-parallel" shape at full 10M×1000 scale,
    BASELINE.json:10; the treeAggregate-over-partitions analogue,
    SURVEY.md §3.5).

    Each shard's host row slice streams chunk-by-chunk to ITS OWN device
    (``GramLeastSquaresGradient._streamed_prefix`` with per-device
    placement), so no device ever holds more than one chunk of rows plus
    its own prefix stack; the per-shard stacks are then assembled into
    globally-sharded stats arrays via
    ``jax.make_array_from_single_device_arrays`` — zero cross-device row
    movement, zero host-side concatenation.

    Rows are split evenly: shard ``i`` owns host rows
    ``[i*n_local, i*n_local + nbf*B)`` where ``n_local = n // k`` — the
    ``n % k`` remainder plus each shard's ``n_local % B`` tail are dropped
    (the same block-truncation deviation as the single-device
    ``build_streamed``, <0.1% of rows at scale).  Single-process only
    (every mesh device must be addressable); on a multi-host pod each
    process would run this over its local shard slice.

    ``resume_dir`` (opt-in): per-shard resumable builds — shard ``i``
    checkpoints under ``resume_dir/shard_i`` (see
    ``GramLeastSquaresGradient._streamed_prefix``), so a mid-pass kill
    resumes every shard from its own high-water block.

    ``wire_dtype``/``prefetch_depth``/``pipeline`` route each shard's
    feed through the shared ingest layer (``tpu_sgd/io``; README
    "Ingestion pipeline"): fixed-shape chunks with the next chunk's
    host assembly + ``device_put`` overlapping the current chunk's
    kernel, and an opt-in bf16 wire halving the bytes on the hop.

    Returns ``(stats_leaves, B, n_used_local)``.
    """
    import numpy as np

    from jax.sharding import NamedSharding

    mesh = as_data_mesh(mesh)  # trivial extra axes flatten; real ones raise
    k = mesh.shape[DATA_AXIS]
    n, d = Xh.shape
    n_local = n // k
    if n_local < 1:
        raise ValueError(f"{n} rows cannot shard {k} ways")
    B = max(1, min(int(block_rows), n_local))
    nbf = n_local // B
    n_used = nbf * B
    data_dtype = (Xh.dtype if jnp.issubdtype(Xh.dtype, jnp.inexact)
                  else jnp.float32)
    sd = GramLeastSquaresGradient._resolve_stats_dtype(data_dtype, None)
    chunk_blocks = max(1, int(batch_rows) // B) if batch_rows else 64
    chunk = chunk_blocks * B

    devices = list(mesh.devices.reshape(-1))
    per_dev = []
    import os

    for i, dev in enumerate(devices):
        s = i * n_local
        PG, Pb, Pyy = GramLeastSquaresGradient._streamed_prefix(
            Xh[s:s + n_used], np.asarray(yh[s:s + n_used]), B, sd, chunk,
            device=dev,
            resume_dir=(None if resume_dir is None
                        else os.path.join(resume_dir, f"shard_{i}")),
            wire_dtype=wire_dtype, prefetch_depth=prefetch_depth,
            pipeline=pipeline,
        )
        per_dev.append((PG, Pb, Pyy, PG[-1], Pb[-1], Pyy[-1]))
    jax.block_until_ready(per_dev)

    shapes = ((nbf + 1, d, d), (nbf + 1, d), (nbf + 1,),
              (d, d), (d,), ())
    leaves = []
    for leaf_i, (shape, spec) in enumerate(zip(shapes, _STATS_SPECS)):
        bufs = [
            jax.device_put(per_dev[i][leaf_i][None], devices[i])
            for i in range(k)
        ]
        leaves.append(jax.make_array_from_single_device_arrays(
            (k,) + shape, NamedSharding(mesh, spec), bufs
        ))
    return tuple(leaves), B, n_used


def dp_virtual_gram_run_fn(
    updater: Updater,
    config: SGDConfig,
    mesh,
    block_rows: int,
    n_local: int,
    d: int,
    data_dtype_name: str,
):
    """Jitted shard_map'ed full-loop runner over per-shard VIRTUAL stats
    (no rows on device at all): each shard reconstructs a rows-free
    ``GramData`` carrying its logical ``(n_local, d)`` shape, so windows
    run block-aligned from the prefix stacks and only the (grad, loss,
    count) psums ride the ICI.  Signature:
    ``fn(w0, yd, *stats_leaves) -> (w, losses, n_rec)`` (``yd`` is the
    tiny label vector, sharded for shape parity — the virtual window path
    never reads it)."""
    from tpu_sgd.optimize.gradient_descent import make_run

    run = make_run(GramLeastSquaresGradient(), updater, config,
                   axis_name=DATA_AXIS)

    def body(w, yl, PG, Pb, Pyy, Gt, bt, yyt):
        gd = GramData(None, PG[0], Pb[0], Pyy[0], Gt[0], bt[0], yyt[0],
                      block_rows, logical_shape=(n_local, d),
                      logical_dtype=data_dtype_name)
        return run(w, gd, yl, None)

    in_specs = (P(), P(DATA_AXIS)) + _STATS_SPECS
    out_specs = (P(), P(), P())
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def _validate_data_mesh(mesh):
    """``(mesh, k)``: the 1-D data view (the canonical 2-D mesh with a
    TRIVIAL model axis flattens; a real model axis raises)."""
    mesh = as_data_mesh(mesh)
    return mesh, mesh.shape[DATA_AXIS]


def build_sharded_total_stats(mesh, Xd, yd,
                              block_rows: int = DEFAULT_BLOCK_ROWS):
    """Replicated EXACT total statistics ``(G, b, yy)`` of a dataset via
    per-shard blockwise accumulation + one ``psum`` — the quasi-Newton
    meshed sufficient-statistics substitution.

    The quasi-Newton CostFun reads ONLY totals (full-batch sums and the
    line-search sweep — never windows), so the meshed build needs no
    prefix stacks: each shard scans its rows block-by-block with an O(d²)
    carry (``GramLeastSquaresGradient._total_stats``) and one psum makes
    the totals replicated.  Non-divisible row counts pad with a valid
    mask and stay EXACT (masked-operand matmuls).  Returns a VIRTUAL
    totals-only :class:`GramData` (windows degenerate to the full batch
    — quasi-Newton only; GD sliced sampling must not use it).
    """
    from tpu_sgd.parallel.data_parallel import shard_dataset

    import numpy as np

    mesh, k = _validate_data_mesh(mesh)
    # Host inputs stay numpy until shard_dataset places each shard on its
    # own device — jnp.asarray here would stage the whole (possibly
    # beyond-one-HBM) matrix through the default device first.
    if not isinstance(Xd, jax.Array):
        Xd = np.asarray(Xd)
    if not jnp.issubdtype(Xd.dtype, jnp.inexact):
        Xd = Xd.astype(np.float32 if isinstance(Xd, np.ndarray)
                       else jnp.float32)
    if not isinstance(yd, jax.Array):
        yd = np.asarray(yd)
    if not jnp.issubdtype(yd.dtype, jnp.inexact):
        yd = yd.astype(np.float32 if isinstance(yd, np.ndarray)
                       else jnp.float32)
    n, d = Xd.shape
    Xs, ys, valid = shard_dataset(mesh, Xd, yd)
    if valid is None:
        valid = jax.device_put(
            jnp.ones((Xs.shape[0],), bool),
            jax.sharding.NamedSharding(mesh, P(DATA_AXIS)),
        )
    n_local = Xs.shape[0] // k
    B = max(1, min(int(block_rows), n_local))
    sd = GramLeastSquaresGradient._resolve_stats_dtype(Xd.dtype, None)

    def body(Xl, yl, vl):
        G, b, yy = GramLeastSquaresGradient._total_stats(
            Xl, yl, B=B, stats_dtype=sd, valid=vl
        )
        return jax.lax.psum((G, b, yy), DATA_AXIS)

    fn = jax.jit(shard_map_fn(
        mesh, body,
        (P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        (P(), P(), P()),
    ))
    G, b, yy = fn(Xs, ys, valid)
    return GramLeastSquaresGradient.totals_only_data(
        G, b, yy, n, d, Xd.dtype
    )


def build_streamed_total_stats(mesh, Xh, yh,
                               block_rows: int = DEFAULT_BLOCK_ROWS,
                               batch_rows=None, resume_dir=None,
                               wire_dtype=None, prefetch_depth=2,
                               pipeline=True, wire_compress=None):
    """Replicated EXACT total statistics of HOST-resident rows — the
    quasi-Newton beyond-HBM build composed with the data mesh.

    Shard ``i`` streams its contiguous host row slice chunk-by-chunk to
    ITS OWN device with an O(d²) totals carry
    (``GramLeastSquaresGradient._streamed_totals``) — no prefix stacks,
    no dropped rows (the ``n % k`` remainder rides with the last shard),
    peak per-device footprint one chunk + (d, d).  The k tiny (d, d)
    totals then combine on the first device.  Single-process build (every
    mesh device addressable); a multi-host pod runs this per process over
    its local slice.  Returns a VIRTUAL totals-only :class:`GramData`
    (quasi-Newton only — see :func:`build_sharded_total_stats`).

    ``wire_compress="topk:<frac>"`` (README "Compressed wire"): the
    per-shard totals MERGE ships top-k ``(indices, values)`` segments
    through a persistent error-feedback accumulator instead of k-1
    dense ``(d, d)`` adds — each shard's delta folds into the SAME
    jitted donated accumulate (``ops/gram._scatter_acc_flat``), the
    top-k selection runs in host numpy (the shape-trap rule), and the
    accumulated residual flushes ONCE, dense, at the end, so the merged
    totals carry every shard's full mass (exact up to f.p.
    reassociation vs the dense merge — the EF accumulator reorders the
    adds).  Wire bytes: ``(k-1) · 2·frac + 1`` dense-equivalents
    instead of ``k-1`` — the win grows with the shard count.
    """
    import numpy as np

    mesh, k = _validate_data_mesh(mesh)
    Xh = np.asarray(Xh)
    yh = np.asarray(yh)
    n, d = Xh.shape
    if n < k:
        raise ValueError(f"{n} rows cannot shard {k} ways")
    data_dtype = (Xh.dtype if jnp.issubdtype(Xh.dtype, jnp.inexact)
                  else jnp.float32)
    sd = GramLeastSquaresGradient._resolve_stats_dtype(data_dtype, None)
    n_local = n // k
    from tpu_sgd.ops.gram import streamed_totals_chunking

    B, chunk = streamed_totals_chunking(n_local, block_rows, batch_rows)

    import os

    devices = list(mesh.devices.reshape(-1))
    totals = []
    for i, dev in enumerate(devices):
        s = i * n_local
        e = (i + 1) * n_local if i + 1 < k else n  # remainder to the last
        totals.append(GramLeastSquaresGradient._streamed_totals(
            Xh[s:e], yh[s:e], B, sd, chunk, device=dev,
            resume_dir=(None if resume_dir is None
                        else os.path.join(resume_dir, f"shard_{i}")),
            finalize=False,  # a later shard's crash must not force the
            # completed shards to re-stream — clean up only when ALL done
            wire_dtype=wire_dtype, prefetch_depth=prefetch_depth,
            pipeline=pipeline,
        ))
    jax.block_until_ready(totals)
    if resume_dir is not None:
        import shutil

        shutil.rmtree(resume_dir, ignore_errors=True)
    dev0 = devices[0]
    from tpu_sgd.io.sparse_wire import ErrorFeedback, parse_wire_compress
    from tpu_sgd.obs.counters import record_wire
    from tpu_sgd.ops.gram import _acc_totals

    frac = parse_wire_compress(wire_compress)
    if frac is not None and k > 1:
        # Compressed merge wire: flat [G.ravel(), b, yy] accumulator on
        # the first device; shards 1..k-1 ship top-k (indices, values)
        # segments selected HOST-side through ONE persistent
        # error-feedback accumulator, folded in by the jitted donated
        # scatter-accumulate; the EF residual flushes dense, once.
        from functools import partial as _partial

        from tpu_sgd.ops.gram import _dense_acc_flat, _scatter_acc_flat

        dd = d * d
        sd_np = np.dtype(jnp.dtype(sd).name)

        def _flat_host(t):
            Gi, bi, yyi = t
            return np.concatenate([
                np.asarray(Gi).reshape(-1), np.asarray(bi),
                np.asarray(yyi).reshape(1),
            ]).astype(sd_np)

        flat = jax.device_put(_flat_host(totals[0]), dev0)
        ef = ErrorFeedback(dd + d + 1, frac, dtype=sd_np)
        for t in totals[1:]:
            # shard-merge boundary fetch: the shard's (d, d) totals come
            # back to host ONCE so the top-k selection can run in numpy
            # (graftlint shape-trap rule) — this read IS the wire being
            # compressed
            idx, vals = ef.compress(_flat_host(t))
            flat = _scatter_acc_flat(
                flat, jax.device_put(idx, dev0),
                jax.device_put(vals, dev0))
        res = ef.residual()
        record_wire("dense-f32", logical_nbytes=int(res.nbytes),
                    physical_nbytes=int(res.nbytes))
        flat = _dense_acc_flat(flat, jax.device_put(res, dev0))
        split = jax.jit(_partial(_split_flat_totals, d=d))
        G, b, yy = split(flat)
    else:
        G, b, yy = (jax.device_put(t, dev0) for t in totals[0])
        for Gi, bi, yyi in totals[1:]:
            # ONE jitted donated accumulate per shard
            # (ops/gram._acc_totals) instead of three eager per-shard
            # adds, each of which compiled and launched its own one-op
            # program
            record_wire(
                "dense-f32",
                logical_nbytes=int(Gi.nbytes + bi.nbytes + yyi.nbytes),
                physical_nbytes=int(Gi.nbytes + bi.nbytes + yyi.nbytes))
            G, b, yy = _acc_totals(
                G, b, yy,
                jax.device_put(Gi, dev0),
                jax.device_put(bi, dev0),
                jax.device_put(yyi, dev0),
            )
    return GramLeastSquaresGradient.totals_only_data(
        G, b, yy, n, d, data_dtype
    )


def _split_flat_totals(flat, *, d: int):
    """Traced split of the flat merge accumulator back into ``(G, b,
    yy)`` (jitted once per build by the compressed merge — the reshape
    needs a static ``d``)."""
    dd = d * d
    return (flat[:dd].reshape(d, d), flat[dd:dd + d], flat[dd + d])
