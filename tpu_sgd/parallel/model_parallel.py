"""2-D (data x model) sharded SGD: example axis AND feature axis sharded.

The reference has no tensor parallelism — its model is one dense vector
(SURVEY.md §2 parallelism ledger) — but the ledger reserves a 2-D
``('data', 'model')`` hook for very wide feature spaces.  This module is that
hook: ``X`` is sharded over both axes, ``w`` is sharded over features, the
per-core partial margins ``X_block @ w_block`` are all-reduced over the
``model`` axis, gradients over ``data``, and the updater runs block-local
with its scalar reg value combined over ``model``.  Both all-reduces ride ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.updaters import Updater
from tpu_sgd.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map_fn


def pad_features_to_multiple(X: np.ndarray, w0: np.ndarray, n_shards: int):
    """Zero-pad the feature axis; zero columns stay exactly zero through all
    three updaters (grad is 0 and every update rule maps 0 -> 0), so padding
    is invisible in the result. Returns (X, w0, orig_dim)."""
    d = X.shape[1]
    rem = (-d) % n_shards
    if rem:
        X = np.concatenate([X, np.zeros((X.shape[0], rem), X.dtype)], axis=1)
        w0 = np.concatenate([w0, np.zeros((rem,), w0.dtype)])
    return X, w0, d


def dp_mp_run_fn(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    with_valid: bool,
):
    """Jitted shard_map'ed runner over a 2-D ('data', 'model') mesh."""
    from tpu_sgd.optimize.gradient_descent import make_run

    run = make_run(
        gradient, updater, config,
        axis_name=DATA_AXIS, model_axis_name=MODEL_AXIS,
    )
    if with_valid:
        body = lambda w, X, y, v: run(w, X, y, v)
        in_specs = (P(MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS),
                    P(DATA_AXIS))
    else:
        body = lambda w, X, y: run(w, X, y, None)
        in_specs = (P(MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS))
    out_specs = (P(MODEL_AXIS), P(), P())
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def dp_mp_optimize(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    mesh: Mesh,
    initial_weights,
    X,
    y,
):
    """Shard 2-D, run, return ``(weights[:orig_dim], loss_history, n_rec)``."""
    from tpu_sgd.parallel.data_parallel import pad_to_multiple

    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    Xh = np.asarray(X)
    yh = np.asarray(y)
    w0h = np.asarray(initial_weights)
    n = Xh.shape[0]
    Xh, yh, validh = pad_to_multiple(Xh, yh, n_data)
    Xh, w0h, orig_dim = pad_features_to_multiple(Xh, w0h, n_model)
    need_valid = n != Xh.shape[0]

    Xd = jax.device_put(Xh, NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)))
    yd = jax.device_put(yh, NamedSharding(mesh, P(DATA_AXIS)))
    wd = jax.device_put(w0h, NamedSharding(mesh, P(MODEL_AXIS)))
    fn = dp_mp_run_fn(gradient, updater, config, mesh, need_valid)
    if need_valid:
        vd = jax.device_put(validh, NamedSharding(mesh, P(DATA_AXIS)))
        w, losses, n_rec = fn(wd, Xd, yd, vd)
    else:
        w, losses, n_rec = fn(wd, Xd, yd)
    return w[:orig_dim], losses, n_rec
