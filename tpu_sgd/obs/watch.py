"""Live trace watcher: tail a RUNNING trace, render windowed tables +
active alerts.

``obs.report`` is the post-mortem; this is the pager screen::

    python -m tpu_sgd.obs.watch run_trace.jsonl            # follow
    python -m tpu_sgd.obs.watch run_trace.jsonl --once     # one render

The watcher tails the JSONL file the way ``tail -f`` would — an
incremental reader that buffers a torn/in-flight final line until its
newline arrives (the shared crash-forensics contract) and SKIPS (but
counts) malformed interior lines instead of dying: a live view must
survive whatever a crashing producer wrote.  Records feed the same
fixed-width windowing the offline report uses
(:func:`tpu_sgd.obs.report.windowed_stats` over a BOUNDED deque of
recent records — memory is bounded by the retention cap, never by how
long the watched run has been going), so the table on this screen and
the table in the post-mortem report are the same numbers.

Rendered per refresh: the last ``--last`` windows' per-span
count/p50/p99/max tables, the latest cumulative counter snapshot's
headline counts, and the ACTIVE alerts — ``obs_alert`` records whose
window falls inside the last ``--active-s`` seconds of trace time
(typed records from ``tpu_sgd.obs.detect``, not grepped log lines).

Exit codes: 0 on EOF (``--once``) or Ctrl-C (follow mode), 2 on an
unreadable trace path — the report CLI's usage-error class.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque
from typing import List, Optional

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — the watcher is a single-threaded reader; it owns no shared
#: mutable state and no locks.
GRAFTLINT_LOCKS: dict = {}


class TraceTail:
    """Incremental JSONL reader: ``poll()`` returns the records whose
    lines completed since the last poll.  A final line with no newline
    yet is buffered (the producer is mid-write); a malformed
    newline-terminated line is counted in ``parse_errors`` and
    skipped — the live view renders on, the post-mortem ``read()``
    still treats interior corruption as fatal."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path)
        self._buf = ""
        self.parse_errors = 0
        self.records_seen = 0

    def poll(self) -> List[dict]:
        chunk = self._f.read()
        if not chunk:
            return []
        self._buf += chunk
        *complete, self._buf = self._buf.split("\n")
        out = []
        for line in complete:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                self.parse_errors += 1
        self.records_seen += len(out)
        return out

    def close(self):
        self._f.close()


class WatchState:
    """Bounded rolling state: recent records for the window tables,
    alerts, and the newest cumulative counter snapshot."""

    def __init__(self, retain: int = 20000, alert_retain: int = 256):
        self.recent: deque = deque(maxlen=int(retain))
        self.alerts: deque = deque(maxlen=int(alert_retain))
        self.counters: Optional[dict] = None
        self.last_ts: float = 0.0

    def feed(self, records: List[dict]) -> None:
        for r in records:
            kind = r.get("kind")
            ts = r.get("ts")
            if ts is not None:
                self.last_ts = max(self.last_ts, float(ts))
            if kind in ("trace_span", "obs_alert"):
                self.recent.append(r)
            if kind == "obs_alert":
                self.alerts.append(r)
            elif kind == "metric_counters":
                self.counters = r.get("counters")

    def active_alerts(self, horizon_s: float) -> List[dict]:
        cutoff = self.last_ts - horizon_s
        return [a for a in self.alerts
                if float(a.get("ts", 0.0)) >= cutoff]


def render(state: WatchState, tail: TraceTail, window_s: float,
           last: int, active_s: float) -> str:
    from tpu_sgd.obs.report import (_fmt_num, render_windows,
                                    windowed_stats)

    lines = [
        f"== obs.watch {tail.path}  records={tail.records_seen}"
        + (f"  parse_errors={tail.parse_errors}"
           if tail.parse_errors else "")
    ]
    wins = windowed_stats(list(state.recent), window_s)
    lines.append(render_windows(wins, last=last))
    active = state.active_alerts(active_s)
    if active:
        lines.append(f"ACTIVE ALERTS (last {active_s:g}s):")
        for a in active:
            lines.append(
                f"  [{a.get('rule')}] {a.get('series')}: "
                f"value={_fmt_num(a.get('value'))} "
                f"bound={_fmt_num(a.get('bound'))}"
                f"  {a.get('detail', '')}")
    else:
        lines.append(f"no active alerts (last {active_s:g}s)")
    if state.counters:
        headline = {k: v for k, v in sorted(state.counters.items())
                    if k.endswith((".dispatch", ".compile",
                                   ".host_sync")) or
                    k.startswith("obs.alert.")}
        if headline:
            lines.append("counters (cumulative):")
            for k, v in headline.items():
                lines.append(f"  {k:<40}{v['n']:>10}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_sgd.obs.watch",
        description=__doc__.split("\n")[0])
    ap.add_argument("trace", help="trace JSONL path being written")
    ap.add_argument("--window", metavar="SECONDS", type=float,
                    default=1.0, help="window width (default 1s)")
    ap.add_argument("--last", type=int, default=6,
                    help="windows to render (default 6)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh interval in follow mode (default 1s)")
    ap.add_argument("--active-s", type=float, default=30.0,
                    help="alert active horizon in trace seconds")
    ap.add_argument("--once", action="store_true",
                    help="read to EOF, render once, exit (the CI/test "
                         "spelling)")
    args = ap.parse_args(argv)
    try:
        tail = TraceTail(args.trace)
    except OSError as e:
        print(f"error: cannot open trace {args.trace!r}: {e}",
              file=sys.stderr)
        return 2
    state = WatchState()
    try:
        if args.once:
            state.feed(tail.poll())
            print(render(state, tail, args.window, args.last,
                         args.active_s))
            return 0
        while True:
            fed = tail.poll()
            if fed:
                state.feed(fed)
            print(render(state, tail, args.window, args.last,
                         args.active_s), flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        tail.close()


if __name__ == "__main__":
    sys.exit(main())
