"""Windowed time-series: the LIVE half of the observability layer.

PR 8 made runs legible after the fact — ``obs.report`` turns a finished
trace into p50/p99 tables.  Nothing could read the run *while it runs*,
which is exactly what the adaptive control plane (ROADMAP item 2,
PAPERS.md *AdaBatch*) needs as its sensor and what an overloaded
production endpoint needs to notice drift/stragglers/overload before
the post-mortem.  This module is that sensor: a **bounded ring of
fixed-width time windows** fed by the hooks the codebase already has —

* **span closes** (``obs.spans``): every closed span lands its duration
  in the window as a value sample of the series named after the span
  (``serve.batch``, ``train.superstep``), with a declared fan-out for
  per-actor series (:data:`SPAN_FANOUT` — ``replica.step`` fans out to
  ``replica.step[w0]`` per worker, the straggler-skew surface);
* **counter incs** (``obs.counters``): every counted dispatch / sync /
  h2d / explicit ``inc`` site lands its count+bytes in the window under
  the counter's own name (``serve.shed.interactive``,
  ``train.dispatch``, ``replica.wire.topk``);
* **instant events** (``obs.spans.event``): counted per window, with a
  declared value extraction (:data:`EVENT_VALUES` — an accepted
  ``replica.push``'s ``staleness`` becomes the
  ``replica.push.staleness`` value series, the store version gap);
* **observed-loop scalars** (:func:`observe_scalar`): the per-step
  loss / weight-delta norms that already ride the scan ys and are
  already host floats at replay time become ``train.loss`` /
  ``train.weight_delta`` series — the near-free AdaBatch variance
  sensor, ZERO added fetches (the values were fetched for bookkeeping
  regardless).

Each window keeps per-series ``count`` / ``sum`` / ``max`` / ``bytes``
exactly, plus a BOUNDED sample buffer for p50/p99 (nearest-rank, via
the ONE shared rule ``serve.metrics.nearest_rank`` — an SLO written
against a live window p99 means the same thing everywhere).  Memory is
bounded by construction: ``max_windows`` closed windows in a ring plus
one open window, ``samples_per_series`` samples per series per window
(beyond the cap, count/sum/max stay exact and the percentile is over
the first-cap samples — honest, flagged by ``samples_capped``).  Run
length NEVER grows the store.

Cost contract: every hook is pure host work — dict updates under one
lock, no jax calls, no device touches — so the PR 8 acceptance pin
(enabled obs adds ZERO dispatches / compiles / host syncs on the
warmed superstep and resident drivers) holds with the time-series ON
(re-asserted in ``tests/test_obs.py``).  Disabled, each hook is one
module-global load and a falsy branch (the failpoints discipline).

Window closes fire listeners (``tpu_sgd.obs.detect``'s detector engine
registers here) on a DEDICATED daemon thread, never on the observing
thread: the observation that rolls a window may be a counter inc fired
while its caller holds a hot-path lock (the serve batcher's ``_cond``
during an admission decision), and detector evaluation + alert
emission + a flight-recorder dump happening inline there would stall
every lane at exactly the overloaded moment the shed-rate rule trips.
The observer only enqueues the closed window; :func:`flush` closes the
open window AND waits for the dispatch queue to drain, so a harness
that flushes and then reads trip counts still sees deterministic
results.  A raising listener is dropped, never kills anything.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["WindowStore", "enable", "disable", "is_enabled", "snapshot",
           "flush", "observe_scalar", "SPAN_FANOUT", "EVENT_VALUES"]

logger = logging.getLogger("tpu_sgd.obs")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the window
#: ring and the open window are mutated by every observing thread
#: (training loop, prefetch worker, serving flush thread, replica
#: workers, the counter patches) — all rolls/updates hold the lock.
#: Close listeners fire OUTSIDE the lock on a popped window.  The
#: module-level ``_STORE``/``_ENABLED`` are GIL-atomic single
#: references (the ``obs.spans`` ``_SINK`` pattern).
GRAFTLINT_LOCKS = {
    "WindowStore": {
        "_windows": "_lock",
        "_current": "_lock",
        "_floor_index": "_lock",
        "_listeners": "_lock",
        # the close-dispatch queue rides its own condition: the worker
        # thread and every observing thread meet there, and it must
        # never nest inside ``_lock`` (enqueues happen after the roll
        # releases it)
        "_pending": "_dispatch_cv",
        "_dispatch_busy": "_dispatch_cv",
        "_dispatch_stop": "_dispatch_cv",
        # lazily spawned by add_close_listener(), snapshotted by
        # close() — both under the cv since ISSUE 19 (the unlocked
        # close-side read raced the first-listener spawn)
        "_dispatch_thread": "_dispatch_cv",
    },
}

#: span names fanned out into per-actor sub-series by an attribute:
#: ``replica.step`` spans carry ``worker=``, so each worker gets its
#: own ``replica.step[w0]`` series — the per-worker progress signal the
#: straggler detector compares across the fleet.
SPAN_FANOUT: Dict[str, str] = {
    "replica.step": "worker",
}

#: instant-event value extraction: ``{event name: ((attr, only_if),
#: ...)}`` — the named attr becomes the ``<event>.<attr>`` value
#: series, gated on a truthy ``only_if`` attr when given.  An accepted
#: ``replica.push``'s ``staleness`` is the store's live version gap
#: (rejected pushes are excluded: their gap was refused, not served).
EVENT_VALUES: Dict[str, tuple] = {
    "replica.push": (("staleness", "accepted"),),
    # each tenant touched by a predict batch reports how stale its slab
    # row is — the per-tenant freshness SLO feed (tenant.predict.
    # staleness_s value series; tpu_sgd/tenant/engine.py emits it)
    "tenant.predict": (("staleness_s", None),),
}

#: instant events fanned into per-actor count series by an attribute
#: (the event twin of :data:`SPAN_FANOUT`): membership transitions
#: become ``replica.join[w0]`` / ``replica.rejoin[w0]`` /
#: ``replica.leave[w0]`` series — the straggler detector's membership
#: feed.  Convention: an event carrying a truthy ``error`` attr lands
#: in the ``<name>.error[actor]`` twin instead, so a death-leave and a
#: clean leave are distinct series (the detector keeps hunting the
#: former and forgets the latter).
EVENT_FANOUT: Dict[str, str] = {
    "replica.join": "worker",
    "replica.rejoin": "worker",
    "replica.leave": "worker",
    # store failovers land next to the worker churn: the plain
    # ``replica.failover`` count series feeds the failover detector
    # (and the straggler detector's roster reset — a promotion's
    # fleet-wide stall must not read as one worker lagging), the
    # fanned ``replica.failover[s1]`` series names the promoted store
    "replica.failover": "new_primary",
    # the sharded store's per-shard push routing
    # (tpu_sgd/replica/shard.py): one event per touched shard per
    # push, fanned by shard id into ``replica.shard.push[s0]``-style
    # count series — the shard-imbalance detector's feed
    "replica.shard.push": "shard",
    # the tenant slab's residency transitions (tpu_sgd/tenant/store.py),
    # fanned by tenant id: ``tenant.admit[7]`` / ``tenant.evict[7]`` /
    # ``tenant.swap[7]`` count series are the per-tenant SLO surface,
    # and the unfanned totals feed the opt-in SlabThrashDetector;
    # ``tenant.predict`` fans each batch's touched tenants into
    # per-tenant serve-rate series next to them
    "tenant.admit": "tenant",
    "tenant.evict": "tenant",
    "tenant.swap": "tenant",
    "tenant.predict": "tenant",
}

#: fast-path gate (the failpoints discipline): every hook reads this
#: ONE module global and returns when falsy.
_ENABLED = False

_STORE: Optional["WindowStore"] = None


class _SeriesAgg:
    """One series' aggregate inside one window.  ``n``/``total``/
    ``vmax``/``nbytes`` are exact however many observations arrive;
    ``samples`` is bounded by the store's per-series cap (percentiles
    degrade to first-cap honesty, never memory growth)."""

    __slots__ = ("n", "total", "vmax", "nbytes", "samples", "capped")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.vmax = None
        self.nbytes = 0
        self.samples: List[float] = []
        self.capped = False


class _Window:
    __slots__ = ("index", "t_start", "t_end", "series")

    def __init__(self, index: int, width_s: float):
        self.index = index
        self.t_start = index * width_s
        self.t_end = (index + 1) * width_s
        self.series: Dict[str, _SeriesAgg] = {}


def _percentile(xs: List[float], p: float) -> float:
    # lazy import: serve.metrics is leaf-light but importing it at
    # module top would drag tpu_sgd.serve.__init__ (batcher, engine)
    # into every obs import — the same deferral obs.report uses
    from tpu_sgd.serve.metrics import nearest_rank

    return nearest_rank(sorted(xs), p)


def _series_snapshot(agg: _SeriesAgg) -> dict:
    out = {
        "count": agg.n,
        "sum": agg.total,
        "max": agg.vmax,
        "mean": (agg.total / agg.n) if agg.n else 0.0,
        "bytes": agg.nbytes,
    }
    if agg.samples:
        out["p50"] = _percentile(agg.samples, 50)
        out["p99"] = _percentile(agg.samples, 99)
    if agg.capped:
        out["samples_capped"] = True
    return out


class WindowStore:
    """See module docstring.  ``clock`` is injectable (tests drive a
    synthetic long run through thousands of windows without sleeping);
    observations may also carry their own ``ts`` (the watch CLI replays
    a trace's record timestamps through the same windowing)."""

    def __init__(self, width_s: float = 1.0, max_windows: int = 64,
                 samples_per_series: int = 256,
                 clock: Callable[[], float] = time.time):
        if width_s <= 0:
            raise ValueError(f"width_s must be > 0, got {width_s}")
        if max_windows < 1:
            raise ValueError(f"max_windows must be >= 1, got {max_windows}")
        self.width_s = float(width_s)
        self.max_windows = int(max_windows)
        self.samples_per_series = int(samples_per_series)
        self._clock = clock
        self._lock = threading.Lock()
        # the ring: CLOSED windows only, bounded by construction; the
        # open window lives in _current until a later observation (or
        # flush) rolls past its edge
        self._windows: deque = deque(maxlen=self.max_windows)
        self._current: Optional[_Window] = None
        self._floor_index = 0  # flush() bumps it: no duplicate indices
        self._listeners: List[Callable[[dict], None]] = []
        # close-dispatch machinery (started lazily by the first
        # add_close_listener; plain time-series users never pay for it)
        self._dispatch_cv = threading.Condition()
        self._pending: deque = deque(maxlen=4 * self.max_windows)
        self._dispatch_busy = False
        self._dispatch_stop = False
        self._dispatch_thread: Optional[threading.Thread] = None

    # -- feeding -----------------------------------------------------------
    def observe(self, series: str, value: Optional[float] = None,
                n: int = 1, nbytes: int = 0,
                ts: Optional[float] = None) -> None:
        """The one entry point: count ``n`` (and ``nbytes``) into the
        window containing ``ts`` (default: now), and when ``value`` is
        given, fold it into sum/max and the bounded sample buffer.
        A ``ts`` older than the open window folds into the open window
        (late cross-thread records never reopen closed windows)."""
        if ts is None:
            ts = self._clock()
        idx = int(ts // self.width_s)
        with self._lock:
            if idx < self._floor_index:
                # a mid-run flush() already closed this index: the
                # remainder of the wall-clock window lands in the next
                # one rather than duplicating a ring index
                idx = self._floor_index
            cur = self._current
            if cur is None:
                cur = self._current = _Window(idx, self.width_s)
            elif idx > cur.index:
                self._windows.append(cur)
                # enqueue INSIDE the rolling critical section: rolls
                # are serialized by _lock, so the dispatch queue sees
                # closed windows in index order (enqueuing after the
                # release let a preempted thread's window N arrive
                # after another thread's N+1, feeding detectors
                # history out of order)
                self._enqueue_close_locked(cur)
                cur = self._current = _Window(idx, self.width_s)
            agg = cur.series.get(series)
            if agg is None:
                agg = cur.series[series] = _SeriesAgg()
            agg.n += n
            agg.nbytes += nbytes
            if value is not None:
                v = float(value)
                agg.total += v
                if agg.vmax is None or v > agg.vmax:
                    agg.vmax = v
                if len(agg.samples) < self.samples_per_series:
                    agg.samples.append(v)
                else:
                    agg.capped = True

    def flush(self, drain_timeout_s: float = 10.0) -> None:
        """Close the open window NOW and WAIT for the close-dispatch
        queue to drain (detectors have evaluated every closed window
        when this returns — the harnesses flush then read trip counts).
        The trailing window of a finished run never sees a later
        observation, so detectors would otherwise never evaluate it —
        ``obs.disable`` calls this before tearing anything down."""
        with self._lock:
            closed, self._current = self._current, None
            if closed is not None:
                self._windows.append(closed)
                self._floor_index = closed.index + 1
                self._enqueue_close_locked(closed)
        if not self.drain(timeout_s=drain_timeout_s):
            logger.warning(
                "window-close dispatch did not drain within %.1fs — a "
                "listener is wedged; detector verdicts for the "
                "undispatched windows are MISSING, not clean",
                drain_timeout_s)

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every enqueued window close has been dispatched
        (False on timeout — a wedged listener must not hang teardown
        forever)."""
        deadline = time.monotonic() + timeout_s
        with self._dispatch_cv:
            while self._pending or self._dispatch_busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._dispatch_cv.wait(timeout=remaining)
        return True

    def close(self) -> None:
        """Stop the close-dispatch thread (module ``disable()`` calls
        this).  Pending windows are dropped; flush first if they
        matter."""
        with self._dispatch_cv:
            self._dispatch_stop = True
            self._dispatch_cv.notify_all()
            # snapshot under the cv — add_close_listener() lazily
            # spawns the thread under it, and an unlocked read here
            # races that spawn; the join stays OUTSIDE the cv
            # (ADVICE.md "A lock order is a declaration, not a
            # convention": joining under the cv the dispatch loop's
            # finally-block needs would deadlock the close)
            t = self._dispatch_thread
        if t is not None:
            t.join(timeout=5.0)

    # -- consuming ---------------------------------------------------------
    def add_close_listener(self, fn: Callable[[dict], None]) -> None:
        """``fn(window_snapshot)`` fires on every window close, on the
        store's dedicated dispatch thread — NEVER on the observing
        thread, whose caller may hold a hot-path lock (the serve
        batcher's admission path incs counters under its condition; a
        detector sweep + flight dump inline there would stall every
        lane at the exact overloaded moment the rules trip).  A raising
        listener is logged and dropped."""
        with self._lock:
            self._listeners.append(fn)
        with self._dispatch_cv:
            if self._dispatch_thread is None:
                self._dispatch_thread = threading.Thread(
                    target=self._dispatch_loop, name="obs-window-close",
                    daemon=True)
                self._dispatch_thread.start()

    def _dispatch_loop(self) -> None:
        while True:
            with self._dispatch_cv:
                while not self._pending and not self._dispatch_stop:
                    self._dispatch_cv.wait()
                if self._dispatch_stop:
                    self._dispatch_cv.notify_all()
                    return
                w = self._pending.popleft()
                self._dispatch_busy = True
            try:
                snap = self.window_snapshot(w, True)
                with self._lock:
                    listeners = list(self._listeners)
                for fn in listeners:
                    try:
                        fn(snap)
                    except Exception:
                        logger.warning(
                            "window-close listener raised; dropped",
                            exc_info=True)
            finally:
                with self._dispatch_cv:
                    self._dispatch_busy = False
                    self._dispatch_cv.notify_all()

    def window_snapshot(self, w: "_Window", closed: bool,
                        prefix: Optional[str] = None) -> Optional[dict]:
        """One window as a plain dict, or ``None`` when the ``prefix``
        filter leaves no series (filtering happens BEFORE the
        percentile sorts — a ``healthz`` scrape of the serve series
        must not pay for every replica fanout series it throws away)."""
        names = [n for n in w.series
                 if prefix is None or n.startswith(prefix)]
        if prefix is not None and not names:
            return None
        return {
            "index": w.index,
            "t_start": w.t_start,
            "t_end": w.t_end,
            "closed": closed,
            "series": {n: _series_snapshot(w.series[n]) for n in names},
        }

    def snapshot(self, prefix: Optional[str] = None,
                 last: Optional[int] = None) -> List[dict]:
        """The ring as plain dicts (closed windows oldest-first, then
        the open window) — the ``healthz``/watch surface.  ``prefix``
        filters series names; ``last`` keeps only the newest N
        windows.  Windows left empty by the filter are dropped.

        The OPEN window's aggregates are snapshotted UNDER the lock
        (already prefix-filtered, so the held time is small): observer
        threads mutate its series dict concurrently, and an unlocked
        iteration would race them (dict-changed-size crashes out of a
        healthz scrape).  Closed windows are immutable and snapshotted
        outside, newest-first, stopping at ``last`` non-empty ones —
        never paying percentile sorts for windows the caller drops."""
        with self._lock:
            closed_wins = list(self._windows)
            open_snap = (None if self._current is None
                         else self.window_snapshot(self._current, False,
                                                   prefix))
        want = None if last is None else int(last)
        out = [] if open_snap is None else [open_snap]
        for w in reversed(closed_wins):
            if want is not None and len(out) >= want:
                break
            snap = self.window_snapshot(w, True, prefix)
            if snap is not None:
                out.append(snap)
        out.reverse()
        if want is not None:
            out = out[-want:]
        return out

    def _enqueue_close_locked(self, w: "_Window") -> None:
        """Enqueue a closed window for the dispatch thread — O(1),
        called with ``_lock`` HELD (the lock ordering is always
        ``_lock`` -> ``_dispatch_cv``; the dispatch thread takes them
        one at a time, never nested, so no inversion).  Snapshotting
        and listener calls happen on the worker; a closed window is
        immutable, so handing the raw object over is safe.  No
        listeners registered = nothing enqueued."""
        if not self._listeners:
            return
        with self._dispatch_cv:
            if len(self._pending) == self._pending.maxlen:
                # a wedged listener backed the queue up to its bound:
                # the eviction must be LOUD — an unevaluated window is
                # a missing verdict, not a clean one
                logger.warning(
                    "window-close queue full (%d); dropping the oldest "
                    "pending window undispatched", len(self._pending))
            self._pending.append(w)
            self._dispatch_cv.notify_all()


# -- the module-level live store + hook plumbing -----------------------------

def observe_scalar(series: str, value: float) -> None:
    """Hot-path hook for HOST scalars the observed loops already hold
    (the per-step loss / weight-delta riding the scan ys).  NEVER pass
    a device value: formatting one forces a device->host sync at the
    record site (graftlint's obs-discipline rule flags it statically).
    Disabled cost: one module-global load + falsy branch."""
    if not _ENABLED:
        return
    st = _STORE
    if st is not None:
        st.observe(series, value=value)


def _on_span_close(name, dur_s, ts, attrs, error) -> None:
    st = _STORE
    if st is None:
        return
    st.observe(name, value=dur_s, ts=ts)
    if error:
        st.observe(name + ".error", ts=ts)
    key = SPAN_FANOUT.get(name)
    if key is not None:
        actor = attrs.get(key)
        if actor is not None:
            st.observe(f"{name}[{actor}]", value=dur_s, ts=ts)


def _on_event(name, ts, attrs) -> None:
    st = _STORE
    if st is None:
        return
    st.observe(name, ts=ts)
    for attr, only_if in EVENT_VALUES.get(name, ()):
        if only_if is not None and not attrs.get(only_if):
            continue
        v = attrs.get(attr)
        if v is not None:
            st.observe(f"{name}.{attr}", value=float(v), ts=ts)
    key = EVENT_FANOUT.get(name)
    if key is not None:
        actor = attrs.get(key)
        if actor is not None:
            fan = name + (".error" if attrs.get("error") else "")
            st.observe(f"{fan}[{actor}]", ts=ts)


def _forward_count(name, n, nbytes) -> None:
    st = _STORE
    if st is not None:
        st.observe(name, n=n, nbytes=nbytes)


def enable(width_s: float = 1.0, max_windows: int = 64,
           samples_per_series: int = 256) -> WindowStore:
    """Build THE live window store and attach it to the span-close /
    event / counter hooks.  Idempotent: a second enable keeps the
    running store (``obs.enable`` may be re-entered with a new trace
    path without losing windows).  Prefer the ``tpu_sgd.obs.enable``
    facade, which wires tracing/counters/detectors with it."""
    global _ENABLED, _STORE
    if _ENABLED and _STORE is not None:
        if (_STORE.width_s != float(width_s)
                or _STORE.max_windows != int(max_windows)):
            import warnings

            warnings.warn(
                "obs time-series already enabled with width_s="
                f"{_STORE.width_s}/max_windows={_STORE.max_windows}; "
                f"keeping the running store ({width_s}/{max_windows} "
                "ignored — disable() first to resize)",
                RuntimeWarning, stacklevel=3)
        return _STORE
    store = WindowStore(width_s=width_s, max_windows=max_windows,
                        samples_per_series=samples_per_series)
    _STORE = store
    from tpu_sgd.obs import counters as _counters
    from tpu_sgd.obs import spans as _spans

    _spans._ON_SPAN = _on_span_close
    _spans._ON_EVENT = _on_event
    _counters._GLOBAL.forward = _forward_count
    _ENABLED = True
    return store


def disable() -> None:
    """Detach every hook, stop the close-dispatch thread, and drop the
    store.  Idempotent.  Callers who want the trailing window evaluated
    flush FIRST (``obs.disable`` does)."""
    global _ENABLED, _STORE
    _ENABLED = False
    from tpu_sgd.obs import counters as _counters
    from tpu_sgd.obs import spans as _spans

    _spans._ON_SPAN = None
    _spans._ON_EVENT = None
    _counters._GLOBAL.forward = None
    store, _STORE = _STORE, None
    if store is not None:
        store.close()


def is_enabled() -> bool:
    return _ENABLED


def snapshot(prefix: Optional[str] = None,
             last: Optional[int] = None) -> Optional[List[dict]]:
    """The live store's window snapshots, or ``None`` when the
    time-series layer is off — the ``Server.healthz()`` /
    ``ReplicaDriver.windows()`` scrape surface."""
    st = _STORE
    if st is None:
        return None
    return st.snapshot(prefix=prefix, last=last)


def flush() -> None:
    """Close the open window of the live store (no-op when off)."""
    st = _STORE
    if st is not None:
        st.flush()
