"""tpu_sgd.obs: the unified observability layer.

Three pieces, one opt-in switch (ROADMAP items 1 and 3 both presuppose
this surface: straggler detection for async replicas needs per-stage
timings that run in production, and the closed production loop needs
SLO assertions evaluated over a trace):

* **span tracing** (:mod:`tpu_sgd.obs.spans`) — hierarchical,
  thread-aware ``span("train.superstep")`` regions and instant
  ``event(...)`` records wired through every hot path (ingest prefetch,
  superstep/resident cadence windows, serve batcher flushes, registry
  reloads, checkpoint save/restore, retry/breaker/failpoint incidents),
  emitted as ``trace_*`` JSONL records on the shared
  ``JsonLinesEventLog`` contract;
* **runtime counters** (:mod:`tpu_sgd.obs.counters`) — the
  test-twin monkeypatch machinery (``tpu_sgd.analysis.runtime``)
  promoted to an always-on accounting layer: program dispatches,
  compiles, host syncs, h2d/d2h transfer counts and bytes, io_callback
  firings, tagged by the subsystem whose span caused them;
* **the report pipeline** (:mod:`tpu_sgd.obs.report`) —
  ``python -m tpu_sgd.obs.report trace.jsonl`` renders per-stage
  breakdowns, counter deltas, p50/p99 tables, exports Chrome
  trace-event JSON (Perfetto), and evaluates declarative SLO files
  with CI-able exit codes.

Quickstart::

    from tpu_sgd import obs

    obs.enable("run_trace.jsonl")        # tracing + counters on
    ...                                   # train / serve as usual
    obs.disable()                         # flushes counters, closes log
    # then: python -m tpu_sgd.obs.report run_trace.jsonl --slo slo.json

Disabled (the default, forever, unless an operator opts in) every hook
is one module-global load and a falsy branch — the failpoints
discipline, measured in ``tests/test_obs.py``.  Enabled, the layer adds
wall-clock overhead but ZERO dispatches, compiles, or host syncs on the
warmed hot paths (the acceptance pin, measured with the
``tpu_sgd.analysis`` runtime twins; ``BENCH_OBS.json`` records both).
Span timestamps never force a device sync — see ADVICE.md "Span
timestamps are attribution, not truth".
"""

from __future__ import annotations

from typing import Optional

from tpu_sgd.obs import spans
from tpu_sgd.obs import counters
from tpu_sgd.obs.spans import (current_subsystem, disable_tracing,
                               enable_tracing, event, span)
from tpu_sgd.obs.counters import RuntimeCounters, deltas, inc, snapshot

__all__ = [
    "span", "event", "inc", "snapshot", "deltas", "RuntimeCounters",
    "enable", "disable", "flush_counters", "is_enabled",
    "enable_tracing", "disable_tracing", "current_subsystem",
    "spans", "counters",
]

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — the facade owns one GIL-atomic module reference
#: (``_OWNED_LOG``); all guarded state lives in the submodules.
GRAFTLINT_LOCKS: dict = {}

_OWNED_LOG = None  # a JsonLinesEventLog this facade opened (and closes)


def enable(trace=None, *, with_counters: bool = True,
           fsync: bool = False) -> None:
    """Turn the observability layer on.

    ``trace`` is a JSONL path (a ``JsonLinesEventLog`` is opened and
    owned — ``disable()`` closes it) or any sink with ``emit(kind,
    payload)`` (e.g. an event log shared with training/serving records,
    the chaos soak's spelling — caller keeps ownership).  ``None``
    enables counters only.  ``with_counters=False`` skips the runtime
    patches (tracing only)."""
    global _OWNED_LOG
    sink = owned = None
    if trace is not None:
        if hasattr(trace, "emit"):
            sink = trace
        else:
            from tpu_sgd.utils.events import JsonLinesEventLog

            sink = owned = JsonLinesEventLog(str(trace), fsync=fsync)
    if sink is not None:
        enable_tracing(sink)
        # re-enable with a NEW sink: close the log a previous enable()
        # opened (records already route to the new sink above) — a
        # second enable must not leak the first's file handle
        prev, _OWNED_LOG = _OWNED_LOG, owned
        if prev is not None and prev is not sink:
            prev.close()
    if with_counters:
        counters.enable()


def flush_counters() -> None:
    """Write the cumulative counter snapshot as one ``metric_counters``
    record on the trace sink (no-op without both sides enabled).  The
    report pipeline diffs consecutive flushes into window deltas."""
    sink = spans._SINK
    if sink is None or not counters.is_enabled():
        return
    import time

    try:
        sink.emit("metric_counters", {"ts": time.time(),
                                      "counters": counters.snapshot()})
    except Exception:
        import logging

        logging.getLogger("tpu_sgd.obs").warning(
            "trace sink raised; counter flush dropped", exc_info=True)


def disable() -> None:
    """Turn everything off: flush counters into the trace (if both were
    on), unwind the runtime patches, close an owned trace log.
    Idempotent."""
    global _OWNED_LOG
    flush_counters()
    counters.disable()
    disable_tracing()
    owned, _OWNED_LOG = _OWNED_LOG, None
    if owned is not None:
        owned.close()


def is_enabled() -> bool:
    return spans.is_enabled() or counters.is_enabled()
