"""tpu_sgd.obs: the unified observability layer.

Six pieces, one opt-in switch (ROADMAP items 1 and 3 both presuppose
this surface: straggler detection for async replicas needs per-stage
timings that run in production, and the closed production loop needs
SLO assertions evaluated over a trace):

* **span tracing** (:mod:`tpu_sgd.obs.spans`) — hierarchical,
  thread-aware ``span("train.superstep")`` regions and instant
  ``event(...)`` records wired through every hot path (ingest prefetch,
  superstep/resident cadence windows, serve batcher flushes, registry
  reloads, checkpoint save/restore, retry/breaker/failpoint incidents),
  emitted as ``trace_*`` JSONL records on the shared
  ``JsonLinesEventLog`` contract;
* **runtime counters** (:mod:`tpu_sgd.obs.counters`) — the
  test-twin monkeypatch machinery (``tpu_sgd.analysis.runtime``)
  promoted to an always-on accounting layer: program dispatches,
  compiles, host syncs, h2d/d2h transfer counts and bytes, io_callback
  firings, tagged by the subsystem whose span caused them;
* **windowed time-series** (:mod:`tpu_sgd.obs.timeseries`) — the LIVE
  half: a bounded ring of fixed-width windows over the span / counter /
  event streams (per-window count, sum, max, p50/p99 via the shared
  nearest-rank rule), memory bounded by window count, never run
  length.  On by default whenever the layer is enabled; the
  ``Server.healthz()`` ``windows`` snapshot and the watch CLI read it;
* **anomaly detectors** (:mod:`tpu_sgd.obs.detect`) — declarative
  rules evaluated per window close (loss divergence, staleness creep,
  shed-rate spikes, replica straggler skew, wire-ratio collapse,
  dispatch regression), each trip a typed ``obs_alert`` record on the
  one event stream plus an ``obs.alert.<rule>`` counter;
* **the flight recorder** (:mod:`tpu_sgd.obs.flightrec`) — a bounded
  ring of recent trace records dumped to a standalone
  ``flightrec.jsonl`` on any alert, error unwind, or explicit trigger,
  so post-mortems start from the incident's tail, not the full trace;
* **the report pipeline** (:mod:`tpu_sgd.obs.report`) —
  ``python -m tpu_sgd.obs.report trace.jsonl`` renders per-stage
  breakdowns (``--window`` adds time-bucketed tables), an alerts
  section, Chrome trace-event JSON (Perfetto), and declarative SLO
  files with CI-able exit codes; ``python -m tpu_sgd.obs.watch``
  tails a RUNNING trace live.

Quickstart::

    from tpu_sgd import obs

    obs.enable("run_trace.jsonl")        # tracing + counters + windows
    obs.enable("t.jsonl", detect=True,   # + detectors + flight recorder
               flightrec="flightrec.jsonl")
    ...                                   # train / serve as usual
    obs.disable()                         # flushes windows+counters, closes log
    # then: python -m tpu_sgd.obs.report run_trace.jsonl --slo slo.json
    # live: python -m tpu_sgd.obs.watch run_trace.jsonl

Disabled (the default, forever, unless an operator opts in) every hook
is one module-global load and a falsy branch — the failpoints
discipline, measured in ``tests/test_obs.py``.  Enabled, the layer adds
wall-clock overhead but ZERO dispatches, compiles, or host syncs on the
warmed hot paths (the acceptance pin, re-measured with the time-series
ON; ``BENCH_OBS.json`` records both, and ``scripts/bench_gate.py``
gates the committed headline counts in CI).  Span timestamps never
force a device sync — see ADVICE.md "Span timestamps are attribution,
not truth"; alert semantics — ADVICE.md "Alerts are typed events, not
log lines".
"""

from __future__ import annotations

from typing import Optional

from tpu_sgd.obs import counters
from tpu_sgd.obs import detect
from tpu_sgd.obs import flightrec
from tpu_sgd.obs import spans
from tpu_sgd.obs import timeseries
from tpu_sgd.obs.counters import RuntimeCounters, deltas, inc, snapshot
from tpu_sgd.obs.spans import (current_subsystem, disable_tracing,
                               enable_tracing, event, span)
from tpu_sgd.obs.timeseries import observe_scalar

__all__ = [
    "span", "event", "inc", "snapshot", "deltas", "RuntimeCounters",
    "enable", "disable", "flush_counters", "flush_windows", "is_enabled",
    "enable_tracing", "disable_tracing", "current_subsystem",
    "observe_scalar", "windows_snapshot", "detector_engine",
    "spans", "counters", "timeseries", "detect", "flightrec",
]

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — the facade owns GIL-atomic module references only
#: (``_OWNED_LOG``/``_ENGINE``); all guarded state lives in the
#: submodules.
GRAFTLINT_LOCKS: dict = {}

_OWNED_LOG = None  # a JsonLinesEventLog this facade opened (and closes)
_ENGINE = None     # the live DetectorEngine (when detect was requested)


def enable(trace=None, *, with_counters: bool = True,
           fsync: bool = False, timeseries: bool = True,
           window_s: float = 1.0, max_windows: int = 64,
           detect: bool = False, detectors=None,
           flightrec: Optional[str] = None,
           flightrec_capacity: int = 512) -> None:
    """Turn the observability layer on.

    ``trace`` is a JSONL path (a ``JsonLinesEventLog`` is opened and
    owned — ``disable()`` closes it) or any sink with ``emit(kind,
    payload)`` (e.g. an event log shared with training/serving records,
    the chaos soak's spelling — caller keeps ownership).  ``None``
    enables counters only.  ``with_counters=False`` skips the runtime
    patches (tracing only).

    The windowed time-series ride along by default
    (``timeseries=True``; ``window_s``/``max_windows`` shape the
    bounded ring).  ``detect=True`` (or an explicit ``detectors``
    list) registers the anomaly-detector engine on window closes;
    ``flightrec=<path>`` arms the flight recorder — the trace sink is
    teed through its ring, every detector alert and error-closing span
    triggers a dump there."""
    # the boolean/path kwargs shadow the submodule names by design (the
    # caller-facing spelling is `obs.enable(log, detect=True,
    # flightrec="f.jsonl")`); alias the modules locally
    from tpu_sgd.obs import detect as _detect
    from tpu_sgd.obs import flightrec as _flightrec
    from tpu_sgd.obs import timeseries as _timeseries

    global _OWNED_LOG, _ENGINE
    sink = owned = None
    if trace is not None:
        if hasattr(trace, "emit"):
            sink = trace
        else:
            from tpu_sgd.utils.events import JsonLinesEventLog

            sink = owned = JsonLinesEventLog(str(trace), fsync=fsync)
    want_detect = detect or detectors is not None
    if want_detect and sink is None:
        import warnings

        warnings.warn(
            "obs.enable(detect=True) without a trace sink: the span/"
            "event-fed series (replica.step fanout, push staleness) "
            "never record — straggler and staleness rules cannot fire; "
            "only counter-fed rules (shed-rate, dispatch, wire) work",
            RuntimeWarning, stacklevel=2)
    store = None
    if timeseries or want_detect:  # detectors presuppose windows
        store = _timeseries.enable(width_s=window_s,
                                   max_windows=max_windows)
    rec = None
    if flightrec is not None:
        rec = _flightrec.enable(flightrec,
                                capacity=flightrec_capacity,
                                window_source=_timeseries.snapshot)
        if sink is not None:
            sink = _flightrec.TeeSink(sink, rec)
    else:
        # a re-enable that does NOT arm a flight recorder must drop a
        # previous enable's: its ring stops being fed at the sink swap,
        # so later alert dumps would overwrite the preserved incident
        # with a stale tail (no-op on a first enable)
        _flightrec.disable()

    def _on_alert(a, _rec=rec):
        if _rec is not None:
            _rec.trigger(f"alert:{a.rule}", detail=a.series)

    if want_detect and _ENGINE is None:
        _ENGINE = _detect.DetectorEngine(detectors, on_alert=_on_alert)
        store.add_close_listener(_ENGINE.on_window_close)
    elif _ENGINE is not None:
        # the engine (and its detector state) survives a re-enable, but
        # alert dumps must route to THIS enable's flight recorder (or
        # nowhere), never a closure over the previous one
        _ENGINE.on_alert = _on_alert
    if sink is not None:
        enable_tracing(sink)
        # re-enable with a NEW sink: close the log a previous enable()
        # opened (records already route to the new sink above) — a
        # second enable must not leak the first's file handle
        prev, _OWNED_LOG = _OWNED_LOG, owned
        if prev is not None and prev is not sink:
            prev.close()
    if with_counters:
        counters.enable()


def flush_counters() -> None:
    """Write the cumulative counter snapshot as one ``metric_counters``
    record on the trace sink (no-op without both sides enabled).  The
    report pipeline diffs consecutive flushes into window deltas."""
    sink = spans._SINK
    if sink is None or not counters.is_enabled():
        return
    import time

    try:
        sink.emit("metric_counters", {"ts": time.time(),
                                      "counters": counters.snapshot()})
    except Exception:
        import logging

        logging.getLogger("tpu_sgd.obs").warning(
            "trace sink raised; counter flush dropped", exc_info=True)


def flush_windows() -> None:
    """Close the open time-series window NOW so its data is visible to
    snapshots and the detectors evaluate it — the trailing window of a
    finished phase never sees a later observation otherwise.
    ``disable()`` calls this first."""
    timeseries.flush()


def windows_snapshot(prefix: Optional[str] = None,
                     last: Optional[int] = None):
    """The live windowed time-series (``None`` when off) — the facade
    spelling of ``timeseries.snapshot`` that ``healthz`` probes use."""
    return timeseries.snapshot(prefix=prefix, last=last)


def detector_engine():
    """The live :class:`~tpu_sgd.obs.detect.DetectorEngine` (or
    ``None``): ``active_alerts()``/``trip_counts()`` scrape surface."""
    return _ENGINE


def disable() -> None:
    """Turn everything off: evaluate the trailing window, flush
    counters into the trace (if both were on), unwind the runtime
    patches, drop the time-series/detector/flight-recorder hooks,
    close an owned trace log.  Idempotent."""
    global _OWNED_LOG, _ENGINE
    flush_windows()  # detectors see the trailing window BEFORE teardown
    flush_counters()
    counters.disable()
    disable_tracing()
    timeseries.disable()
    flightrec.disable()
    _ENGINE = None
    owned, _OWNED_LOG = _OWNED_LOG, None
    if owned is not None:
        owned.close()


def is_enabled() -> bool:
    return (spans.is_enabled() or counters.is_enabled()
            or timeseries.is_enabled())
