"""Declarative anomaly detectors over the windowed time-series.

Alerts are TYPED EVENTS, not log lines (ADVICE.md "Alerts are typed
events, not log lines"): a detector never greps raw records — it
evaluates a CLOSED window's aggregates against a declared rule, and a
trip is DATA: one ``obs_alert`` record on the shared event stream (the
same lock-serialized JSONL every span/counter/listener record rides,
so ``obs.report``'s alerts section, the watch CLI, and the future
adaptive control plane all consume trips the same way they consume
everything else) plus an ``obs.alert.<rule>`` counter bump.  On every
trip the flight recorder dumps its ring (``tpu_sgd.obs.flightrec``) so
the post-mortem starts with the record, not a grep.

The rules (each a small class; :func:`default_detectors` builds the
production set):

* **loss-divergence** — the ``train.loss`` window mean grows past
  ``factor`` x the best trailing window mean (or goes non-finite).
  The companion :class:`LossPlateauDetector` (NOT in the defaults — a
  converged run plateaus legitimately; this one is the AdaBatch
  grow-the-batch sensor the control plane opts into) trips when the
  relative improvement across ``windows`` closed windows falls under
  ``eps``.
* **staleness-creep** — the ``replica.push.staleness`` window max (the
  store version gap of ACCEPTED pushes) exceeds ``max_staleness``.
* **shed-rate** — per serving lane, typed rejections over offered
  requests in the window (from the ``serve.admitted/rejected/shed/
  displaced.<lane>`` counter series) exceed ``threshold`` with at
  least ``min_offered`` offered.
* **replica-straggler** — a worker's ``replica.step[<wid>]`` series is
  SILENT while the rest of the fleet accumulates ``min_fleet_steps``
  steps (per-worker progress skew from the heartbeat-per-cycle span
  records, cumulative across windows so a loaded host that slows
  everyone equally trips nothing; fleet-wide silence — a finished
  round — trips nothing either).
* **wire-ratio-collapse** — a COMPRESSED wire format's window ratio
  (logical / physical bytes from the ``*.wire.<fmt>`` series) falls
  under ``min_ratio`` (dense-f32/bf16 are exempt: their ratios are 1x
  and 2x by construction).
* **dispatch-regression** — the ``train.dispatch`` window count jumps
  past ``factor`` x the median of the trailing closed windows (with a
  floor so idle phases cannot trip on noise): the live spelling of the
  bench gate's dispatch-count headline.
* **failover** — a ``replica.failover`` observation landed in the
  window (the promotion span and the membership event both feed the
  series): a store promotion is ALWAYS an incident worth a typed
  alert + flight-recorder dump, even when the system healed itself —
  a failover nobody noticed is a standby budget silently spent.
* **integrity** — any ``integrity.corrupt.<site>`` counter moved in
  the window (a checksum mismatch at a verified wire, a poisoned
  push): detected-and-HEALED corruption is still an incident — a bit
  flipping somewhere is a hardware/storage signal, and the one that
  finally slips through will look exactly like the ones that did not.
  A clean run never records the series, so the rule has no
  false-positive surface (ISSUE 15).
* **heartbeat-stall** — a WATCHED component's heartbeat series went
  silent for ``stall_windows`` consecutive windows while another
  watched component kept beating (hang was the one failure mode chaos
  could not see: a wedged feed raises nothing, it just stops).  The
  roster is membership-driven like the straggler rule:
  ``HealthMonitor.watch_heartbeat`` admits, ``unwatch_heartbeat``
  retires (so a finished run's silence never false-trips the next),
  and fleet-wide silence — an idle process — trips nothing.

Trip semantics: the engine tracks active ``(rule, series)`` pairs and
emits one ``obs_alert`` per TRANSITION into the tripped state; a rule
that stays tripped across consecutive windows stays one alert, and it
re-arms after a window that does not trip.  A raising detector is
logged and dropped — detection must never kill the observed path.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Alert", "Detector", "DetectorEngine", "default_detectors",
           "LossDivergenceDetector", "LossPlateauDetector",
           "StalenessCreepDetector", "LaneRejectionDetector",
           "StragglerDetector", "WireRatioDetector",
           "DispatchRegressionDetector", "FailoverDetector",
           "IntegrityDetector", "HeartbeatStallDetector",
           "ShardImbalanceDetector", "SlabThrashDetector"]

logger = logging.getLogger("tpu_sgd.obs")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the
#: engine's history ring, active-alert set, and trip tallies are
#: touched by whichever observing thread closed the window.
GRAFTLINT_LOCKS = {
    "DetectorEngine": {
        "_history": "_lock",
        "_active": "_lock",
        "_trips": "_lock",
    },
}


@dataclasses.dataclass
class Alert:
    """One typed detector trip — serialized verbatim as the
    ``obs_alert`` record's payload (plus the emit timestamp)."""

    rule: str
    series: str
    value: float
    bound: float
    window_index: int
    t_start: float
    t_end: float
    detail: str = ""


def _series(window: dict, name: str) -> Optional[dict]:
    return window["series"].get(name)


def _count(window: dict, name: str) -> int:
    s = _series(window, name)
    return int(s["count"]) if s else 0


class Detector:
    """One rule.  ``evaluate(window, history)`` receives the CLOSED
    window's snapshot and the engine's trailing closed-window snapshots
    (oldest first, NOT including ``window``) and returns the trips."""

    rule = "base"

    def evaluate(self, window: dict, history: List[dict]) -> List[Alert]:
        raise NotImplementedError

    def _alert(self, window: dict, series: str, value: float,
               bound: float, detail: str = "") -> Alert:
        return Alert(rule=self.rule, series=series, value=float(value),
                     bound=float(bound), window_index=window["index"],
                     t_start=window["t_start"], t_end=window["t_end"],
                     detail=detail)


class LossDivergenceDetector(Detector):
    rule = "loss-divergence"

    def __init__(self, series: str = "train.loss", factor: float = 2.5,
                 min_history: int = 3):
        self.series = series
        self.factor = float(factor)
        self.min_history = int(min_history)

    def evaluate(self, window, history):
        import math

        s = _series(window, self.series)
        if s is None or not s["count"]:
            return []
        mean = s["mean"]
        if not math.isfinite(mean):
            return [self._alert(window, self.series, mean, self.factor,
                                "non-finite window loss")]
        past = [w["series"][self.series]["mean"] for w in history
                if self.series in w["series"]
                and w["series"][self.series]["count"]]
        if len(past) < self.min_history:
            return []
        best = min(past)
        if best > 0 and mean > self.factor * best:
            return [self._alert(
                window, self.series, mean, self.factor * best,
                f"window mean loss {mean:.4g} vs best trailing "
                f"{best:.4g}")]
        return []


class LossPlateauDetector(Detector):
    """The AdaBatch grow-the-batch sensor (NOT in the defaults: a
    converged run plateaus legitimately — this is a control-plane
    actuation signal, an anomaly only when the operator says so)."""

    rule = "loss-plateau"

    def __init__(self, series: str = "train.loss", eps: float = 1e-3,
                 windows: int = 4):
        self.series = series
        self.eps = float(eps)
        self.windows = int(windows)

    def evaluate(self, window, history):
        means = [w["series"][self.series]["mean"] for w in history
                 if self.series in w["series"]
                 and w["series"][self.series]["count"]]
        s = _series(window, self.series)
        if s is None or not s["count"]:
            return []
        means.append(s["mean"])
        if len(means) < self.windows:
            return []
        tail = means[-self.windows:]
        lo, hi = min(tail), max(tail)
        denom = max(abs(hi), 1e-12)
        rel = (hi - lo) / denom
        if rel < self.eps:
            return [self._alert(window, self.series, rel, self.eps,
                                f"loss flat across {self.windows} "
                                "windows")]
        return []


class StalenessCreepDetector(Detector):
    rule = "staleness-creep"

    def __init__(self, series: str = "replica.push.staleness",
                 max_staleness: float = 8.0):
        self.series = series
        self.max_staleness = float(max_staleness)

    def evaluate(self, window, history):
        s = _series(window, self.series)
        if s is None or s["max"] is None:
            return []
        if s["max"] > self.max_staleness:
            return [self._alert(window, self.series, s["max"],
                                self.max_staleness,
                                "accepted-push version gap creeping")]
        return []


class LaneRejectionDetector(Detector):
    """shed-rate AND rejection-rate spikes, per lane, one rule: the
    typed-rejection fraction of the window's offered requests."""

    rule = "shed-rate"

    def __init__(self, threshold: float = 0.3, min_offered: int = 20):
        self.threshold = float(threshold)
        self.min_offered = int(min_offered)

    def evaluate(self, window, history):
        lanes = set()
        for name in window["series"]:
            for pref in ("serve.admitted.", "serve.rejected.",
                         "serve.shed.", "serve.displaced."):
                if name.startswith(pref):
                    lane = name[len(pref):]
                    if "." not in lane:
                        lanes.add(lane)
        out = []
        for lane in sorted(lanes):
            admitted = _count(window, f"serve.admitted.{lane}")
            rejected = _count(window, f"serve.rejected.{lane}")
            shed = _count(window, f"serve.shed.{lane}")
            displaced = _count(window, f"serve.displaced.{lane}")
            # offered counts each request once (a displaced request
            # already sits in admitted — the report's accounting rule)
            offered = admitted + rejected + shed
            if offered < self.min_offered:
                continue
            rate = (rejected + shed + displaced) / offered
            if rate > self.threshold:
                out.append(self._alert(
                    window, f"serve.lane.{lane}", rate, self.threshold,
                    f"{rejected + shed + displaced} typed rejections "
                    f"of {offered} offered"))
        return out


class StragglerDetector(Detector):
    """Trips when a worker has been SILENT while the rest of the fleet
    accumulated >= ``min_fleet_steps`` steps since its last step —
    cumulative across windows, so detection latency scales with fleet
    PROGRESS, not wall clock: a loaded host that slows everyone down
    equally never trips (the window-count spelling flaked exactly
    there — under ambient load no single window held enough survivor
    steps), while a dead worker trips on any host once its peers have
    provably moved on without it.  Fleet-wide silence (a finished
    round) accumulates nothing and can never trip.

    Threshold guidance: the replica store's SSP progress bound caps a
    LIVE worker's lag at ~``(n_workers - 1) * tau`` peer steps, so any
    ``min_fleet_steps`` above that is structurally reachable only by a
    dead/stalled worker.  Stateful (peer-step deficits per worker);
    the engine serializes evaluation under its lock.

    Membership rides the ``replica.join/rejoin/leave`` event fan-out
    (``timeseries.EVENT_FANOUT``): a join/rejoin admits (or resets) a
    worker — so one that joined but never stepped IS tracked and a
    spawn-stall becomes visible once peers move; a CLEAN leave removes
    the entry (a finished run or a deliberate scale-down must not
    leave a phantom accumulating deficit that false-trips the next
    fleet sharing this engine); a leave carrying an error (the
    ``replica.leave.error[...]`` twin) KEEPS the entry accumulating —
    a death is exactly what this rule exists to surface until the
    rejoin resets it."""

    rule = "replica-straggler"

    def __init__(self, prefix: str = "replica.step[",
                 min_fleet_steps: int = 10,
                 membership_prefix: str = "replica."):
        self.prefix = prefix
        self.min_fleet_steps = int(min_fleet_steps)
        self.membership_prefix = membership_prefix
        self._behind: Dict[str, int] = {}  # wid -> peer steps since its last

    def _membership(self, window) -> None:
        mp = self.membership_prefix
        if (mp + "failover") in window["series"]:
            # a store failover stalls the WHOLE fleet (workers re-route,
            # re-pull, recompute): the roster survives, but accumulated
            # deficits from the promotion window are re-routing latency,
            # not straggling — reset so a healed failover never
            # false-trips the worker that happened to be mid-push
            for wid in self._behind:
                self._behind[wid] = 0
        for name in window["series"]:
            for kind in ("join[", "rejoin["):
                pre = mp + kind
                if name.startswith(pre) and name.endswith("]"):
                    actor = name[len(pre):-1]
                    self._behind[f"{self.prefix}{actor}]"] = 0
            pre = mp + "leave["  # the CLEAN leave only — never .error
            if name.startswith(pre) and name.endswith("]"):
                actor = name[len(pre):-1]
                self._behind.pop(f"{self.prefix}{actor}]", None)

    def evaluate(self, window, history):
        self._membership(window)
        counts = {n: int(window["series"][n]["count"])
                  for n in window["series"]
                  if n.startswith(self.prefix)}
        for wid in counts:
            self._behind.setdefault(wid, 0)
        if len(self._behind) < 2:
            return []
        total = sum(counts.values())
        out = []
        for wid in sorted(self._behind):
            c = counts.get(wid, 0)
            if c > 0:
                self._behind[wid] = 0  # it stepped: caught up
                continue
            self._behind[wid] += total - c
            if self._behind[wid] >= self.min_fleet_steps:
                out.append(self._alert(
                    window, wid, float(self._behind[wid]),
                    float(self.min_fleet_steps),
                    f"fleet ran {self._behind[wid]} step(s) since this "
                    "worker's last"))
        return out


class WireRatioDetector(Detector):
    rule = "wire-ratio-collapse"

    #: formats whose ratio is fixed by construction, never a collapse
    EXEMPT = ("dense-f32", "bf16")

    def __init__(self, min_ratio: float = 1.1, min_bytes: int = 4096):
        self.min_ratio = float(min_ratio)
        self.min_bytes = int(min_bytes)

    def evaluate(self, window, history):
        out = []
        for name, s in sorted(window["series"].items()):
            if ".wire." not in name or name.endswith(".logical"):
                continue
            fmt = name.rsplit(".", 1)[1]
            # per-instance fan-out (record_wire's tag arg — e.g. the
            # sharded store's ``...wire.dense-f32[s0]``): the exempt
            # list keys on the FORMAT, so strip the bracket suffix
            if fmt.endswith("]") and "[" in fmt:
                fmt = fmt[:fmt.index("[")]
            if fmt in self.EXEMPT:
                continue
            phys = s["bytes"]
            if phys < self.min_bytes:
                continue
            logical = window["series"].get(name + ".logical",
                                           {"bytes": 0})["bytes"]
            if logical <= 0:
                # record_wire emits physical and logical as two incs; a
                # window roll can land them one window apart, leaving a
                # physical-only window — unevaluable, not a collapse
                continue
            ratio = logical / phys
            if ratio < self.min_ratio:
                out.append(self._alert(
                    window, name, ratio, self.min_ratio,
                    f"{phys} physical vs {logical} logical bytes"))
        return out


class ShardImbalanceDetector(Detector):
    """Sharded-store balance sensor (NOT in the defaults — the
    ``LossPlateauDetector`` precedent: an operator opt-in, not an
    anomaly by default).  Contiguous equal-width ranges make DENSE
    push routing balanced by construction; on a COMPRESSED workload
    the per-shard ``replica.shard.push[sK]`` counts follow where the
    top-k mass concentrates, and a shard going quiet means one
    pipeline does most of the combine work — the sharding stopped
    paying.  Trips per lagging shard when its window count falls below
    ``min_frac`` of the busiest shard's (floor ``min_count`` on the
    busiest, so idle windows cannot trip on noise)."""

    rule = "shard-imbalance"

    def __init__(self, prefix: str = "replica.shard.push",
                 min_frac: float = 0.5, min_count: int = 8):
        self.prefix = prefix
        self.min_frac = float(min_frac)
        self.min_count = int(min_count)

    def evaluate(self, window, history):
        counts = {}
        for name, s in window["series"].items():
            if (name.startswith(self.prefix + "[")
                    and name.endswith("]")):
                counts[name] = int(s["count"])
        if len(counts) < 2:
            return []
        busiest = max(counts.values())
        if busiest < self.min_count:
            return []
        out = []
        for name, c in sorted(counts.items()):
            if c < self.min_frac * busiest:
                out.append(self._alert(
                    window, name, float(c), self.min_frac * busiest,
                    f"{c} shard pushes vs busiest shard's {busiest}"))
        return out


class SlabThrashDetector(Detector):
    """Tenant-slab churn sensor (NOT in the defaults — the
    ``ShardImbalanceDetector`` precedent: an operator opt-in for
    deployments running ``tpu_sgd/tenant``).  A healthy slab admits a
    tenant once and serves it many times; when the working set exceeds
    capacity, every admission evicts a tenant the NEXT batch re-admits
    — each predict pays a disk restore plus a row-set dispatch, the
    latency cliff ``plan.choose_slab_capacity`` exists to prevent.
    Trips when the window's ``tenant.evict`` count exceeds
    ``max_evict_frac`` of its ``tenant.admit`` count (floor
    ``min_admits`` on admissions, so a cold-start fill — all admits,
    no evicts — and idle windows cannot trip)."""

    rule = "slab-thrash"

    def __init__(self, max_evict_frac: float = 0.5, min_admits: int = 16):
        self.max_evict_frac = float(max_evict_frac)
        self.min_admits = int(min_admits)

    def evaluate(self, window, history):
        admits = _count(window, "tenant.admit")
        if admits < self.min_admits:
            return []
        evicts = _count(window, "tenant.evict")
        bound = self.max_evict_frac * admits
        if evicts > bound:
            return [self._alert(
                window, "tenant.evict", float(evicts), bound,
                f"{evicts} evictions vs {admits} admissions — working "
                "set exceeds slab capacity")]
        return []


class DispatchRegressionDetector(Detector):
    rule = "dispatch-regression"

    def __init__(self, series: str = "train.dispatch",
                 factor: float = 3.0, min_history: int = 3,
                 floor: int = 20):
        self.series = series
        self.factor = float(factor)
        self.min_history = int(min_history)
        self.floor = int(floor)

    def evaluate(self, window, history):
        n = _count(window, self.series)
        past = sorted(_count(w, self.series) for w in history
                      if self.series in w["series"])
        if len(past) < self.min_history:
            return []
        median = past[len(past) // 2]
        if median < self.floor:
            return []  # idle/low-rate phases cannot trip on noise
        if n > self.factor * median:
            return [self._alert(
                window, self.series, n, self.factor * median,
                f"{n} dispatches vs trailing median {median}")]
        return []


class FailoverDetector(Detector):
    """Trips whenever a ``replica.failover`` observation lands in the
    window — the promotion span close and the membership event both
    feed the series, and a clean run records neither, so the rule has
    no false-positive surface.  The trip's ``obs_alert`` (plus the
    flight-recorder dump the engine's ``on_alert`` hook triggers) is
    the post-mortem's entry point for a store promotion."""

    rule = "failover"

    def __init__(self, series: str = "replica.failover"):
        self.series = series

    def evaluate(self, window, history):
        n = _count(window, self.series)
        if n < 1:
            return []
        return [self._alert(
            window, self.series, float(n), 1.0,
            "store primary promoted (see the replica.failover span / "
            "membership record for old/new primary, epoch, gap)")]


class IntegrityDetector(Detector):
    """Trips when any ``integrity.corrupt.<site>`` counter series moved
    in the window — one alert per site, value = corrupt frames seen.
    Detected-and-healed corruption still alerts ON PURPOSE (module
    docstring): the checksum plane turns silent damage into typed
    retries, and this rule turns the retries into an incident a human
    sees.  A clean run never records the series — no false-positive
    surface, same construction as :class:`FailoverDetector`."""

    rule = "integrity"

    def __init__(self, prefix: str = "integrity.corrupt.",
                 min_frames: int = 1):
        self.prefix = prefix
        self.min_frames = int(min_frames)

    def evaluate(self, window, history):
        out = []
        for name in sorted(window["series"]):
            if not name.startswith(self.prefix):
                continue
            n = _count(window, name)
            if n >= self.min_frames:
                out.append(self._alert(
                    window, name, float(n), float(self.min_frames),
                    f"{n} corrupt frame(s) detected at "
                    f"{name[len(self.prefix):]!r} this window"))
        return out


class HeartbeatStallDetector(Detector):
    """Trips when a WATCHED heartbeat is silent ``stall_windows``
    consecutive windows while at least one other watched heartbeat
    kept beating — the hang detector (class-level rationale in the
    module docstring).

    Roster discipline mirrors :class:`StragglerDetector`'s membership
    rule, with ``HealthMonitor.watch_heartbeat`` /
    ``unwatch_heartbeat`` as the join/leave events
    (``reliability.hb.watch[...]`` / ``...unwatch[...]`` series): only
    DECLARED-should-beat components are candidates (an idle batcher is
    silent and healthy — first-beat auto-join would false-trip every
    quiet component), a retire removes the entry so a clean shutdown
    cannot leave a phantom for the next run sharing this engine, and
    the any-peer-progressed gate makes fleet-wide silence (an idle or
    finished process) trip nothing.  Stateful; the engine serializes
    evaluation under its lock."""

    rule = "heartbeat-stall"

    def __init__(self, prefix: str = "reliability.heartbeat[",
                 roster_prefix: str = "reliability.hb.",
                 stall_windows: int = 4):
        self.prefix = prefix
        self.roster_prefix = roster_prefix
        self.stall_windows = int(stall_windows)
        self._silent: Dict[str, int] = {}  # name -> silent windows

    def _membership(self, window) -> None:
        rp = self.roster_prefix
        for name in window["series"]:
            if name.startswith(rp + "watch[") and name.endswith("]"):
                self._silent.setdefault(
                    name[len(rp) + len("watch["):-1], 0)
            elif name.startswith(rp + "unwatch[") and name.endswith("]"):
                self._silent.pop(
                    name[len(rp) + len("unwatch["):-1], None)

    def evaluate(self, window, history):
        self._membership(window)
        if not self._silent:
            return []
        beats = {name: _count(window, f"{self.prefix}{name}]")
                 for name in self._silent}
        if not any(beats.values()):
            return []  # fleet-wide silence: idle/finished, not a hang
        out = []
        for name in sorted(self._silent):
            if beats[name] > 0:
                self._silent[name] = 0
                continue
            self._silent[name] += 1
            if self._silent[name] >= self.stall_windows:
                out.append(self._alert(
                    window, f"{self.prefix}{name}]",
                    float(self._silent[name]),
                    float(self.stall_windows),
                    f"watched heartbeat {name!r} silent for "
                    f"{self._silent[name]} windows while peers beat"))
        return out


def default_detectors() -> List[Detector]:
    """The production rule set (the ISSUE 13 six, the failover rule,
    and ISSUE 15's integrity + heartbeat-stall rules).  Thresholds are
    the wide, low-false-positive defaults a clean seeded run never
    trips (pinned in tests); harnesses tighten per scenario."""
    return [
        LossDivergenceDetector(),
        StalenessCreepDetector(),
        LaneRejectionDetector(),
        StragglerDetector(),
        WireRatioDetector(),
        DispatchRegressionDetector(),
        FailoverDetector(),
        IntegrityDetector(),
        HeartbeatStallDetector(),
    ]


class DetectorEngine:
    """Evaluates a detector set per window close; registered with the
    live :class:`~tpu_sgd.obs.timeseries.WindowStore` by the
    ``tpu_sgd.obs.enable`` facade."""

    def __init__(self, detectors: Optional[Sequence[Detector]] = None,
                 history: int = 16,
                 on_alert: Optional[Callable[[Alert], None]] = None):
        self.detectors = list(detectors if detectors is not None
                              else default_detectors())
        self.on_alert = on_alert
        self._lock = threading.Lock()
        self._history: deque = deque(maxlen=int(history))
        self._active: Dict[tuple, Alert] = {}
        self._trips: Dict[str, int] = {}

    # -- the window-close listener ----------------------------------------
    def on_window_close(self, window: dict) -> None:
        tripped: Dict[tuple, Alert] = {}
        # evaluation runs UNDER the lock: two threads can close
        # back-to-back windows concurrently (closes fire outside the
        # store lock), and stateful detectors (StragglerDetector's
        # per-worker deficits) must see them serialized
        with self._lock:
            history = list(self._history)
            self._history.append(window)
            for det in self.detectors:
                try:
                    for alert in det.evaluate(window, history):
                        tripped[(alert.rule, alert.series)] = alert
                except Exception:
                    logger.warning(
                        "detector %r raised; skipped this window",
                        getattr(det, "rule", det), exc_info=True)
            fresh = [a for k, a in tripped.items()
                     if k not in self._active]
            self._active = tripped
            for a in fresh:
                self._trips[a.rule] = self._trips.get(a.rule, 0) + 1
        for alert in fresh:  # emit OUTSIDE the lock (sink IO, counters)
            self._emit(alert)

    def _emit(self, alert: Alert) -> None:
        from tpu_sgd.obs import counters as _counters
        from tpu_sgd.obs import spans as _spans

        _counters.inc(f"obs.alert.{alert.rule}")
        sink = _spans._SINK
        if sink is not None:
            payload = dataclasses.asdict(alert)
            payload["ts"] = time.time()
            try:
                sink.emit("obs_alert", payload)
            except Exception:
                logger.warning("trace sink raised; alert record dropped",
                               exc_info=True)
        if self.on_alert is not None:
            try:
                self.on_alert(alert)
            except Exception:
                logger.warning("on_alert hook raised; dropped",
                               exc_info=True)

    # -- scrape surface ----------------------------------------------------
    def active_alerts(self) -> List[Alert]:
        with self._lock:
            return list(self._active.values())

    def trip_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._trips)
