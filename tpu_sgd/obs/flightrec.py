"""The flight recorder: a bounded ring of recent trace records dumped
whole on trouble.

A production trace can run to millions of records; the forensics that
matter are the LAST few hundred — what the system was doing when the
alert tripped, the SLO broke, or the error unwound.  The flight
recorder keeps exactly that: a bounded in-memory ring of every record
the observability layer emits (spans, instant events, counter flushes,
alerts — it tees the trace sink, so the ring is byte-for-byte the
trace's tail), and on any **trigger** writes a standalone
``flightrec.jsonl``:

* record 0: a ``flightrec_meta`` header — trigger reason, timestamp,
  dump ordinal, ring size;
* the ring, oldest first, each record's original ``kind`` preserved;
* one ``obs_window`` record per live time-series window snapshot
  (``tpu_sgd.obs.timeseries``) — the windowed tables a post-mortem
  renders without replaying the full trace.

Triggers: every detector alert transition (wired by the
``tpu_sgd.obs.enable`` facade), every span that closes with an error
(the tee sees ``error`` on the ``trace_span`` record), and explicit
:func:`trigger` calls (the chaos/scenario harnesses fire one when an
invariant or SLO gate fails).  Each dump REPLACES the file via an
atomic rename — the newest incident wins, and a reader never sees a
half-written dump.

Cost: ring appends are O(1) deque ops under one lock; a dump is file
IO on the triggering thread (errors and alert transitions are rare by
definition — steady state pays only the append).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["FlightRecorder", "enable", "disable", "is_enabled",
           "trigger", "TeeSink"]

logger = logging.getLogger("tpu_sgd.obs")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the ring
#: is appended by every emitting thread and drained by dumps; the dump
#: counter rides the same lock.  ``_REC`` is a GIL-atomic module
#: reference (the ``obs.spans`` ``_SINK`` pattern).
GRAFTLINT_LOCKS = {
    "FlightRecorder": {
        "_ring": "_lock",
        "_dumps": "_lock",
        # the rate-limit clock: an undeclared read-modify-write lets
        # two concurrent triggers both pass the min-interval check and
        # dump twice (declared since ISSUE 19; accesses were already
        # locked, the declaration makes drift fail lint)
        "_last_dump_t": "_lock",
    },
}

_REC: Optional["FlightRecorder"] = None


class FlightRecorder:
    """See module docstring."""

    def __init__(self, path: str, capacity: int = 512,
                 window_source: Optional[Callable[[], Optional[list]]]
                 = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = str(path)
        self.capacity = int(capacity)
        self.window_source = window_source
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._dumps = 0
        self._last_dump_t = float("-inf")

    def record(self, kind: str, payload: dict) -> None:
        with self._lock:
            self._ring.append((kind, dict(payload)))

    def trigger(self, reason: str, detail: str = "",
                min_interval_s: Optional[float] = None) -> Optional[str]:
        """Dump the ring + live window snapshots to ``self.path``
        (atomic rename; the newest dump wins).  Returns the path, or
        ``None`` when the dump failed OR was rate-limited (logged,
        never raised — the recorder must not kill the path that
        triggered it).

        ``min_interval_s`` debounces ROUTINE trigger classes: under
        fault injection, error-closing spans are a per-retry
        occurrence, and serializing the whole ring on the stressed
        thread for each — then overwriting the incident that mattered
        — would make the recorder worse than useless.  Alert
        transitions and explicit triggers pass ``None`` and always
        dump; a skipped dump still leaves its records in the ring for
        the next one."""
        with self._lock:
            now = time.monotonic()
            if (min_interval_s is not None
                    and now - self._last_dump_t < min_interval_s):
                return None
            self._last_dump_t = now
            records = list(self._ring)
            self._dumps += 1
            ordinal = self._dumps
        windows = None
        if self.window_source is not None:
            try:
                windows = self.window_source()
            except Exception:
                logger.warning("flight recorder window source raised",
                               exc_info=True)
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    "kind": "flightrec_meta", "ts": time.time(),
                    "reason": reason, "detail": detail,
                    "dump_ordinal": ordinal, "records": len(records),
                    "windows": len(windows) if windows else 0,
                }, default=float) + "\n")
                for kind, payload in records:
                    f.write(json.dumps({"kind": kind, **payload},
                                       default=float) + "\n")
                for w in windows or ():
                    f.write(json.dumps({"kind": "obs_window", **w},
                                       default=float) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            logger.warning("flight recorder dump to %r failed",
                           self.path, exc_info=True)
            return None
        return self.path

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps


class TeeSink:
    """Wraps a trace sink: every record passes through to the inner
    sink AND lands in the flight recorder's ring; a span record closing
    with an ``error`` triggers a dump (the error-unwind forensics
    contract), DEBOUNCED to one per ``error_dump_interval_s`` — under
    fault injection error spans are routine, and a per-retry full-ring
    dump on the stressed thread (each overwriting the last incident)
    would defeat the recorder.  The ring append happens FIRST so a
    dump includes the record that triggered it, and skipped dumps'
    records survive in the ring for the next trigger."""

    def __init__(self, inner, recorder: FlightRecorder,
                 error_dump_interval_s: float = 5.0):
        self.inner = inner
        self.recorder = recorder
        self.error_dump_interval_s = float(error_dump_interval_s)

    def emit(self, kind: str, payload: dict) -> None:
        self.recorder.record(kind, payload)
        if kind == "trace_span" and payload.get("error"):
            self.recorder.trigger(
                f"span-error:{payload.get('name', '?')}",
                detail=str(payload["error"]),
                min_interval_s=self.error_dump_interval_s)
        self.inner.emit(kind, payload)


def enable(path: str, capacity: int = 512,
           window_source=None) -> FlightRecorder:
    """Install THE live flight recorder (prefer the ``tpu_sgd.obs``
    facade's ``flightrec=`` knob, which also tees the trace sink and
    wires detector-alert triggers)."""
    global _REC
    rec = FlightRecorder(path, capacity=capacity,
                         window_source=window_source)
    _REC = rec
    return rec


def disable() -> None:
    global _REC
    _REC = None


def is_enabled() -> bool:
    return _REC is not None


def trigger(reason: str, detail: str = "") -> Optional[str]:
    """Explicit trigger against the live recorder (the harness hook for
    invariant/SLO-gate failures); no-op returning ``None`` when off."""
    rec = _REC
    if rec is None:
        return None
    return rec.trigger(reason, detail=detail)
