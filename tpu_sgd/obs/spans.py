"""Hierarchical span tracing: the low-overhead production half of the
observability layer.

The repo's timing signals were fragments — ``StepTimer`` wall clocks in
benches, ``wall_time_s`` on iteration events, ad-hoc ``perf_counter``
pairs in drivers.  A **span** unifies them: a named region with a
monotonic start/duration, a thread-local parent (so nested regions form
a tree), and arbitrary host-scalar attributes, emitted as one
``trace_span`` JSONL record through the shared event-log contract
(``tpu_sgd.utils.events.JsonLinesEventLog``; ``obs.report`` turns the
records into per-stage breakdowns, Chrome trace-event JSON, and SLO
verdicts)::

    from tpu_sgd.obs.spans import span, event

    with span("train.superstep", i0=i0, steps=steps):
        ...                       # device dispatch + host replay
    event("reliability.retry", attempt=2, error="FaultInjected")

Cost contract (the failpoints discipline, measured in
``tests/test_obs.py``): DISABLED — the only state a production process
runs in unless an operator opts in — is ONE module-global load and a
falsy branch; ``span(...)`` returns a shared no-op singleton, allocates
nothing, and formats nothing.  Enabling (``tpu_sgd.obs.enable``) routes
records to a sink; a raising sink drops the record and never kills the
observed hot path.

Thread-awareness: each thread keeps its own span stack, so the ingest
prefetch worker, the serving flush thread, and the io_callback thread
each nest their own spans correctly instead of parenting onto whatever
the main thread happens to be doing.  The current span's first dotted
segment (``train.superstep`` -> ``train``) is published as the thread's
*subsystem tag*, which ``obs.counters`` uses to attribute patch-counted
dispatches/syncs/transfers to the subsystem that caused them.

Timestamp truth contract (ADVICE.md "Span timestamps are attribution,
not truth"): spans time the HOST region only and must NEVER call
``block_until_ready`` (or any other sync) to "include device time" —
under async dispatch that would turn every traced hot loop back into
lockstep, which is precisely what the resident/superstep drivers exist
to avoid (and what graftlint's host-sync rule + the windows+3 sync pin
in ``tests/test_resident.py`` enforce).  Counts and bytes
(``obs.counters``) are the truth on this harness; span durations
attribute where host wall clock went.

A ``jax.profiler`` capture rides the span API: ``span("train.run",
profile_dir="/tmp/jaxtrace")`` brackets the region with
``jax.profiler.start_trace``/``stop_trace`` (TensorBoard/Perfetto),
so a deep-dive capture attaches to exactly one traced region.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time

__all__ = ["span", "event", "enable_tracing", "disable_tracing",
           "is_enabled", "current_subsystem"]

logger = logging.getLogger("tpu_sgd.obs")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose, and load-bearing as documentation.  All mutable tracing
#: state is either thread-local (the per-thread span stack and
#: subsystem tag in ``_TL``) or a GIL-atomic single reference
#: (``_SINK``, swapped whole by enable/disable; ``_IDS`` is an atomic
#: ``itertools.count``).  Record serialization is the SINK's problem —
#: ``JsonLinesEventLog`` already lock-serializes its writes.  Adding
#: shared mutable state to this module means adding a lock AND
#: declaring it here.
GRAFTLINT_LOCKS: dict = {}

#: fast-path gate: ``span()``/``event()`` read this ONE module global
#: and return when falsy — the entire disabled-mode cost (the
#: failpoints discipline; measured no-op in tests/test_obs.py)
_ENABLED = False

_SINK = None                  # object with .emit(kind, payload)
_IDS = itertools.count(1)     # process-wide span ids (atomic under GIL)
_TL = threading.local()       # .stack: list of _Span; .tag: str

#: the windowed time-series hooks (``tpu_sgd.obs.timeseries`` installs
#: them): ``_ON_SPAN(name, dur_s, ts, attrs, error)`` fires on every
#: span close, ``_ON_EVENT(name, ts, attrs)`` on every instant event —
#: both GIL-atomic single references swapped whole like ``_SINK``, both
#: pure host work (the zero-added-runtime-events pin holds with the
#: time-series ON), and a raising hook is dropped, never propagated.
_ON_SPAN = None
_ON_EVENT = None


def _stack():
    st = getattr(_TL, "stack", None)
    if st is None:
        st = _TL.stack = []
    return st


def current_subsystem() -> str:
    """The accounting tag of the innermost open span on THIS thread
    (its first dotted name segment), or ``"untagged"`` — how
    ``obs.counters`` attributes patch-counted dispatches/syncs to the
    subsystem whose region caused them."""
    return getattr(_TL, "tag", "untagged")


class _NoopSpan:
    """The disabled-mode singleton: every ``span(...)`` call returns
    THIS object, so the disabled hot path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "span_id", "parent_id", "ts", "t0",
                 "_profile_dir")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self._profile_dir = attrs.pop("profile_dir", None)
        self.attrs = attrs
        self.span_id = next(_IDS)
        self.parent_id = 0
        self.ts = 0.0
        self.t0 = 0.0

    def set(self, **attrs):
        """Attach host-scalar attributes after entry (e.g. a batch size
        known only mid-region).  NEVER pass device values: formatting
        one forces a device->host sync (graftlint's obs-discipline
        check flags that statically)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        st = _stack()
        self.parent_id = st[-1].span_id if st else 0
        st.append(self)
        _TL.tag = self.name.split(".", 1)[0]
        # epoch ts for cross-record joins (staleness SLOs), monotonic
        # t0 for durations and the Chrome trace timeline
        self.ts = time.time()
        if self._profile_dir is not None:
            try:
                import jax

                jax.profiler.start_trace(self._profile_dir)
            except Exception:
                logger.warning("jax.profiler.start_trace failed; span "
                               "continues untraced", exc_info=True)
                self._profile_dir = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        # duration FIRST: the profiler stop below is not part of the
        # traced region's cost
        dur = time.perf_counter() - self.t0
        if self._profile_dir is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                logger.warning("jax.profiler.stop_trace failed",
                               exc_info=True)
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        _TL.tag = st[-1].name.split(".", 1)[0] if st else "untagged"
        sink = _SINK
        if sink is not None:
            payload = {
                "name": self.name,
                "ts": self.ts,
                "t0_s": self.t0,
                "dur_s": dur,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "thread": threading.current_thread().name,
                "error": (exc_type.__name__
                          if exc_type is not None else None),
            }
            payload.update(self.attrs)
            try:
                sink.emit("trace_span", payload)
            except Exception:  # observability must never kill hot paths
                logger.warning("trace sink raised; span record dropped",
                               exc_info=True)
        hook = _ON_SPAN
        if hook is not None:
            try:
                hook(self.name, dur, self.ts, self.attrs,
                     exc_type.__name__ if exc_type is not None else None)
            except Exception:
                logger.warning("time-series span hook raised; dropped",
                               exc_info=True)
        return False


def span(name: str, **attrs):
    """Open a trace span.  No-op singleton when tracing is disabled
    (one global load + branch); otherwise a context manager that emits
    one ``trace_span`` record on exit.

    ``attrs`` must be HOST scalars/strings — a device value here forces
    a sync when the record serializes (statically flagged by graftlint).
    ``profile_dir=<dir>`` additionally brackets the region with
    ``jax.profiler`` start/stop for a TensorBoard/Perfetto deep dive."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, attrs)


def event(name: str, **attrs) -> None:
    """Emit one instant ``trace_event`` record (a point, not a region):
    retry attempts, breaker transitions, failpoint triggers, reload
    decisions.  Same cost/discipline contract as :func:`span`."""
    if not _ENABLED:
        return
    sink = _SINK
    if sink is None:
        return
    payload = {
        "name": name,
        "ts": time.time(),
        "t0_s": time.perf_counter(),
        "thread": threading.current_thread().name,
        "subsystem": current_subsystem(),
    }
    payload.update(attrs)
    try:
        sink.emit("trace_event", payload)
    except Exception:
        logger.warning("trace sink raised; event record dropped",
                       exc_info=True)
    hook = _ON_EVENT
    if hook is not None:
        try:
            hook(name, payload["ts"], attrs)
        except Exception:
            logger.warning("time-series event hook raised; dropped",
                           exc_info=True)


def enable_tracing(sink) -> None:
    """Route spans/events to ``sink`` (anything with ``emit(kind,
    payload)`` — a ``JsonLinesEventLog``) and open the gate.  Use the
    ``tpu_sgd.obs.enable`` facade unless you are wiring a custom sink."""
    global _SINK, _ENABLED
    _SINK = sink
    _ENABLED = True


def disable_tracing() -> None:
    """Close the gate and drop the sink reference (the caller owns the
    sink's lifecycle — a ``JsonLinesEventLog`` still needs ``close()``)."""
    global _SINK, _ENABLED
    _ENABLED = False
    _SINK = None


def is_enabled() -> bool:
    return _ENABLED
