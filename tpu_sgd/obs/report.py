"""Trace/SLO report pipeline: JSONL trace -> breakdowns, Chrome trace,
SLO verdict.

The consuming half of the observability layer (spans + counters write,
this module reads)::

    python -m tpu_sgd.obs.report events.jsonl            # stage tables
    python -m tpu_sgd.obs.report events.jsonl --chrome t.json   # Perfetto
    python -m tpu_sgd.obs.report events.jsonl --slo slo.json    # verdict

* **Per-stage breakdowns** — ``trace_span`` records grouped by name:
  count, total/mean wall, p50/p99/max (nearest-rank, the same
  percentile rule ``serve.metrics.ServingMetrics`` scrapes with).
* **Counter deltas** — ``metric_counters`` records (cumulative
  snapshots flushed by ``tpu_sgd.obs``): last minus first, so a trace
  covering one soak reports what THAT soak spent.
* **Chrome trace-event export** — spans become ``ph:"X"`` complete
  events and instant events become ``ph:"i"`` on a per-thread-named
  timeline; the file loads in Perfetto / ``chrome://tracing``.
* **SLO evaluation** — a declarative JSON file of assertions over the
  trace; exit code 0 = all hold, 1 = violation, 2 = usage/parse error.
  This is the harness ROADMAP open item 3's continuous-deployment
  scenario asserts through (p99 bound, served-weight staleness, zero
  dropped requests across reloads).

SLO file format (README "Observability")::

    {"slos": [
      {"name": "serve-p99",  "metric": "span_p99_s",
       "span": "serve.batch", "max": 0.050},
      {"name": "no-drops",   "metric": "counter",
       "counter": "serve.reject", "max": 0},
      {"name": "fresh-weights", "metric": "staleness_s", "max": 30.0}
    ]}

``metric`` kinds: ``span_p50_s`` / ``span_p99_s`` / ``span_max_s`` /
``span_mean_s`` / ``span_count`` (over ``span`` name), ``counter``
(delta ``n`` of ``counter``; ``field: "bytes"`` selects bytes),
``staleness_s`` — for every ``serve_reload``-kind ``reloaded`` record,
the age of the served weights at swap time: reload ts minus the ts of
the ``checkpoint.save`` span that wrote that version (reloads of
checkpoints older than the trace window are skipped — their save is
simply not in the trace) — and two per-lane serving metrics (ISSUE 12,
both take a ``"lane"`` field): ``lane_p99_s`` — p99 over the per-batch
per-lane max latencies the ``serve_batch`` records carry (a
conservative UPPER estimate of the per-request p99, since each sample
is a batch's worst row) — and ``lane_shed_fraction`` — typed
rejections (rejected + shed + displaced) over offered requests for the
lane, from the ``serve.admitted/rejected/shed/displaced.<lane>``
counter deltas (offered counts each request once: displaced requests
already sit in admitted).  ISSUE 13 adds ``alert_count`` (``obs_alert``
records, optional ``rule`` filter; absent = 0, honest for both a
``max: 0`` clean gate and a ``min: 1`` the-detector-tripped gate) and
two WINDOWED metrics taking ``span`` + ``window_s``:
``window_span_p99_s`` (the worst per-window p99 — unevaluable when the
span fired in no window, a violation, never silent green) and
``window_span_count_min`` (the minimum per-window count over the
trace's whole window grid — a window the span skipped counts ZERO, so
a mid-run stall fails a ``min`` bound).  Every SLO takes ``max``
and/or ``min``.

Parsing reuses ``JsonLinesEventLog.read`` — a crash-torn trailing line
is tolerated (the soak/crash forensics contract), a malformed interior
line still raises.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from tpu_sgd.utils.events import JsonLinesEventLog

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — this module is a single-threaded offline reader; it owns
#: no shared mutable state and no locks.
GRAFTLINT_LOCKS: dict = {}


def load_trace(path: str) -> List[dict]:
    """All records of a trace JSONL, via the shared torn-tail-tolerant
    ``read()`` semantics."""
    return JsonLinesEventLog.read(path)


def _percentile(xs: List[float], p: float) -> float:
    """Nearest-rank percentile — ONE shared definition with the live
    scrape (``serve.metrics.nearest_rank``), so an SLO written against
    a live p99 means the same thing evaluated offline."""
    from tpu_sgd.serve.metrics import nearest_rank

    return nearest_rank(sorted(xs), p)


def span_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-span-name aggregate: ``{name: {count, total_s, mean_s,
    p50_s, p99_s, max_s, errors}}``."""
    by_name: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    for r in records:
        if r.get("kind") != "trace_span":
            continue
        by_name.setdefault(r["name"], []).append(float(r["dur_s"]))
        if r.get("error"):
            errors[r["name"]] = errors.get(r["name"], 0) + 1
    out = {}
    for name, durs in sorted(by_name.items()):
        out[name] = {
            "count": len(durs),
            "total_s": sum(durs),
            "mean_s": sum(durs) / len(durs),
            "p50_s": _percentile(durs, 50),
            "p99_s": _percentile(durs, 99),
            "max_s": max(durs),
            "errors": errors.get(name, 0),
        }
    return out


def counter_deltas(records: List[dict]) -> Dict[str, Dict[str, int]]:
    """What the traced window spent: last ``metric_counters`` snapshot
    minus the first (one snapshot = that snapshot verbatim — cumulative
    from its enable())."""
    snaps = [r["counters"] for r in records
             if r.get("kind") == "metric_counters"]
    if not snaps:
        return {}
    first, last = snaps[0], snaps[-1]
    if len(snaps) == 1:
        first = {}
    out = {}
    for name, c in last.items():
        s = first.get(name, {"n": 0, "bytes": 0})
        dn = int(c["n"]) - int(s["n"])
        db = int(c["bytes"]) - int(s["bytes"])
        if dn or db:
            out[name] = {"n": dn, "bytes": db}
    return out


def staleness_samples(records: List[dict]) -> List[dict]:
    """Served-weight staleness per hot reload: for each ``serve_reload``
    record with ``event == "reloaded"``, the wall-clock age of the
    swapped-in version — reload ts minus the ts of the
    ``checkpoint.save`` span that wrote that version.  Reloads whose
    save predates the trace are skipped, not guessed."""
    save_ts: Dict[int, float] = {}
    for r in records:
        if r.get("kind") == "trace_span" \
                and r.get("name") == "checkpoint.save" \
                and "iteration" in r:
            # last save of a version wins (re-saves replace the file)
            save_ts[int(r["iteration"])] = float(r["ts"])
    out = []
    for r in records:
        if r.get("kind") == "serve_reload" and r.get("event") == "reloaded":
            v = int(r["version"])
            if v in save_ts:
                out.append({"version": v,
                            "staleness_s": float(r["ts"]) - save_ts[v]})
    return out


def alert_stats(records: List[dict]) -> dict:
    """The trace's typed detector trips (``obs_alert`` records,
    ``tpu_sgd.obs.detect``): ``{"count", "by_rule": {rule: n},
    "alerts": [records...]}`` — the report's alerts section and the
    ``alert_count`` SLO metric both read this."""
    alerts = [r for r in records if r.get("kind") == "obs_alert"]
    by_rule: Dict[str, int] = {}
    for a in alerts:
        rule = a.get("rule", "?")
        by_rule[rule] = by_rule.get(rule, 0) + 1
    return {"count": len(alerts), "by_rule": by_rule, "alerts": alerts}


def windowed_stats(records: List[dict], width_s: float) -> List[dict]:
    """Time-bucketed per-stage tables: ``trace_span`` records bucketed
    by their epoch ``ts`` into fixed ``width_s`` windows — the OFFLINE
    twin of the live ``obs.timeseries`` ring (same fixed-width
    windowing, same nearest-rank percentiles), computed from the raw
    records so any trace gains a time dimension after the fact.  Each
    entry: ``{index, t_start, t_end, spans: {name: span_stats-row},
    alerts: [obs_alert records], staleness: [samples]}``.  Windows the
    trace never touched are ABSENT here; the window SLO metrics treat
    absent as zero/violation, never silent green."""
    if width_s <= 0:
        raise ValueError(f"window width must be > 0, got {width_s}")
    buckets: Dict[int, List[dict]] = {}
    for r in records:
        kind = r.get("kind")
        if kind not in ("trace_span", "obs_alert") or "ts" not in r:
            continue
        # an alert DESCRIBES a window (its t_start) but is EMITTED at
        # dispatch time, at least one window later (arbitrarily later
        # after a stall) — bucket it where the anomaly happened, next
        # to the spans it indicts, not where the detector ran
        ts = (float(r.get("t_start", r["ts"])) if kind == "obs_alert"
              else float(r["ts"]))
        buckets.setdefault(int(ts // width_s), []).append(r)
    # the staleness join gains its time dimension here: each sample is
    # bucketed at its RELOAD's ts (the moment the gap was served)
    stale_by_idx: Dict[int, List[dict]] = {}
    reload_ts = {int(r["version"]): float(r["ts"]) for r in records
                 if r.get("kind") == "serve_reload"
                 and r.get("event") == "reloaded"}
    for s in staleness_samples(records):
        ts = reload_ts.get(s["version"])
        if ts is not None:
            stale_by_idx.setdefault(int(ts // width_s), []).append(s)
    out = []
    for idx in sorted(set(buckets) | set(stale_by_idx)):
        bucket = buckets.get(idx, [])
        out.append({
            "index": idx,
            "t_start": idx * width_s,
            "t_end": (idx + 1) * width_s,
            "spans": span_stats(bucket),
            "alerts": [r for r in bucket if r.get("kind") == "obs_alert"],
            "staleness": stale_by_idx.get(idx, []),
        })
    return out


def lane_latency_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-priority-lane serving latency aggregate from the
    ``serve_batch`` records' ``lanes`` composition: ``{lane: {batches,
    requests, p50_s, p99_s, max_s}}``.  The percentile samples are each
    batch's per-lane MAX latency, so p99 here upper-bounds the true
    per-request p99 — the conservative direction for an SLO gate."""
    by_lane: Dict[str, List[float]] = {}
    requests: Dict[str, int] = {}
    for r in records:
        if r.get("kind") != "serve_batch" or not r.get("lanes"):
            continue
        for lane, st in r["lanes"].items():
            by_lane.setdefault(lane, []).append(float(st["max_latency_s"]))
            requests[lane] = requests.get(lane, 0) + int(st["n"])
    out = {}
    for lane, maxima in sorted(by_lane.items()):
        out[lane] = {
            "batches": len(maxima),
            "requests": requests[lane],
            "p50_s": _percentile(maxima, 50),
            "p99_s": _percentile(maxima, 99),
            "max_s": max(maxima),
        }
    return out


def lane_admission_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-lane admission-control table from the counter deltas:
    ``{lane: {admitted, rejected, shed, displaced, offered,
    reject_rate}}``.  ``offered`` counts each request ONCE —
    admitted + rejected + shed (a displaced request already sits in
    ``admitted``; that is why displacement is its own counter) — and
    ``reject_rate = (rejected + shed + displaced) / offered``: the
    fraction of offered requests that ended in a typed rejection, the
    number the overload scenario's verdict gates on."""
    deltas = counter_deltas(records)
    lanes: Dict[str, dict] = {}

    def bucket(prefix: str, key: str):
        for name, c in deltas.items():
            if name.startswith(prefix):
                lane = name[len(prefix):]
                if "." in lane:
                    continue  # not a lane leaf (e.g. a wire counter)
                st = lanes.setdefault(
                    lane, {"admitted": 0, "rejected": 0, "shed": 0,
                           "displaced": 0})
                st[key] += int(c["n"])

    bucket("serve.admitted.", "admitted")
    bucket("serve.rejected.", "rejected")
    bucket("serve.shed.", "shed")
    bucket("serve.displaced.", "displaced")
    for st in lanes.values():
        st["offered"] = st["admitted"] + st["rejected"] + st["shed"]
        st["reject_rate"] = (
            (st["rejected"] + st["shed"] + st["displaced"])
            / st["offered"] if st["offered"] else 0.0)
    return dict(sorted(lanes.items()))


# -- Chrome trace-event export ----------------------------------------------

def to_chrome_trace(records: List[dict]) -> dict:
    """Chrome trace-event JSON (object form), loadable in Perfetto /
    chrome://tracing.  Spans -> ``ph:"X"`` complete events on their
    thread's track (monotonic ``t0_s`` timebase, µs); instant events ->
    ``ph:"i"``; thread-name metadata rides ``ph:"M"`` records."""
    events = []
    tids: Dict[str, int] = {}

    def tid_of(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tids[thread],
                           "args": {"name": thread}})
        return tids[thread]

    core = {"kind", "name", "ts", "t0_s", "dur_s", "span_id",
            "parent_id", "thread", "subsystem"}
    for r in records:
        kind = r.get("kind")
        if kind == "trace_span":
            events.append({
                "ph": "X",
                "name": r["name"],
                "cat": r["name"].split(".", 1)[0],
                "pid": 1,
                "tid": tid_of(r.get("thread", "?")),
                "ts": float(r["t0_s"]) * 1e6,
                "dur": float(r["dur_s"]) * 1e6,
                "args": {k: v for k, v in r.items() if k not in core},
            })
        elif kind == "trace_event":
            events.append({
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "name": r["name"],
                "cat": r.get("subsystem", "event"),
                "pid": 1,
                "tid": tid_of(r.get("thread", "?")),
                "ts": float(r["t0_s"]) * 1e6,
                "args": {k: v for k, v in r.items() if k not in core},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- SLO evaluation ----------------------------------------------------------

_SPAN_METRICS = {"span_p50_s": "p50_s", "span_p99_s": "p99_s",
                 "span_max_s": "max_s", "span_mean_s": "mean_s",
                 "span_count": "count"}


def evaluate_slos(records: List[dict], slo_doc: dict) -> List[dict]:
    """Evaluate a declarative SLO document against a trace; returns one
    verdict dict per SLO: ``{name, metric, value, max?, min?, ok,
    detail?}``.  Unknown metric kinds and malformed entries raise
    ``ValueError`` (a typo'd SLO must fail the gate loudly, never pass
    green unevaluated)."""
    slos = slo_doc.get("slos")
    if not isinstance(slos, list):
        raise ValueError('SLO document must have a "slos" list')
    stats = span_stats(records)
    counters = counter_deltas(records)
    # pure functions of the records: compute once per document, not
    # once per SLO (a soak trace runs to 10^5 records, and the harness
    # documents carry several alert/window entries)
    alerts_memo: List[Optional[dict]] = [None]
    windows_memo: Dict[float, List[dict]] = {}

    def _alerts() -> dict:
        if alerts_memo[0] is None:
            alerts_memo[0] = alert_stats(records)
        return alerts_memo[0]

    def _windows(width: float) -> List[dict]:
        if width not in windows_memo:
            windows_memo[width] = windowed_stats(records, width)
        return windows_memo[width]

    verdicts = []
    for i, slo in enumerate(slos):
        metric = slo.get("metric")
        name = slo.get("name", f"slo-{i}")
        detail = None
        if metric in _SPAN_METRICS:
            span_name = slo.get("span")
            if not span_name:
                raise ValueError(f"SLO {name!r}: span metrics need a "
                                 '"span" field')
            st = stats.get(span_name)
            if st is None:
                # an SLO over a span that never fired: a count bound of
                # 0 legitimately passes; a latency bound cannot be
                # evaluated and must not silently pass
                if metric == "span_count":
                    value: Optional[float] = 0
                else:
                    value = None
                    detail = f"span {span_name!r} absent from trace"
            else:
                value = st[_SPAN_METRICS[metric]]
        elif metric == "counter":
            cname = slo.get("counter")
            if not cname:
                raise ValueError(f"SLO {name!r}: counter metric needs a "
                                 '"counter" field')
            field = slo.get("field", "n")
            if field not in ("n", "bytes"):
                raise ValueError(f"SLO {name!r}: field must be n|bytes")
            value = counters.get(cname, {"n": 0, "bytes": 0})[field]
        elif metric == "staleness_s":
            samples = staleness_samples(records)
            if not samples:
                value = None
                detail = "no reload-with-traced-save pairs in trace"
            else:
                value = max(s["staleness_s"] for s in samples)
        elif metric == "lane_p99_s":
            lane = slo.get("lane")
            if not lane:
                raise ValueError(f"SLO {name!r}: lane metrics need a "
                                 '"lane" field')
            st = lane_latency_stats(records).get(lane)
            if st is None:
                # a latency bound over a lane that never served cannot
                # be evaluated and must not silently pass
                value = None
                detail = f"lane {lane!r} absent from serve_batch records"
            else:
                value = st["p99_s"]
        elif metric == "lane_shed_fraction":
            lane = slo.get("lane")
            if not lane:
                raise ValueError(f"SLO {name!r}: lane metrics need a "
                                 '"lane" field')
            st = lane_admission_stats(records).get(lane)
            if st is None:
                # no admission counters for the lane at all: the trace
                # never ran admission control — unevaluable, not green
                value = None
                detail = (f"no serve.admitted/rejected/shed.{lane} "
                          "counters in trace")
            else:
                value = st["reject_rate"]
        elif metric == "alert_count":
            # typed detector trips (ISSUE 13): an absent rule counts 0
            # — honest for both directions (max 0 = clean-run gate,
            # min 1 = the-detector-really-tripped gate)
            rule = slo.get("rule")
            stats_a = _alerts()
            value = (stats_a["by_rule"].get(rule, 0)
                     if rule else stats_a["count"])
        elif metric in ("window_span_p99_s", "window_span_count_min"):
            span_name = slo.get("span")
            width = slo.get("window_s")
            if not span_name or not width:
                raise ValueError(f"SLO {name!r}: window metrics need "
                                 '"span" and "window_s" fields')
            wins = _windows(float(width))
            per = [w["spans"][span_name] for w in wins
                   if span_name in w["spans"]]
            if metric == "window_span_p99_s":
                if not per:
                    # a windowed latency bound over a span that never
                    # fired cannot be evaluated — a violation, never
                    # silent green
                    value = None
                    detail = (f"span {span_name!r} absent from every "
                              "window")
                else:
                    value = max(st["p99_s"] for st in per)
            else:
                if not wins:
                    value = None
                    detail = "trace has no windows at all"
                else:
                    # the MINIMUM per-window count over the trace's
                    # whole [first, last] window grid: a window the
                    # span skipped counts ZERO (a serving stall is a
                    # gap, not a missing row)
                    lo = min(w["index"] for w in wins)
                    hi = max(w["index"] for w in wins)
                    by_idx = {w["index"]: w for w in wins}
                    value = min(
                        by_idx.get(i, {"spans": {}})["spans"]
                        .get(span_name, {"count": 0})["count"]
                        for i in range(lo, hi + 1))
        else:
            raise ValueError(f"SLO {name!r}: unknown metric {metric!r}")
        lo, hi = slo.get("min"), slo.get("max")
        if lo is None and hi is None:
            raise ValueError(f"SLO {name!r}: needs max and/or min")
        if value is None:
            ok = False  # unevaluable is a violation, not a free pass
        else:
            ok = ((hi is None or value <= hi)
                  and (lo is None or value >= lo))
        v = {"name": name, "metric": metric, "value": value, "ok": ok}
        if hi is not None:
            v["max"] = hi
        if lo is not None:
            v["min"] = lo
        if detail:
            v["detail"] = detail
        verdicts.append(v)
    return verdicts


# -- CLI ---------------------------------------------------------------------

def _fmt_s(x: float) -> str:
    return f"{x * 1e3:9.3f}ms" if x < 1.0 else f"{x:8.3f}s "


def _fmt_num(x) -> str:
    """Alert value/bound formatting that survives a record missing the
    field (a foreign producer or schema drift must degrade the render,
    never crash the report or the live watcher)."""
    return f"{x:.4g}" if isinstance(x, (int, float)) else "?"


def render_report(records: List[dict]) -> str:
    lines = []
    stats = span_stats(records)
    if stats:
        lines.append("per-stage breakdown (trace_span records):")
        lines.append(f"  {'span':<28}{'count':>7}{'total':>12}"
                     f"{'p50':>12}{'p99':>12}{'max':>12}{'err':>5}")
        for name, st in stats.items():
            lines.append(
                f"  {name:<28}{st['count']:>7}"
                f"{_fmt_s(st['total_s']):>12}{_fmt_s(st['p50_s']):>12}"
                f"{_fmt_s(st['p99_s']):>12}{_fmt_s(st['max_s']):>12}"
                f"{st['errors']:>5}")
    else:
        lines.append("no trace_span records in trace")
    deltas = counter_deltas(records)
    if deltas:
        lines.append("counter deltas (metric_counters records):")
        for name, c in sorted(deltas.items()):
            extra = f"  bytes={c['bytes']}" if c["bytes"] else ""
            lines.append(f"  {name:<40}{c['n']:>10}{extra}")
        from tpu_sgd.obs.counters import wire_ratios

        ratios = wire_ratios(deltas)
        if ratios:
            lines.append("wire formats (physical vs dense-f32-logical "
                         "bytes; ratio = compression):")
            for name, r in sorted(ratios.items()):
                lines.append(
                    f"  {name:<34}{r['n']:>8}"
                    f"  physical={r['physical_bytes']:>12}"
                    f"  logical={r['logical_bytes']:>12}"
                    f"  ratio={r['ratio']:.1f}x")
    lane_lat = lane_latency_stats(records)
    lane_adm = lane_admission_stats(records)
    if lane_lat or lane_adm:
        lines.append("serving lanes (admission control + per-batch "
                     "lane-max latency):")
        lines.append(f"  {'lane':<14}{'admitted':>9}{'rejected':>9}"
                     f"{'shed':>7}{'displ':>7}{'rej-rate':>9}"
                     f"{'p50':>12}{'p99':>12}")
        for lane in sorted(set(lane_lat) | set(lane_adm)):
            a = lane_adm.get(lane, {})
            lt = lane_lat.get(lane)
            lines.append(
                f"  {lane:<14}{a.get('admitted', 0):>9}"
                f"{a.get('rejected', 0):>9}{a.get('shed', 0):>7}"
                f"{a.get('displaced', 0):>7}"
                f"{a.get('reject_rate', 0.0):>8.1%}"
                + (f"{_fmt_s(lt['p50_s']):>12}{_fmt_s(lt['p99_s']):>12}"
                   if lt else f"{'-':>12}{'-':>12}"))
    stale = staleness_samples(records)
    if stale:
        worst = max(s["staleness_s"] for s in stale)
        lines.append(f"served-weight staleness: {len(stale)} reload(s), "
                     f"worst {worst:.3f}s")
    alerts = alert_stats(records)
    if alerts["count"]:
        lines.append(f"alerts ({alerts['count']} typed obs_alert "
                     "trips):")
        for rule, n in sorted(alerts["by_rule"].items()):
            lines.append(f"  {rule:<28}{n:>5}")
        for a in alerts["alerts"][:20]:
            lines.append(
                f"    [{a.get('rule')}] {a.get('series')}: "
                f"value={_fmt_num(a.get('value'))} "
                f"bound={_fmt_num(a.get('bound'))}"
                f"  {a.get('detail', '')}")
        if alerts["count"] > 20:
            lines.append(f"    ... {alerts['count'] - 20} more")
    return "\n".join(lines)


def render_windows(windows: List[dict], last: Optional[int] = None) -> str:
    """Text tables for :func:`windowed_stats` output (shared by the
    report CLI's ``--window`` and the live watch CLI)."""
    lines = []
    if last is not None:
        windows = windows[-int(last):]
    if not windows:
        return "no windowed records"
    for w in windows:
        head = (f"window {w['index']}  [{w['t_start']:.3f}, "
                f"{w['t_end']:.3f})")
        if w["alerts"]:
            head += f"  ALERTS={len(w['alerts'])}"
        lines.append(head)
        if w["spans"]:
            lines.append(f"  {'span':<28}{'count':>7}{'p50':>12}"
                         f"{'p99':>12}{'max':>12}{'err':>5}")
            for name, st in w["spans"].items():
                lines.append(
                    f"  {name:<28}{st['count']:>7}"
                    f"{_fmt_s(st['p50_s']):>12}{_fmt_s(st['p99_s']):>12}"
                    f"{_fmt_s(st['max_s']):>12}{st['errors']:>5}")
        for a in w["alerts"]:
            lines.append(f"  ALERT [{a.get('rule')}] {a.get('series')}: "
                         f"value={_fmt_num(a.get('value'))} "
                         f"bound={_fmt_num(a.get('bound'))}")
        for s in w["staleness"]:
            lines.append(f"  staleness: version {s['version']} served "
                         f"{s['staleness_s']:.3f}s old")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_sgd.obs.report",
        description=__doc__.split("\n")[0])
    ap.add_argument("trace", help="trace JSONL path (JsonLinesEventLog)")
    ap.add_argument("--chrome", metavar="OUT.json",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--slo", metavar="SLO.json",
                    help="evaluate a declarative SLO file; exit 1 on "
                         "violation")
    ap.add_argument("--window", metavar="SECONDS", type=float,
                    default=None,
                    help="add time-bucketed per-stage tables at this "
                         "window width (the offline twin of the live "
                         "obs.timeseries ring)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args(argv)
    if args.window is not None and args.window <= 0:
        # the exit-code contract: 2 is the usage-error class, never a
        # traceback (1 is reserved for SLO violations)
        print(f"error: --window must be > 0, got {args.window}",
              file=sys.stderr)
        return 2
    try:
        records = load_trace(args.trace)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read trace {args.trace!r}: {e}",
              file=sys.stderr)
        return 2

    verdicts = None
    if args.slo:
        try:
            with open(args.slo) as f:
                slo_doc = json.load(f)
            verdicts = evaluate_slos(records, slo_doc)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            print(f"error: bad SLO file {args.slo!r}: {e}",
                  file=sys.stderr)
            return 2

    if args.chrome:
        try:
            with open(args.chrome, "w") as f:
                json.dump(to_chrome_trace(records), f)
        except OSError as e:
            # an unwritable export path is the usage-error class (2),
            # NOT the SLO-violation class (1) chaos_soak gates on
            print(f"error: cannot write Chrome trace {args.chrome!r}: "
                  f"{e}", file=sys.stderr)
            return 2

    if args.json:
        from tpu_sgd.obs.counters import wire_ratios

        out = {"spans": span_stats(records),
               "counters": counter_deltas(records),
               "wire": wire_ratios(counter_deltas(records)),
               "staleness": staleness_samples(records),
               "lanes": {"latency": lane_latency_stats(records),
                         "admission": lane_admission_stats(records)},
               "alerts": alert_stats(records)}
        if args.window:
            out["windows"] = windowed_stats(records, args.window)
        if verdicts is not None:
            out["slos"] = verdicts
        print(json.dumps(out, indent=2))
    else:
        print(render_report(records))
        if args.window:
            print(f"time-bucketed tables ({args.window:g}s windows):")
            print(render_windows(windowed_stats(records, args.window)))
        if verdicts is not None:
            for v in verdicts:
                bound = " ".join(
                    f"{k}={v[k]}" for k in ("min", "max") if k in v)
                state = "PASS" if v["ok"] else "FAIL"
                val = ("<unevaluable>" if v["value"] is None
                       else f"{v['value']:.6g}")
                extra = f"  ({v['detail']})" if v.get("detail") else ""
                print(f"SLO {state}: {v['name']}: {v['metric']}="
                      f"{val} vs {bound}{extra}")

    if verdicts is not None and not all(v["ok"] for v in verdicts):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
