"""Trace/SLO report pipeline: JSONL trace -> breakdowns, Chrome trace,
SLO verdict.

The consuming half of the observability layer (spans + counters write,
this module reads)::

    python -m tpu_sgd.obs.report events.jsonl            # stage tables
    python -m tpu_sgd.obs.report events.jsonl --chrome t.json   # Perfetto
    python -m tpu_sgd.obs.report events.jsonl --slo slo.json    # verdict

* **Per-stage breakdowns** — ``trace_span`` records grouped by name:
  count, total/mean wall, p50/p99/max (nearest-rank, the same
  percentile rule ``serve.metrics.ServingMetrics`` scrapes with).
* **Counter deltas** — ``metric_counters`` records (cumulative
  snapshots flushed by ``tpu_sgd.obs``): last minus first, so a trace
  covering one soak reports what THAT soak spent.
* **Chrome trace-event export** — spans become ``ph:"X"`` complete
  events and instant events become ``ph:"i"`` on a per-thread-named
  timeline; the file loads in Perfetto / ``chrome://tracing``.
* **SLO evaluation** — a declarative JSON file of assertions over the
  trace; exit code 0 = all hold, 1 = violation, 2 = usage/parse error.
  This is the harness ROADMAP open item 3's continuous-deployment
  scenario asserts through (p99 bound, served-weight staleness, zero
  dropped requests across reloads).

SLO file format (README "Observability")::

    {"slos": [
      {"name": "serve-p99",  "metric": "span_p99_s",
       "span": "serve.batch", "max": 0.050},
      {"name": "no-drops",   "metric": "counter",
       "counter": "serve.reject", "max": 0},
      {"name": "fresh-weights", "metric": "staleness_s", "max": 30.0}
    ]}

``metric`` kinds: ``span_p50_s`` / ``span_p99_s`` / ``span_max_s`` /
``span_mean_s`` / ``span_count`` (over ``span`` name), ``counter``
(delta ``n`` of ``counter``; ``field: "bytes"`` selects bytes),
``staleness_s`` — for every ``serve_reload``-kind ``reloaded`` record,
the age of the served weights at swap time: reload ts minus the ts of
the ``checkpoint.save`` span that wrote that version (reloads of
checkpoints older than the trace window are skipped — their save is
simply not in the trace) — and two per-lane serving metrics (ISSUE 12,
both take a ``"lane"`` field): ``lane_p99_s`` — p99 over the per-batch
per-lane max latencies the ``serve_batch`` records carry (a
conservative UPPER estimate of the per-request p99, since each sample
is a batch's worst row) — and ``lane_shed_fraction`` — typed
rejections (rejected + shed + displaced) over offered requests for the
lane, from the ``serve.admitted/rejected/shed/displaced.<lane>``
counter deltas (offered counts each request once: displaced requests
already sit in admitted).  Every SLO takes ``max`` and/or ``min``.

Parsing reuses ``JsonLinesEventLog.read`` — a crash-torn trailing line
is tolerated (the soak/crash forensics contract), a malformed interior
line still raises.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from tpu_sgd.utils.events import JsonLinesEventLog

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — this module is a single-threaded offline reader; it owns
#: no shared mutable state and no locks.
GRAFTLINT_LOCKS: dict = {}


def load_trace(path: str) -> List[dict]:
    """All records of a trace JSONL, via the shared torn-tail-tolerant
    ``read()`` semantics."""
    return JsonLinesEventLog.read(path)


def _percentile(xs: List[float], p: float) -> float:
    """Nearest-rank percentile — ONE shared definition with the live
    scrape (``serve.metrics.nearest_rank``), so an SLO written against
    a live p99 means the same thing evaluated offline."""
    from tpu_sgd.serve.metrics import nearest_rank

    return nearest_rank(sorted(xs), p)


def span_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-span-name aggregate: ``{name: {count, total_s, mean_s,
    p50_s, p99_s, max_s, errors}}``."""
    by_name: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    for r in records:
        if r.get("kind") != "trace_span":
            continue
        by_name.setdefault(r["name"], []).append(float(r["dur_s"]))
        if r.get("error"):
            errors[r["name"]] = errors.get(r["name"], 0) + 1
    out = {}
    for name, durs in sorted(by_name.items()):
        out[name] = {
            "count": len(durs),
            "total_s": sum(durs),
            "mean_s": sum(durs) / len(durs),
            "p50_s": _percentile(durs, 50),
            "p99_s": _percentile(durs, 99),
            "max_s": max(durs),
            "errors": errors.get(name, 0),
        }
    return out


def counter_deltas(records: List[dict]) -> Dict[str, Dict[str, int]]:
    """What the traced window spent: last ``metric_counters`` snapshot
    minus the first (one snapshot = that snapshot verbatim — cumulative
    from its enable())."""
    snaps = [r["counters"] for r in records
             if r.get("kind") == "metric_counters"]
    if not snaps:
        return {}
    first, last = snaps[0], snaps[-1]
    if len(snaps) == 1:
        first = {}
    out = {}
    for name, c in last.items():
        s = first.get(name, {"n": 0, "bytes": 0})
        dn = int(c["n"]) - int(s["n"])
        db = int(c["bytes"]) - int(s["bytes"])
        if dn or db:
            out[name] = {"n": dn, "bytes": db}
    return out


def staleness_samples(records: List[dict]) -> List[dict]:
    """Served-weight staleness per hot reload: for each ``serve_reload``
    record with ``event == "reloaded"``, the wall-clock age of the
    swapped-in version — reload ts minus the ts of the
    ``checkpoint.save`` span that wrote that version.  Reloads whose
    save predates the trace are skipped, not guessed."""
    save_ts: Dict[int, float] = {}
    for r in records:
        if r.get("kind") == "trace_span" \
                and r.get("name") == "checkpoint.save" \
                and "iteration" in r:
            # last save of a version wins (re-saves replace the file)
            save_ts[int(r["iteration"])] = float(r["ts"])
    out = []
    for r in records:
        if r.get("kind") == "serve_reload" and r.get("event") == "reloaded":
            v = int(r["version"])
            if v in save_ts:
                out.append({"version": v,
                            "staleness_s": float(r["ts"]) - save_ts[v]})
    return out


def lane_latency_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-priority-lane serving latency aggregate from the
    ``serve_batch`` records' ``lanes`` composition: ``{lane: {batches,
    requests, p50_s, p99_s, max_s}}``.  The percentile samples are each
    batch's per-lane MAX latency, so p99 here upper-bounds the true
    per-request p99 — the conservative direction for an SLO gate."""
    by_lane: Dict[str, List[float]] = {}
    requests: Dict[str, int] = {}
    for r in records:
        if r.get("kind") != "serve_batch" or not r.get("lanes"):
            continue
        for lane, st in r["lanes"].items():
            by_lane.setdefault(lane, []).append(float(st["max_latency_s"]))
            requests[lane] = requests.get(lane, 0) + int(st["n"])
    out = {}
    for lane, maxima in sorted(by_lane.items()):
        out[lane] = {
            "batches": len(maxima),
            "requests": requests[lane],
            "p50_s": _percentile(maxima, 50),
            "p99_s": _percentile(maxima, 99),
            "max_s": max(maxima),
        }
    return out


def lane_admission_stats(records: List[dict]) -> Dict[str, dict]:
    """Per-lane admission-control table from the counter deltas:
    ``{lane: {admitted, rejected, shed, displaced, offered,
    reject_rate}}``.  ``offered`` counts each request ONCE —
    admitted + rejected + shed (a displaced request already sits in
    ``admitted``; that is why displacement is its own counter) — and
    ``reject_rate = (rejected + shed + displaced) / offered``: the
    fraction of offered requests that ended in a typed rejection, the
    number the overload scenario's verdict gates on."""
    deltas = counter_deltas(records)
    lanes: Dict[str, dict] = {}

    def bucket(prefix: str, key: str):
        for name, c in deltas.items():
            if name.startswith(prefix):
                lane = name[len(prefix):]
                if "." in lane:
                    continue  # not a lane leaf (e.g. a wire counter)
                st = lanes.setdefault(
                    lane, {"admitted": 0, "rejected": 0, "shed": 0,
                           "displaced": 0})
                st[key] += int(c["n"])

    bucket("serve.admitted.", "admitted")
    bucket("serve.rejected.", "rejected")
    bucket("serve.shed.", "shed")
    bucket("serve.displaced.", "displaced")
    for st in lanes.values():
        st["offered"] = st["admitted"] + st["rejected"] + st["shed"]
        st["reject_rate"] = (
            (st["rejected"] + st["shed"] + st["displaced"])
            / st["offered"] if st["offered"] else 0.0)
    return dict(sorted(lanes.items()))


# -- Chrome trace-event export ----------------------------------------------

def to_chrome_trace(records: List[dict]) -> dict:
    """Chrome trace-event JSON (object form), loadable in Perfetto /
    chrome://tracing.  Spans -> ``ph:"X"`` complete events on their
    thread's track (monotonic ``t0_s`` timebase, µs); instant events ->
    ``ph:"i"``; thread-name metadata rides ``ph:"M"`` records."""
    events = []
    tids: Dict[str, int] = {}

    def tid_of(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tids[thread],
                           "args": {"name": thread}})
        return tids[thread]

    core = {"kind", "name", "ts", "t0_s", "dur_s", "span_id",
            "parent_id", "thread", "subsystem"}
    for r in records:
        kind = r.get("kind")
        if kind == "trace_span":
            events.append({
                "ph": "X",
                "name": r["name"],
                "cat": r["name"].split(".", 1)[0],
                "pid": 1,
                "tid": tid_of(r.get("thread", "?")),
                "ts": float(r["t0_s"]) * 1e6,
                "dur": float(r["dur_s"]) * 1e6,
                "args": {k: v for k, v in r.items() if k not in core},
            })
        elif kind == "trace_event":
            events.append({
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "name": r["name"],
                "cat": r.get("subsystem", "event"),
                "pid": 1,
                "tid": tid_of(r.get("thread", "?")),
                "ts": float(r["t0_s"]) * 1e6,
                "args": {k: v for k, v in r.items() if k not in core},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- SLO evaluation ----------------------------------------------------------

_SPAN_METRICS = {"span_p50_s": "p50_s", "span_p99_s": "p99_s",
                 "span_max_s": "max_s", "span_mean_s": "mean_s",
                 "span_count": "count"}


def evaluate_slos(records: List[dict], slo_doc: dict) -> List[dict]:
    """Evaluate a declarative SLO document against a trace; returns one
    verdict dict per SLO: ``{name, metric, value, max?, min?, ok,
    detail?}``.  Unknown metric kinds and malformed entries raise
    ``ValueError`` (a typo'd SLO must fail the gate loudly, never pass
    green unevaluated)."""
    slos = slo_doc.get("slos")
    if not isinstance(slos, list):
        raise ValueError('SLO document must have a "slos" list')
    stats = span_stats(records)
    counters = counter_deltas(records)
    verdicts = []
    for i, slo in enumerate(slos):
        metric = slo.get("metric")
        name = slo.get("name", f"slo-{i}")
        detail = None
        if metric in _SPAN_METRICS:
            span_name = slo.get("span")
            if not span_name:
                raise ValueError(f"SLO {name!r}: span metrics need a "
                                 '"span" field')
            st = stats.get(span_name)
            if st is None:
                # an SLO over a span that never fired: a count bound of
                # 0 legitimately passes; a latency bound cannot be
                # evaluated and must not silently pass
                if metric == "span_count":
                    value: Optional[float] = 0
                else:
                    value = None
                    detail = f"span {span_name!r} absent from trace"
            else:
                value = st[_SPAN_METRICS[metric]]
        elif metric == "counter":
            cname = slo.get("counter")
            if not cname:
                raise ValueError(f"SLO {name!r}: counter metric needs a "
                                 '"counter" field')
            field = slo.get("field", "n")
            if field not in ("n", "bytes"):
                raise ValueError(f"SLO {name!r}: field must be n|bytes")
            value = counters.get(cname, {"n": 0, "bytes": 0})[field]
        elif metric == "staleness_s":
            samples = staleness_samples(records)
            if not samples:
                value = None
                detail = "no reload-with-traced-save pairs in trace"
            else:
                value = max(s["staleness_s"] for s in samples)
        elif metric == "lane_p99_s":
            lane = slo.get("lane")
            if not lane:
                raise ValueError(f"SLO {name!r}: lane metrics need a "
                                 '"lane" field')
            st = lane_latency_stats(records).get(lane)
            if st is None:
                # a latency bound over a lane that never served cannot
                # be evaluated and must not silently pass
                value = None
                detail = f"lane {lane!r} absent from serve_batch records"
            else:
                value = st["p99_s"]
        elif metric == "lane_shed_fraction":
            lane = slo.get("lane")
            if not lane:
                raise ValueError(f"SLO {name!r}: lane metrics need a "
                                 '"lane" field')
            st = lane_admission_stats(records).get(lane)
            if st is None:
                # no admission counters for the lane at all: the trace
                # never ran admission control — unevaluable, not green
                value = None
                detail = (f"no serve.admitted/rejected/shed.{lane} "
                          "counters in trace")
            else:
                value = st["reject_rate"]
        else:
            raise ValueError(f"SLO {name!r}: unknown metric {metric!r}")
        lo, hi = slo.get("min"), slo.get("max")
        if lo is None and hi is None:
            raise ValueError(f"SLO {name!r}: needs max and/or min")
        if value is None:
            ok = False  # unevaluable is a violation, not a free pass
        else:
            ok = ((hi is None or value <= hi)
                  and (lo is None or value >= lo))
        v = {"name": name, "metric": metric, "value": value, "ok": ok}
        if hi is not None:
            v["max"] = hi
        if lo is not None:
            v["min"] = lo
        if detail:
            v["detail"] = detail
        verdicts.append(v)
    return verdicts


# -- CLI ---------------------------------------------------------------------

def _fmt_s(x: float) -> str:
    return f"{x * 1e3:9.3f}ms" if x < 1.0 else f"{x:8.3f}s "


def render_report(records: List[dict]) -> str:
    lines = []
    stats = span_stats(records)
    if stats:
        lines.append("per-stage breakdown (trace_span records):")
        lines.append(f"  {'span':<28}{'count':>7}{'total':>12}"
                     f"{'p50':>12}{'p99':>12}{'max':>12}{'err':>5}")
        for name, st in stats.items():
            lines.append(
                f"  {name:<28}{st['count']:>7}"
                f"{_fmt_s(st['total_s']):>12}{_fmt_s(st['p50_s']):>12}"
                f"{_fmt_s(st['p99_s']):>12}{_fmt_s(st['max_s']):>12}"
                f"{st['errors']:>5}")
    else:
        lines.append("no trace_span records in trace")
    deltas = counter_deltas(records)
    if deltas:
        lines.append("counter deltas (metric_counters records):")
        for name, c in sorted(deltas.items()):
            extra = f"  bytes={c['bytes']}" if c["bytes"] else ""
            lines.append(f"  {name:<40}{c['n']:>10}{extra}")
        from tpu_sgd.obs.counters import wire_ratios

        ratios = wire_ratios(deltas)
        if ratios:
            lines.append("wire formats (physical vs dense-f32-logical "
                         "bytes; ratio = compression):")
            for name, r in sorted(ratios.items()):
                lines.append(
                    f"  {name:<34}{r['n']:>8}"
                    f"  physical={r['physical_bytes']:>12}"
                    f"  logical={r['logical_bytes']:>12}"
                    f"  ratio={r['ratio']:.1f}x")
    lane_lat = lane_latency_stats(records)
    lane_adm = lane_admission_stats(records)
    if lane_lat or lane_adm:
        lines.append("serving lanes (admission control + per-batch "
                     "lane-max latency):")
        lines.append(f"  {'lane':<14}{'admitted':>9}{'rejected':>9}"
                     f"{'shed':>7}{'displ':>7}{'rej-rate':>9}"
                     f"{'p50':>12}{'p99':>12}")
        for lane in sorted(set(lane_lat) | set(lane_adm)):
            a = lane_adm.get(lane, {})
            lt = lane_lat.get(lane)
            lines.append(
                f"  {lane:<14}{a.get('admitted', 0):>9}"
                f"{a.get('rejected', 0):>9}{a.get('shed', 0):>7}"
                f"{a.get('displaced', 0):>7}"
                f"{a.get('reject_rate', 0.0):>8.1%}"
                + (f"{_fmt_s(lt['p50_s']):>12}{_fmt_s(lt['p99_s']):>12}"
                   if lt else f"{'-':>12}{'-':>12}"))
    stale = staleness_samples(records)
    if stale:
        worst = max(s["staleness_s"] for s in stale)
        lines.append(f"served-weight staleness: {len(stale)} reload(s), "
                     f"worst {worst:.3f}s")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_sgd.obs.report",
        description=__doc__.split("\n")[0])
    ap.add_argument("trace", help="trace JSONL path (JsonLinesEventLog)")
    ap.add_argument("--chrome", metavar="OUT.json",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--slo", metavar="SLO.json",
                    help="evaluate a declarative SLO file; exit 1 on "
                         "violation")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args(argv)
    try:
        records = load_trace(args.trace)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read trace {args.trace!r}: {e}",
              file=sys.stderr)
        return 2

    verdicts = None
    if args.slo:
        try:
            with open(args.slo) as f:
                slo_doc = json.load(f)
            verdicts = evaluate_slos(records, slo_doc)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            print(f"error: bad SLO file {args.slo!r}: {e}",
                  file=sys.stderr)
            return 2

    if args.chrome:
        try:
            with open(args.chrome, "w") as f:
                json.dump(to_chrome_trace(records), f)
        except OSError as e:
            # an unwritable export path is the usage-error class (2),
            # NOT the SLO-violation class (1) chaos_soak gates on
            print(f"error: cannot write Chrome trace {args.chrome!r}: "
                  f"{e}", file=sys.stderr)
            return 2

    if args.json:
        from tpu_sgd.obs.counters import wire_ratios

        out = {"spans": span_stats(records),
               "counters": counter_deltas(records),
               "wire": wire_ratios(counter_deltas(records)),
               "staleness": staleness_samples(records),
               "lanes": {"latency": lane_latency_stats(records),
                         "admission": lane_admission_stats(records)}}
        if verdicts is not None:
            out["slos"] = verdicts
        print(json.dumps(out, indent=2))
    else:
        print(render_report(records))
        if verdicts is not None:
            for v in verdicts:
                bound = " ".join(
                    f"{k}={v[k]}" for k in ("min", "max") if k in v)
                state = "PASS" if v["ok"] else "FAIL"
                val = ("<unevaluable>" if v["value"] is None
                       else f"{v['value']:.6g}")
                extra = f"  ({v['detail']})" if v.get("detail") else ""
                print(f"SLO {state}: {v['name']}: {v['metric']}="
                      f"{val} vs {bound}{extra}")

    if verdicts is not None and not all(v["ok"] for v in verdicts):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
