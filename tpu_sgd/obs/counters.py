"""Always-on runtime counters: dispatches, compiles, syncs, transfers.

``tpu_sgd.analysis.runtime`` proved the counting machinery — patch the
runtime's Python-level funnels (``ExecuteReplicated.__call__`` for
program launches, the ``ArrayImpl`` ``_value``/``item``/``__array__``
funnels for device→host materializations) and the counts are exact,
structural, and immune to the wall-clock noise this 2-core harness
drowns timings in.  But those twins are test-scoped context managers:
``count_dispatches`` cannot run in production because it is built to
bracket one region on one actor.  This module promotes the same
machinery into a long-lived, opt-in accounting layer:

* ``enable()`` installs the patches ONCE (plus a ``jax.monitoring``
  compile listener and a ``jax.device_put`` wrapper for h2d transfer
  counts/bytes) and they stay up until ``disable()`` — counters
  accumulate across threads, subsystems, and requests for the life of
  the process.
* every count is tagged with the **subsystem** whose span region caused
  it (``obs.spans.current_subsystem()`` — thread-local, so the serving
  flush thread's dispatches land under ``serve`` while the training
  thread's land under ``train``).
* explicit hook sites (``inc("serve.reject")``,
  ``inc("train.io_callback")``) ride the same registry for events the
  patches cannot see.

Cost contract: DISABLED is one module-global load and a falsy branch
per ``inc()`` call (the failpoints discipline; measured no-op in
``tests/test_obs.py``), and ZERO patches are installed — production
processes that never opt in run the stock runtime.  ENABLED is honest
but not free: counting launches requires declining jit's C++ fastpath
(warm effect-free programs otherwise execute entirely in C++, invisible
to any Python hook), so every dispatch takes the Python path — the
overhead is wall-clock only; the counter layer adds ZERO dispatches,
compiles, or host syncs of its own (the acceptance pin in
``tests/test_obs.py``, measured with the analysis twins, which nest
cleanly over these patches because both patch/restore LIFO).

Semantics (inherited from the twins, documented there in full): eager
jnp ops are dispatches AND compiles (one-op programs — the shape-trap
cost model); a ``lax.while_loop``/``scan`` program counts ONCE however
many trips it runs; ``np.asarray`` on the CPU backend is buffer-protocol
zero-copy and honestly invisible to the sync funnels; ``device_put``
h2d bytes are counted at the public ``jax.device_put`` spelling (the
one this codebase's feeds use), summing the argument's leaf ``nbytes``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from tpu_sgd.obs import spans as _spans

__all__ = ["RuntimeCounters", "inc", "enable", "disable", "is_enabled",
           "record_wire", "snapshot", "reset", "deltas", "wire_ratios"]

logger = logging.getLogger("tpu_sgd.obs")

#: graftlint lock-discipline declaration (tpu_sgd/analysis): the counts
#: dict is written from every thread the patches observe (training,
#: prefetch worker, serving flush, io_callback) — `a += 1` on a dict
#: entry is a read-modify-write that loses updates without the lock.
GRAFTLINT_LOCKS = {
    "RuntimeCounters": {
        "_counts": "_lock",
    },
}

#: fast-path gate: ``inc()`` reads this ONE module global and returns
#: when falsy — the entire disabled-mode cost (failpoints discipline)
_ENABLED = False


class RuntimeCounters:
    """Thread-safe ``name -> {n, bytes}`` accumulator.  Names are
    dotted, leading segment = subsystem (``train.dispatch``,
    ``serve.host_sync``, ``ingest.h2d_bytes`` ride ``n``/``bytes``).

    ``forward`` (a GIL-atomic single reference, default ``None``) tees
    every inc to a second consumer — the windowed time-series store
    (``tpu_sgd.obs.timeseries``) installs it on THE global instance so
    per-window counter series exist without a second set of hook
    sites.  It is called OUTSIDE the lock (the forward target has its
    own lock; holding both would invert against the window store's
    close listeners) and is pure host work, so the zero-added-runtime
    pin holds with it installed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, Dict[str, int]] = {}
        self.forward = None

    def inc(self, name: str, n: int = 1, nbytes: int = 0) -> None:
        with self._lock:
            c = self._counts.get(name)
            if c is None:
                c = self._counts[name] = {"n": 0, "bytes": 0}
            c["n"] += n
            c["bytes"] += nbytes
        fwd = self.forward
        if fwd is not None:
            try:
                fwd(name, n, nbytes)
            except Exception:  # accounting must never kill the hot path
                logger.warning("counter forward raised; dropped",
                               exc_info=True)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in self._counts.items()}

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


#: THE process-wide registry instance (tests may build private ones)
_GLOBAL = RuntimeCounters()


def inc(name: str, n: int = 1, nbytes: int = 0) -> None:
    """Hot-path hook: bump a named counter.  This function sits on
    per-request / per-window paths; keep the disabled branch to the
    single global check."""
    if not _ENABLED:
        return
    _GLOBAL.inc(name, n, nbytes)


def record_wire(fmt: str, logical_nbytes: int, physical_nbytes: int,
                tag: Optional[str] = None) -> None:
    """Tag one wire transfer by FORMAT (``dense-f32`` / ``bf16`` /
    ``bcoo`` / ``topk``): ``physical`` is what actually crosses the
    link, ``logical`` the dense-f32-equivalent payload it represents —
    the pair is what makes the per-stage compression ratio a measured
    number (``obs.report`` prints ``logical / physical``;
    :func:`wire_ratios` computes it).  Counter names:
    ``<subsystem>.wire.<fmt>`` carries the physical bytes,
    ``<subsystem>.wire.<fmt>.logical`` the logical bytes, both with one
    ``n`` per transfer.  ``tag`` fans the format out per-instance with
    the SAME bracket syntax the span/event fan-outs use
    (``<subsystem>.wire.<fmt>[<tag>]`` — e.g. the sharded store's
    per-shard wires tag ``s0..s{S-1}``); consumers that key on the
    format (the wire-ratio detector's exempt list) strip the bracket
    suffix before comparing.  Same disabled-mode cost contract as
    :func:`inc` — one global load + falsy branch."""
    if not _ENABLED:
        return
    base = f"{_tagged('wire')}.{fmt}"
    if tag is not None:
        base = f"{base}[{tag}]"
    _GLOBAL.inc(base, nbytes=int(physical_nbytes))
    _GLOBAL.inc(base + ".logical", nbytes=int(logical_nbytes))


def wire_ratios(counts: Optional[Dict[str, Dict[str, int]]] = None
                ) -> Dict[str, Dict[str, float]]:
    """Per-stage wire compression table from a counter snapshot:
    ``{"<subsystem>.wire.<fmt>": {n, physical_bytes, logical_bytes,
    ratio}}`` where ``ratio = logical / physical`` (>= 1 means the wire
    shipped fewer bytes than the dense-f32 payload it represents).  THE
    one definition shared by ``obs.report`` and the benches."""
    counts = snapshot() if counts is None else counts
    out: Dict[str, Dict[str, float]] = {}
    for name, c in counts.items():
        if ".wire." not in name or name.endswith(".logical"):
            continue
        logical = counts.get(name + ".logical", {"bytes": 0})["bytes"]
        phys = c["bytes"]
        out[name] = {
            "n": c["n"],
            "physical_bytes": phys,
            "logical_bytes": logical,
            "ratio": (logical / phys) if phys else float("inf"),
        }
    return out


def snapshot() -> Dict[str, Dict[str, int]]:
    """Cumulative counters since ``enable()``/``reset()`` — the scrape
    surface.  ``{name: {"n": count, "bytes": bytes}}``."""
    return _GLOBAL.snapshot()


def reset() -> None:
    _GLOBAL.reset()


class deltas:
    """Region helper over the GLOBAL registry: ``with deltas() as d:``
    then ``d.get()`` returns the per-name count/byte deltas the region
    produced — the production spelling of what the analysis twins pin
    in tests (requires counters already enabled)."""

    def __enter__(self):
        self._start = snapshot()
        return self

    def get(self) -> Dict[str, Dict[str, int]]:
        out = {}
        for name, c in snapshot().items():
            s = self._start.get(name, {"n": 0, "bytes": 0})
            dn, db = c["n"] - s["n"], c["bytes"] - s["bytes"]
            if dn or db:
                out[name] = {"n": dn, "bytes": db}
        return out

    def __exit__(self, *exc):
        return False


# -- runtime patches ---------------------------------------------------------

_PATCHES: Optional[dict] = None  # saved originals while enabled


def _tagged(kind: str) -> str:
    return f"{_spans.current_subsystem()}.{kind}"


def enable() -> None:
    """Install the accounting patches and open the ``inc`` gate.
    Idempotent.  Prefer the ``tpu_sgd.obs.enable`` facade, which also
    wires tracing and flushes counters into the trace on disable."""
    global _ENABLED, _PATCHES
    if _ENABLED:
        return
    from jax._src import array as _array
    from jax._src import monitoring as _monitoring
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla as _pxla
    from jax._src.lib import xla_client as _xc
    import jax as _jax

    cls = _array.ArrayImpl
    saved = {
        "fastpath": _pjit._get_fastpath_data,
        "call": _pxla.ExecuteReplicated.__call__,
        "_value": cls._value,
        "item": cls.item,
        "__array__": cls.__array__,
        "device_put": _jax.device_put,
    }
    orig_call = saved["call"]
    orig_value, orig_item, orig_array = (saved["_value"], saved["item"],
                                         saved["__array__"])
    orig_put = saved["device_put"]
    depth = threading.local()

    def _no_fastpath(*a, **kw):
        return None

    def _counting_call(self, *args):
        _GLOBAL.inc(_tagged("dispatch"))
        return orig_call(self, *args)

    def _tick_sync(arr):
        if getattr(depth, "d", 0) > 0:
            return  # inner funnel of an already-counted materialization
        if arr._npy_value is None:  # an actual copy, not a cache hit
            _GLOBAL.inc(_tagged("host_sync"),
                        nbytes=int(getattr(arr, "nbytes", 0) or 0))

    class _nested:
        def __enter__(self):
            depth.d = getattr(depth, "d", 0) + 1

        def __exit__(self, *exc):
            depth.d -= 1

    @property
    def _counting_value(self):
        _tick_sync(self)
        with _nested():
            return orig_value.fget(self)

    def _counting_item(self, *args):
        _tick_sync(self)
        with _nested():
            return orig_item(self, *args)

    def _counting_array(self, *args, **kwargs):
        _tick_sync(self)
        with _nested():
            return orig_array(self, *args, **kwargs)

    def _counting_device_put(x, *args, **kwargs):
        try:
            nbytes = sum(int(getattr(leaf, "nbytes", 0) or 0)
                         for leaf in _jax.tree_util.tree_leaves(x))
        except Exception:
            nbytes = 0
        _GLOBAL.inc(_tagged("h2d"), nbytes=nbytes)
        return orig_put(x, *args, **kwargs)

    def _compile_listener(name: str, dur: float, **kw):
        # one backend_compile per XLA program built — eager one-op
        # programs included, which is exactly the shape-trap cost model
        if name.endswith("backend_compile_duration"):
            _GLOBAL.inc(_tagged("compile"))

    def _clear_cpp_caches():
        _pjit._cpp_pjit_cache_fun_only.clear()
        _pjit._cpp_pjit_cache_explicit_attributes.clear()
        _xc._xla.PjitFunctionCache.clear_all()

    # install INSIDE the try: these touch deep-private jax internals,
    # and a renamed attribute on a future jax must unwind whatever DID
    # install rather than leave the process half-hook-routed (the same
    # containment count_dispatches documents)
    try:
        _pjit._get_fastpath_data = _no_fastpath
        _pxla.ExecuteReplicated.__call__ = _counting_call
        cls._value = _counting_value
        cls.item = _counting_item
        cls.__array__ = _counting_array
        _jax.device_put = _counting_device_put
        _monitoring.register_event_duration_secs_listener(_compile_listener)
        saved["compile_listener"] = _compile_listener
        # functions warmed BEFORE enable hold installed fastpaths that
        # would bypass the dispatch hook — drop them so their next call
        # re-enters the (now fastpath-less) Python path; the compiled
        # executables survive, so this costs a re-trace of the C++
        # cache entry, never an XLA recompile
        _clear_cpp_caches()
    except Exception:
        _restore(saved)
        raise
    _PATCHES = saved
    _ENABLED = True


def _restore(saved: dict) -> None:
    from jax._src import array as _array
    from jax._src import monitoring as _monitoring
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla as _pxla
    from jax._src.lib import xla_client as _xc
    import jax as _jax

    _pjit._get_fastpath_data = saved["fastpath"]
    _pxla.ExecuteReplicated.__call__ = saved["call"]
    cls = _array.ArrayImpl
    cls._value = saved["_value"]
    cls.item = saved["item"]
    cls.__array__ = saved["__array__"]
    _jax.device_put = saved["device_put"]
    listener = saved.get("compile_listener")
    if listener is not None:
        try:
            _monitoring._unregister_event_duration_listener_by_callback(
                listener)
        except Exception:
            logger.warning("could not unregister the compile listener",
                           exc_info=True)
    # entries cached while the fastpath was declined carry no fastpath
    # data and would stay on the slow path forever — drop them
    try:
        _pjit._cpp_pjit_cache_fun_only.clear()
        _pjit._cpp_pjit_cache_explicit_attributes.clear()
        _xc._xla.PjitFunctionCache.clear_all()
    except Exception:
        logger.warning("could not clear the C++ pjit caches",
                       exc_info=True)


def disable() -> None:
    """Unwind every patch and close the gate.  Idempotent.  Counter
    VALUES survive (scrape after disable is fine); ``reset()`` clears."""
    global _ENABLED, _PATCHES
    if not _ENABLED:
        return
    _ENABLED = False
    saved, _PATCHES = _PATCHES, None
    if saved is not None:
        _restore(saved)


def is_enabled() -> bool:
    return _ENABLED
