"""Exact least-squares via normal equations — the one-pass TPU solver.

Reference parity note: the reference solves config 1/4's least-squares
problems iteratively through ``GradientDescent.runMiniBatchSGD`` ([U]
mllib/optimization/GradientDescent.scala, SURVEY.md §2 #2) because on a
Spark cluster each pass over the RDD costs a full job.  On TPU a *single*
pass is one Gram-matrix matmul on the MXU, so the exact solution

    (XᵀX / n + reg·I) w = Xᵀy / n

is cheaper than a handful of SGD iterations whenever ``d`` is modest
(d ≤ a few thousand: the Gram matmul reads X once and the (d, d) solve is
microseconds).  Upstream Spark ships the same idea one package over as
``spark.ml``'s WeightedLeastSquares "normal" solver; here it slots behind
the SAME ``Optimizer`` boundary (SURVEY.md §2 #1) so the GLM harness,
intercept handling, persistence, and streaming warm-starts all compose
with it unchanged.

Scaling: the Gram accumulation is data-parallel by construction — each
shard computes its local ``(XᵀX, Xᵀy, yᵀy, n)`` and one ``lax.psum``
combines them over ICI (the same collective pattern as the SGD path,
SURVEY.md §5.8); the tiny (d, d) solve then runs replicated on every core.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.ops.gradients import acc_dtype, matmul_dtype
from tpu_sgd.optimize.optimizer import Dataset, Optimizer

Array = jax.Array


def _gram_sums(X: Array, y: Array) -> Tuple[Array, Array, Array, Array]:
    """One pass: ``(XᵀX, Xᵀy, yᵀy, n)`` with f32 accumulation (bf16 data
    runs the Gram matmul on the MXU in bf16)."""
    mm_dtype = matmul_dtype(X)
    acc = acc_dtype(mm_dtype)
    Xc = X.astype(mm_dtype)
    A = jnp.dot(Xc.T, Xc, preferred_element_type=acc)
    b = jnp.dot(Xc.T, y.astype(mm_dtype), preferred_element_type=acc)
    yty = jnp.dot(y, y, preferred_element_type=acc)
    return A, b, yty, jnp.float32(X.shape[0])


def _solve(A, b, yty, n, reg_param: float):
    """Solve the regularized normal equations and return (w, loss).

    Objective matched to the SGD path's SquaredL2Updater semantics:
    ``(1/n)·Σ ½(x.w − y)² + (reg/2)·‖w‖²``.
    """
    d = A.shape[0]
    An = A / n + reg_param * jnp.eye(d, dtype=A.dtype)
    bn = b / n
    # Cholesky: the regularized Gram is SPD for reg>0 and full-rank data;
    # rank deficiency surfaces as NaNs, which ``optimize`` checks and raises.
    L = jax.lax.linalg.cholesky(An)
    w = jax.lax.linalg.triangular_solve(
        L,
        jax.lax.linalg.triangular_solve(
            L, bn[:, None], left_side=True, lower=True
        ),
        left_side=True,
        lower=True,
        transpose_a=True,
    )[:, 0]
    # HIGHEST-precision loss dots (ops/gram.py contract): near the optimum
    # the loss is the near-zero difference of ~||y||^2-magnitude terms,
    # and TPU default-precision (bf16-pass) dots would report garbage —
    # the streamed path accumulates its totals at HIGHEST only to throw
    # that precision away here otherwise
    from tpu_sgd.ops.gram import _dot_hi

    sd = A.dtype
    loss = (
        0.5 * (_dot_hi(w, _dot_hi(A, w, sd), sd) - 2.0 * _dot_hi(w, b, sd)
               + yty) / n
        + 0.5 * reg_param * _dot_hi(w, w, sd)
    )
    return w, loss


#: memo-key contract (graftlint memo-key rule): the compiled-solver
#: cache keys on exactly these roots; reg is baked into the program, so
#: dropping reg_param from the key would serve one lambda's solver to
#: every other
GRAFTLINT_MEMO = {
    "NormalEquations._cache": ("reg_param", "mesh", "with_valid"),
}


class NormalEquations(Optimizer):
    """Exact least-squares solver behind the Optimizer boundary.

    Drop-in alternative to ``GradientDescent`` for the least-squares family
    (LeastSquaresGradient × Simple/SquaredL2 updater); raises nothing for
    other losses because it never sees them — model wrappers choose it
    explicitly.  ``reg_param`` is the L2 coefficient (0 = plain OLS).

    ``set_mesh`` shards the Gram accumulation row-wise over a 1-D data mesh
    with a single ICI all-reduce; the solve is replicated.
    """

    def __init__(self, reg_param: float = 0.0):
        self.reg_param = float(reg_param)
        self.mesh = None
        #: None = AUTO: stream when host data exceeds the probed device
        #: budget (the zero-flag placement contract); True/False force
        self.host_streaming = None
        self.stream_batch_rows = None
        self.stream_resume_dir = None
        self._loss = None
        self._cache = {}

    def set_reg_param(self, r: float):
        self.reg_param = float(r)
        return self

    def set_host_streaming(self, flag: bool = True,
                           batch_rows: int = None,
                           resume_dir: str = None):
        """Beyond-HBM EXACT least squares: accumulate the Gram totals by
        streaming host row chunks through the device with an O(d²) carry
        (``GramLeastSquaresGradient._streamed_totals``) — the literal
        analogue of the reference's spark.ml normal solver aggregating
        its Gram over an RDD of ANY size — then run the tiny (d, d)
        solve.  EXACT: every row contributes (no dropped tail).
        Composes with ``set_mesh``: each shard streams its own host
        slice to its own device and the totals combine once
        (``parallel/gram_parallel.py`` ``build_streamed_total_stats``).

        Precision note: the streamed totals accumulate at f32 HIGHEST
        (the statistics contract, ``ops/gram.py``), which is MORE
        precise than the resident bf16-data Gram matmul — trajectories
        agree to that rounding.  ``batch_rows`` caps the host→device
        chunk EXACTLY (default 64 blocks); ``resume_dir`` makes the
        accumulation resumable (one tiny carry checkpoint per chunk —
        see ``_streamed_totals``).

        The DEFAULT is AUTO: with no flag set, ``optimize`` streams
        whenever the host data exceeds the probed device budget (and
        runs resident otherwise) — ``set_host_streaming(False)`` forces
        the resident path.

        The chunk feed runs through the shared double-buffered ingest
        pipeline (``tpu_sgd/io``; README "Ingestion pipeline"): chunk
        ``k+1`` transfers while chunk ``k`` accumulates, and the
        ``batch_rows`` budget should allow for the two in-flight
        chunks."""
        self.host_streaming = bool(flag)
        if batch_rows is not None:
            if int(batch_rows) < 1:
                raise ValueError(
                    f"batch_rows must be positive, got {batch_rows}"
                )
            self.stream_batch_rows = int(batch_rows)
        if resume_dir is not None:
            # sticky like batch_rows: re-asserting the flag must not
            # silently drop crash protection (clear via the attribute)
            self.stream_resume_dir = resume_dir
        return self

    def set_mesh(self, mesh):
        from tpu_sgd.parallel.mesh import has_model_axis

        if has_model_axis(mesh):
            raise ValueError(
                "NormalEquations shards rows over a 1-D 'data' mesh; a "
                "2-D (data, model) mesh would silently replicate X across "
                "the model axis — use a data-only mesh"
            )
        self.mesh = mesh
        return self

    @property
    def loss_history(self):
        """Length-1 loss history (the final objective), matching the SGD
        optimizers' return contract shape (SURVEY.md §5.5)."""
        return self._loss

    def _solver(self, with_valid: bool):
        # Mesh is hashable and used directly (an id() key could alias a new
        # mesh to a stale compiled solver after GC id reuse).
        key = (self.reg_param, self.mesh, with_valid)
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        reg = self.reg_param
        if self.mesh is None:

            @jax.jit
            def fn(X, y):
                return _solve(*_gram_sums(X, y), reg)

        else:
            from jax.sharding import PartitionSpec as P

            from tpu_sgd.parallel.mesh import DATA_AXIS, shard_map_fn

            def local(X, y, valid=None):
                if valid is not None:
                    vf = valid.astype(jnp.float32)
                    X = X * vf[:, None].astype(X.dtype)
                    y = y * vf
                    n_local = jnp.sum(vf)
                else:
                    n_local = jnp.float32(X.shape[0])
                A, b, yty, _ = _gram_sums(X, y)
                A, b, yty, n = jax.lax.psum(
                    (A, b, yty, n_local), DATA_AXIS
                )
                return _solve(A, b, yty, n, reg)

            if with_valid:
                body = local
                in_specs = (P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS))
            else:
                body = lambda X, y: local(X, y)
                in_specs = (P(DATA_AXIS, None), P(DATA_AXIS))
            fn = jax.jit(shard_map_fn(self.mesh, body, in_specs, (P(), P())))
        self._cache[key] = fn
        return fn

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        X, y = data
        from tpu_sgd.ops.sparse import is_sparse

        if is_sparse(X):
            raise NotImplementedError(
                "NormalEquations needs dense features: the d x d Gram "
                "matrix is dense regardless of input sparsity (47k "
                "features -> 8.8 GB), so wide sparse problems should use "
                "GradientDescent/LBFGS/OWLQN instead"
            )
        stream = self.host_streaming
        if stream is None and not isinstance(X, jax.Array):
            # AUTO placement (the user never picks it — the scheduler
            # contract, SURVEY.md §2 #16): a host dataset beyond the
            # probed per-device budget streams its Gram totals instead
            # of OOMing on the full commit; shards divide the budget.
            from tpu_sgd.plan import device_budget

            shape = np.shape(X)
            budget, _src = device_budget()
            multihost = False
            if self.mesh is not None:
                from tpu_sgd.optimize.streamed_costfun import (
                    mesh_spans_processes,
                )
                from tpu_sgd.parallel.mesh import DATA_AXIS

                multihost = mesh_spans_processes(self.mesh)
                if multihost:
                    # each process holds only ITS rows, spread over its
                    # LOCAL devices — scaling by the global shard count
                    # would over-commit HBM by process_count
                    budget *= max(1, len(self.mesh.local_devices))
                else:
                    budget *= dict(self.mesh.shape).get(DATA_AXIS, 1)
            itemsize = np.dtype(getattr(X, "dtype", np.float32)).itemsize
            data_bytes = shape[0] * shape[1] * itemsize + shape[0] * 4.0
            stream = data_bytes > budget
            if stream and multihost:
                # the streamed totals builder is single-host; AUTO must
                # not pick a path it cannot run — take the resident route
                # and SAY that it may not fit, rather than crash later
                # blaming a choice the user never made
                import warnings

                warnings.warn(
                    f"data ({data_bytes / 1e9:.2f} GB/process) exceeds "
                    f"the local-device budget ({budget / 1e9:.2f} GB) "
                    "but the streamed totals build is single-host; "
                    "committing resident and it may exhaust device "
                    "memory — shrink the per-process rows or stream on "
                    "a local mesh",
                    RuntimeWarning, stacklevel=3,
                )
                stream = False
            if stream:
                from tpu_sgd.plan import logger

                logger.info(
                    "plan: normal host_streamed — data "
                    f"({data_bytes / 1e9:.2f} GB) exceeds the device "
                    f"budget ({budget / 1e9:.2f} GB); Gram totals "
                    "accumulate from host-streamed chunks (exact)"
                )
        if stream:
            # BEFORE any device coercion: the whole point is that X never
            # lives on the device in full
            if np.shape(initial_weights)[-1] != np.shape(X)[1]:
                raise ValueError(
                    f"initial_weights has length "
                    f"{np.shape(initial_weights)[-1]} but the data has "
                    f"{np.shape(X)[1]} features"
                )
            return self._optimize_host_streamed(X, y)
        X = jnp.asarray(X)
        y = jnp.asarray(y)
        if not jnp.issubdtype(y.dtype, jnp.inexact):
            y = y.astype(jnp.float32)
        w0 = jnp.asarray(initial_weights)
        if w0.shape[-1] != X.shape[1]:
            raise ValueError(
                f"initial_weights has length {w0.shape[-1]} but the data has "
                f"{X.shape[1]} features"
            )
        if self.mesh is None:
            w, loss = self._solver(with_valid=False)(X, y)
        else:
            from tpu_sgd.parallel.data_parallel import shard_dataset

            Xd, yd, valid = shard_dataset(self.mesh, X, y)
            if valid is not None:
                w, loss = self._solver(with_valid=True)(Xd, yd, valid)
            else:
                w, loss = self._solver(with_valid=False)(Xd, yd)
        return self._finish(w, loss)

    def _finish(self, w, loss):
        """Shared postlude: rank-deficiency surface + loss history."""
        if not bool(jnp.all(jnp.isfinite(w))):
            raise FloatingPointError(
                "normal-equations solve produced non-finite weights: the "
                "Gram matrix is rank-deficient (collinear or constant "
                "features) and reg_param="
                f"{self.reg_param} does not regularize it; set a positive "
                "reg_param or drop redundant features"
            )
        self._loss = np.asarray([float(loss)], np.float32)
        return w

    def _optimize_host_streamed(self, X, y):
        """Exact solve from host-streamed Gram totals (see
        ``set_host_streaming``)."""
        from tpu_sgd.ops.gram import (DEFAULT_BLOCK_ROWS,
                                      GramLeastSquaresGradient)

        Xh = np.asarray(X)
        yh = np.asarray(y)
        if not jnp.issubdtype(Xh.dtype, jnp.inexact):
            Xh = Xh.astype(np.float32)
        if not jnp.issubdtype(yh.dtype, jnp.inexact):
            yh = yh.astype(np.float32)
        n = Xh.shape[0]
        if self.mesh is not None:
            from tpu_sgd.optimize.streamed_costfun import (
                mesh_spans_processes,
            )
            from tpu_sgd.parallel.gram_parallel import (
                build_streamed_total_stats,
            )

            if mesh_spans_processes(self.mesh):
                # the per-device streamed builder device_puts to every
                # mesh device, which crashes on non-addressable remote
                # devices — fail with a real message instead
                raise NotImplementedError(
                    "streamed normal totals build single-host; on a "
                    "multi-host job run the resident meshed path, or "
                    "stream on a mesh of this process's devices"
                )

            data = build_streamed_total_stats(
                self.mesh, Xh, yh,
                batch_rows=self.stream_batch_rows,
                resume_dir=self.stream_resume_dir,
            )
            G, b, yty = data.G_tot, data.b_tot, data.yy_tot
        else:
            from tpu_sgd.ops.gram import streamed_totals_chunking

            B, chunk = streamed_totals_chunking(
                n, DEFAULT_BLOCK_ROWS, self.stream_batch_rows)
            sd = GramLeastSquaresGradient._resolve_stats_dtype(
                Xh.dtype, None)
            G, b, yty = GramLeastSquaresGradient._streamed_totals(
                Xh, yh, B, sd, chunk,
                resume_dir=self.stream_resume_dir)
        w, loss = jax.jit(_solve, static_argnums=(4,))(
            G, b, yty, jnp.asarray(float(n), G.dtype), self.reg_param
        )
        return self._finish(w, loss)
