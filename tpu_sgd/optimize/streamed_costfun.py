"""Host-streamed full-batch cost evaluation for the quasi-Newton optimizers.

Reference parity: the reference's LBFGS ``CostFun`` evaluates the FULL-batch
(loss, gradient) with ONE ``treeAggregate`` over an RDD of ANY size, for ANY
``Gradient`` ([U] mllib/optimization/LBFGS.scala, SURVEY.md §2 #18, §3.5) —
dataset scale and loss family are orthogonal there.  This module is the
TPU-native analogue for host-resident datasets larger than device HBM: each
evaluation streams the rows through the device in fixed-size chunks,
accumulating ``(grad_sum, loss_sum, count)`` in device-resident accumulators
(donated buffers, so accumulation allocates nothing per chunk), with the
next chunk's host→device transfer overlapping the current chunk's compute —
the executors-read-partitions-while-the-driver-schedules overlap of
SURVEY.md §3.1 without per-task scheduling cost.

Works for ANY gradient implementing ``batch_sums`` (least squares, logistic,
hinge, multinomial's flattened matrix weights): unlike the
sufficient-statistics schedule (least squares only — ``ops/gram.py``),
nothing here assumes the loss has fixed-size statistics.  This is the
literal chunked treeAggregate.

Mesh composition: under a 1-D data mesh each chunk is ``device_put``
row-sharded across the cores and the per-chunk partial sums ``psum`` over
ICI before accumulating into replicated accumulators — the multi-executor
treeAggregate shape.  On a multi-host job each process streams ITS OWN
local row slice and per-chunk global arrays assemble via
``make_array_from_process_local_data`` (no cross-host rows; the chunk
grid is agreed by allgather so every process runs the same psum'd
programs); single-process meshes stream every shard from this host.

Cost model: every evaluation re-reads the whole dataset through the host
feed (an LBFGS iteration is ~2 cost evaluations + 1 sweep), so this is the
schedule of LAST RESORT — ``plan_quasi_newton`` picks it only when the data
exceeds HBM and no statistics substitution exists (non-least-squares
losses).  The reference pays the same shape of cost: its CostFun re-reads
every partition per evaluation, from executor memory when cached and from
disk/recomputation when not.
"""

from __future__ import annotations

import math
from functools import lru_cache as _lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

#: default host→device chunk budget in bytes (~256 MB keeps two in-flight
#: buffers ~0.5 GB beside the model state; the planner overrides per the
#: probed HBM budget)
_DEFAULT_CHUNK_BYTES = 256e6


def mesh_spans_processes(mesh) -> bool:
    """True when ``mesh`` contains devices of OTHER processes — the
    multihost regime where chunk arrays must assemble from per-process
    local slices and the chunk grid is agreed by collectives.  A mesh of
    only this process's devices streams single-host even inside a
    multi-process job (gating on ``process_count() > 1`` alone would
    run a job-wide allgather nobody else joins)."""
    import jax

    pid = jax.process_index()
    return any(d.process_index != pid for d in mesh.devices.flat)


def default_stream_batch_rows(d: int, itemsize: int,
                              chunk_bytes: Optional[float] = None) -> int:
    """Rows per streamed chunk at a byte budget (default ~256 MB) —
    THE chunk-sizing policy, shared with ``plan_quasi_newton`` so the
    planner's estimate and the evaluator's default cannot drift."""
    if chunk_bytes is None:
        chunk_bytes = _DEFAULT_CHUNK_BYTES
    return max(1024, int(chunk_bytes // max(1, d * itemsize)))


@_lru_cache(maxsize=64)
def _replicated_zeros_fn(shape, dtype_name, sharding):
    """Cached jitted maker of replicated global zero accumulators."""
    return jax.jit(partial(jnp.zeros, shape, jnp.dtype(dtype_name)),
                   out_shardings=sharding)


class StreamedCostFun:
    """Chunked full-batch ``(loss, grad)`` / loss-sweep evaluator over
    host-resident rows.

    Returns RAW SUMS (``grad_sum``, ``loss_sum``, ``count``) — callers
    normalize and add their regularization terms, exactly like the
    in-memory ``Gradient.batch_sums`` contract the quasi-Newton loops
    already consume.

    One instance binds ``(gradient, X, y, chunking, mesh)`` and compiles
    its accumulate kernels once; every ``cost_sums``/``sweep_sums``/
    ``loss_sums`` call then streams the fixed chunk grid through them.
    """

    def __init__(self, gradient, X, y, batch_rows: Optional[int] = None,
                 mesh=None, device=None):
        self.gradient = gradient
        Xh = np.asarray(X)
        yh = np.asarray(y)
        multihost = mesh is not None and mesh_spans_processes(mesh)
        if Xh.ndim != 2 or (Xh.shape[0] == 0 and not multihost):
            # a multihost process MAY hold zero local rows (uneven
            # splits): it still must join every collective, feeding
            # all-invalid chunks
            raise ValueError(f"need a non-empty (n, d) matrix, got {Xh.shape}")
        if not jnp.issubdtype(Xh.dtype, jnp.inexact):
            Xh = Xh.astype(np.float32)  # match optimize()'s coercion
        if not jnp.issubdtype(yh.dtype, jnp.inexact):
            yh = yh.astype(np.float32)
        self.X = Xh
        self.y = yh
        n, d = Xh.shape
        self.n = n
        if batch_rows is None:
            batch_rows = default_stream_batch_rows(d, Xh.dtype.itemsize)
        cap = int(min(max(1, int(batch_rows)), n))
        self.mesh = mesh
        if mesh is None:
            self.device = device if device is not None else jax.devices()[0]
            self._row_sharding = self.device
            self._vec_sharding = self.device
            self._rep_sharding = self.device
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tpu_sgd.parallel.mesh import DATA_AXIS

            k = mesh.shape[DATA_AXIS]
            cap += (-cap) % k  # equal shard rows; padding rows are invalid
            self.device = None
            self._row_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            self._vec_sharding = NamedSharding(mesh, P(DATA_AXIS))
            self._rep_sharding = NamedSharding(mesh, P())
        self._multihost = multihost
        if self._multihost:
            # Multi-host: (X, y) are THIS process's local rows (the
            # executor-reads-its-own-splits contract, SURVEY.md §3.4).
            # Every process must run the SAME number of psum'd chunk
            # programs, so the chunk grid is agreed via allgather on the
            # LARGEST local slice; processes that exhaust their rows feed
            # all-invalid padding chunks (masked, exact sums).
            from jax.experimental import multihost_utils

            from tpu_sgd.parallel.mesh import DATA_AXIS

            k = mesh.shape[DATA_AXIS]
            k_local = dict(mesh.local_mesh.shape).get(DATA_AXIS, 1)
            # derive the chunk size from batch_rows ALONE — the
            # single-process `min(batch_rows, n)` clamp uses the LOCAL
            # row count, which differs across processes and would
            # desync the global chunk shapes
            cap_global = max(1, int(batch_rows))
            cap_global += (-cap_global) % k
            cap_local = max(1, cap_global * k_local // k)
            cap_local += (-cap_local) % max(1, k_local)
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray(n)))
            self.cap = cap_local  # per-process rows per chunk
            self.n_chunks = math.ceil(int(counts.max()) / cap_local)
        else:
            self.cap = cap
            self.n_chunks = math.ceil(n / cap)
        self._valid_full = None  # cached all-true mask for full chunks
        # zero-padded partial-chunk host buffers, keyed by row span: X/y
        # are immutable for the instance's lifetime, so the tail's
        # alloc+memcpy (and an exhausted multihost process's all-invalid
        # chunk) is paid once, not per evaluation (~3/LBFGS iteration)
        self._pad_cache = {}
        self._shape_cache = {}  # (mode, w shape/dtype) -> output aval tuple
        self._acc_cost = self._make_acc(mode="cost")
        self._acc_loss = self._make_acc(mode="loss")
        self._acc_sweep = (
            self._make_acc(mode="sweep")
            if hasattr(gradient, "loss_sweep") else None
        )

    # -- kernels -----------------------------------------------------------
    def _make_acc(self, mode: str):
        """Jitted chunk accumulator ``(w, Xc, yc, valid, *accs) -> accs``.
        ``mode``: 'cost' accumulates (grad, loss, count); 'loss' only
        (loss, count) — XLA dead-code-eliminates the gradient matmul;
        'sweep' accumulates the (T,) trial losses + count."""
        g = self.gradient
        mesh = self.mesh

        def psum_if_meshed(vals):
            if mesh is None:
                return vals
            from tpu_sgd.parallel.mesh import DATA_AXIS

            return jax.lax.psum(vals, DATA_AXIS)

        if mode == "cost":
            def body(w, Xc, yc, valid, ag, al, ac):
                gs, ls, c = g.batch_sums(Xc, yc, w, mask=valid)
                gs, ls, c = psum_if_meshed((gs, ls, c))
                return ag + gs, al + ls, ac + c
            n_acc = 3
        elif mode == "loss":
            def body(w, Xc, yc, valid, al, ac):
                _, ls, c = g.batch_sums(Xc, yc, w, mask=valid)
                ls, c = psum_if_meshed((ls, c))
                return al + ls, ac + c
            n_acc = 2
        else:  # sweep: w is the (T, d_flat) trial stack
            def body(w, Xc, yc, valid, al, ac):
                ls, c = g.loss_sweep(Xc, yc, w, mask=valid)
                ls, c = psum_if_meshed((ls, c))
                return al + ls, ac + c
            n_acc = 2

        donate = tuple(range(4, 4 + n_acc))
        if mesh is None:
            return jax.jit(body, donate_argnums=donate)
        from jax.sharding import PartitionSpec as P

        from tpu_sgd.parallel.mesh import DATA_AXIS, shard_map_fn

        in_specs = (P(), P(DATA_AXIS, None), P(DATA_AXIS),
                    P(DATA_AXIS)) + (P(),) * n_acc
        out_specs = (P(),) * n_acc
        return jax.jit(shard_map_fn(self.mesh, body, in_specs, out_specs),
                       donate_argnums=donate)

    # -- chunk feed --------------------------------------------------------
    def _chunk(self, i: int):
        """``(Xc, yc, valid)`` device buffers for chunk ``i`` — the tail
        chunk is zero-padded to the fixed ``cap`` so ONE compiled program
        serves the whole grid (the valid mask keeps sums exact).  On a
        multi-host job, ``cap`` is the PER-PROCESS chunk rows and the
        global array assembles from each process's local slice
        (``make_array_from_process_local_data`` — no cross-host rows)."""
        if self._multihost:
            return self._chunk_multihost(i)
        s = i * self.cap
        e = min(s + self.cap, self.n)
        Xb, yb = self.X[s:e], self.y[s:e]
        if e - s < self.cap:
            hit = self._pad_cache.get((s, e))
            if hit is None:
                Xp = np.zeros((self.cap, self.X.shape[1]), self.X.dtype)
                Xp[: e - s] = Xb
                yp = np.zeros((self.cap,), self.y.dtype)
                yp[: e - s] = yb
                valid = np.zeros((self.cap,), bool)
                valid[: e - s] = True
                hit = (Xp, yp,
                       jax.device_put(valid, self._vec_sharding))
                self._pad_cache[(s, e)] = hit
            Xb, yb, vd = hit
        else:
            if self._valid_full is None:
                self._valid_full = jax.device_put(
                    np.ones((self.cap,), bool), self._vec_sharding)
            vd = self._valid_full
        return (
            jax.device_put(Xb, self._row_sharding),
            jax.device_put(yb, self._vec_sharding),
            vd,
        )

    def _chunk_multihost(self, i: int):
        s = min(i * self.cap, self.n)
        e = min(s + self.cap, self.n)
        if e - s == self.cap:  # full chunk: zero-copy slices, cached mask
            Xp, yp = self.X[s:e], self.y[s:e]
            if self._valid_full is None:
                self._valid_full = jax.make_array_from_process_local_data(
                    self._vec_sharding, np.ones((self.cap,), bool))
            vd = self._valid_full
        else:  # partial or exhausted: zero-pad, mask the real rows
            # cached per span — every exhausted chunk shares (s, e) with
            # s == e, so a zero-row process builds its all-invalid chunk
            # once, not n_chunks times per evaluation
            hit = self._pad_cache.get((s, e))
            if hit is None:
                Xp = np.zeros((self.cap, self.X.shape[1]), self.X.dtype)
                yp = np.zeros((self.cap,), self.y.dtype)
                valid = np.zeros((self.cap,), bool)
                if e > s:
                    Xp[: e - s] = self.X[s:e]
                    yp[: e - s] = self.y[s:e]
                    valid[: e - s] = True
                hit = (Xp, yp, jax.make_array_from_process_local_data(
                    self._vec_sharding, valid))
                self._pad_cache[(s, e)] = hit
            Xp, yp, vd = hit
        return (
            jax.make_array_from_process_local_data(self._row_sharding, Xp),
            jax.make_array_from_process_local_data(self._vec_sharding, yp),
            vd,
        )

    def _stream(self, w, kernel, accs):
        """Drive the chunk grid through ``kernel``: the device step for
        chunk ``i`` is dispatched (async) BEFORE chunk ``i+1`` is
        assembled and transferred, so host feed and device compute
        overlap; only the caller's final read blocks."""
        if self._multihost:
            # device_put cannot target non-addressable devices; the
            # replicated weights assemble from identical per-process data
            w = jax.make_array_from_process_local_data(
                self._rep_sharding, np.asarray(w))
        else:
            w = jax.device_put(w, self._rep_sharding)
        nxt = self._chunk(0)
        for i in range(self.n_chunks):
            cur = nxt
            accs = kernel(w, *cur, *accs)
            if i + 1 < self.n_chunks:
                nxt = self._chunk(i + 1)
        return accs

    def _zeros(self, shapes):
        if self._multihost:
            # a compiled SPMD program may produce global replicated
            # arrays where a host-side placement cannot; the jitted
            # makers are cached per (shape, dtype, sharding) — the
            # DONATED buffers must be fresh, the compiled fn need not be
            return tuple(
                _replicated_zeros_fn(s.shape, jnp.dtype(s.dtype).name,
                                     self._rep_sharding)()
                for s in shapes
            )
        return tuple(
            jnp.zeros(s.shape, s.dtype, device=self._rep_sharding)
            for s in shapes
        )

    def _probe_shapes(self, mode, fn, w):
        """Accumulator output avals for ``fn`` at this weight shape —
        memoized: re-tracing the gradient via eval_shape on every hot
        evaluation (3+/LBFGS iteration) would be pure waste."""
        key = (mode, tuple(jnp.shape(w)), str(jnp.result_type(w)))
        hit = self._shape_cache.get(key)
        if hit is None:
            sds = jax.ShapeDtypeStruct
            Xc = sds((self.cap, self.X.shape[1]), self.X.dtype)
            yc = sds((self.cap,), self.y.dtype)
            valid = sds((self.cap,), jnp.bool_)
            hit = jax.eval_shape(fn, w, Xc, yc, valid)
            self._shape_cache[key] = hit
        return hit

    # -- public sums -------------------------------------------------------
    def cost_sums(self, w):
        """Full-batch ``(grad_sum, loss_sum, count)`` of ``w``."""
        g = self.gradient
        shapes = self._probe_shapes(
            "cost", lambda w_, X_, y_, v_: g.batch_sums(X_, y_, w_, mask=v_), w)
        return self._stream(w, self._acc_cost, self._zeros(shapes))

    def loss_sums(self, w):
        """Full-batch ``(loss_sum, count)`` — the gradient matmul is
        compiled out (line-search trials of non-sweep gradients)."""
        g = self.gradient
        shapes = self._probe_shapes(
            "loss", lambda w_, X_, y_, v_: g.batch_sums(X_, y_, w_, mask=v_)[1:], w)
        return self._stream(w, self._acc_loss, self._zeros(shapes))

    def sweep_sums(self, W):
        """Full-batch ``(loss_sums (T,), count)`` of a trial-weight stack
        — the whole backtracking ladder reads each chunk once."""
        if self._acc_sweep is None:
            raise NotImplementedError(
                f"{type(self.gradient).__name__} has no loss_sweep rule"
            )
        g = self.gradient
        shapes = self._probe_shapes(
            "sweep", lambda w_, X_, y_, v_: g.loss_sweep(X_, y_, w_, mask=v_), W)
        return self._stream(W, self._acc_sweep, self._zeros(shapes))
