"""Device-resident training driver: the whole run as ONE dispatch.

PR 5's superstep executor cut the per-iteration host dispatch tax K-fold
but kept one full host round-trip per superstep: the convergence test,
the stop-signal poll, and the bookkeeping replay all lived host-side, so
even at K=8 the driver paid a measured ~1.7 ms/iter residual slope
(``BENCH_SUPERSTEP.json``).  The MLlib lineage we reproduce defines
convergence as a weight-delta test that is pure device arithmetic
(arXiv:1505.06807) — there is no reason the steady state ever touches
the host.  This module moves the *run loop itself* onto the device:

* a ``lax.while_loop`` whose body is the existing fused superstep scan
  (the same per-step math as :func:`make_superstep` /
  :func:`make_shared_batch_superstep` — measured bitwise-identical to
  the dispatched superstep programs on this harness, all three sampling
  modes, ``tests/test_resident.py``),
* the convergence predicate (weight-delta tolerance), iteration
  counter, and per-step loss/norm history carried in the loop state, so
  a converged-or-budget-exhausted run is ONE program dispatch
  (``assert_dispatch_count(1)``-pinned), and
* host involvement ONLY at checkpoint/listener cadence and stop-signal
  polls: an ordered ``io_callback`` fires every ``cadence`` supersteps
  with a bounded ring buffer of per-step ys, which replays through the
  existing :func:`_replay_fused_steps` — the loss history, the detected
  convergence iteration, listener events, and the checkpoint cadence
  are byte-for-byte the superstep driver's, and
* feature state as CARRY state (``with_extra``): the compressed wire's
  error-feedback accumulator rides the while-loop carry next to the
  weights with its per-step history on a seventh ring leaf, so
  ``set_residency`` + ``wire_compress`` composes in ONE program (the
  lifted PR 9 DEVIATION; ADVICE.md "One driver, many carries") —
  every feed is a ``step_fn`` + ``*data`` variant of this one driver
  (dense full-batch, fully-resident slab, fixed-nse BCOO in
  ``optimize/streamed_sparse.py``), never a second loop.

Why a bounded RING, not whole-run ys: a while_loop cannot return
per-trip stacked outputs (its carry is fixed-shape), and even if it
could, an unbounded ``(num_iterations, d)`` history pinned in the carry
is exactly the host/device-memory trap the cadence exists to avoid — a
10M-iteration run must not stage a 10M-row weight history anywhere.
The ring holds one cadence window (``cadence * k`` steps); each window
is surfaced to the host once and overwritten.

Convergence authority: the device predicate replicates the host rule
(``delta < tol * max(||w||, 1)`` from the second recorded update on) in
f32 and decides only when the LOOP exits; the host replay remains the
single bookkeeping authority.  In the astronomically-unlikely event the
f32 predicate fires where the host f64 comparison disagrees, the driver
simply re-dispatches the program from the exact replayed state — the
per-step math is bitwise-stable across dispatches, so the trajectory is
unchanged and the disagreement costs one extra launch, never a drift.

Failure containment: the window callback NEVER lets an exception cross
the FFI boundary (an exception escaping an ``io_callback`` would
surface as an opaque ``XlaRuntimeError`` and defeat the retry/resume
machinery).  The stop-probe phase passes the ``io.resident_callback``
failpoint inside the ingest ``RetryPolicy`` scope (transient faults
heal in place, before any bookkeeping mutates); anything that still
raises — an injected checkpoint-save fault, a listener error — is
stashed, the loop is stopped via the returned flag, and the ORIGINAL
exception re-raises host-side after the dispatch returns, where
``TrainingSupervisor`` can see its true class and resume from the last
checkpoint (bitwise, like every other healed path).

Since graftlint v2 these are CHECKED contracts, not conventions: the
``callback-discipline`` rule pins the stash-flag-reraise shape, the
``ordered=True`` requirement, and the bounded-ring no-growth rule at
every ``io_callback`` site, and ``carry-stability`` pins the
``jnp.asarray``-pinned loop carry below (see ADVICE.md "Weak-type
carry drift" and "io_callback exception boundary", and README "Static
analysis" for the rule table).  Runtime twins:
``tpu_sgd.analysis.assert_no_host_sync`` (a warmed resident run syncs
once per cadence window + three end-of-run scalars, pinned in
``tests/test_resident.py``) and ``assert_bounded_callback_buffer``.

Observability (``tpu_sgd.obs``, PR 8): the driver emits
``train.resident_dispatch`` / ``train.window`` spans and a
``train.io_callback`` counter, and the windows+3 sync pin holds with
tracing ON — span timestamps must never ``block_until_ready`` mid-loop;
under async dispatch a span duration is *attribution*, not device
truth, and counts/bytes are the truth on this harness (ADVICE.md "Span
timestamps are attribution, not truth").
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.config import SGDConfig
from tpu_sgd.obs.counters import inc as obs_inc
from tpu_sgd.obs.spans import span
from tpu_sgd.reliability.failpoints import failpoint

_BOOL = jax.ShapeDtypeStruct((), jnp.bool_)


class ResidentBookkeeper:
    """Host-side bookkeeping state for ONE resident run.

    Owns the mutable pieces the legacy loops kept inline — the loss
    list, the running reg value, the listener, the checkpoint save
    callback — and replays ring-buffer windows through the one shared
    :func:`_replay_fused_steps`, so resident bookkeeping cannot drift
    from the superstep driver's.  ``on_window`` is the ``io_callback``
    target body; ``replay`` is also called by the driver for the tail
    window after the dispatch returns.
    """

    def __init__(self, config: SGDConfig, k: int, cadence: int, *,
                 losses: list, reg_val: float, start_iter: int,
                 listener=None, save_cb: Optional[Callable] = None,
                 save_every: int = 0, stop_signal=None,
                 retry_policy=None, check_numerics: bool = False,
                 extras_cb: Optional[Callable] = None):
        self.cfg = config
        self.k = int(k)
        self.cadence = int(cadence)
        self.losses = losses
        self.reg_val = float(reg_val)
        self.listener = listener
        self.save_cb = save_cb
        self.save_every = int(save_every)
        self.stop_signal = stop_signal
        self.retry_policy = retry_policy
        self.check_numerics = bool(check_numerics)
        #: installed by callers whose step carries extra optimizer state
        #: (the EF accumulator): called as ``extras_cb(i0w, extras_ring)``
        #: BEFORE each window replay so a checkpoint save fired inside
        #: the replay reads the iteration-exact post-update extras
        self.extras_cb = extras_cb
        #: last iteration whose bookkeeping has been replayed (the
        #: preemption boundary and the resume point after a false
        #: device-convergence)
        self.replayed_through = int(start_iter) - 1
        #: host copy of the weights AT ``replayed_through`` (from the
        #: ring ys — the truncation-safe final state when a run ends
        #: mid-superstep, exactly like the superstep drivers')
        self.last_w: Optional[np.ndarray] = None
        #: host copy of the extras leaf AT ``replayed_through`` (set only
        #: when the loop carries extras) — the resume state for a false
        #: device-convergence re-dispatch, like ``last_w``
        self.last_extra: Optional[np.ndarray] = None
        self.host_converged = False
        self.stop_requested = False
        self.error: Optional[BaseException] = None
        self.windows_fired = 0
        self._t_mark = time.perf_counter()

    # -- io_callback target --------------------------------------------------
    def on_window(self, i0w, *rings) -> np.bool_:
        """Replay one FULL cadence window and poll the stop signal.

        Returns the device-side stop flag.  Never raises: see the module
        docstring's failure-containment contract."""
        try:
            self.windows_fired += 1
            # explicit counter for the one event the runtime patches
            # cannot classify on their own (a callback firing is neither
            # a dispatch nor a transfer); disabled cost: one global
            # load + branch
            obs_inc("train.io_callback")
            # the span opens BEFORE the win_start fetch so the window's
            # one counted scalar sync (and, off-CPU, the ring fetch
            # bytes) attribute to `train`, and it runs on the runtime's
            # callback thread — thread-local stacks keep it from
            # parenting onto whatever the dispatching thread has open
            with span("train.window", supersteps=self.cadence) as sp:

                def _probe():
                    # THE host-side fault-injection site of the resident
                    # path (registered in HOOK_SITES); placed BEFORE any
                    # bookkeeping mutation so a healed retry replays
                    # nothing twice
                    failpoint("io.resident_callback")
                    return bool(self.stop_signal()) \
                        if self.stop_signal is not None else False
                if self.retry_policy is not None:
                    want_stop = self.retry_policy.call(_probe)
                else:
                    want_stop = _probe()
                # the window's ONE scalar fetch (win_start), made once
                # and shared by the span attr and the replay — the
                # windows+3 sync pin in tests/test_resident.py holds
                # with tracing ON because nothing here fetches twice
                i0_host = int(i0w)
                sp.set(i0=i0_host)
                # materialize to HOST numpy at the FFI boundary:
                # io_callback hands the rings over as device arrays, and
                # replaying with python slicing/indexing on those would
                # dispatch an eager one-op program per touched element
                # (the shape-trap cost model) — one bulk fetch per leaf
                # instead
                self.replay(i0_host,
                            tuple(np.asarray(r) for r in rings),
                            self.cadence)
            if want_stop and not self.host_converged:
                self.stop_requested = True
            return np.bool_(self.host_converged or self.stop_requested)
        except BaseException as e:  # noqa: BLE001 — FFI boundary, see doc
            self.error = e
            return np.bool_(True)

    # -- shared replay -------------------------------------------------------
    def replay(self, i0w: int, rings, n_supersteps: int) -> None:
        """Replay ``n_supersteps`` supersteps of ring ys starting at
        iteration ``i0w`` with EXACTLY the fused drivers' bookkeeping
        (:func:`_replay_fused_steps` per superstep: per-iteration loss
        history, listener events, convergence at the true iteration,
        checkpoint cadence).  Overshoot steps past ``num_iterations``
        (the while body's scan never branches on the budget) are bounded
        out here, exactly as the superstep drivers truncate their tails.

        A 7-leaf ``rings`` carries per-step EXTRAS (the EF accumulator
        ring of the compressed carry) as its last leaf: ``extras_cb``
        fires first with the whole window so a mid-window checkpoint
        save reads the iteration-exact post-update state, and
        ``last_extra`` tracks the replayed boundary like ``last_w``.
        """
        from tpu_sgd.optimize.gradient_descent import _replay_fused_steps

        K, cfg = self.k, self.cfg
        exs = None
        if len(rings) == 7:
            exs = rings[6]
            rings = rings[:6]
            if self.extras_cb is not None:
                self.extras_cb(i0w, exs)
        ws, ls, rs, cs, dns, wns = rings
        now = time.perf_counter()
        n_steps = max(1, n_supersteps * K)
        wall_dt = (now - self._t_mark) / n_steps
        self._t_mark = now
        for s in range(n_supersteps):
            base = i0w + s * K
            if base > cfg.num_iterations:
                break  # whole superstep is overshoot (tail window only)
            steps = min(K, cfg.num_iterations - base + 1)
            lo = s * K
            t_last, self.reg_val, conv = _replay_fused_steps(
                (ws[lo:lo + K], ls[lo:lo + K], rs[lo:lo + K],
                 cs[lo:lo + K], dns[lo:lo + K], wns[lo:lo + K]),
                base, steps, self.losses, self.reg_val, cfg,
                listener=self.listener, wall_dt=wall_dt,
                check_numerics=self.check_numerics,
                save_cb=self.save_cb, save_every=self.save_every,
            )
            self.replayed_through = base + t_last
            self.last_w = np.asarray(ws[lo + t_last])
            if exs is not None:
                self.last_extra = np.asarray(exs[lo + t_last])
            if conv:
                self.host_converged = True
                break


class ResidentLoop:
    """One compiled whole-run program: ``lax.while_loop`` over fused
    superstep scans, with an ordered ``io_callback`` window hook.

    ``step_fn(w, i, reg_val, *data) -> (new_w, loss_i, new_reg, count)``
    is the per-iteration unit — an adapter around the SAME
    :func:`make_step` the superstep drivers scan over, closed over
    nothing (the data rides as program arguments ``*data`` so it enters
    as buffers, not baked constants).  ``k`` steps fuse per superstep
    (the scan), ``cadence`` supersteps per host window (the ring).

    ``with_extra=True`` is the SAME driver with one more carry leaf —
    feature state (the compressed wire's EF accumulator) rides the
    while-loop carry next to the weights and its per-step post-update
    values ride a seventh ring leaf, mirroring how
    :func:`make_compressed_superstep` carries EF in the scan.  The
    step contract becomes ``step_fn(w, extra, i, reg_val, *data) ->
    (new_w, new_extra, loss_i, new_reg, count)`` and ``run()`` takes
    ``extra0`` (see ADVICE.md "One driver, many carries": feature
    state must be carry state of the one driver, never per-driver
    bookkeeping — this is what lifted the PR 9 DEVIATION).

    One instance = one jitted program; ``run()`` may be called
    repeatedly (the stepwise driver memoizes instances per
    ``(gradient, updater, config, K, C)``) — a whole run, including
    resumes and tail windows, leaves exactly ONE compiled program
    behind (``assert_compile_count(1)``-guarded in tests).
    """

    def __init__(self, step_fn: Callable, config: SGDConfig, k: int,
                 cadence: int, *, with_extra: bool = False):
        if int(cadence) < 1:
            raise ValueError(f"cadence must be >= 1, got {cadence}")
        if int(k) < 1:
            raise ValueError(f"superstep k must be >= 1, got {k}")
        self.config = config
        self.k = int(k)
        self.cadence = int(cadence)
        self.with_extra = bool(with_extra)
        self._step_fn = step_fn
        # Installed by run() immediately before each dispatch and read
        # by the io_callback (which may execute on the runtime's
        # host-callback thread).  Safe vs the callback thread without a
        # lock — the write happens-before the dispatch that triggers
        # the reads, and no callback outlives its dispatch (the driver
        # blocks on the carry before clearing it) — but instances are
        # SHARED via the drivers' memo caches, so concurrent run()s
        # from different threads would clobber the handoff: _run_lock
        # serializes them (each run is independent; the per-step math
        # is bitwise-stable across dispatches, so ordering is free).
        self._hooks: Optional[ResidentBookkeeper] = None
        self._run_lock = threading.Lock()
        self._fn = jax.jit(self._build())

    # -- trace-time ----------------------------------------------------------
    def _fire(self, i0w, *rings):
        """io_callback trampoline: bound once into the trace, routed to
        the bookkeeper installed for the current dispatch."""
        return self._hooks.on_window(i0w, *rings)

    def _build(self):
        cfg = self.config
        K, C = self.k, self.cadence
        CK = C * K
        N = cfg.num_iterations
        tol = float(cfg.convergence_tol)
        step_fn = self._step_fn
        fire_cb = self._fire

        def loop(w0, rv0, i0, *data):
            from jax.experimental import io_callback

            from tpu_sgd.optimize.gradient_descent import pack_step_ys

            rings0 = (
                jnp.zeros((CK,) + w0.shape, w0.dtype),
                jnp.zeros((CK,), jnp.float32),  # loss
                jnp.zeros((CK,), jnp.float32),  # reg value
                jnp.zeros((CK,), jnp.float32),  # realized batch count
                jnp.zeros((CK,), jnp.float32),  # ||w_t - w_{t-1}||
                jnp.zeros((CK,), jnp.float32),  # ||w_t||
            )

            def superstep(carry):
                (i, w, rv, rws, rls, rrs, rcs, rdns, rwns, slot, conv,
                 stop) = carry
                idx = i + jnp.arange(K, dtype=jnp.int32)

                def body(c, ii):
                    cw, crv = c
                    new_w, loss_i, new_rv, cnt = step_fn(cw, ii, crv,
                                                         *data)
                    # per-step norms ride the ring (f32, the carry's
                    # fixed dtype) so the host replay keeps EXACTLY the
                    # legacy convergence comparison
                    return (new_w, new_rv), pack_step_ys(
                        cw, new_w, loss_i, new_rv, cnt, f32=True)

                (w, rv), ys = jax.lax.scan(body, (w, rv), idx)
                base = slot * K
                rws = jax.lax.dynamic_update_slice_in_dim(
                    rws, ys[0], base, 0)
                rls = jax.lax.dynamic_update_slice_in_dim(
                    rls, ys[1], base, 0)
                rrs = jax.lax.dynamic_update_slice_in_dim(
                    rrs, ys[2], base, 0)
                rcs = jax.lax.dynamic_update_slice_in_dim(
                    rcs, ys[3], base, 0)
                rdns = jax.lax.dynamic_update_slice_in_dim(
                    rdns, ys[4], base, 0)
                rwns = jax.lax.dynamic_update_slice_in_dim(
                    rwns, ys[5], base, 0)
                if tol > 0.0:
                    # the device twin of _replay_fused_steps' rule —
                    # recorded step (count > 0), second update on
                    conv_t = ((ys[3] > 0) & (idx > 1)
                              & (ys[4] < tol * jnp.maximum(ys[5], 1.0)))
                    conv = jnp.any(conv_t)
                slot = slot + 1
                # fire the window hook only on a FULL, un-converged
                # window: a converged (or budget-ending) partial window
                # replays host-side from the returned carry instead
                fire = (slot == C) & jnp.logical_not(conv)
                win_start = i - (C - 1) * K
                stop = jax.lax.cond(
                    fire,
                    lambda a: io_callback(fire_cb, _BOOL, *a,
                                          ordered=True),
                    lambda a: stop,
                    (win_start, rws, rls, rrs, rcs, rdns, rwns))
                slot = jnp.where(fire, 0, slot)
                return (i + K, w, rv, rws, rls, rrs, rcs, rdns, rwns,
                        slot, conv, stop)

            def cond(carry):
                i, conv, stop = carry[0], carry[10], carry[11]
                return ((i <= N) & jnp.logical_not(conv)
                        & jnp.logical_not(stop))

            init = (jnp.asarray(i0, jnp.int32), w0,
                    jnp.asarray(rv0, jnp.float32), *rings0,
                    jnp.asarray(0, jnp.int32), jnp.asarray(False),
                    jnp.asarray(False))
            return jax.lax.while_loop(cond, superstep, init)

        def loop_extra(w0, e0, rv0, i0, *data):
            # the extras-carrying twin of `loop`: identical structure
            # with ONE more carry leaf (the extras state, e.g. the EF
            # accumulator) and one more ring leaf (its per-step
            # post-update history).  Kept as a separate trace so the
            # legacy carry layout — and every bitwise pin on it —
            # is untouched when no extras ride.
            from jax.experimental import io_callback

            from tpu_sgd.optimize.gradient_descent import pack_step_ys

            rings0 = (
                jnp.zeros((CK,) + w0.shape, w0.dtype),
                jnp.zeros((CK,), jnp.float32),  # loss
                jnp.zeros((CK,), jnp.float32),  # reg value
                jnp.zeros((CK,), jnp.float32),  # realized batch count
                jnp.zeros((CK,), jnp.float32),  # ||w_t - w_{t-1}||
                jnp.zeros((CK,), jnp.float32),  # ||w_t||
                jnp.zeros((CK,) + e0.shape, e0.dtype),  # extras (EF)
            )

            def superstep(carry):
                (i, w, e, rv, rws, rls, rrs, rcs, rdns, rwns, res,
                 slot, conv, stop) = carry
                idx = i + jnp.arange(K, dtype=jnp.int32)

                def body(c, ii):
                    cw, ce, crv = c
                    new_w, new_e, loss_i, new_rv, cnt = step_fn(
                        cw, ce, ii, crv, *data)
                    # extras ride the ys like the compressed superstep's
                    # seventh leaf: mid-window checkpoints need
                    # iteration-exact extras just as they need
                    # iteration-exact weights
                    return (new_w, new_e, new_rv), pack_step_ys(
                        cw, new_w, loss_i, new_rv, cnt, f32=True
                    ) + (new_e,)

                (w, e, rv), ys = jax.lax.scan(body, (w, e, rv), idx)
                base = slot * K
                rws = jax.lax.dynamic_update_slice_in_dim(
                    rws, ys[0], base, 0)
                rls = jax.lax.dynamic_update_slice_in_dim(
                    rls, ys[1], base, 0)
                rrs = jax.lax.dynamic_update_slice_in_dim(
                    rrs, ys[2], base, 0)
                rcs = jax.lax.dynamic_update_slice_in_dim(
                    rcs, ys[3], base, 0)
                rdns = jax.lax.dynamic_update_slice_in_dim(
                    rdns, ys[4], base, 0)
                rwns = jax.lax.dynamic_update_slice_in_dim(
                    rwns, ys[5], base, 0)
                res = jax.lax.dynamic_update_slice_in_dim(
                    res, ys[6], base, 0)
                if tol > 0.0:
                    conv_t = ((ys[3] > 0) & (idx > 1)
                              & (ys[4] < tol * jnp.maximum(ys[5], 1.0)))
                    conv = jnp.any(conv_t)
                slot = slot + 1
                fire = (slot == C) & jnp.logical_not(conv)
                win_start = i - (C - 1) * K
                stop = jax.lax.cond(
                    fire,
                    lambda a: io_callback(fire_cb, _BOOL, *a,
                                          ordered=True),
                    lambda a: stop,
                    (win_start, rws, rls, rrs, rcs, rdns, rwns, res))
                slot = jnp.where(fire, 0, slot)
                return (i + K, w, e, rv, rws, rls, rrs, rcs, rdns,
                        rwns, res, slot, conv, stop)

            def cond(carry):
                i, conv, stop = carry[0], carry[12], carry[13]
                return ((i <= N) & jnp.logical_not(conv)
                        & jnp.logical_not(stop))

            init = (jnp.asarray(i0, jnp.int32), w0, e0,
                    jnp.asarray(rv0, jnp.float32), *rings0,
                    jnp.asarray(0, jnp.int32), jnp.asarray(False),
                    jnp.asarray(False))
            return jax.lax.while_loop(cond, superstep, init)

        return loop_extra if self.with_extra else loop

    def compile_cache_size(self) -> int:
        """Compiled-program count of the underlying jitted loop (for
        ``assert_compile_count``)."""
        return self._fn._cache_size()

    # -- run-time ------------------------------------------------------------
    def run(self, w0, reg_val: float, start_iter: int, data: tuple,
            hooks: ResidentBookkeeper, *, extra0=None):
        """Dispatch the whole-run program and finalize through ``hooks``.

        Returns ``(weights_np, converged)`` with every side effect (loss
        history, listener events, checkpoint saves) already applied via
        the window replays.  Raises the stashed callback exception, or
        ``TrainingPreempted`` at the exact replayed boundary when the
        stop signal fired.  Normally ONE dispatch; a false f32
        device-convergence (see module docstring) re-dispatches from the
        exact replayed state — bitwise-stable, never a drift.

        ``extra0`` seeds the extras carry leaf of a ``with_extra`` loop
        (e.g. the restored-or-zero EF accumulator); its boundary state
        surfaces through ``hooks.last_extra`` / ``hooks.extras_cb``.
        """
        from tpu_sgd.reliability.supervisor import TrainingPreempted

        cfg = self.config
        K = self.k
        WE = self.with_extra
        if WE and extra0 is None:
            raise ValueError(
                "this loop carries extras (with_extra=True); pass "
                "extra0 — the initial extras state")
        w_dev = w0
        e_dev = extra0
        rv = float(reg_val)
        i0 = int(start_iter)
        while True:
            # the span times the whole-run host region (dispatch, the
            # documented barrier, and the boundary fetches) so the
            # counted syncs attribute to `train`; the barrier is the
            # driver's own contract, not the span's — span timestamps
            # never force a sync (ADVICE.md "Span timestamps are
            # attribution, not truth")
            with span("train.resident_dispatch", i0=i0):
                with self._run_lock:
                    self._hooks = hooks
                    try:
                        carry = (self._fn(w_dev, e_dev, rv, i0, *data)
                                 if WE else
                                 self._fn(w_dev, rv, i0, *data))
                        # dispatch is async: block on the carry BEFORE
                        # clearing the hook — no callback outlives its
                        # dispatch only once the program has completed.
                        # This barrier is the whole-run dispatch's own
                        # contract (one trip per run; re-trips only on
                        # a false f32 device-convergence)
                        jax.block_until_ready(carry)
                    finally:
                        self._hooks = None
                # boundary fetch: three scalars once per RUN, not per
                # iteration
                i_f = int(carry[0])
                slot_f = int(carry[11 if WE else 9])
                conv_f = bool(carry[12 if WE else 10])
                if hooks.error is None and slot_f:
                    # tail window: the un-replayed supersteps since the
                    # last fired window sit in ring rows
                    # [0, slot_f * K) — the rings are fetched to host
                    # ONLY here (a completed or stopped run with
                    # slot_f == 0 never pays the (C*K, d) device->host
                    # copy).  An extras carry shifts the ring block by
                    # one (the extras leaf sits at carry[2]) and adds
                    # its ring as the seventh leaf.
                    rings = tuple(np.asarray(r) for r in
                                  (carry[4:11] if WE else carry[3:9]))
                    hooks.replay(i_f - slot_f * K, rings, slot_f)
            if hooks.error is not None:
                raise hooks.error
            if hooks.stop_requested and not hooks.host_converged:
                boundary = hooks.replayed_through
                if hooks.save_cb is not None:
                    hooks.save_cb(boundary, hooks.last_w, hooks.reg_val)
                raise TrainingPreempted(boundary)
            if hooks.host_converged \
                    or hooks.replayed_through >= cfg.num_iterations:
                return hooks.last_w, hooks.host_converged
            if not conv_f:  # pragma: no cover — cond exhausts the cases
                raise AssertionError(
                    "resident loop exited without budget, convergence, "
                    f"or stop (i={i_f}, replayed="
                    f"{hooks.replayed_through})")
            # device predicate fired where the host comparison did not:
            # continue from the exact replayed state (one extra launch)
            i0 = hooks.replayed_through + 1
            w_dev = jnp.asarray(hooks.last_w).astype(w0.dtype)
            if WE:
                e_dev = jnp.asarray(hooks.last_extra).astype(
                    extra0.dtype)
            rv = hooks.reg_val
