"""Mini-batch gradient descent: the TPU-native ``GradientDescent``.

Reference parity: [U] mllib/optimization/GradientDescent.scala (SURVEY.md §2
#2, §3.1).  The reference's per-iteration pattern —

    broadcast(weights) -> sample(frac, 42+i) -> treeAggregate(seqOp/combOp)
    -> grad /= miniBatchSize -> updater.compute -> convergence check

— is re-designed TPU-first rather than translated (SURVEY.md §7 design
stance):

  * The whole optimization runs as ONE compiled XLA program: a
    ``lax.while_loop`` whose body is the fused batched gradient step.  Spark
    pays per-iteration driver hops (broadcast setup, job scheduling, task
    serialization — SURVEY.md §3.1 "outer hot loop"); here there are zero
    host round-trips until the final result fetch.
  * ``sample(false, frac, 42 + i)`` becomes a per-example Bernoulli mask from
    ``fold_in(key, i)`` — distributional parity, normalized by the *realized*
    mini-batch count exactly as the reference divides by ``miniBatchSize``
    (SURVEY.md §7 hard parts, sampling-semantics parity).
  * ``treeAggregate`` + Torrent broadcast become ``lax.psum`` over the mesh
    axis (hardware ICI all-reduce) + deterministic replicated updates
    (SURVEY.md §3.5, §5.8).  Pass ``axis_name`` to get the sharded body;
    ``None`` gives the single-device body from the same code.
  * The loss-history contract is preserved: ``loss[t] = lossSum/miniBatchSize
    + regVal(prev iteration's weights)`` and the convergence rule is
    ``||w_t - w_{t-1}|| < tol * max(||w_t||, 1)`` checked from the second
    update on (SURVEY.md §5.5, §3.1).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_sgd.config import SGDConfig
from tpu_sgd.obs.spans import span
from tpu_sgd.obs.timeseries import observe_scalar
from tpu_sgd.ops.gradients import Gradient, LeastSquaresGradient
from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS
from tpu_sgd.ops.sparse import is_sparse
from tpu_sgd.ops.updaters import SimpleUpdater, Updater
from tpu_sgd.optimize.optimizer import Dataset, Optimizer

Array = jax.Array


def _raise_if_nonfinite(losses, first_iteration: int = 1) -> None:
    """Shared numerics check (``set_check_numerics``), one message for all
    optimizer paths.  ``first_iteration`` is the 1-based iteration number
    of ``losses[0]`` — the stepwise driver checks one loss at a time and
    must report the TRUE diverging iteration, not 'iteration 1'."""
    import numpy as np

    arr = np.asarray(losses)
    bad = np.nonzero(~np.isfinite(arr))[0]
    if bad.size:
        raise FloatingPointError(
            f"non-finite loss at iteration {int(bad[0]) + first_iteration} "
            f"(loss={arr[bad[0]]}); reduce step_size or check the data"
        )


def _coerce_w0(gradient, initial_weights, n_features):
    """ONE coerce-and-validate for initial weights, shared by every
    driver branch (resident / host-streamed / GramData / meshed
    streamed-gram): float32 master weights (mixed-precision mode: bf16
    data halves HBM traffic, f32 weights keep convergence) and the
    clear length error instead of an opaque XLA shape failure."""
    w0 = jnp.asarray(initial_weights)
    if not jnp.issubdtype(w0.dtype, jnp.inexact):
        w0 = w0.astype(jnp.float32)
    expect_dim = gradient.weight_dim(n_features)
    if w0.shape[-1] != expect_dim:
        raise ValueError(
            f"initial_weights has length {w0.shape[-1]} but this "
            f"gradient needs {expect_dim} for {n_features}-feature data"
        )
    return w0


def _sample_key(key, i, axis_name, shard_index=None):
    """THE per-iteration (and per-shard, like Spark's per-partition
    sampler) sample-key recipe, deterministic in (seed, iteration, shard
    index).  One definition shared by the Bernoulli mask and the
    indexed/sliced streams so an edit to the fold order cannot silently
    desync them.

    ``shard_index`` is the OUT-OF-MESH spelling of the shard fold: a
    replica worker (``tpu_sgd/replica``) runs its shard's local sums as
    a standalone program — no ``shard_map``, so no ``axis_index`` — and
    folds its static shard index exactly where the meshed program folds
    the axis index, which is what makes the τ=0 replica trajectory
    bitwise-equal to the synchronous data-parallel path (the fold order
    is identical, so the per-shard sample keys are identical bits)."""
    k = jax.random.fold_in(key, i)
    if axis_name is not None:
        k = jax.random.fold_in(k, jax.lax.axis_index(axis_name))
    elif shard_index is not None:
        k = jax.random.fold_in(k, shard_index)
    return k


def _make_mask(cfg: SGDConfig, key, i, n_local, valid, axis_name,
               shard_index=None):
    """Per-iteration Bernoulli mini-batch mask (None = take everything)."""
    if cfg.mini_batch_fraction < 1.0:
        k = _sample_key(key, i, axis_name, shard_index)
        mask = jax.random.bernoulli(k, cfg.mini_batch_fraction, (n_local,))
        return mask if valid is None else mask & valid
    return valid


def _make_local_sums(gradient, cfg, key, axis_name, model_axis_name,
                     shard_index=None):
    """THE per-iteration LOCAL ``(grad_sum, loss_sum, count)`` recipe —
    sampling (bernoulli / indexed / sliced) + the fused batch sums,
    pre-psum.  One definition shared by :func:`make_step` (dense
    all-reduce), :func:`make_compressed_step` (top-k + error-feedback
    all-reduce), and the async replica workers
    (``tpu_sgd/replica/worker.py``, via ``shard_index`` — see
    :func:`_sample_key`) so the sampled sequence can never drift between
    the wires."""
    indexed = cfg.sampling == "indexed" and cfg.mini_batch_fraction < 1.0
    sliced = cfg.sampling == "sliced" and cfg.mini_batch_fraction < 1.0

    def local_sums(weights, X, y, i, valid):
        if sliced or indexed:
            m = max(1, round(cfg.mini_batch_fraction * X.shape[0]))
            k = _sample_key(key, i, axis_name, shard_index)
        if sliced:
            # HBM-optimal path: a contiguous row window at a random offset —
            # one sequential DMA (zero-copy under PallasGradient) instead of
            # a random gather.  Assumes exchangeable row order (see
            # SGDConfig.sampling docs).
            start = jax.random.randint(k, (), 0, max(1, X.shape[0] - m + 1))
            return gradient.window_sums(
                X, y, weights, start, m, valid=valid,
                margin_axis_name=model_axis_name,
            )
        if indexed:
            # TPU fast path: gather a fixed-size batch (with replacement)
            # instead of masking the whole dataset — touches only ``frac``
            # of HBM per iteration.
            idx = jax.random.randint(k, (m,), 0, X.shape[0])
            Xb, yb = X[idx], y[idx]
            mask = None if valid is None else valid[idx]
        else:
            Xb, yb = X, y
            mask = _make_mask(cfg, key, i, X.shape[0], valid, axis_name,
                              shard_index)
        return gradient.batch_sums(
            Xb, yb, weights, mask, margin_axis_name=model_axis_name
        )

    return local_sums


def make_step(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    axis_name: Optional[str] = None,
    model_axis_name: Optional[str] = None,
):
    """Build one SGD iteration as a pure function.

    ``step(weights, X, y, i, reg_val, valid) ->
    (new_weights, loss_i, new_reg_val, count)`` — the unit the streaming mode
    and the fused driver both build on.  ``loss_i`` already includes the
    previous iteration's ``reg_val`` per the reference's loss-history contract.

    ``axis_name`` shards the example axis (data parallelism — the reference's
    only strategy); ``model_axis_name`` additionally shards the FEATURE axis
    (the optional wide-weights hook, SURVEY.md §2 ledger TP row): each core
    holds a block of ``w`` and the matching column block of ``X``, partial
    margins are all-reduced over the model axis, and the updater runs on the
    local block with its scalar reg value all-reduced.
    """
    cfg = config
    key = jax.random.PRNGKey(cfg.seed)
    local_sums = _make_local_sums(gradient, cfg, key, axis_name,
                                  model_axis_name)

    def step(weights, X, y, i, reg_val, valid=None):
        g, l, c = local_sums(weights, X, y, i, valid)
        if axis_name is not None:
            g, l, c = jax.lax.psum((g, l, c), axis_name)
        has_batch = c > 0
        safe_c = jnp.maximum(c, 1.0)
        loss_i = l / safe_c + reg_val
        new_w, new_reg = updater.compute(
            weights, g / safe_c, cfg.step_size, i, cfg.reg_param
        )
        if model_axis_name is not None:
            # reg value is a sum over features -> combine the local blocks
            new_reg = jax.lax.psum(new_reg, model_axis_name)
        # Reference behavior on an empty sampled batch: warn, skip the update.
        new_w = jnp.where(has_batch, new_w, weights)
        new_reg = jnp.where(has_batch, new_reg, reg_val)
        return new_w, loss_i, new_reg, c

    return step


def make_run(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    axis_name: Optional[str] = None,
    model_axis_name: Optional[str] = None,
):
    """Build the full optimization loop as one traceable function.

    ``run(initial_weights, X, y, valid) -> (weights, loss_history, n_recorded)``
    where ``loss_history`` has static length ``config.num_iterations`` padded
    with NaN beyond ``n_recorded`` (the while_loop may exit early on the
    convergence tolerance).  Runs unchanged inside ``shard_map`` when
    ``axis_name`` (and optionally ``model_axis_name``) is given.
    """
    cfg = config
    check_conv = cfg.convergence_tol > 0.0
    step = make_step(gradient, updater, cfg, axis_name, model_axis_name)

    def _global_norms(new_w, w):
        diff_sq = jnp.sum((new_w - w) ** 2)
        w_sq = jnp.sum(new_w**2)
        if model_axis_name is not None:
            diff_sq, w_sq = jax.lax.psum((diff_sq, w_sq), model_axis_name)
        return jnp.sqrt(diff_sq), jnp.sqrt(w_sq)

    def run(initial_weights, X, y, valid=None):
        w0 = initial_weights
        # Initial regVal from a zero-gradient probe update, exactly as the
        # reference initializes it before the loop (SURVEY.md §5.5).
        _, reg_val0 = updater.compute(
            w0, jnp.zeros_like(w0), 0.0, jnp.asarray(1, jnp.int32), cfg.reg_param
        )
        if model_axis_name is not None:
            # the reg value sums over FEATURES, and each model shard holds
            # only its block of w0 — combine like make_step's new_reg, or
            # a warm-started 2-D run records a block-local iteration-1 loss
            reg_val0 = jax.lax.psum(reg_val0, model_axis_name)
        losses0 = jnp.full((cfg.num_iterations,), jnp.nan, jnp.float32)

        def cond(carry):
            i, _, _, _, _, converged = carry
            return (i <= cfg.num_iterations) & jnp.logical_not(converged)

        def body(carry):
            i, w, reg_val, losses, n_rec, _ = carry
            new_w, loss_i, new_reg, c = step(w, X, y, i, reg_val, valid)
            has_batch = c > 0
            losses = jnp.where(
                has_batch, losses.at[n_rec].set(loss_i.astype(jnp.float32)), losses
            )
            n_rec = n_rec + has_batch.astype(n_rec.dtype)
            if check_conv:
                diff, w_norm = _global_norms(new_w, w)
                conv = (
                    has_batch
                    & (i > 1)
                    & (diff < cfg.convergence_tol * jnp.maximum(w_norm, 1.0))
                )
            else:
                conv = jnp.asarray(False)
            return (i + 1, new_w, new_reg, losses, n_rec, conv)

        carry = (
            jnp.asarray(1, jnp.int32),
            w0,
            reg_val0,
            losses0,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(False),
        )
        _, w, _, losses, n_rec, _ = jax.lax.while_loop(cond, body, carry)
        return w, losses, n_rec

    return run


def pack_step_ys(prev_w, new_w, loss_i, new_rv, count, f32: bool = False):
    """THE per-step scan-ys tuple every fused driver emits — ``(new_w,
    loss, reg_val, count, ||w_t - w_{t-1}||, ||w_t||)``, exactly what
    :func:`_replay_fused_steps` consumes.  One definition shared by the
    four scan bodies (:func:`make_superstep`,
    :func:`make_shared_batch_superstep`,
    :func:`make_resident_window_superstep`, and the resident driver's
    while-loop body) so the norms-ride-the-ys convergence contract
    cannot drift between drivers.  ``f32`` casts the scalar leaves for
    the resident ring buffer's fixed-dtype carry."""
    dn = jnp.linalg.norm(new_w - prev_w)
    wn = jnp.linalg.norm(new_w)
    if f32:
        f = jnp.float32
        return (new_w, loss_i.astype(f), new_rv.astype(f),
                count.astype(f), dn.astype(f), wn.astype(f))
    return (new_w, loss_i, new_rv, count, dn, wn)


#: fused ``(||w_t - w_{t-1}||, ||w_t||)`` for the OBSERVED stepwise
#: drivers (this module's K=1 loop and the host-streamed loop in
#: ``optimize/streamed.py``): one compiled program and ONE host fetch
#: where the eager spelling paid three one-op dispatches and two
#: separate device->host syncs per iteration (graftlint host-sync
#: finding; bitwise-equal to the eager norms on CPU — the reduce
#: lowers identically fused or not)
step_norms = jax.jit(lambda new_w, w: jnp.stack(
    (jnp.linalg.norm(new_w - w), jnp.linalg.norm(new_w))))


def observe_step(
    i, prev_w, new_w, loss_i, new_reg, count, losses, reg_val, cfg, *,
    listener=None, wall_dt=0.0, check_numerics=False,
    save_cb=None, save_every=0,
):
    """One OBSERVED iteration's host bookkeeping — THE single definition
    of the per-step record/convergence/checkpoint recipe the stepwise
    drivers share (the fused twin is :func:`_replay_fused_steps`, which
    replays the same recipe from scan ys).

    Consumers: the dense host-streamed K=1 loop
    (``optimize/streamed.py``), the sparse host-streamed K=1 loop
    (``optimize/streamed_sparse.py``), and the async replica parameter
    store's push-apply (``tpu_sgd/replica/store.py``) — extracted after
    the PR 9 review flagged the first two as duplicated and the replica
    driver would have made a third copy.

    Takes the step's DEVICE results plus the host-side running state;
    fetches each scalar exactly once (the observed-driver contract: the
    per-iteration host hop IS the bookkeeping), appends to ``losses``
    in place, and fires ``save_cb(i, w_np, reg_val)`` on the legacy
    cadence (``i % save_every == 0``, on convergence, and at the final
    iteration).  An empty sampled batch (``count == 0``) records
    nothing and returns ``prev_w`` unchanged, exactly as the loops it
    replaced did.

    Returns ``(w, reg_val, converged)`` — ``w`` is ``new_w`` when the
    step recorded, else ``prev_w``.
    """
    import numpy as np

    from tpu_sgd.utils.events import IterationEvent

    c_host = int(count)  # count gates the whole bookkeeping branch (fetched ONCE)
    converged = False
    if c_host <= 0:
        return prev_w, reg_val, converged
    loss_f = float(loss_i)  # per-iteration loss history is the contract
    if check_numerics and not np.isfinite(loss_f):
        _raise_if_nonfinite([loss_f], first_iteration=i)
    losses.append(loss_f)
    reg_val = float(new_reg)  # feeds the next step's host-side argument
    # ONE fused program + ONE fetch for both norms (the host-sync
    # finding the PR 7 sweep fixed; step_norms is the shared program)
    delta, w_norm = (
        float(v)
        for v in np.asarray(step_norms(new_w, prev_w))
    )
    # the live loss/variance series (obs.timeseries): these are the
    # host floats the bookkeeping already fetched — the near-free
    # AdaBatch sensor, ZERO added syncs; disabled = one global load
    observe_scalar("train.loss", loss_f)
    observe_scalar("train.weight_delta", delta)
    if listener is not None:
        listener.on_iteration(IterationEvent(
            iteration=i,
            loss=loss_f,
            weight_delta_norm=delta,
            mini_batch_size=c_host,
            wall_time_s=wall_dt,
        ))
    if cfg.convergence_tol > 0 and i > 1:
        converged = delta < cfg.convergence_tol * max(w_norm, 1.0)
    if save_cb is not None and (
            (save_every and i % save_every == 0)
            or converged or i == cfg.num_iterations):
        save_cb(i, np.asarray(new_w), reg_val)
    return new_w, reg_val, converged


def observed_loop_tail(
    i, w, new_w, loss_i, new_reg, count, losses, reg_val, cfg, *,
    listener=None, wall_dt=0.0, save_cb=None, save_every=0,
    stop_signal=None,
):
    """One observed iteration's ENTIRE host tail: the shared
    :func:`observe_step` bookkeeping plus the cooperative-preemption
    check (persist the CURRENT iteration through ``save_cb``, then
    unwind :class:`~tpu_sgd.reliability.supervisor.TrainingPreempted`).

    This is the K=1 observed-loop duplication the PR 9 review flagged
    between ``optimize/streamed.py`` and ``optimize/streamed_sparse.py``
    — the same statements, now with one home next to ``observe_step``
    (both drivers' bitwise pins stay green: extraction moved code, not
    math).  The caller owns the per-step barrier and the wall-clock
    timing (they live inside its ``train.step`` span)."""
    import numpy as np

    w, reg_val, converged = observe_step(
        i, w, new_w, loss_i, new_reg, count, losses, reg_val, cfg,
        listener=listener, wall_dt=wall_dt,
        save_cb=save_cb, save_every=save_every,
    )
    if not converged and stop_signal is not None and stop_signal():
        # cooperative preemption (TrainingSupervisor): persist the
        # CURRENT iteration — not just the last cadence save — then
        # unwind cleanly; the save is atomic, so a SIGKILL racing this
        # still leaves the previous checkpoint intact
        from tpu_sgd.reliability.supervisor import TrainingPreempted

        if save_cb is not None:
            save_cb(i, np.asarray(w), reg_val)
        raise TrainingPreempted(i)
    return w, reg_val, converged


def make_superstep(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    axis_name: Optional[str] = None,
    model_axis_name: Optional[str] = None,
):
    """Fuse K consecutive SGD iterations over PER-STEP batches into ONE
    compiled program (``lax.scan`` over the superchunk's leading axis).

    ``superstep(weights, reg_val, i0, Xs, ys, valids) ->
    (carry_weights, ys_out)``: ``Xs``/``ys``/``valids`` stack K
    per-iteration batches on axis 0 — the host-assembled *superchunk*
    (``tpu_sgd.io.stack_superchunk``) that replaces K ``device_put`` +
    dispatch round-trips with one of each.  The scan body is EXACTLY
    ``make_step``: iteration ``i0 + t`` consumes batch ``t`` with the
    same per-step math and the same deterministic sample sequence as
    the per-iteration loop.  ``ys_out`` is the per-step ``(weights,
    loss, reg_val, count, delta_norm, weight_norm)`` history:
    everything the host loop used to read back one iteration at a time
    (loss history, convergence norms, checkpoint state) now arrives as
    one stacked fetch.

    Trajectory contract (measured, tests/test_superstep.py): everything
    SAME-PROGRAM is bitwise — a fused run replayed, resumed from a
    checkpoint, or fed through a different prefetch depth reproduces
    its weights exactly.  Against the per-iteration loop the math is
    identical but XLA lowers the batch dot through a different emitter
    inside a scanned program than as a standalone dispatch (measured 1
    ulp/step on the CPU harness — even a scan over a ``(1, m, d)``
    superchunk differs from the unscanned program), so fused-vs-legacy
    trajectories agree to reassociation noise, with the loss-history
    LENGTH, sampled sequence, and detected convergence iteration
    exactly equal — the same cross-program caveat
    ``optimize/streamed.py`` documents for the partial-residency
    ``resident_step``.

    The device program never branches on convergence or run length: a
    tail superstep (K ∤ remaining iterations) rides all-False
    ``valids`` rows, which ``make_step``'s empty-batch rule turns into
    no-op updates, and the host truncates overshoot from the ys
    (:func:`_replay_fused_steps`).  One shape -> exactly one fused-body
    program per build (``assert_compile_count``-guarded in
    tests/test_superstep.py).
    """
    step = make_step(gradient, updater, config, axis_name, model_axis_name)

    def superstep(weights, reg_val, i0, Xs, ys, valids):
        idx = i0 + jnp.arange(Xs.shape[0], dtype=jnp.int32)

        def body(carry, xs):
            w, rv = carry
            i, Xb, yb, vb = xs
            new_w, loss_i, new_rv, c = step(w, Xb, yb, i, rv, vb)
            # per-step norms ride the ys so the host-side convergence
            # check stays EXACTLY the legacy per-iteration rule
            return (new_w, new_rv), pack_step_ys(w, new_w, loss_i,
                                                 new_rv, c)

        (w, _), out = jax.lax.scan(body, (weights, reg_val),
                                   (idx, Xs, ys, valids))
        return w, out

    return superstep


def make_shared_batch_superstep(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    k: int,
    axis_name: Optional[str] = None,
    model_axis_name: Optional[str] = None,
):
    """The shared-batch variant of :func:`make_superstep`: K fused
    iterations over ONE ``(X, y)`` — the resident/stepwise driver
    (per-iteration sampling happens inside ``make_step``, on device)
    and the streamed full-batch feed (every iteration's "sample" IS the
    whole transferred batch, so it moves once and the scan reuses it).

    Same return contract and the same one-program guarantee as
    :func:`make_superstep`.  Steps past ``num_iterations`` in a tail
    superstep run real updates here (there is no per-step valids row to
    blank them); the caller discards the carry and takes the true last
    iteration's weights from the ys — ≤ K-1 wasted updates once per
    run.
    """
    step = make_step(gradient, updater, config, axis_name, model_axis_name)
    K = int(k)

    def superstep(weights, reg_val, i0, X, y, valid=None):
        idx = i0 + jnp.arange(K, dtype=jnp.int32)

        def body(carry, i):
            w, rv = carry
            new_w, loss_i, new_rv, c = step(w, X, y, i, rv, valid)
            return (new_w, new_rv), pack_step_ys(w, new_w, loss_i,
                                                 new_rv, c)

        (w, _), out = jax.lax.scan(body, (weights, reg_val), idx)
        return w, out

    return superstep


def make_resident_window_superstep(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    window_rows: int,
):
    """The partial-residency variant of :func:`make_superstep`: each
    fused step's window comes EITHER from the device-resident slab
    (sliced on device at a host-drawn start — zero transfer) OR from
    the transferred superchunk batch, selected per step by a flag the
    host packs alongside the superchunk.

    ``superstep(weights, reg_val, i0, Xres, yres, starts, flags, Xs,
    ys, valids) -> (carry_weights, ys_out)`` with the same ys contract
    as :func:`make_superstep`.  ``starts``/``flags`` are ``(K,)``
    per-step window starts and residency flags; resident steps ride
    zero rows in ``Xs`` (the fixed superchunk shape is the price of
    one compiled program — fusing trades those windows' transfer-byte
    savings for the K-fold dispatch cut, which the tunnel-attached
    target's 10-100x dispatch tax usually wins; the fully-resident
    slab feed avoids even that via the resident driver).  Both window
    sources feed bit-identical rows through the SAME scan body, so
    same-program contracts stay bitwise across mixed
    resident/transferred windows — this is what lifts the old
    "superstep fusion applies ... without partial residency" warning.
    """
    step = make_step(gradient, updater, config)
    m = int(window_rows)

    def superstep(weights, reg_val, i0, Xres, yres, starts, flags,
                  Xs, ys, valids):
        idx = i0 + jnp.arange(Xs.shape[0], dtype=jnp.int32)

        def body(carry, xs):
            w, rv = carry
            i, s0, res, Xb, yb, vb = xs
            Xw, yw = jax.lax.cond(
                res,
                lambda: (jax.lax.dynamic_slice_in_dim(Xres, s0, m, 0),
                         jax.lax.dynamic_slice_in_dim(yres, s0, m, 0)),
                lambda: (Xb, yb))
            new_w, loss_i, new_rv, c = step(w, Xw, yw, i, rv, vb)
            return (new_w, new_rv), pack_step_ys(w, new_w, loss_i,
                                                 new_rv, c)

        (w, _), out = jax.lax.scan(body, (weights, reg_val),
                                   (idx, starts, flags, Xs, ys, valids))
        return w, out

    return superstep


def make_compressed_step(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    axis_name: Optional[str] = None,
):
    """One SGD iteration over the COMPRESSED gradient wire: top-k +
    error feedback (``wire_compress="topk:<frac>"``; README "Compressed
    wire", SparCML arXiv:1802.08021).

    ``step(weights, ef, X, y, i, reg_val, valid) -> (new_w, new_ef,
    loss_i, new_reg_val, count)``.  Sampling and the local batch sums
    are EXACTLY :func:`make_step`'s (one shared ``_make_local_sums``);
    what changes is the combine: each shard folds its normalized
    gradient contribution into a persistent per-shard error-feedback
    accumulator, extracts the top-k ``(values, indices)`` segment with
    ``jax.lax.top_k`` (``k`` is STATIC — shape-stable inside the traced
    program; the host-numpy-top-k rule is for HOST wires), and only
    those segments cross the link (``lax.all_gather`` of ``2·k``
    entries per shard instead of a dense ``(d,)`` psum) before a
    scatter-add rebuilds the applied update on every shard.  The
    dropped mass stays in ``ef`` and ships on later iterations — the
    EF-SGD update rule, convergent at matched final loss where plain
    top-k is not.

    ``ef`` is OPTIMIZER STATE (ADVICE.md "Error feedback is optimizer
    state, not a transport detail"): the caller carries it across
    iterations (the superstep scan carries it in
    :func:`make_compressed_superstep`), checkpoints it
    (``CheckpointManager.save(extras={"ef": ...})``), and restores it
    on resume — a compressed run resumed mid-stream is bitwise equal
    to its uninterrupted twin only if the accumulator travels too.
    Loss and count still combine densely (two scalars); an empty
    sampled batch leaves weights AND accumulator untouched (the
    reference's skip-the-update rule — extracted mass must not vanish
    on a skipped step).  Single-device (``axis_name=None``) the same
    rule applies without the gather: the update is the top-k of the
    accumulated gradient — the sparsified-update twin used for
    matched-loss A/B runs.
    """
    from tpu_sgd.io.sparse_wire import topk_nnz

    cfg = config
    key = jax.random.PRNGKey(cfg.seed)
    frac = float(topk_frac)
    local_sums = _make_local_sums(gradient, cfg, key, axis_name, None)

    def step(weights, ef, X, y, i, reg_val, valid=None):
        g, l, c = local_sums(weights, X, y, i, valid)
        if axis_name is not None:
            l, c = jax.lax.psum((l, c), axis_name)
        has_batch = c > 0
        safe_c = jnp.maximum(c, 1.0)
        loss_i = l / safe_c + reg_val
        dim = g.shape[-1]
        k = topk_nnz(dim, frac)  # static at trace time: one program
        acc = ef + (g / safe_c).astype(ef.dtype)
        _, idx = jax.lax.top_k(jnp.abs(acc), k)
        vals = jnp.take(acc, idx)
        new_ef = acc.at[idx].set(0.0)
        if axis_name is not None:
            # the compressed all-reduce: (values, indices) segments ride
            # the link, each shard scatter-adds every shard's segment
            vals_all = jax.lax.all_gather(vals, axis_name)
            idx_all = jax.lax.all_gather(idx, axis_name)
            ghat = jnp.zeros((dim,), acc.dtype).at[
                idx_all.reshape(-1)].add(vals_all.reshape(-1))
        else:
            ghat = jnp.zeros((dim,), acc.dtype).at[idx].add(vals)
        new_w, new_reg = updater.compute(
            weights, ghat.astype(weights.dtype), cfg.step_size, i,
            cfg.reg_param
        )
        # empty sampled batch: skip the update AND keep the accumulator
        # (the extracted mass must not vanish on a skipped step)
        new_w = jnp.where(has_batch, new_w, weights)
        new_reg = jnp.where(has_batch, new_reg, reg_val)
        new_ef = jnp.where(has_batch, new_ef, ef)
        return new_w, new_ef, loss_i, new_reg, c

    return step


def make_compressed_superstep(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    axis_name: Optional[str] = None,
):
    """:func:`make_superstep` over the compressed wire: the
    error-feedback accumulator rides the scan CARRY (state, like the
    weights) and the per-step post-update accumulators ride the ys as a
    seventh leaf — checkpoints taken mid-superstep need iteration-exact
    EF state just as they need iteration-exact weights.

    ``superstep(weights, ef, reg_val, i0, Xs, ys, valids) ->
    (carry_weights, carry_ef, ys_out)`` with ``ys_out = (*pack_step_ys,
    efs)``.  Same one-program / tail-padding contract as
    :func:`make_superstep` (a padded no-op step passes ``ef`` through
    unchanged)."""
    step = make_compressed_step(gradient, updater, config, topk_frac,
                                axis_name)

    def superstep(weights, ef, reg_val, i0, Xs, ys, valids):
        idx = i0 + jnp.arange(Xs.shape[0], dtype=jnp.int32)

        def body(carry, xs):
            w, e, rv = carry
            i, Xb, yb, vb = xs
            new_w, new_e, loss_i, new_rv, c = step(w, e, Xb, yb, i, rv,
                                                   vb)
            return (new_w, new_e, new_rv), pack_step_ys(
                w, new_w, loss_i, new_rv, c) + (new_e,)

        (w, e, _), out = jax.lax.scan(body, (weights, ef, reg_val),
                                      (idx, Xs, ys, valids))
        return w, e, out

    return superstep


def make_compressed_shared_superstep(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    topk_frac: float,
    k: int,
    axis_name: Optional[str] = None,
):
    """The shared-batch variant of :func:`make_compressed_superstep`
    (one transferred ``(X, y)``, K fused compressed steps; same
    overshoot-truncation contract as
    :func:`make_shared_batch_superstep`)."""
    step = make_compressed_step(gradient, updater, config, topk_frac,
                                axis_name)
    K = int(k)

    def superstep(weights, ef, reg_val, i0, X, y, valid=None):
        idx = i0 + jnp.arange(K, dtype=jnp.int32)

        def body(carry, i):
            w, e, rv = carry
            new_w, new_e, loss_i, new_rv, c = step(w, e, X, y, i, rv,
                                                   valid)
            return (new_w, new_e, new_rv), pack_step_ys(
                w, new_w, loss_i, new_rv, c) + (new_e,)

        (w, e, _), out = jax.lax.scan(body, (weights, ef, reg_val), idx)
        return w, e, out

    return superstep


def _replay_fused_steps(
    ys_host, i0, steps, losses, reg_val, cfg, *,
    listener=None, wall_dt=0.0, check_numerics=False,
    save_cb=None, save_every=0,
):
    """Replay one superstep's scan ys with EXACTLY the per-iteration
    loop's host bookkeeping — THE one definition of fused-mode
    loss-history / convergence / checkpoint semantics, shared by the
    host-streamed and stepwise drivers so they cannot drift.

    ``ys_host`` is the numpy-fetched per-step ``(weights, loss, reg,
    count, delta_norm, weight_norm)`` stack; ``steps`` bounds the
    replay to the REAL iterations (a tail superstep's padded no-op
    steps, and shared-batch overshoot past ``num_iterations``, are
    never read).  Convergence is detected per STEP from the ys — the
    true converged iteration, never the superstep boundary — with the
    identical host float comparison the legacy loops make
    (``delta < tol * max(||w||, 1)`` from the second update on), and
    empty sampled batches (``count == 0``) skip the record exactly as
    before.  ``save_cb(i, w_np, reg_val)`` fires on the legacy cadence
    (``i % save_every == 0``, on convergence, and at the final
    iteration) with the EXACT iteration-``i`` state from the ys, so
    fused checkpoints are indistinguishable from per-iteration ones and
    resume stays bitwise.

    Returns ``(t_last, reg_val, converged)``; the caller truncates the
    device program's overshoot by taking ``ys weights[t_last]`` as the
    final state when the run ends mid-superstep.
    """
    import numpy as np

    from tpu_sgd.utils.events import IterationEvent

    ws, ls, rs, cs, dns, wns = ys_host
    converged = False
    t_last = 0
    for t in range(steps):
        i = i0 + t
        t_last = t
        if int(cs[t]) > 0:
            loss_f = float(ls[t])
            if check_numerics and not np.isfinite(loss_f):
                _raise_if_nonfinite([loss_f], first_iteration=i)
            losses.append(loss_f)
            reg_val = float(rs[t])
            # live loss/variance series from the replayed ys — the
            # values are ALREADY host numpy (one bulk fetch per
            # superstep), so the zero-added-syncs pin holds
            observe_scalar("train.loss", loss_f)
            observe_scalar("train.weight_delta", float(dns[t]))
            if listener is not None:
                listener.on_iteration(IterationEvent(
                    iteration=i,
                    loss=loss_f,
                    weight_delta_norm=float(dns[t]),
                    mini_batch_size=int(cs[t]),
                    wall_time_s=wall_dt,
                ))
            if cfg.convergence_tol > 0 and i > 1:
                converged = float(dns[t]) < cfg.convergence_tol * max(
                    float(wns[t]), 1.0)
            if save_cb is not None and (
                    (save_every and i % save_every == 0)
                    or converged or i == cfg.num_iterations):
                save_cb(i, ws[t], reg_val)
        if converged:
            break
    return t_last, reg_val, converged


#: memo-key contract (graftlint memo-key rule): every compiled runner
#: cached in ``_run_cache`` must key on the roots below — the rule
#: decomposes each store site's key and the stored program's factory
#: reads and flags a program-affecting value the key misses (the
#: incomplete-memo-key class the PR 6 review caught by hand)
GRAFTLINT_MEMO = {
    "GradientDescent._run_cache": (
        "gradient", "updater", "config", "mesh", "with_valid",
        "k", "cadence", "sparse_shape",
        # gram-runner keys carry the data geometry and the gram/ingest
        # knobs the compiled prefix programs bake in
        "X", "y", "gram_aligned", "gram_batch_rows", "gram_block_rows",
        "gram_chunk_iters", "ingest_pipeline", "ingest_prefetch_depth",
        "ingest_wire_dtype",
    ),
}


class GradientDescent(Optimizer):
    """Drop-in mini-batch SGD optimizer (``TpuGradientDescent``).

    Fluent setters mirror the reference's builder API (SURVEY.md §5.6):
    ``set_step_size``, ``set_num_iterations``, ``set_reg_param``,
    ``set_mini_batch_fraction``, ``set_convergence_tol``.  Passing a
    ``jax.sharding.Mesh`` via ``set_mesh`` switches the same loop to the
    data-parallel shard_map body with ICI all-reduce.
    """

    def __init__(
        self,
        gradient: Gradient = None,
        updater: Updater = None,
        config: SGDConfig = None,
    ):
        self.gradient = gradient if gradient is not None else LeastSquaresGradient()
        self.updater = updater if updater is not None else SimpleUpdater()
        self.config = config if config is not None else SGDConfig()
        self.mesh = None
        self.listener = None
        self.host_streaming = False
        self.streaming_resident_rows = 0
        self.check_numerics = False
        self.checkpoint_manager = None
        self.checkpoint_every = 10
        self.sufficient_stats = False
        self.streamed_stats = False
        self.gram_block_rows = DEFAULT_BLOCK_ROWS
        self.gram_batch_rows = None
        self.gram_aligned = False
        self.gram_chunk_iters = None
        #: ingest-pipeline knobs (tpu_sgd/io; set_ingest_options): wire
        #: dtype for the host→device hop (None = data dtype), prefetch
        #: lookahead (2 = double buffer, 0 = synchronous), and the
        #: pipelined-build master switch (False = legacy sync loops)
        self.ingest_wire_dtype = None
        self.ingest_prefetch_depth = 2
        self.ingest_pipeline = True
        #: compressed gradient/update wire (tpu_sgd/io/sparse_wire;
        #: README "Compressed wire"): "topk:<frac>" ships top-k
        #: (values, indices) segments with error-feedback state on the
        #: update-shaped wires; None = dense wire.  The planner may
        #: choose it (plan.choose_wire_compress); user wins
        self.ingest_wire_compress = None
        #: reliability knobs (tpu_sgd/reliability): a RetryPolicy for
        #: transient host-feed faults (set_ingest_options(retry=...))
        #: and the cooperative preemption probe (set_stop_signal — the
        #: TrainingSupervisor installs it)
        self.ingest_retry_policy = None
        self._stop_signal = None
        #: fused-step count (set_superstep): K consecutive iterations
        #: run as ONE compiled lax.scan program on the host-dispatched
        #: paths (host-streamed + stepwise); 1 = the legacy
        #: one-dispatch-per-iteration drivers.  The planner picks K for
        #: host_streamed schedules (plan.choose_superstep)
        self.superstep = 1
        #: device-residency cadence (set_residency): C >= 2 moves the
        #: WHOLE run loop into one compiled lax.while_loop over fused
        #: supersteps on the device-resident-data paths, with host
        #: callbacks every C supersteps (optimize/resident_driver.py);
        #: 0 = the per-superstep host driver.  The planner picks C for
        #: host_streamed schedules (plan.choose_residency)
        self.resident_cadence = 0
        #: gram-knob fields the USER set via set_gram_options /
        #: set_streamed_stats — the planner preserves these and resets
        #: only plan-owned fields (Plan.apply)
        self._user_gram_opts = frozenset()
        self.last_plan = None
        self._plan_key = None
        self._gram_entry = None
        self._gram_dp_entry = None
        self._streamed_gram_entry = None
        self._streamed_gram_dp_entry = None
        self._loss_history = None
        self._run_cache = {}

    # -- fluent config (returns self, like the reference's setters) --------
    def set_gradient(self, g: Gradient):
        self.gradient = g
        return self

    def set_updater(self, u: Updater):
        self.updater = u
        return self

    def set_step_size(self, s: float):
        self.config = self.config.replace(step_size=float(s))
        return self

    def set_num_iterations(self, n: int):
        if n < 1:
            raise ValueError(f"num_iterations must be positive, got {n}")
        self.config = self.config.replace(num_iterations=int(n))
        return self

    def set_reg_param(self, r: float):
        self.config = self.config.replace(reg_param=float(r))
        return self

    def set_mini_batch_fraction(self, f: float):
        if not 0.0 < f <= 1.0:
            raise ValueError("mini_batch_fraction must be in (0, 1]")
        self.config = self.config.replace(mini_batch_fraction=float(f))
        return self

    def set_convergence_tol(self, t: float):
        if not 0.0 <= t <= 1.0:
            raise ValueError("convergence_tol must be in [0, 1]")
        self.config = self.config.replace(convergence_tol=float(t))
        return self

    def set_seed(self, s: int):
        self.config = self.config.replace(seed=int(s))
        return self

    def set_sampling(self, mode: str):
        """'bernoulli' (reference parity), 'indexed' (gathered fast path) or
        'sliced' (contiguous-window fast path — HBM-optimal; assumes
        exchangeable row order, see ``SGDConfig.sampling``)."""
        self.config = self.config.replace(sampling=mode)
        return self

    def set_mesh(self, mesh):
        self.mesh = mesh
        return self

    def set_listener(self, listener):
        """Attach an ``SGDListener`` (tpu_sgd.utils.events).

        Switches ``optimize`` to the step-wise traced path: one jitted step
        per iteration with host-visible loss/timing events — the analogue of
        Spark's per-job listener bus (SURVEY.md §5.1) — instead of the single
        fused while_loop program.
        """
        self.listener = listener
        return self

    def set_check_numerics(self, flag: bool = True):
        """Raise ``FloatingPointError`` when the loss goes non-finite
        (diverging step size, bad data) — the JAX-side analogue of the
        reference's JVM sanitizer story (SURVEY.md §5.2: functional purity
        plus explicit NaN checks; no TSAN equivalent is needed)."""
        self.check_numerics = bool(flag)
        return self

    def set_host_streaming(self, flag: bool = True, resident_rows: int = 0):
        """Keep the dataset in host RAM and stream per-iteration sampled
        batches to the device with double-buffered prefetch — for datasets
        larger than HBM (SURVEY.md §7, config 4 at full 40 GB scale).
        Composes with ``set_mesh`` on a 1-D data mesh: each batch is
        row-sharded across cores and gradients all-reduce over ICI.

        ``resident_rows``: partial residency (sliced sampling, single
        device) — rows ``[0, resident_rows)`` are placed on the device once
        and windows inside that prefix are sliced on-device, cutting
        per-epoch host->device traffic by ~``resident_rows/n`` with an
        unchanged window sequence (see ``optimize_host_streamed``).

        The per-iteration feed runs through the shared ingest pipeline
        (``tpu_sgd/io``): iteration ``i+1``'s batch assembles and
        transfers on a worker thread while ``i`` computes, and
        ``set_ingest_options(wire_dtype="bfloat16")`` halves the bytes on
        the wire — see README "Ingestion pipeline" for the knobs and the
        bf16 safety notes."""
        self._clear_planned_schedule()
        self.host_streaming = bool(flag)
        self.streaming_resident_rows = int(resident_rows)
        self._mark_manual_schedule()
        return self

    def _clear_planned_schedule(self):
        """A manual schedule setter taking the wheel AFTER an auto-planned
        run: the previous plan's sibling flags are the PLANNER's, not the
        user's — reset them so the schedule-exclusion guards never blame
        the user for a flag a plan set (user-set flags always come with
        ``last_plan is None``)."""
        if self.last_plan is not None:
            self.host_streaming = False
            self.streaming_resident_rows = 0
            self.sufficient_stats = False
            self.streamed_stats = False
            # ...and the plan's SIZING knobs: a block size / chunk cap
            # sized for the planned dataset must not leak into a manual
            # schedule on a different one (user-set knobs survive)
            from tpu_sgd.plan import reset_plan_owned_gram_knobs

            reset_plan_owned_gram_knobs(self)

    def _mark_manual_schedule(self):
        """A user-called schedule setter invalidates any auto-plan: the
        planner's 'manual flags win' contract keys on ``last_plan is
        None`` (tpu_sgd/models/glm.py), so clear it (and the repeat-run
        plan cache key) whenever the user takes the wheel."""
        self.last_plan = None
        self._plan_key = None

    def set_sufficient_stats(self, flag: bool = True):
        """Execute least-squares via precomputed block-prefix Gram
        statistics (``ops/gram.py``): window/full-batch gradients become
        two (d, d) matvecs plus masked edge blocks instead of two full
        passes over the sampled rows — exact, and far below the two-read
        HBM bandwidth floor the stock path sits at (PROFILE_TPU.json).

        Applies when the gradient is exactly ``LeastSquaresGradient``, the
        data is dense and device-resident (no mesh, no host streaming), and
        sampling is ``sliced`` or full-batch; any other combination runs
        unchanged.  The one-time build pass is cached per ``(X, y)`` array
        identity — and RETAINED after ``optimize`` returns (the streaming
        mode's repeated calls on the same arrays must not rebuild), which
        pins the dataset plus the ~GB-scale prefix stack in HBM until a
        different dataset is passed, the optimizer is dropped, or
        :meth:`release_sufficient_stats` is called."""
        self._clear_planned_schedule()
        self.sufficient_stats = bool(flag)
        self._mark_manual_schedule()
        return self

    def set_gram_options(self, block_rows: int = None, aligned: bool = None,
                         batch_rows: int = None, chunk_iters: int = None):
        """Tuning knobs for the sufficient-statistics schedules.

        ``block_rows`` trades prefix-stack memory (``n/B · d² · 4`` bytes)
        against per-iteration edge-read traffic (see ``ops/gram.py``).
        ``aligned=True`` floors window starts to block boundaries, skipping
        the edge corrections (~71% of the exact iteration) at the cost of
        the same floored-window sampling deviation the Pallas tiled kernel
        makes — fine on shuffled rows, not on sorted/grouped data.
        ``batch_rows`` caps the streamed build's host→device chunk (the
        chunk is co-resident with the growing prefix stack, so a tight
        device budget needs a smaller chunk than the 64-block default).
        ``chunk_iters=K`` switches block-aligned sliced execution to the
        chunked-gather driver (``optimize/gram_driver.py``): K window
        endpoints gathered from the prefix stacks per outer step, the
        same per-iteration contract — opt-in until the hardware
        decomposition capture settles its default.  SINGLE-DEVICE only:
        the meshed gram runners keep the per-iteration driver (a warning
        says so when both are set).
        The execution planner (``tpu_sgd/plan.py``) sets ``block_rows``/
        ``batch_rows`` automatically; ``aligned`` stays opt-in."""
        from tpu_sgd.plan import apply_user_gram_knobs

        apply_user_gram_knobs(self, block_rows=block_rows, aligned=aligned,
                              batch_rows=batch_rows,
                              chunk_iters=chunk_iters)
        return self

    def set_ingest_options(self, wire_dtype=None, prefetch_depth=None,
                           pipeline=None, retry=None, wire_compress=None):
        """Tuning knobs for the host→device ingest pipeline
        (``tpu_sgd/io``; README "Ingestion pipeline") — they apply to
        every streaming schedule: ``set_host_streaming``,
        ``set_streamed_stats`` (single-device and meshed), and the
        planner's streamed choices.

        ``wire_dtype="bfloat16"`` casts each transferred chunk on host
        and moves half the bytes; the device side still accumulates in
        f32+ (see ``tpu_sgd/io/wire.py`` for when that is safe).
        ``prefetch_depth`` caps the chunks materialized at once,
        INCLUDING the one being consumed (2 = double buffer — the 2×
        staging footprint the planner budgets ``batch_rows`` for;
        depths above 2 grow that footprint proportionally, so shrink
        ``batch_rows`` to match on a tight device); ``0``/``1`` and
        ``pipeline=False`` fall back to the synchronous legacy feed
        (bitwise A/B, one chunk live at a time; ``pipeline=False`` also
        disables the wire cast).

        ``retry`` (the reliability knob; README "Reliability"): a
        ``tpu_sgd.reliability.RetryPolicy`` that re-runs a failed
        host-side batch assembly/transfer with seeded backoff before
        the error propagates — transient ``device_put``/disk faults
        heal in place on the ``set_host_streaming`` feed.  Retries do
        not change WHAT is sampled (the sample is deterministic in
        ``(seed, i)``), so a healed run stays bitwise identical.  For
        whole-run crash-resume and preemption safety wrap the run in a
        ``tpu_sgd.reliability.TrainingSupervisor``.

        ``wire_compress="topk:<frac>"`` (README "Compressed wire"): the
        COMPRESSED gradient/update wire — top-k ``(values, indices)``
        segments with error-feedback accumulation on the wires that
        move update-shaped data: the per-step gradient all-reduce of
        the ``set_host_streaming`` feed (meshed: segments replace the
        dense psum; single-device: the same EF top-k update rule, the
        matched-loss A/B twin) and the per-shard totals merge of the
        streamed statistics builds.  The EF accumulator is optimizer
        state — checkpointed and scan-carried, see ADVICE.md "Error
        feedback is optimizer state, not a transport detail".  Pass
        ``False`` to clear a previously set spec."""
        from tpu_sgd.plan import apply_user_ingest_options

        apply_user_ingest_options(self, wire_dtype=wire_dtype,
                                  prefetch_depth=prefetch_depth,
                                  pipeline=pipeline, retry=retry,
                                  wire_compress=wire_compress)
        return self

    def set_superstep(self, k: int):
        """Fuse ``k`` consecutive SGD iterations into ONE compiled
        program (``lax.scan`` of the per-iteration step) on the paths
        that pay a host round-trip per iteration — the host-streamed
        feed (``set_host_streaming``; the prefetcher assembles a
        ``k``-batch *superchunk* so ``device_put`` fires once per
        superstep too) and the observed stepwise driver
        (listener/checkpoint attached).  Per-step math and the sampled
        sequence are unchanged: loss history and convergence detection
        stay per-iteration exact (the scan returns per-step ys),
        checkpoints land on the same iterations, and every
        same-program contract is bitwise — fused runs replay, resume,
        and prefetch-A/B to identical weights.  Versus the ``k=1``
        legacy loop, trajectories agree to reassociation noise (~1
        ulp/step: XLA lowers the batch dot differently inside a
        scanned program — see ``make_superstep``'s trajectory
        contract).  What changes: dispatch + transfer count drops
        ~``k``×
        (BENCH_SUPERSTEP.json), listener events arrive in bursts of
        ``k`` with averaged per-iteration wall times, and cooperative
        preemption (``set_stop_signal``) is polled at superstep
        boundaries — worst-case preemption latency grows to ``k``
        iterations (see ADVICE.md; keep ``k`` at or below the
        checkpoint cadence).  ``k=1`` restores the legacy drivers.
        Single-device only: meshed and partial-residency feeds keep the
        per-iteration driver (a warning says so).  The fused
        single-program paths (no listener/checkpoint/streaming) already
        run zero host dispatches and ignore it."""
        if int(k) < 1:
            raise ValueError(f"superstep must be >= 1, got {k}")
        self.superstep = int(k)
        self._user_gram_opts = self._user_gram_opts | {"superstep"}
        self._plan_key = None
        return self

    def set_residency(self, cadence: int = 8):
        """Move the WHOLE run loop on device: a single compiled
        ``lax.while_loop`` over fused superstep scans drives the run
        from start to converged-or-budget-exhausted in ONE program
        dispatch, with the host involved only every ``cadence``
        supersteps — an ordered ``io_callback`` surfaces a bounded
        ring buffer of per-step history that replays through the exact
        superstep bookkeeping (loss history, listener events,
        convergence at the true iteration, checkpoint cadence; see
        ``optimize/resident_driver.py`` and README "Device-resident
        training").  Applies where the per-iteration data already
        lives on device: the observed stepwise driver and the
        host-streamed full-batch / fully-resident-slab feeds; the
        host-sampled streamed feeds keep the superstep driver (the
        host hop there IS the data feed).  Requires ``set_superstep(K
        >= 2)`` (or a planner-chosen K) — residency fuses the
        superstep executor, it does not replace it.  Stop signals are
        polled once per cadence window, so worst-case preemption
        latency grows to ``cadence * K`` iterations (ADVICE.md); keep
        the window at or below the checkpoint cadence.  ``cadence=0``
        restores the per-superstep host driver; a window of ONE
        superstep is the superstep driver already, so ``cadence=1``
        is rejected.  ``plan.choose_residency`` picks the cadence
        automatically for planned host-streamed schedules."""
        c = int(cadence)
        if c == 1:
            raise ValueError(
                "residency cadence 1 is the per-superstep driver "
                "(set_superstep); use cadence >= 2 or 0 to disable")
        if c < 0:
            raise ValueError(f"cadence must be >= 0, got {cadence}")
        self.resident_cadence = c
        self._user_gram_opts = self._user_gram_opts | {"residency"}
        self._plan_key = None
        return self

    def set_stop_signal(self, stop_signal):
        """Install a zero-arg callable polled once per iteration on the
        observed (listener/checkpoint) and host-streamed paths: when it
        returns True the current state is checkpointed (if a manager is
        attached) and the run unwinds with ``TrainingPreempted`` — the
        cooperative half of preemption-safe training.  Pass ``None`` to
        clear.  Installed automatically by
        ``tpu_sgd.reliability.TrainingSupervisor``; the fused
        single-program paths (no per-iteration host hop) cannot poll
        and simply run to completion."""
        self._stop_signal = stop_signal
        return self

    def set_streamed_stats(self, flag: bool = True, block_rows: int = None):
        """Beyond-HBM least squares via streamed statistics: ONE host-
        streaming pass builds the block-prefix Gram stack on device
        (``GramLeastSquaresGradient.build_streamed``), after which
        iterations run entirely from the statistics — zero per-iteration
        host transfer (measured 0.026 ms/iter on the true 10M×1000,
        BASELINE.md round 3).  Windows are ALIGNED (block-floored) by
        construction and the trailing ``n % block_rows`` rows are dropped —
        a sampling deviation that is harmless on shuffled rows but not on
        sorted/grouped data; use ``set_host_streaming`` for exact-window
        streaming.  Applies to exactly ``LeastSquaresGradient`` on dense
        single-device data with sliced or full-batch sampling; the build is
        identity-cached per ``(X, y)`` like ``set_sufficient_stats``.

        The one-time build pass streams through the shared ingest
        pipeline (``tpu_sgd/io``): double-buffered fixed-shape chunks
        (f32 wire bitwise-identical to the legacy sync feed), with an
        opt-in bf16 wire via ``set_ingest_options`` — see README
        "Ingestion pipeline"."""
        self._clear_planned_schedule()
        self.streamed_stats = bool(flag)
        if block_rows is not None:
            self.gram_block_rows = int(block_rows)
            self._user_gram_opts = self._user_gram_opts | {"block_rows"}
        self._mark_manual_schedule()
        return self

    def release_sufficient_stats(self):
        """Drop the cached sufficient-statistics bundles (single-device,
        DP-mesh, and streamed-virtual) and the compiled runners keyed on
        them, so the bound dataset plus the prefix stacks can be freed from
        HBM.  Call after a one-shot ``optimize`` when the statistics are no
        longer needed; the next run rebuilds from scratch.
        (The DP-mesh runner takes its stats as call arguments, so clearing
        the entry alone frees them; only the single-device gram gradients
        appear in run-cache keys.)"""
        for entry in (self._gram_entry, self._streamed_gram_entry):
            if entry is not None:
                self._purge_run_cache_for(entry[2])
        self._gram_entry = None
        self._gram_dp_entry = None
        self._streamed_gram_entry = None
        self._streamed_gram_dp_entry = None
        return self

    def _purge_run_cache_for(self, obj):
        """Drop compiled runners whose cache key contains ``obj`` (by
        identity) so a superseded gram gradient's GB-scale prefix stack is
        not pinned by a closure."""
        self._run_cache = {
            k: v for k, v in self._run_cache.items()
            if not any(part is obj for part in k)
        }

    def set_checkpoint(self, manager, every: int = 10):
        """Attach a ``CheckpointManager``; optimizer state is saved every
        ``every`` iterations and ``optimize`` resumes from the latest
        checkpoint when one exists (SURVEY.md §5.4)."""
        self.checkpoint_manager = manager
        self.checkpoint_every = int(every)
        return self

    # -- optimization ------------------------------------------------------
    @property
    def loss_history(self):
        """Stochastic loss history of the last ``optimize`` call (np array)."""
        return self._loss_history

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        w, losses = self.optimize_with_history(data, initial_weights)
        return w

    def optimize_with_history(self, data: Dataset, initial_weights: Array):
        import numpy as np

        X, y = data
        from tpu_sgd.ops.gram import GramData, GramLeastSquaresGradient

        if isinstance(X, GramData):
            # Statistics-first input (build/build_streamed): the rows may
            # be virtual (beyond-HBM datasets), so coerce only y/w0 and
            # route straight to the resident single-device path.
            if not isinstance(self.gradient, GramLeastSquaresGradient):
                raise ValueError(
                    "GramData input needs a GramLeastSquaresGradient "
                    "(use GramLeastSquaresGradient.build/build_streamed "
                    "and pass it as the gradient)"
                )
            if self.mesh is not None or self.host_streaming:
                raise NotImplementedError(
                    "GramData input supports the single-device resident "
                    "path (stats are already on device); drop set_mesh/"
                    "set_host_streaming"
                )
            cfg = self.config
            if cfg.mini_batch_fraction < 1.0 and cfg.sampling != "sliced":
                raise NotImplementedError(
                    "GramData input supports sliced sampling or full "
                    f"batch (got sampling={cfg.sampling!r})"
                )
            if (cfg.mini_batch_fraction < 1.0 and X.X is None
                    and X.PG.shape[0] <= 2):
                import warnings

                # a single-block virtual stack (e.g. a persisted
                # totals-only bundle from the quasi-Newton/normal paths)
                # cannot express sub-batch windows: every "window" IS
                # the full batch — the run silently stops being SGD
                warnings.warn(
                    "these virtual statistics hold a single block, so "
                    f"sliced windows at frac={cfg.mini_batch_fraction} "
                    "degenerate to FULL-BATCH iterations; rebuild with "
                    "a smaller block_rows for true mini-batch sampling",
                    RuntimeWarning, stacklevel=3,
                )
            y = jnp.asarray(y)
            if not jnp.issubdtype(y.dtype, jnp.inexact):
                y = y.astype(jnp.float32)
            w0 = _coerce_w0(self.gradient, initial_weights, X.shape[1])
            return self._optimize_routed(X, y, w0, sparse_X=False)
        sparse_X = is_sparse(X)
        if sparse_X:
            # BCOO feature path (VERDICT r1 missing #2; [U] SparseVector
            # training, SURVEY.md §2 #10): same fused step, gather/segment
            # lowering.  Everything that needs a dense row layout raises.
            if self.host_streaming:
                # host-streamed SPARSE feed (optimize/streamed_sparse.py;
                # README "Compressed wire"): the dataset stays host-
                # resident as CSR entry arrays and each sampled batch
                # ships as fixed-nse BCOO components — never densified
                # anywhere on the path
                from tpu_sgd.optimize.streamed_sparse import (
                    optimize_host_streamed_sparse,
                )

                if self.mesh is not None:
                    raise NotImplementedError(
                        "host-streamed sparse training is single-device "
                        "(shard the resident BCOO path with set_mesh "
                        "instead)"
                    )
                if self.ingest_wire_dtype is not None:
                    import warnings

                    warnings.warn(
                        "wire_dtype applies to dense row chunks; the "
                        "sparse feed ships BCOO components at the data "
                        "dtype (its compression is the sparsity itself)",
                        RuntimeWarning, stacklevel=2,
                    )
                w0 = _coerce_w0(self.gradient, initial_weights,
                                X.shape[1])
                w, hist = optimize_host_streamed_sparse(
                    self.gradient, self.updater, self.config, X,
                    np.asarray(y), w0,
                    listener=self.listener,
                    checkpoint_manager=self.checkpoint_manager,
                    checkpoint_every=self.checkpoint_every,
                    prefetch_depth=(self.ingest_prefetch_depth
                                    if self.ingest_pipeline else 0),
                    retry_policy=self.ingest_retry_policy,
                    stop_signal=self._stop_signal,
                    superstep_k=self.superstep,
                    resident_cadence=self.resident_cadence,
                    wire_compress=(self.ingest_wire_compress
                                   if self.ingest_pipeline else None),
                )
                self._loss_history = hist
                if self.check_numerics:
                    _raise_if_nonfinite(hist)
                return w, hist
            if self.mesh is not None and self._mesh_kind() == "dp_mp":
                raise NotImplementedError(
                    "feature-axis ('model') sharding needs dense column "
                    "blocks; sparse (BCOO) features support 1-D 'data' "
                    "meshes"
                )
            if (self.config.sampling != "bernoulli"
                    and self.config.mini_batch_fraction < 1.0):
                raise NotImplementedError(
                    "sparse features support bernoulli sampling only "
                    f"(got sampling={self.config.sampling!r})"
                )
        if self.streamed_stats:
            # Beyond-HBM sufficient statistics (set_streamed_stats): build
            # once from the host rows, then iterate from the on-device
            # statistics.  Routed BEFORE host_streaming/device conversion —
            # the rows never live on the device at all.  With a 1-D data
            # mesh the build streams each shard's rows to its own device
            # and the run is the shard_map'ed virtual-stats loop; single-
            # device re-enters through the GramData branch above.
            self._check_streamed_stats_applies(sparse_X)
            if self.mesh is not None:
                # this route returns before _optimize_routed's warning
                # would fire — the user's explicit chunk_iters request is
                # being dropped and must not go silent
                self._warn_chunk_iters_with_mesh(stacklevel=3)
                return self._optimize_streamed_stats_mesh(
                    X, y, initial_weights
                )
            gram = self._route_streamed_stats(X, y)
            orig, self.gradient = self.gradient, gram
            try:
                n_logical = gram.data.shape[0]
                return self.optimize_with_history(
                    (gram.data, np.asarray(y)[:n_logical]), initial_weights
                )
            finally:
                self.gradient = orig
        if self.host_streaming:
            # Route BEFORE any device conversion: the whole point is that X
            # never lives on the device in full.
            from tpu_sgd.optimize.streamed import optimize_host_streamed

            if self.mesh is not None and self._mesh_kind() == "dp_mp":
                raise NotImplementedError(
                    "host streaming supports 1-D data meshes; feature-axis "
                    "('model') sharding needs the resident path"
                )
            Xh = np.asarray(X)
            # same weight validation/coercion as the resident paths — a
            # wrong-length w0 must raise the clear ValueError here, not
            # an opaque XLA dot-shape error inside the streamed step
            w0 = _coerce_w0(self.gradient, initial_weights, Xh.shape[1])
            if Xh.shape[0] == 0:
                self._loss_history = np.zeros((0,), np.float32)
                return w0, self._loss_history
            w, hist = optimize_host_streamed(
                self.gradient, self.updater, self.config, Xh, np.asarray(y),
                w0, mesh=self.mesh, listener=self.listener,
                checkpoint_manager=self.checkpoint_manager,
                checkpoint_every=self.checkpoint_every,
                resident_rows=self.streaming_resident_rows,
                # pipeline=False is the LEGACY feed: no wire cast, no
                # lookahead — the bitwise A/B contract (the gram
                # builders make the same reduction)
                wire_dtype=(self.ingest_wire_dtype
                            if self.ingest_pipeline else None),
                prefetch_depth=(self.ingest_prefetch_depth
                                if self.ingest_pipeline else 0),
                retry_policy=self.ingest_retry_policy,
                stop_signal=self._stop_signal,
                superstep_k=self.superstep,
                resident_cadence=self.resident_cadence,
                wire_compress=(self.ingest_wire_compress
                               if self.ingest_pipeline else None),
            )
            self._loss_history = hist
            if self.check_numerics:
                _raise_if_nonfinite(hist)
            return w, hist
        if not sparse_X:
            X = jnp.asarray(X)
            if not jnp.issubdtype(X.dtype, jnp.inexact):
                X = X.astype(jnp.float32)  # int/bool features (one-hot etc.)
        y = jnp.asarray(y)
        if not jnp.issubdtype(y.dtype, jnp.inexact):
            y = y.astype(jnp.float32)
        w0 = _coerce_w0(self.gradient, initial_weights, X.shape[1])
        n = X.shape[0]
        if n == 0:
            self._loss_history = np.zeros((0,), np.float32)
            return w0, self._loss_history
        if n * self.config.mini_batch_fraction < 1:
            import warnings

            warnings.warn(
                "The miniBatchFraction is too small", RuntimeWarning, stacklevel=2
            )
        gram = self._maybe_gram(X, y, sparse_X)
        if gram is not None:
            # The stats ride as the X argument (GramData pytree) so they
            # enter the jit program as buffers, not closure constants.
            orig, self.gradient = self.gradient, gram
            try:
                return self._optimize_routed(gram.data, y, w0, sparse_X)
            finally:
                self.gradient = orig
        return self._optimize_routed(X, y, w0, sparse_X)

    def _optimize_routed(self, X, y, w0, sparse_X):
        """Resident-data path routing (single-device / mesh / sparse /
        stepwise), after input coercion and the optional sufficient-stats
        substitution."""
        import numpy as np

        self._warn_chunk_iters_with_mesh(stacklevel=4)

        if self.listener is not None or self.checkpoint_manager is not None:
            if self.gram_chunk_iters:
                import warnings

                warnings.warn(
                    "chunk_iters is ignored on the observed "
                    "(listener/checkpoint) path: chunking amortizes the "
                    "per-iteration host hop that listeners exist to "
                    "provide; detach the listener to use the chunked "
                    "driver",
                    RuntimeWarning, stacklevel=3,
                )
            if (self.sufficient_stats and self.mesh is not None
                    and not sparse_X):
                import warnings

                warnings.warn(
                    "sufficient_stats is not applied on the meshed "
                    "listener/checkpoint path (the observed per-iteration "
                    "stepper uses the stock DP step); detach the listener "
                    "or run single-device to combine them",
                    RuntimeWarning,
                    stacklevel=3,
                )
            return self._optimize_stepwise(X, y, w0)
        if sparse_X and self.mesh is not None:
            # Distributed sparse: equal-nse BCOO blocks per shard, same
            # make_run body, psum over ICI (the treeAggregate-over-sparse-
            # partitions analogue — see parallel/sparse_parallel.py).
            from tpu_sgd.parallel.sparse_parallel import (
                shard_bcoo,
                sparse_dp_run_fn,
            )

            data, idx, yd, valid, rows_local, d = shard_bcoo(self.mesh, X, y)
            with_valid = valid is not None
            key = ("sparse_run", self.gradient, self.updater, self.config,
                   self.mesh, rows_local, d, with_valid)
            fn = self._run_cache.get(key)
            if fn is None:
                fn = sparse_dp_run_fn(self.gradient, self.updater,
                                      self.config, self.mesh, rows_local, d,
                                      with_valid)
                self._run_cache[key] = fn
            if with_valid:
                w, losses, n_rec = fn(w0, data, idx, yd, valid)
            else:
                w, losses, n_rec = fn(w0, data, idx, yd)
        elif self.mesh is not None and self._mesh_kind() == "dp_mp":
            from tpu_sgd.parallel.model_parallel import dp_mp_optimize

            if self.gradient.weight_dim(X.shape[1]) != X.shape[1]:
                raise NotImplementedError(
                    "feature-axis ('model') sharding supports vector-weight "
                    "gradients only; matrix-weight gradients (multinomial) "
                    "need a 1-D 'data' mesh"
                )
            w, losses, n_rec = dp_mp_optimize(
                self.gradient, self.updater, self.config, self.mesh, w0, X, y
            )
        elif self.mesh is not None:
            from tpu_sgd.parallel.data_parallel import shard_dataset

            Xd, yd, valid = shard_dataset(self.mesh, X, y)
            stats = self._maybe_gram_dp(X, y, Xd, yd, valid)
            if stats is not None:
                stats_leaves, block_rows = stats
                key = ("gram_dp_run", self.updater, self.config,
                       self.mesh, block_rows, self.gram_aligned)
                fn = self._run_cache.get(key)
                if fn is None:
                    from tpu_sgd.parallel.gram_parallel import (
                        dp_gram_run_fn,
                    )

                    fn = dp_gram_run_fn(self.updater, self.config,
                                        self.mesh, block_rows,
                                        aligned=self.gram_aligned)
                    self._run_cache[key] = fn
                w, losses, n_rec = fn(w0, Xd, yd, *stats_leaves)
            else:
                fn = self._runner(with_valid=valid is not None)
                if valid is not None:
                    w, losses, n_rec = fn(w0, Xd, yd, valid)
                else:
                    w, losses, n_rec = fn(w0, Xd, yd)
        else:
            fn = self._maybe_chunked_gram_run(X)
            if fn is not None:
                w, losses, n_rec = fn(w0, X, y)
            else:
                w, losses, n_rec = self._runner(with_valid=False)(w0, X, y)
        n_rec = int(n_rec)
        self._loss_history = np.asarray(losses)[:n_rec]
        if self.check_numerics:
            _raise_if_nonfinite(self._loss_history)
        return w, self._loss_history

    def _warn_chunk_iters_with_mesh(self, stacklevel: int = 3) -> None:
        """One warning for every route that drops an explicit
        ``chunk_iters`` because a mesh is set — the meshed gram runners
        keep the per-iteration driver."""
        if self.gram_chunk_iters and self.mesh is not None:
            import warnings

            warnings.warn(
                "chunk_iters applies to the single-device aligned-gram "
                "driver only; the meshed gram runners keep the "
                "per-iteration driver (drop set_mesh to use the chunked "
                "driver)",
                RuntimeWarning, stacklevel=stacklevel,
            )

    def _maybe_chunked_gram_run(self, X):
        """The chunked-gather driver (``optimize/gram_driver.py``) when
        the ``chunk_iters`` knob is set and this execution is block-
        ALIGNED statistics with sliced windows — virtual stats (X.X is
        None) are aligned by construction; resident stats qualify in
        aligned mode.  None otherwise (the per-iteration driver runs)."""
        from tpu_sgd.ops.gram import GramData, GramLeastSquaresGradient

        cfg = self.config
        if (not self.gram_chunk_iters
                or not isinstance(X, GramData)
                or not isinstance(self.gradient, GramLeastSquaresGradient)
                # engage ONLY where the per-iteration path itself runs
                # aligned windows (window_sums' own dispatch): gating on
                # the optimizer-level gram_aligned knob would switch a
                # prebuilt non-aligned gradient to aligned math and
                # silently change the trajectory chunk_iters promises to
                # preserve
                or not (X.X is None or self.gradient.aligned)
                or cfg.sampling != "sliced"
                or cfg.mini_batch_fraction >= 1.0):
            return None
        n = X.shape[0]
        key = ("chunked_gram_run", self.updater, cfg, n, X.block_rows,
               self.gram_chunk_iters)
        fn = self._run_cache.get(key)
        if fn is None:
            from tpu_sgd.optimize.gram_driver import make_chunked_gram_run

            fn = jax.jit(make_chunked_gram_run(
                self.updater, cfg, n=n, block_rows=X.block_rows,
                chunk_iters=self.gram_chunk_iters,
            ))
            self._run_cache[key] = fn
        return fn

    def _check_streamed_stats_applies(self, sparse_X):
        """Shared guards for ``set_streamed_stats`` (single-device and
        meshed)."""
        from tpu_sgd.ops.gradients import LeastSquaresGradient as _LS

        if sparse_X:
            raise NotImplementedError(
                "streamed statistics need dense rows; BCOO features are "
                "~1000x smaller and stay device-resident instead"
            )
        if self.mesh is not None and self._mesh_kind() == "dp_mp":
            raise NotImplementedError(
                "streamed statistics compose with a 1-D 'data' mesh; "
                "feature-axis ('model') sharding needs resident column "
                "blocks"
            )
        if self.host_streaming:
            raise ValueError(
                "set_streamed_stats and set_host_streaming are alternative "
                "beyond-HBM schedules; enable exactly one"
            )
        if type(self.gradient) is not _LS:
            raise NotImplementedError(
                "streamed statistics exist for least squares only (the "
                f"quadratic loss); got {type(self.gradient).__name__} — "
                "use set_host_streaming"
            )
        cfg = self.config
        if cfg.mini_batch_fraction < 1.0 and cfg.sampling != "sliced":
            raise NotImplementedError(
                "streamed statistics support sliced sampling or full "
                f"batch (got sampling={cfg.sampling!r}); use "
                "set_host_streaming for bernoulli/indexed parity"
            )

    def _optimize_streamed_stats_mesh(self, X, y, initial_weights):
        """Meshed ``set_streamed_stats``: per-shard virtual statistics
        built by streaming each shard's HOST rows to its own device, then
        the shard_map'ed virtual-stats loop (zero rows on device —
        config 4's 8-way DP shape at beyond-HBM scale;
        ``parallel/gram_parallel.py``)."""
        import numpy as np

        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_sgd.parallel.gram_parallel import (
            build_streamed_sharded_gram_stats,
            dp_virtual_gram_run_fn,
        )
        from tpu_sgd.parallel.mesh import DATA_AXIS

        if self.listener is not None or self.checkpoint_manager is not None:
            import warnings

            warnings.warn(
                "listener/checkpoint callbacks are not applied on the "
                "meshed streamed-statistics path (the shard_map'ed "
                "virtual loop has no per-iteration host hop); detach "
                "them or run single-device to combine",
                RuntimeWarning,
                stacklevel=3,
            )
        Xh = np.asarray(X)
        d = Xh.shape[1]
        entry = getattr(self, "_streamed_gram_dp_entry", None)
        opts = (self.gram_block_rows, self.gram_batch_rows,
                self._ingest_opts())
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[2] is self.mesh and entry[4] == opts):
            stats, B, n_used, yd = entry[3]
        else:
            stats, B, n_used = build_streamed_sharded_gram_stats(
                self.mesh, Xh, np.asarray(y),
                block_rows=self.gram_block_rows,
                batch_rows=self.gram_batch_rows,
                wire_dtype=self.ingest_wire_dtype,
                prefetch_depth=self.ingest_prefetch_depth,
                pipeline=self.ingest_pipeline,
            )
            k = self.mesh.shape[DATA_AXIS]
            n_local_host = Xh.shape[0] // k
            yh = np.asarray(y, np.float32)
            # labels ride along for shape parity only (the virtual window
            # path never reads them); cached with the stats so repeat
            # calls skip the concat + sharded transfer
            yd = jax.device_put(
                np.concatenate([
                    yh[i * n_local_host:i * n_local_host + n_used]
                    for i in range(k)
                ]),
                NamedSharding(self.mesh, P(DATA_AXIS)),
            )
            self._streamed_gram_dp_entry = (
                X, y, self.mesh, (stats, B, n_used, yd), opts,
            )
        w0 = _coerce_w0(self.gradient, initial_weights, d)
        dtype_name = str(np.dtype(Xh.dtype)
                         if np.issubdtype(Xh.dtype, np.inexact)
                         else np.dtype(np.float32))
        key = ("virtual_gram_dp_run", self.updater, self.config, self.mesh,
               B, n_used, d, dtype_name)
        fn = self._run_cache.get(key)
        if fn is None:
            fn = dp_virtual_gram_run_fn(self.updater, self.config,
                                        self.mesh, B, n_used, d, dtype_name)
            self._run_cache[key] = fn
        w, losses, n_rec = fn(w0, yd, *stats)
        n_rec = int(n_rec)
        self._loss_history = np.asarray(losses)[:n_rec]
        if self.check_numerics:
            _raise_if_nonfinite(self._loss_history)
        return w, self._loss_history

    def _ingest_opts(self):
        """The ingest-pipeline knobs as a cache-key tuple — a wire/depth
        change must invalidate the identity-cached streamed builds (the
        statistics DEPEND on the wire dtype)."""
        return (self.ingest_wire_dtype, self.ingest_prefetch_depth,
                self.ingest_pipeline)

    def _route_streamed_stats(self, X, y):
        """Identity-cached single-device build for ``set_streamed_stats``
        (guards already checked)."""
        from tpu_sgd.ops.gram import GramLeastSquaresGradient

        entry = self._streamed_gram_entry
        opts = (self.gram_block_rows, self.gram_batch_rows,
                self._ingest_opts())
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[3] == opts):
            return entry[2]
        if entry is not None:
            self._purge_run_cache_for(entry[2])
        import numpy as np

        g = GramLeastSquaresGradient.build_streamed(
            np.asarray(X), np.asarray(y),
            block_rows=self.gram_block_rows,
            batch_rows=self.gram_batch_rows,
            wire_dtype=self.ingest_wire_dtype,
            prefetch_depth=self.ingest_prefetch_depth,
            pipeline=self.ingest_pipeline,
        )
        self._streamed_gram_entry = (X, y, g, opts)
        return g

    def _maybe_gram(self, X, y, sparse_X):
        """The sufficient-stats substitution, when it applies (see
        ``set_sufficient_stats``); identity-cached so the streaming mode's
        repeated ``optimize`` calls on the same arrays build once."""
        from tpu_sgd.ops.gradients import LeastSquaresGradient as _LS
        from tpu_sgd.ops.gram import GramLeastSquaresGradient

        cfg = self.config
        if (sparse_X or self.mesh is not None or self.host_streaming
                or (cfg.mini_batch_fraction < 1.0
                    and cfg.sampling != "sliced")):
            return None
        if (isinstance(self.gradient, GramLeastSquaresGradient)
                and self.gradient.data is not None
                and self.gradient.data.X is X):
            # user-built gram gradient on exactly this matrix: route its
            # GramData through so the traced program accelerates
            return self.gradient
        if not self.sufficient_stats or type(self.gradient) is not _LS:
            return None
        entry = self._gram_entry
        opts = (self.gram_block_rows, self.gram_aligned)
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[3:] == opts):
            return entry[2]
        if entry is not None:
            # new dataset (or new gram options): drop compiled runners
            # keyed on the superseded gram gradient so its GB-scale prefix
            # stack can be freed
            self._purge_run_cache_for(entry[2])
        g = GramLeastSquaresGradient.build(
            X, y, block_rows=self.gram_block_rows, aligned=self.gram_aligned
        )
        # keep the ORIGINAL arrays in the key: build() may re-coerce
        self._gram_entry = (X, y, g) + opts
        return g

    def _maybe_gram_dp(self, X, y, Xd, yd, valid):
        """The sufficient-stats substitution over a 1-D data mesh (see
        ``parallel/gram_parallel.py``): per-shard prefix stats, identity-
        cached per ``(X, y, mesh)``.  Returns ``(stats_leaves, block_rows)``
        or None.  Padded datasets (``valid`` mask) fall back — the gram
        window normalizes by the full window length, which would differ
        from the stock path's realized valid count."""
        from tpu_sgd.ops.gradients import LeastSquaresGradient as _LS

        cfg = self.config
        if (
            not self.sufficient_stats
            or valid is not None
            or type(self.gradient) is not _LS
            or (cfg.mini_batch_fraction < 1.0 and cfg.sampling != "sliced")
        ):
            return None
        entry = getattr(self, "_gram_dp_entry", None)
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[2] is self.mesh
                and entry[4] == self.gram_block_rows):
            return entry[3]
        from tpu_sgd.parallel.gram_parallel import build_sharded_gram_stats

        stats = build_sharded_gram_stats(self.mesh, Xd, yd,
                                         block_rows=self.gram_block_rows)
        self._gram_dp_entry = (X, y, self.mesh, stats,
                               self.gram_block_rows)
        return stats

    def _optimize_stepwise(self, X, y, w0):
        """Observed path: jitted step per iteration with host round-trips.

        Used when a listener or checkpoint manager is attached.  Supports
        single-device and 1-D data-parallel meshes; preserves the exact loss
        history / convergence semantics of the fused path (same make_step).
        """
        import time as _time

        import numpy as np

        from tpu_sgd.utils.events import IterationEvent, RunEvent

        cfg = self.config
        if self.mesh is not None and self._mesh_kind() == "dp_mp":
            raise NotImplementedError(
                "listener/checkpoint mode supports single-device and 1-D "
                "data meshes"
            )
        valid = None
        sparse_shape = None
        if self.mesh is not None:
            if is_sparse(X):
                from tpu_sgd.parallel.sparse_parallel import shard_bcoo

                data, idx, y, valid, rows_local, d_feat = shard_bcoo(
                    self.mesh, X, y
                )
                X = (data, idx)  # component tuple; the stepper rebuilds
                sparse_shape = (rows_local, d_feat)
            else:
                from tpu_sgd.parallel.data_parallel import shard_dataset

                X, y, valid = shard_dataset(self.mesh, X, y)
        step = self._stepper(with_valid=valid is not None,
                             sparse_shape=sparse_shape)

        # regVal probe init (same as the fused path)
        _, reg_val = self.updater.compute(
            w0, jnp.zeros_like(w0), 0.0, jnp.asarray(1, jnp.int32), cfg.reg_param
        )
        reg_val = float(reg_val)
        losses = []
        start_iter = 1
        config_key = repr((type(self.gradient).__name__,
                           type(self.updater).__name__, cfg))
        mgr = self.checkpoint_manager
        if mgr is not None:
            state = mgr.restore()
            if state is not None:
                if state["config_key"] and state["config_key"] != config_key:
                    import warnings

                    warnings.warn(
                        "checkpoint config differs from current config; "
                        "resuming anyway",
                        RuntimeWarning,
                        stacklevel=3,
                    )
                w0 = jnp.asarray(state["weights"])
                reg_val = state["reg_val"]
                losses = list(np.asarray(state["loss_history"], np.float32))
                start_iter = state["iteration"] + 1
        if self.listener is not None:
            self.listener.on_run_start(cfg)

        fused_k = int(self.superstep or 1)
        if fused_k > 1 and sparse_shape is not None:
            import warnings

            warnings.warn(
                "set_superstep applies to dense data on the meshed "
                "observed path; the sparse meshed stepper stays "
                "per-iteration",
                RuntimeWarning, stacklevel=4,
            )
            fused_k = 1
        resident_c = int(self.resident_cadence or 0)
        if resident_c >= 2 and fused_k > 1 and self.mesh is not None:
            import warnings

            warnings.warn(
                "set_residency is single-device (io_callback cadence "
                "hooks do not ride shard_map); the meshed observed "
                "path runs the fused superstep driver",
                RuntimeWarning, stacklevel=4,
            )
            resident_c = 0
        if resident_c >= 2 and fused_k <= 1:
            import warnings

            warnings.warn(
                "set_residency rides the fused superstep executor; "
                "call set_superstep(K >= 2) (or let the planner pick "
                "K) to engage the device-resident driver",
                RuntimeWarning, stacklevel=4,
            )
            resident_c = 0

        w = w0
        t_run = _time.perf_counter()
        converged_early = False
        if fused_k > 1 and resident_c >= 2:
            # Device-resident route: the WHOLE run is one lax.while_loop
            # program over fused superstep scans — one dispatch for a
            # converged-or-budget-exhausted run, host hops only at the
            # cadence io_callback (optimize/resident_driver.py).  The
            # ring ys replay through the same _replay_fused_steps, so
            # history, events, convergence, and checkpoint bytes are
            # exactly the superstep driver's (bitwise-pinned in
            # tests/test_resident.py).
            from tpu_sgd.optimize.resident_driver import (
                ResidentBookkeeper,
            )

            loop = self._resident_loop(fused_k, resident_c)

            def _save_res(ii, w_np, rv_):
                mgr.save(ii, np.asarray(w_np), rv_, np.asarray(losses),
                         config_key)

            hooks = ResidentBookkeeper(
                cfg, fused_k, resident_c, losses=losses,
                reg_val=reg_val, start_iter=start_iter,
                listener=self.listener,
                save_cb=(_save_res if mgr is not None else None),
                save_every=self.checkpoint_every,
                stop_signal=self._stop_signal,
                retry_policy=self.ingest_retry_policy,
                check_numerics=self.check_numerics)
            if start_iter <= cfg.num_iterations:
                w_np, converged_early = loop.run(
                    jnp.asarray(w0), reg_val, start_iter, (X, y), hooks)
                w = jnp.asarray(w_np)
                reg_val = hooks.reg_val
        elif fused_k > 1:
            # Fused stepwise: K iterations per compiled lax.scan
            # dispatch, per-step loss/norm/weights returned as scan ys
            # and replayed host-side with the EXACT legacy bookkeeping
            # (_replay_fused_steps) — listener events, convergence at
            # the true iteration, checkpoints on the same cadence with
            # identical state.  X/y stay resident, so the only
            # per-superstep host work is the one dispatch.  On a 1-D
            # data mesh the same fused scan runs under shard_map with
            # the ICI gradient all-reduce (dp_shared_superstep_fn).
            fused = self._superstepper(fused_k,
                                       with_valid=valid is not None)

            def _save(ii, w_np, rv):
                mgr.save(ii, np.asarray(w_np), rv, np.asarray(losses),
                         config_key)

            i0 = start_iter
            while i0 <= cfg.num_iterations and not converged_early:
                steps = min(fused_k, cfg.num_iterations - i0 + 1)
                t0 = _time.perf_counter()
                # span times dispatch -> ys-on-host; the fetch below is
                # this driver's own boundary, so tracing adds zero
                # syncs/dispatches on the warmed path (the acceptance
                # pin in tests/test_obs.py)
                with span("train.superstep", i0=i0, steps=steps):
                    if valid is not None:
                        w_dev, ys = fused(
                            w, jnp.asarray(reg_val, jnp.float32),
                            jnp.asarray(i0, jnp.int32), X, y, valid,
                        )
                    else:
                        w_dev, ys = fused(
                            w, jnp.asarray(reg_val, jnp.float32),
                            jnp.asarray(i0, jnp.int32), X, y,
                        )
                    ys_host = tuple(np.asarray(a) for a in ys)  # blocks
                dt = _time.perf_counter() - t0
                t_last, reg_val, converged_early = _replay_fused_steps(
                    ys_host, i0, steps, losses, reg_val, cfg,
                    listener=self.listener, wall_dt=dt / steps,
                    check_numerics=self.check_numerics,
                    save_cb=(_save if mgr is not None else None),
                    save_every=self.checkpoint_every,
                )
                if converged_early or steps < fused_k:
                    # the run ends mid-superstep: truncate the
                    # program's overshoot — the true last iteration's
                    # state rides the ys
                    w = jnp.asarray(ys_host[0][t_last])
                else:
                    w = w_dev
                if (not converged_early and self._stop_signal is not None
                        and self._stop_signal()):
                    # cooperative preemption at the superstep BOUNDARY
                    # (the fused program cannot poll mid-scan):
                    # checkpoint the exact boundary iteration, then
                    # unwind — a resume replays from precisely here, so
                    # interrupted+resumed runs stay bitwise
                    from tpu_sgd.reliability.supervisor import (
                        TrainingPreempted,
                    )

                    boundary = i0 + steps - 1
                    if mgr is not None:
                        # graftlint: disable=host-sync -- preemption save: fires once at unwind, not per trip
                        mgr.save(boundary, np.asarray(w), reg_val,
                                 np.asarray(losses), config_key)
                    raise TrainingPreempted(boundary)
                i0 += steps
        i = start_iter
        while fused_k == 1 and i <= cfg.num_iterations:
            t0 = _time.perf_counter()
            # span around an ALREADY-contractual per-iteration barrier
            # (the observed driver's host hop IS its bookkeeping
            # contract); the span itself adds no sync
            with span("train.step", i=i):
                if valid is not None:
                    new_w, loss_i, new_reg, c = step(
                        w, X, y, jnp.asarray(i, jnp.int32),
                        jnp.asarray(reg_val), valid
                    )
                else:
                    new_w, loss_i, new_reg, c = step(
                        w, X, y, jnp.asarray(i, jnp.int32),
                        jnp.asarray(reg_val)
                    )
                # the observed stepwise driver's host hop IS the
                # contract: per-iteration listener scalars and
                # convergence need the step's results on host every
                # trip — barrier once, then fetch each scalar exactly
                # once
                # graftlint: disable=host-sync -- observed driver: one barrier per step precedes the scalar reads below
                new_w = jax.block_until_ready(new_w)
            dt = _time.perf_counter() - t0
            c = int(c)  # graftlint: disable=host-sync -- observed driver: count gates the whole bookkeeping branch
            if c > 0:
                loss_f = float(loss_i)  # graftlint: disable=host-sync -- observed driver: per-iteration loss history is the contract
                if self.check_numerics and not np.isfinite(loss_f):
                    _raise_if_nonfinite([loss_f], first_iteration=i)
                losses.append(loss_f)
                # ONE fused program + ONE fetch for both norms (was two
                # eager norms with separate syncs — host-sync finding)
                delta, w_norm = (
                    float(v)
                    for v in np.asarray(step_norms(new_w, w))  # graftlint: disable=host-sync -- observed driver: the single per-step norm fetch, post-barrier
                )
                reg_val = float(new_reg)  # graftlint: disable=host-sync -- observed driver: reg_val feeds the next step's host-side argument
                if self.listener is not None:
                    self.listener.on_iteration(
                        IterationEvent(
                            iteration=i,
                            loss=loss_f,
                            weight_delta_norm=delta,
                            mini_batch_size=c,
                            wall_time_s=dt,
                        )
                    )
                if cfg.convergence_tol > 0 and i > 1:
                    if delta < cfg.convergence_tol * max(w_norm, 1.0):
                        converged_early = True
                w = new_w
                if mgr is not None and (
                    i % self.checkpoint_every == 0
                    or converged_early
                    or i == cfg.num_iterations
                ):
                    # graftlint: disable=host-sync -- checkpoint save: cadence-gated (every checkpoint_every iterations), the documented host hop
                    mgr.save(i, np.asarray(w), reg_val, np.asarray(losses),
                             config_key)
            if converged_early:
                break
            if self._stop_signal is not None and self._stop_signal():
                # cooperative preemption (set_stop_signal): checkpoint
                # the CURRENT iteration, then unwind cleanly — the
                # supervised resume replays from exactly here
                from tpu_sgd.reliability.supervisor import TrainingPreempted

                if mgr is not None:
                    # graftlint: disable=host-sync -- preemption save: fires once at unwind, not per trip
                    mgr.save(i, np.asarray(w), reg_val, np.asarray(losses),
                             config_key)
                raise TrainingPreempted(i)
            i += 1

        if self.listener is not None:
            self.listener.on_run_end(
                RunEvent(
                    event="run_completed",
                    num_iterations=len(losses),
                    final_loss=losses[-1] if losses else None,
                    converged_early=converged_early,
                    wall_time_s=_time.perf_counter() - t_run,
                )
            )
        import numpy as _np

        self._loss_history = _np.asarray(losses, _np.float32)
        return w, self._loss_history

    def _superstepper(self, k: int, with_valid: bool = False):
        """Memoized jitted fused K-step function for the stepwise
        driver (``set_superstep``) — built ONCE per (plugin pair,
        config, K, mesh) like ``_stepper``, so every superstep of a run
        (including the tail) reuses the one compiled scan program.
        Single device runs the plain scan; a 1-D data mesh runs the
        same scan under shard_map (``dp_shared_superstep_fn``)."""
        key = ("superstep", self.gradient, self.updater, self.config,
               int(k), self.mesh, with_valid)
        fn = self._run_cache.get(key)
        if fn is None:
            if self.mesh is None:
                fn = jax.jit(make_shared_batch_superstep(
                    self.gradient, self.updater, self.config, int(k)))
            else:
                from tpu_sgd.parallel.data_parallel import (
                    dp_shared_superstep_fn,
                )

                fn = dp_shared_superstep_fn(
                    self.gradient, self.updater, self.config, int(k),
                    self.mesh, with_valid)
            self._run_cache[key] = fn
        return fn

    def _resident_loop(self, k: int, cadence: int):
        """Memoized device-resident whole-run program
        (``set_residency``; ``optimize/resident_driver.py``) — one
        compiled while_loop per (plugin pair, config, K, C); repeated
        runs and resumes re-dispatch the same program."""
        key = ("resident", self.gradient, self.updater, self.config,
               int(k), int(cadence))
        loop = self._run_cache.get(key)
        if loop is None:
            from tpu_sgd.optimize.resident_driver import ResidentLoop

            step = make_step(self.gradient, self.updater, self.config)
            loop = ResidentLoop(
                lambda w, i, rv, X, y: step(w, X, y, i, rv, None),
                self.config, int(k), int(cadence))
            self._run_cache[key] = loop
        return loop

    def _stepper(self, with_valid: bool, sparse_shape=None):
        """Memoized jitted single-step function (mesh-aware; pass
        ``sparse_shape=(rows_local, d)`` when X arrives as sharded BCOO
        component tuples)."""
        # Key on the objects themselves (identity hash, strong ref): an
        # id()-based key could alias a new gradient/mesh to a stale compiled
        # fn after GC id reuse.
        key = ("step", self.gradient, self.updater, self.config,
               self.mesh, with_valid, sparse_shape)
        fn = self._run_cache.get(key)
        if fn is None:
            if self.mesh is None:
                fn = jax.jit(make_step(self.gradient, self.updater, self.config))
            elif sparse_shape is not None:
                from tpu_sgd.parallel.sparse_parallel import sparse_dp_step_fn

                fn = sparse_dp_step_fn(
                    self.gradient, self.updater, self.config, self.mesh,
                    sparse_shape[0], sparse_shape[1], with_valid,
                )
            else:
                from tpu_sgd.parallel.data_parallel import dp_step_fn

                fn = dp_step_fn(self.gradient, self.updater, self.config,
                                self.mesh, with_valid)
            self._run_cache[key] = fn
        return fn

    def _mesh_kind(self) -> str:
        from tpu_sgd.parallel.mesh import has_model_axis

        return "dp_mp" if has_model_axis(self.mesh) else "dp"

    def _runner(self, with_valid: bool):
        """Memoized jitted runner.

        Rebuilt only when the plugin pair, config, or mesh changes —
        repeated ``optimize`` calls (the streaming mode's per-micro-batch
        pattern, SURVEY.md §3.3) hit XLA's compile cache instead of
        retracing; measured ~3000x faster on repeat calls.
        """
        key = ("run", self.gradient, self.updater, self.config,
               self.mesh, with_valid)
        fn = self._run_cache.get(key)
        if fn is None:
            if self.mesh is not None:
                from tpu_sgd.parallel.data_parallel import dp_run_fn

                fn = dp_run_fn(self.gradient, self.updater, self.config,
                               self.mesh, with_valid)
            else:
                fn = jax.jit(make_run(self.gradient, self.updater, self.config))
            self._run_cache[key] = fn
        return fn


def run_mini_batch_sgd(
    data: Dataset,
    gradient: Gradient,
    updater: Updater,
    step_size: float,
    num_iterations: int,
    reg_param: float,
    mini_batch_fraction: float,
    initial_weights: Array,
    convergence_tol: float = 0.001,
    seed: int = 42,
    mesh=None,
    sampling: str = None,
    sufficient_stats: bool = False,
) -> Tuple[Array, "jnp.ndarray"]:
    """Functional entry point, signature-parity with the reference's
    ``object GradientDescent.runMiniBatchSGD`` (SURVEY.md §2 #2).
    ``mesh``, ``sampling`` and ``sufficient_stats`` are the TPU-side
    extensions; note ``sufficient_stats`` engages on sub-unit
    mini-batch fractions only with ``sampling="sliced"`` (see
    ``GradientDescent.set_sufficient_stats``).

    Returns ``(weights, loss_history)``.
    """
    opt = GradientDescent(
        gradient,
        updater,
        SGDConfig(
            step_size=step_size,
            num_iterations=num_iterations,
            reg_param=reg_param,
            mini_batch_fraction=mini_batch_fraction,
            convergence_tol=convergence_tol,
            seed=seed,
        ),
    )
    if mesh is not None:
        opt.set_mesh(mesh)
    if sampling is not None:
        opt.set_sampling(sampling)
    if sufficient_stats:
        opt.set_sufficient_stats(True)
    return opt.optimize_with_history(data, initial_weights)
