"""Mini-batch gradient descent: the TPU-native ``GradientDescent``.

Reference parity: [U] mllib/optimization/GradientDescent.scala (SURVEY.md §2
#2, §3.1).  The reference's per-iteration pattern —

    broadcast(weights) -> sample(frac, 42+i) -> treeAggregate(seqOp/combOp)
    -> grad /= miniBatchSize -> updater.compute -> convergence check

— is re-designed TPU-first rather than translated (SURVEY.md §7 design
stance):

  * The whole optimization runs as ONE compiled XLA program: a
    ``lax.while_loop`` whose body is the fused batched gradient step.  Spark
    pays per-iteration driver hops (broadcast setup, job scheduling, task
    serialization — SURVEY.md §3.1 "outer hot loop"); here there are zero
    host round-trips until the final result fetch.
  * ``sample(false, frac, 42 + i)`` becomes a per-example Bernoulli mask from
    ``fold_in(key, i)`` — distributional parity, normalized by the *realized*
    mini-batch count exactly as the reference divides by ``miniBatchSize``
    (SURVEY.md §7 hard parts, sampling-semantics parity).
  * ``treeAggregate`` + Torrent broadcast become ``lax.psum`` over the mesh
    axis (hardware ICI all-reduce) + deterministic replicated updates
    (SURVEY.md §3.5, §5.8).  Pass ``axis_name`` to get the sharded body;
    ``None`` gives the single-device body from the same code.
  * The loss-history contract is preserved: ``loss[t] = lossSum/miniBatchSize
    + regVal(prev iteration's weights)`` and the convergence rule is
    ``||w_t - w_{t-1}|| < tol * max(||w_t||, 1)`` checked from the second
    update on (SURVEY.md §5.5, §3.1).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient, LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater, Updater
from tpu_sgd.optimize.optimizer import Dataset, Optimizer

Array = jax.Array


def _make_mask(cfg: SGDConfig, key, i, n_local, valid, axis_name):
    """Per-iteration Bernoulli mini-batch mask (None = take everything)."""
    if cfg.mini_batch_fraction < 1.0:
        k = jax.random.fold_in(key, i)
        if axis_name is not None:
            # Independent sample stream per shard, like Spark's per-partition
            # sampler; deterministic in (seed, iteration, shard index).
            k = jax.random.fold_in(k, jax.lax.axis_index(axis_name))
        mask = jax.random.bernoulli(k, cfg.mini_batch_fraction, (n_local,))
        return mask if valid is None else mask & valid
    return valid


def make_step(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    axis_name: Optional[str] = None,
):
    """Build one SGD iteration as a pure function.

    ``step(weights, X, y, i, reg_val, valid) ->
    (new_weights, loss_i, new_reg_val, count)`` — the unit the streaming mode
    and the fused driver both build on.  ``loss_i`` already includes the
    previous iteration's ``reg_val`` per the reference's loss-history contract.
    """
    cfg = config
    key = jax.random.PRNGKey(cfg.seed)

    def step(weights, X, y, i, reg_val, valid=None):
        mask = _make_mask(cfg, key, i, X.shape[0], valid, axis_name)
        g, l, c = gradient.batch_sums(X, y, weights, mask)
        if axis_name is not None:
            g, l, c = jax.lax.psum((g, l, c), axis_name)
        has_batch = c > 0
        safe_c = jnp.maximum(c, 1.0)
        loss_i = l / safe_c + reg_val
        new_w, new_reg = updater.compute(
            weights, g / safe_c, cfg.step_size, i, cfg.reg_param
        )
        # Reference behavior on an empty sampled batch: warn, skip the update.
        new_w = jnp.where(has_batch, new_w, weights)
        new_reg = jnp.where(has_batch, new_reg, reg_val)
        return new_w, loss_i, new_reg, c

    return step


def make_run(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    axis_name: Optional[str] = None,
):
    """Build the full optimization loop as one traceable function.

    ``run(initial_weights, X, y, valid) -> (weights, loss_history, n_recorded)``
    where ``loss_history`` has static length ``config.num_iterations`` padded
    with NaN beyond ``n_recorded`` (the while_loop may exit early on the
    convergence tolerance).  Runs unchanged inside ``shard_map`` when
    ``axis_name`` is given.
    """
    cfg = config
    check_conv = cfg.convergence_tol > 0.0
    step = make_step(gradient, updater, cfg, axis_name)

    def run(initial_weights, X, y, valid=None):
        w0 = initial_weights
        # Initial regVal from a zero-gradient probe update, exactly as the
        # reference initializes it before the loop (SURVEY.md §5.5).
        _, reg_val0 = updater.compute(
            w0, jnp.zeros_like(w0), 0.0, jnp.asarray(1, jnp.int32), cfg.reg_param
        )
        losses0 = jnp.full((cfg.num_iterations,), jnp.nan, jnp.float32)

        def cond(carry):
            i, _, _, _, _, converged = carry
            return (i <= cfg.num_iterations) & jnp.logical_not(converged)

        def body(carry):
            i, w, reg_val, losses, n_rec, _ = carry
            new_w, loss_i, new_reg, c = step(w, X, y, i, reg_val, valid)
            has_batch = c > 0
            losses = jnp.where(
                has_batch, losses.at[n_rec].set(loss_i.astype(jnp.float32)), losses
            )
            n_rec = n_rec + has_batch.astype(n_rec.dtype)
            if check_conv:
                diff = jnp.linalg.norm(new_w - w)
                conv = (
                    has_batch
                    & (i > 1)
                    & (diff < cfg.convergence_tol * jnp.maximum(jnp.linalg.norm(new_w), 1.0))
                )
            else:
                conv = jnp.asarray(False)
            return (i + 1, new_w, new_reg, losses, n_rec, conv)

        carry = (
            jnp.asarray(1, jnp.int32),
            w0,
            reg_val0,
            losses0,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(False),
        )
        _, w, _, losses, n_rec, _ = jax.lax.while_loop(cond, body, carry)
        return w, losses, n_rec

    return run


class GradientDescent(Optimizer):
    """Drop-in mini-batch SGD optimizer (``TpuGradientDescent``).

    Fluent setters mirror the reference's builder API (SURVEY.md §5.6):
    ``set_step_size``, ``set_num_iterations``, ``set_reg_param``,
    ``set_mini_batch_fraction``, ``set_convergence_tol``.  Passing a
    ``jax.sharding.Mesh`` via ``set_mesh`` switches the same loop to the
    data-parallel shard_map body with ICI all-reduce.
    """

    def __init__(
        self,
        gradient: Gradient = None,
        updater: Updater = None,
        config: SGDConfig = None,
    ):
        self.gradient = gradient if gradient is not None else LeastSquaresGradient()
        self.updater = updater if updater is not None else SimpleUpdater()
        self.config = config if config is not None else SGDConfig()
        self.mesh = None
        self._loss_history = None
        self._run_cache = {}

    # -- fluent config (returns self, like the reference's setters) --------
    def set_gradient(self, g: Gradient):
        self.gradient = g
        return self

    def set_updater(self, u: Updater):
        self.updater = u
        return self

    def set_step_size(self, s: float):
        self.config = self.config.replace(step_size=float(s))
        return self

    def set_num_iterations(self, n: int):
        if n < 1:
            raise ValueError(f"num_iterations must be positive, got {n}")
        self.config = self.config.replace(num_iterations=int(n))
        return self

    def set_reg_param(self, r: float):
        self.config = self.config.replace(reg_param=float(r))
        return self

    def set_mini_batch_fraction(self, f: float):
        if not 0.0 < f <= 1.0:
            raise ValueError("mini_batch_fraction must be in (0, 1]")
        self.config = self.config.replace(mini_batch_fraction=float(f))
        return self

    def set_convergence_tol(self, t: float):
        if not 0.0 <= t <= 1.0:
            raise ValueError("convergence_tol must be in [0, 1]")
        self.config = self.config.replace(convergence_tol=float(t))
        return self

    def set_seed(self, s: int):
        self.config = self.config.replace(seed=int(s))
        return self

    def set_mesh(self, mesh):
        self.mesh = mesh
        return self

    # -- optimization ------------------------------------------------------
    @property
    def loss_history(self):
        """Stochastic loss history of the last ``optimize`` call (np array)."""
        return self._loss_history

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        w, losses = self.optimize_with_history(data, initial_weights)
        return w

    def optimize_with_history(self, data: Dataset, initial_weights: Array):
        import numpy as np

        X, y = data
        X = jnp.asarray(X)
        y = jnp.asarray(y)
        if not jnp.issubdtype(X.dtype, jnp.inexact):
            X = X.astype(jnp.float32)  # int/bool features (one-hot etc.)
        if not jnp.issubdtype(y.dtype, jnp.inexact):
            y = y.astype(jnp.float32)
        w0 = jnp.asarray(initial_weights, X.dtype)
        expect_dim = self.gradient.weight_dim(X.shape[1])
        if w0.shape[-1] != expect_dim:
            raise ValueError(
                f"initial_weights has length {w0.shape[-1]} but this gradient "
                f"needs {expect_dim} for {X.shape[1]}-feature data"
            )
        n = X.shape[0]
        if n == 0:
            self._loss_history = np.zeros((0,), np.float32)
            return w0, self._loss_history
        if n * self.config.mini_batch_fraction < 1:
            import warnings

            warnings.warn(
                "The miniBatchFraction is too small", RuntimeWarning, stacklevel=2
            )
        if self.mesh is not None:
            from tpu_sgd.parallel.data_parallel import shard_dataset

            Xd, yd, valid = shard_dataset(self.mesh, X, y)
            fn = self._runner(with_valid=valid is not None)
            if valid is not None:
                w, losses, n_rec = fn(w0, Xd, yd, valid)
            else:
                w, losses, n_rec = fn(w0, Xd, yd)
        else:
            w, losses, n_rec = self._runner(with_valid=False)(w0, X, y)
        n_rec = int(n_rec)
        self._loss_history = np.asarray(losses)[:n_rec]
        return w, self._loss_history

    def _runner(self, with_valid: bool):
        """Memoized jitted runner.

        Rebuilt only when the plugin pair, config, or mesh changes —
        repeated ``optimize`` calls (the streaming mode's per-micro-batch
        pattern, SURVEY.md §3.3) hit XLA's compile cache instead of
        retracing; measured ~3000x faster on repeat calls.
        """
        key = (id(self.gradient), id(self.updater), self.config,
               id(self.mesh), with_valid)
        fn = self._run_cache.get(key)
        if fn is None:
            if self.mesh is not None:
                from tpu_sgd.parallel.data_parallel import dp_run_fn

                fn = dp_run_fn(self.gradient, self.updater, self.config,
                               self.mesh, with_valid)
            else:
                fn = jax.jit(make_run(self.gradient, self.updater, self.config))
            self._run_cache[key] = fn
        return fn


def run_mini_batch_sgd(
    data: Dataset,
    gradient: Gradient,
    updater: Updater,
    step_size: float,
    num_iterations: int,
    reg_param: float,
    mini_batch_fraction: float,
    initial_weights: Array,
    convergence_tol: float = 0.001,
    seed: int = 42,
    mesh=None,
) -> Tuple[Array, "jnp.ndarray"]:
    """Functional entry point, signature-parity with the reference's
    ``object GradientDescent.runMiniBatchSGD`` (SURVEY.md §2 #2).

    Returns ``(weights, loss_history)``.
    """
    opt = GradientDescent(
        gradient,
        updater,
        SGDConfig(
            step_size=step_size,
            num_iterations=num_iterations,
            reg_param=reg_param,
            mini_batch_fraction=mini_batch_fraction,
            convergence_tol=convergence_tol,
            seed=seed,
        ),
    )
    if mesh is not None:
        opt.set_mesh(mesh)
    return opt.optimize_with_history(data, initial_weights)
