"""Independent oracles for the workload configs' pass criteria.

BASELINE.md's table requires each config to "match oracle loss" (VERDICT r1
missing #5): config 1/4's least-squares objective has an EXACT minimizer via
the in-repo :class:`NormalEquations` solver, config 2's logistic+L2 objective
is smooth and strongly convex so a tight-tolerance LBFGS run converges to the
optimum to far more digits than the 1% criterion, and config 3's hinge+L1
objective gets a tight OWL-QN run.  ``full_objective`` evaluates the exact
objective each optimizer family minimizes (mean loss + its reg term), so the
gap ``(L(w) - L(w*)) / L(w*)`` is well-defined and comparable.

Convergence caveat recorded here because it is a *mathematical* property, not
an implementation gap: plain subgradient descent on the nonsmooth hinge
converges at O(1/sqrt(t)), so config 3's SGD cannot reach a 1% objective gap
in any reasonable iteration budget — the reference's ``SVMWithSGD`` has the
identical limitation ([U] mllib/optimization/Gradient.scala HingeGradient is
the same subgradient).  Config 3's criterion is therefore a documented looser
objective bound plus accuracy parity with the oracle's decision rule.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_sgd.ops.gradients import (
    Gradient,
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)


def full_objective(
    gradient: Gradient, X, y, weights, reg_param: float = 0.0,
    reg: str = "none",
) -> float:
    """Exact full-dataset objective ``mean loss + reg term`` for ``weights``.

    ``reg``: 'none', 'l2' (0.5·λ‖w‖², the SquaredL2Updater objective) or
    'l1' (λ‖w‖₁, the L1Updater/OWLQN objective)."""
    w = jnp.asarray(weights)
    _, loss_sum, count = gradient.batch_sums(X, jnp.asarray(y), w)
    val = float(loss_sum) / float(count)
    if reg == "l2":
        val += 0.5 * reg_param * float(jnp.sum(w * w))
    elif reg == "l1":
        val += reg_param * float(jnp.sum(jnp.abs(w)))
    elif reg != "none":
        raise ValueError(f"unknown reg kind {reg!r}")
    return val


def least_squares_oracle(X, y):
    """Exact least-squares minimizer via the normal equations (config 1/4)."""
    from tpu_sgd.optimize.normal import NormalEquations

    X = jnp.asarray(X)
    return NormalEquations().optimize(
        (X, y), jnp.zeros((X.shape[1],), jnp.float32)
    )


def logistic_l2_oracle(X, y, reg_param: float, max_iterations: int = 400):
    """Near-exact logistic+L2 minimizer: tight-tolerance LBFGS (config 2)."""
    from tpu_sgd.ops.updaters import SquaredL2Updater
    from tpu_sgd.optimize.lbfgs import LBFGS

    X = jnp.asarray(X)
    opt = LBFGS(
        LogisticGradient(), SquaredL2Updater(), reg_param=reg_param,
        convergence_tol=1e-12, max_num_iterations=max_iterations,
    )
    return opt.optimize((X, y), jnp.zeros((X.shape[1],), jnp.float32))


def hinge_l1_oracle(X, y, reg_param: float, max_iterations: int = 500):
    """Tight OWL-QN run on hinge+L1 (config 3's reference point)."""
    from tpu_sgd.optimize.owlqn import OWLQN

    X = jnp.asarray(X)
    opt = OWLQN(
        HingeGradient(), reg_param=reg_param, convergence_tol=1e-12,
        max_num_iterations=max_iterations,
    )
    return opt.optimize((X, y), jnp.zeros((X.shape[1],), jnp.float32))


def objective_gap(
    gradient: Gradient, X, y, weights, oracle_weights,
    reg_param: float = 0.0, reg: str = "none",
):
    """Relative optimality gap ``(L(w) - L(w*)) / max(L(w*), eps)`` plus the
    two objective values, for reporting."""
    L = full_objective(gradient, X, y, weights, reg_param, reg)
    L_star = full_objective(gradient, X, y, oracle_weights, reg_param, reg)
    return (L - L_star) / max(abs(L_star), 1e-12), L, L_star


__all__ = [
    "full_objective",
    "least_squares_oracle",
    "logistic_l2_oracle",
    "hinge_l1_oracle",
    "objective_gap",
]
