"""Chunked-gather driver for block-ALIGNED sufficient-statistics SGD.

Round-4's decomposition experiment (``scripts/gram_scan_experiment.py``)
showed the 0.024 ms aligned-gram iteration spends roughly half its time
OUTSIDE the two (d, d) prefix reads — per-iteration loop bookkeeping and
dispatch.  This driver amortizes that: an outer ``while_loop`` advances
``chunk_iters`` iterations at a time, gathering ALL of the chunk's window
endpoints from the prefix stacks in four bulk ``jnp.take`` ops (2·K (d, d)
rows — the same bytes the per-iteration driver reads, in K-fold larger
transfers), then an inner ``fori_loop`` runs the K updates from the
gathered registers.

The CONTRACT IS UNCHANGED from ``make_run`` (``optimize/
gradient_descent.py``): the same per-iteration ``fold_in(seed, i)``
window stream, per-iteration loss history including the previous
iteration's reg value, realized-count normalization, and per-iteration
weight-delta convergence — a converged run masks the chunk's remaining
updates to no-ops and exits at the chunk boundary, recording exactly as
many losses as the per-iteration driver would.  Applies to block-aligned
windows only (virtual statistics, or resident stats in aligned mode)
with sliced sampling — exactly the regime the headline measures.

HARDWARE VERDICT (2026-08-01, ``GRAM_SCAN_EXPERIMENT.json``): on the
TPU v5 lite the gather LOSES — 0.556 ms/iter (trajectory-clean) vs
0.0259 ms/iter for the per-iteration driver, because ``jnp.take`` of
K prefix pairs materializes 2·K (d, d) blocks through HBM while the
per-iteration driver's two dynamic slices stay fused; the bookkeeping
it amortizes measured only ~0.0036 ms/iter (14%).  The driver stays
OPT-IN via ``GradientDescent.set_gram_options(chunk_iters=K)`` — it
still wins ~1.4–2.6× on CPU hosts — and the planner default remains
the per-iteration contract (see BASELINE.md, round-5 decision).

FOLLOW-UP CLOSED (PR 5): the weights_agree-gated product_chunked vs
full_contract comparison the JSON asked for is now computed by
``scripts/gram_scan_experiment.py`` itself (``product_chunked_wins`` +
``verdict`` fields) and the recorded verdict keeps the per-iteration
default.  The dispatch-tax half of the original motivation — the
~44–65 ms fixed cost plus per-iteration host slop — is attacked from
the other side by the superstep executor
(``GradientDescent.set_superstep``; README "Fused stepping"), which
fuses the HOST-dispatched paths where that tax actually dominates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gram import (aligned_window_blocks, aligned_window_k1,
                              aligned_window_terms)
from tpu_sgd.ops.updaters import Updater


def make_chunked_gram_run(
    updater: Updater,
    config: SGDConfig,
    *,
    n: int,
    block_rows: int,
    chunk_iters: int = 16,
):
    """Build the chunked aligned-gram loop as one traceable function.

    ``run(initial_weights, data: GramData, y) -> (weights, loss_history,
    n_recorded)`` — the ``make_run`` return contract.  ``y`` is accepted
    for signature parity and never read (the statistics carry it).
    """
    cfg = config
    K = int(chunk_iters)
    if K < 1:
        raise ValueError(f"chunk_iters must be positive, got {chunk_iters}")
    key = jax.random.PRNGKey(cfg.seed)
    m = max(1, round(cfg.mini_batch_fraction * n))
    B = int(block_rows)
    nbf = n // B
    mb = aligned_window_blocks(m, B, nbf)
    count = float(mb * B)
    check_conv = cfg.convergence_tol > 0.0
    num_iters = cfg.num_iterations

    def k1_of(i):
        # EXACTLY the per-iteration driver's sliced-window stream:
        # fold_in(key, i) -> randint start (make_step's draw) -> the
        # SHARED aligned clamp (ops/gram.py aligned_window_k1)
        k = jax.random.fold_in(key, i)
        start = jax.random.randint(k, (), 0, max(1, n - m + 1))
        return aligned_window_k1(start, n, m, B, nbf, mb).astype(jnp.int32)

    def run(initial_weights, data, y, valid=None):
        del y, valid  # statistics-only execution
        PG, Pb, Pyy = data.PG, data.Pb, data.Pyy
        sd = PG.dtype
        w0 = initial_weights
        _, reg_val0 = updater.compute(
            w0, jnp.zeros_like(w0), 0.0, jnp.asarray(1, jnp.int32),
            cfg.reg_param,
        )
        losses0 = jnp.full((num_iters,), jnp.nan, jnp.float32)

        def cond(carry):
            base, _, _, _, _, converged = carry
            return (base <= num_iters) & jnp.logical_not(converged)

        def chunk_body(carry):
            base, w, reg_val, losses, n_rec, conv = carry
            idx = base + jnp.arange(K, dtype=jnp.int32)
            k1s = jax.vmap(k1_of)(idx)
            k2s = k1s + mb
            # the chunk's window stats in six bulk gathers (the same
            # bytes as K iterations of per-row dynamic slices); indices
            # are provably in [0, nbf] against (nbf+1)-row stacks, so
            # mode="clip" (XLA's native clamped gather) skips the
            # default fill-mode bounds selects on the hot path
            take = partial(jnp.take, axis=0, mode="clip")
            Gd = take(PG, k2s) - take(PG, k1s)
            bd = take(Pb, k2s) - take(Pb, k1s)
            yyd = take(Pyy, k2s) - take(Pyy, k1s)

            def inner(t, ic):
                w, reg_val, losses, n_rec, conv = ic
                i = idx[t]
                active = jnp.logical_not(conv) & (i <= num_iters)
                g_sum, loss_sum = aligned_window_terms(
                    Gd[t], bd[t], yyd[t], w.astype(sd))
                loss_i = (loss_sum / count).astype(jnp.float32) + reg_val
                g_mean = (g_sum / count).astype(w.dtype)
                new_w, new_reg = updater.compute(
                    w, g_mean, cfg.step_size, i, cfg.reg_param
                )
                losses = jnp.where(
                    active, losses.at[n_rec].set(loss_i), losses
                )
                n_rec = n_rec + active.astype(n_rec.dtype)
                if check_conv:
                    diff = jnp.sqrt(jnp.sum((new_w - w) ** 2))
                    w_norm = jnp.sqrt(jnp.sum(new_w ** 2))
                    conv = conv | (
                        active & (i > 1)
                        & (diff < cfg.convergence_tol
                           * jnp.maximum(w_norm, 1.0))
                    )
                w = jnp.where(active, new_w, w)
                reg_val = jnp.where(active, new_reg, reg_val)
                return (w, reg_val, losses, n_rec, conv)

            w, reg_val, losses, n_rec, conv = jax.lax.fori_loop(
                0, K, inner, (w, reg_val, losses, n_rec, conv)
            )
            return (base + K, w, reg_val, losses, n_rec, conv)

        carry = (
            jnp.asarray(1, jnp.int32),
            w0,
            reg_val0,
            losses0,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(False),
        )
        _, w, _, losses, n_rec, _ = jax.lax.while_loop(
            cond, chunk_body, carry
        )
        return w, losses, n_rec

    return run
