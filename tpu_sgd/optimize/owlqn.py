"""OWL-QN: orthant-wise limited-memory quasi-Newton for L1 objectives.

Reference parity: the reference's own ``LBFGS`` docs steer L1 users to
OWL-QN, and upstream Spark ships it (Breeze ``OWLQN``) behind the exact
same ``Optimizer.optimize`` boundary for elastic-net logistic regression
([U] mllib/optimization/LBFGS.scala note; SURVEY.md §2 #18).  This is that
algorithm, TPU-shaped like the sibling ``LBFGS``: the smooth-part cost is
one fused batched matvec pass on the MXU (the shared ``Gradient.batch_sums``
kernel), the two-loop recursion runs on-device, and only the tiny
data-dependent line-search control flow is host-side.

Objective: ``F(w) = (1/n)·Σ loss(w; x, y) + reg_param·‖w‖₁`` — matching
``L1Updater``'s regularization semantics (SURVEY.md §2 #4).

Algorithm (Andrew & Gao 2007):
  1. pseudo-gradient ⋄F of the non-smooth objective,
  2. LBFGS two-loop direction from SMOOTH-part curvature pairs,
     projected onto the pseudo-gradient's descent orthant,
  3. backtracking line search over orthant-projected trial points
     ``π(w + t·d; ξ)`` with ξ the chosen orthant signs,
  4. curvature pairs (s, y) from the smooth gradient only.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.optimize.lbfgs import (
    _coerce_inputs,
    _push_correction,
    _two_loop,
)
from tpu_sgd.optimize.optimizer import Dataset, Optimizer

Array = jax.Array


def _pseudo_gradient(w: Array, g: Array, reg: float) -> Array:
    """⋄F: the steepest-descent direction's negative for f + reg·‖·‖₁."""
    right = g + reg  # derivative approaching from w_i -> 0+
    left = g - reg   # derivative approaching from w_i -> 0-
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, at_zero))


def _project_orthant(v: Array, xi: Array) -> Array:
    """Zero components of ``v`` whose sign disagrees with orthant ``xi``."""
    return jnp.where(jnp.sign(v) == xi, v, 0.0)


class OWLQN(Optimizer):
    """Orthant-wise LBFGS for ``smooth loss + reg_param * ||w||_1``.

    ``reg_param=0`` degenerates to plain LBFGS on the smooth loss.  Shares
    the fused cost kernel and the on-device two-loop with :class:`LBFGS`.
    """

    def __init__(
        self,
        gradient: Gradient = None,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_num_iterations: int = 100,
        reg_param: float = 0.0,
    ):
        from tpu_sgd.ops.gradients import LeastSquaresGradient

        self.gradient = gradient if gradient is not None else LeastSquaresGradient()
        self.num_corrections = int(num_corrections)
        self.convergence_tol = float(convergence_tol)
        self.max_num_iterations = int(max_num_iterations)
        self.reg_param = float(reg_param)
        self._loss_history = None

    # fluent setters, same shape as the siblings
    def set_gradient(self, g):
        self.gradient = g
        return self

    def set_num_corrections(self, m: int):
        self.num_corrections = int(m)
        return self

    def set_convergence_tol(self, t: float):
        self.convergence_tol = float(t)
        return self

    def set_max_num_iterations(self, n: int):
        self.max_num_iterations = int(n)
        return self

    def set_reg_param(self, r: float):
        self.reg_param = float(r)
        return self

    @property
    def loss_history(self):
        return self._loss_history

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        w, _ = self.optimize_with_history(data, initial_weights)
        return w

    def optimize_with_history(self, data: Dataset, initial_weights: Array):
        import numpy as np

        X, y = data
        X, y, w = _coerce_inputs(X, y, initial_weights)
        n = X.shape[0]
        if n == 0:
            self._loss_history = np.zeros((0,), np.float32)
            return w, self._loss_history
        gradient = self.gradient
        reg = self.reg_param

        @jax.jit
        def smooth_cost(w):
            g_sum, l_sum, c = gradient.batch_sums(X, y, w)
            return l_sum / c, g_sum / c

        if hasattr(gradient, "pointwise"):
            # Loss-only evaluation for line-search trials: skips the
            # coeff^T @ X matvec (half the HBM traffic); gradient is
            # computed once, on the accepted point — same trick as LBFGS.
            @jax.jit
            def full_loss(w):
                _, losses = gradient.pointwise(X @ w, y)
                return (
                    jnp.sum(losses) / X.shape[0] + reg * jnp.sum(jnp.abs(w))
                )

        else:  # matrix-weight gradients have no pointwise rule
            @jax.jit
            def full_loss(w):
                _, l_sum, c = gradient.batch_sums(X, y, w)
                return l_sum / c + reg * jnp.sum(jnp.abs(w))

        m = self.num_corrections
        d_dim = w.shape[0]
        s_stack = jnp.zeros((m, d_dim), w.dtype)
        y_stack = jnp.zeros((m, d_dim), w.dtype)
        rho = jnp.zeros((m,), w.dtype)
        k = 0

        f_s, g = smooth_cost(w)
        F = float(f_s) + reg * float(jnp.sum(jnp.abs(w)))
        losses: List[float] = [F]
        for _ in range(self.max_num_iterations):
            pg = _pseudo_gradient(w, g, reg)
            direction = -_two_loop(pg, s_stack, y_stack, rho, jnp.asarray(k))
            if reg > 0:
                # restrict to the descent orthant indicated by -pg
                direction = _project_orthant(direction, jnp.sign(-pg))
            dir_deriv = float(jnp.dot(pg, direction))
            if dir_deriv >= 0:
                direction = -pg
                dir_deriv = float(jnp.dot(pg, direction))
                if dir_deriv >= 0:  # pg == 0: stationary point
                    break
            # orthant for the trial points: sign(w), or sign(-pg) at zeros
            xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
            t = 1.0
            accepted = False
            for _ls in range(30):
                w_new = w + t * direction
                if reg > 0:
                    w_new = _project_orthant(w_new, xi)
                F_new = float(full_loss(w_new))
                if F_new <= F + 1e-4 * t * dir_deriv:
                    accepted = True
                    break
                t *= 0.5
            if not accepted:
                break
            _, g_new = smooth_cost(w_new)
            s = w_new - w
            yv = g_new - g  # smooth-part curvature only
            sy = float(jnp.dot(s, yv))
            if sy > 1e-10:
                s_stack, y_stack, rho, k = _push_correction(
                    s_stack, y_stack, rho, k, m, s, yv, sy
                )
            w, g = w_new, g_new
            F = F_new
            losses.append(F)
            rel = abs(losses[-2] - losses[-1]) / max(
                abs(losses[-2]), abs(losses[-1]), 1.0
            )
            if rel < self.convergence_tol:
                break

        self._loss_history = np.asarray(losses, np.float32)
        return w, self._loss_history
