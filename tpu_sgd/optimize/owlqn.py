"""OWL-QN: orthant-wise limited-memory quasi-Newton for L1 objectives.

Reference parity: the reference's own ``LBFGS`` docs steer L1 users to
OWL-QN, and upstream Spark ships it (Breeze ``OWLQN``) behind the exact
same ``Optimizer.optimize`` boundary for elastic-net logistic regression
([U] mllib/optimization/LBFGS.scala note; SURVEY.md §2 #18).  This is that
algorithm, TPU-shaped like the sibling ``LBFGS``: the smooth-part cost is
one fused batched matvec pass on the MXU (the shared ``Gradient.batch_sums``
kernel), the two-loop recursion runs on-device, and only the tiny
data-dependent line-search control flow is host-side.

Objective: ``F(w) = (1/n)·Σ loss(w; x, y) + reg_param·‖w‖₁`` — matching
``L1Updater``'s regularization semantics (SURVEY.md §2 #4).

Algorithm (Andrew & Gao 2007):
  1. pseudo-gradient ⋄F of the non-smooth objective,
  2. LBFGS two-loop direction from SMOOTH-part curvature pairs,
     projected onto the pseudo-gradient's descent orthant,
  3. backtracking line search over orthant-projected trial points
     ``π(w + t·d; ξ)`` with ξ the chosen orthant signs,
  4. curvature pairs (s, y) from the smooth gradient only.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.optimize.lbfgs import (
    LBFGS,
    _build_cost,
    _build_loss_only,
    _build_loss_sweep,
    _coerce_inputs,
    _push_correction,
    _shard_for_mesh,
    _two_loop,
    _warn_sequential_line_search,
)
from tpu_sgd.optimize.optimizer import Dataset

Array = jax.Array


def _pseudo_gradient(w: Array, g: Array, reg: Array) -> Array:
    """⋄F: the steepest-descent direction's negative for f + ‖reg·w‖₁.

    ``reg`` is a per-coordinate penalty vector (0 entries are unpenalized —
    the intercept column, matching upstream's zero L1 strength for it)."""
    right = g + reg  # derivative approaching from w_i -> 0+
    left = g - reg   # derivative approaching from w_i -> 0-
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, at_zero))


def _project_orthant(v: Array, xi: Array, penalized: Array) -> Array:
    """Zero PENALIZED components of ``v`` whose sign disagrees with orthant
    ``xi``; unpenalized coordinates move freely (their objective is
    smooth)."""
    return jnp.where(jnp.logical_and(penalized, jnp.sign(v) != xi), 0.0, v)


class OWLQN(LBFGS):
    """Orthant-wise LBFGS for ``smooth loss + reg_param * ||w||_1``.

    ``reg_param=0`` degenerates to plain LBFGS on the smooth loss.
    Subclasses :class:`LBFGS` for the shared surface (fluent setters,
    ``loss_history``, ``optimize`` wrapper, fused cost kernel, two-loop);
    only the orthant-wise optimization loop is its own.

    ``penalize_intercept=False`` (used by the model wrappers) exempts the
    LAST weight coordinate — the GLM harness's appended bias column — from
    the L1 penalty, matching upstream's zero intercept L1 strength.
    """

    #: deeper backtracking than plain LBFGS: orthant projection can zero
    #: out most of a large step, so more halvings are worth trying
    _LS_TRIALS = 30

    def __init__(
        self,
        gradient: Gradient = None,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_num_iterations: int = 100,
        reg_param: float = 0.0,
        penalize_intercept: bool = True,
    ):
        super().__init__(
            gradient=gradient,
            updater=None,
            num_corrections=num_corrections,
            convergence_tol=convergence_tol,
            max_num_iterations=max_num_iterations,
            reg_param=reg_param,
        )
        self.penalize_intercept = bool(penalize_intercept)

    def set_updater(self, u):  # pragma: no cover - guardrail
        raise AttributeError(
            "OWLQN has no Updater axis: the L1 penalty is part of the "
            "objective (reg_param); use LBFGS for updater-style reg"
        )

    def set_penalize_intercept(self, flag: bool):
        self.penalize_intercept = bool(flag)
        return self

    def _reg_vector(self, w):
        """Per-coordinate L1 strengths; the intercept exemption assumes
        VECTOR weights (the GLM bias rides as the LAST coordinate) — a
        flattened multinomial matrix has one intercept per class row, so
        exempting only the last coordinate would silently mis-penalize
        K-2 intercepts."""
        reg = jnp.full(w.shape, self.reg_param, w.dtype)
        if not self.penalize_intercept:
            if getattr(self.gradient, "num_classes", 2) > 2:
                raise NotImplementedError(
                    "penalize_intercept=False assumes vector weights "
                    "(one bias as the last coordinate); multinomial "
                    "weights carry one intercept per class row — "
                    "penalize the intercepts or use LBFGS with "
                    "SquaredL2Updater"
                )
            reg = reg.at[-1].set(0.0)
        return reg

    def _host_streamed_evaluators(self, X, y, initial_weights):
        """OWL-QN shape of the host-streamed chunked CostFun (see
        ``LBFGS._host_streamed_evaluators``): ``(w0, reg, smooth_cost1,
        sweep1, full_loss1)`` where the sweep/loss closures return the
        FULL objective (smooth + L1) and the smooth cost returns the
        smooth part only — exactly what :meth:`_owlqn_loop` consumes."""
        import numpy as np

        if int(np.shape(X)[0]) == 0 and not self._mesh_spans_processes():
            # see LBFGS._host_streamed_evaluators: a multihost process
            # with zero local rows must still join the collectives
            return None
        scf = self._host_streamed_costfun(X, y)
        w = jnp.asarray(initial_weights)
        if not jnp.issubdtype(w.dtype, jnp.inexact):
            w = w.astype(jnp.float32)
        reg = self._reg_vector(w)
        l1_value = lambda wv: jnp.sum(reg * jnp.abs(wv))

        def _build_finishes():
            @jax.jit
            def _finish_smooth(gs, ls, c):
                return ls / c, gs / c

            @jax.jit
            def _finish_sweep(ls, c, W):
                return ls / c + jax.vmap(l1_value)(W)

            @jax.jit
            def _finish_loss(ls, c, wv):
                return ls / c + l1_value(wv)

            return _finish_smooth, _finish_sweep, _finish_loss

        # the l1 closure bakes reg as a jit constant — key on its inputs
        _finish_smooth, _finish_sweep, _finish_loss = self._cached_eval(
            ("owlqn_stream_finish", float(self.reg_param),
             bool(self.penalize_intercept),
             tuple(reg.shape), str(reg.dtype)),
            _build_finishes)

        def smooth_cost1(wv):
            return _finish_smooth(*scf.cost_sums(wv))

        if hasattr(self.gradient, "loss_sweep"):
            def sweep1(W):
                return _finish_sweep(*scf.sweep_sums(W), W)

            return w, reg, smooth_cost1, sweep1, None
        _warn_sequential_line_search(self.gradient, self._LS_TRIALS)

        def full_loss1(wv):
            return _finish_loss(*scf.loss_sums(wv), wv)

        return w, reg, smooth_cost1, None, full_loss1

    def optimize_with_history(self, data: Dataset, initial_weights: Array):
        import numpy as np

        X, y = data
        streamed = self._maybe_streamed_reentry(X, y, initial_weights)
        if streamed is not None:
            return streamed
        if self.host_streaming:
            # BEFORE _coerce_inputs: jnp.asarray would commit the
            # beyond-HBM matrix to the device
            ev = self._host_streamed_evaluators(X, y, initial_weights)
            if ev is not None:
                return self._owlqn_loop(*ev)
        X, y, w = _coerce_inputs(X, y, initial_weights,
                                 defer_commit=self.mesh is not None)
        n = X.shape[0]
        if n == 0:
            self._loss_history = np.zeros((0,), np.float32)
            return w, self._loss_history
        from tpu_sgd.ops.gram import GramData as _GramData

        was_gram_input = isinstance(X, _GramData)
        gradient, X = self._substitute_gram(self.gradient, X, y)
        reg = self._reg_vector(w)  # per-coordinate, broadcast through

        mesh = self.mesh
        if isinstance(X, _GramData) and not was_gram_input:
            # internally substituted statistics are replicated: run
            # unmeshed from exact totals (see LBFGS.optimize_with_history)
            mesh = None
            if not isinstance(y, jnp.ndarray):
                # the statistics carry Xᵀy / yᵀy — y is never read; do
                # not re-upload the host array per evaluation
                y = jnp.zeros((0,), jnp.float32)
        valid = None
        sparse_shape = None
        if mesh is not None:
            X, y, valid, sparse_shape = _shard_for_mesh(mesh, X, y)
        with_valid = valid is not None
        data_args = (X, y, valid) if with_valid else (X, y)

        l1_value = lambda wv: jnp.sum(reg * jnp.abs(wv))
        zero = lambda wv: jnp.zeros((), wv.dtype)
        zero_grad = jnp.zeros_like
        # cache keys: the l1 closures BAKE the reg vector as a jit
        # constant, so anything that changes its contents (reg_param,
        # intercept exemption, weight shape/dtype) must key the entry
        base_key = (gradient, mesh, with_valid, sparse_shape)
        l1_key = base_key + (float(self.reg_param),
                             bool(self.penalize_intercept),
                             tuple(reg.shape), str(reg.dtype))
        # smooth cost (mesh-aware psum inside); the L1 part is added where
        # the algorithm needs the FULL objective
        _smooth = self._cached_eval(
            ("owlqn_smooth",) + base_key,
            lambda: _build_cost(gradient, zero, zero_grad, mesh,
                                with_valid, sparse_shape))

        def smooth_cost1(wv):
            return _smooth(wv, *data_args)

        if hasattr(gradient, "loss_sweep"):
            sweep = self._cached_eval(
                ("owlqn_sweep",) + l1_key,
                lambda: _build_loss_sweep(gradient, l1_value, mesh,
                                          with_valid, sparse_shape))

            def sweep1(W):
                return sweep(W, *data_args)

            return self._owlqn_loop(w, reg, smooth_cost1, sweep1, None)
        # exotic gradients without a sweep rule
        _warn_sequential_line_search(gradient, self._LS_TRIALS)
        # loss-only compile: XLA drops the gradient matmul per trial
        _loss = self._cached_eval(
            ("owlqn_loss",) + l1_key,
            lambda: _build_loss_only(gradient, l1_value, mesh,
                                     with_valid, sparse_shape))

        def full_loss1(wv):
            return _loss(wv, *data_args)

        return self._owlqn_loop(w, reg, smooth_cost1, None, full_loss1)

    def _owlqn_loop(self, w, reg, smooth_cost1, sweep1, full_loss1):
        """The orthant-wise iteration loop over abstract FULL-BATCH
        evaluators: ``smooth_cost1(w) -> (f_smooth, g_smooth)``,
        ``sweep1(W_trials) -> (T,)`` FULL objectives (None for gradients
        without a sweep rule), ``full_loss1(w) -> F`` (the sequential
        fallback).  Device-resident and host-streamed CostFun paths both
        drive this loop."""
        import numpy as np

        penalized = reg > 0
        any_penalty = self.reg_param > 0
        n_ls = self._LS_TRIALS  # inherited ladder-length knob (see LBFGS)
        ladder = np.asarray(0.5 ** np.arange(n_ls), np.float32)
        swept = sweep1 is not None
        if swept:
            # Whole orthant-projected backtracking ladder in ONE fused
            # multi-weight pass (X read once, one host sync) — same sweep
            # machinery as LBFGS, plus the per-trial predicted decrease
            # pg . (w_trial - w) the Armijo test needs.
            ladder_j = jnp.asarray(ladder)

            @jax.jit
            def make_trials(wv, direction, xi, pg):
                W = wv[None, :] + ladder_j[:, None] * direction[None, :]
                if any_penalty:
                    W = jax.vmap(
                        lambda v: _project_orthant(v, xi, penalized)
                    )(W)
                preds = (W - wv[None, :]) @ pg
                return W, preds

        m = self.num_corrections
        d_dim = w.shape[0]
        s_stack = jnp.zeros((m, d_dim), w.dtype)
        y_stack = jnp.zeros((m, d_dim), w.dtype)
        rho = jnp.zeros((m,), w.dtype)
        k = 0

        f_s, g = smooth_cost1(w)
        F = float(f_s) + float(jnp.sum(reg * jnp.abs(w)))
        losses: List[float] = [F]
        for _ in range(self.max_num_iterations):
            pg = _pseudo_gradient(w, g, reg)
            direction = -_two_loop(pg, s_stack, y_stack, rho, jnp.asarray(k))
            if any_penalty:
                # restrict to the descent orthant indicated by -pg
                direction = _project_orthant(direction, jnp.sign(-pg), penalized)
            dir_deriv = float(jnp.dot(pg, direction))
            if dir_deriv >= 0:
                direction = -pg
                dir_deriv = float(jnp.dot(pg, direction))
                if dir_deriv >= 0:  # pg == 0: stationary point
                    break
            # orthant for the trial points: sign(w), or sign(-pg) at zeros
            xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
            # Armijo on the PROJECTED step (Andrew & Gao): predicted
            # decrease is pg . (w_trial - w), not t * pg . d — the
            # projection may have removed part of the movement, and
            # t * dir_deriv would then over-predict decrease and reject
            # every halving.
            if swept:
                W_trials, preds = make_trials(w, direction, xi, pg)
                # graftlint: disable=host-sync -- swept line search: ONE bulk fetch of all trial objectives per outer iteration (the host Armijo decision), not a per-trial sync
                F_trials = np.asarray(sweep1(W_trials))
                # graftlint: disable=host-sync -- swept line search: the matching one-per-outer-iteration fetch of the predicted decreases
                preds_h = np.asarray(preds)
                ok = (F_trials <= F + 1e-4 * preds_h) & (preds_h < 0)
                j = int(np.argmax(ok)) if ok.any() else -1
                accepted = j >= 0
                if accepted:
                    w_new = W_trials[j]
                    F_new = float(F_trials[j])
            else:
                t = 1.0
                accepted = False
                for _ls in range(n_ls):
                    w_new = w + t * direction
                    if any_penalty:
                        w_new = _project_orthant(w_new, xi, penalized)
                    F_new = float(full_loss1(w_new))
                    pred = float(jnp.dot(pg, w_new - w))
                    if F_new <= F + 1e-4 * pred and pred < 0:
                        accepted = True
                        break
                    t *= 0.5
            if not accepted:
                break
            _, g_new = smooth_cost1(w_new)
            s = w_new - w
            yv = g_new - g  # smooth-part curvature only
            sy = float(jnp.dot(s, yv))
            if sy > 1e-10:
                s_stack, y_stack, rho, k = _push_correction(
                    s_stack, y_stack, rho, k, m, s, yv, sy
                )
            w, g = w_new, g_new
            F = F_new
            losses.append(F)
            rel = abs(losses[-2] - losses[-1]) / max(
                abs(losses[-2]), abs(losses[-1]), 1.0
            )
            if rel < self.convergence_tol:
                break

        self._loss_history = np.asarray(losses, np.float32)
        return w, self._loss_history
