"""The Optimizer plugin boundary.

Reference parity: [U] mllib/optimization/Optimizer.scala (SURVEY.md §2 #1,
§1 L4): ``trait Optimizer { def optimize(data, initialWeights): Vector }`` is
the boundary the TPU backend slots behind (BASELINE.json:5).  Here ``data`` is
a ``(X, y)`` pair of arrays (the dense-resident analogue of
``RDD[(label, features)]``) and weights are 1-D jax arrays.
"""

from __future__ import annotations

from typing import Tuple

import jax

Array = jax.Array
Dataset = Tuple[Array, Array]  # (X: (n, d), y: (n,))


class Optimizer:
    """Anything that maps ``(data, initial_weights) -> weights``."""

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        raise NotImplementedError
