from tpu_sgd.optimize.optimizer import Optimizer
from tpu_sgd.optimize.gradient_descent import (
    GradientDescent,
    make_run,
    make_step,
    run_mini_batch_sgd,
)
from tpu_sgd.optimize.lbfgs import LBFGS, run_lbfgs
from tpu_sgd.optimize.normal import NormalEquations
from tpu_sgd.optimize.owlqn import OWLQN

__all__ = [
    "Optimizer",
    "GradientDescent",
    "LBFGS",
    "NormalEquations",
    "OWLQN",
    "make_run",
    "make_step",
    "run_mini_batch_sgd",
    "run_lbfgs",
]
