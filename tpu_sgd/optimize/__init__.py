from tpu_sgd.optimize.optimizer import Optimizer
from tpu_sgd.optimize.gradient_descent import (
    GradientDescent,
    make_run,
    make_step,
    run_mini_batch_sgd,
)
from tpu_sgd.optimize.lbfgs import LBFGS
from tpu_sgd.optimize.normal import NormalEquations

__all__ = [
    "Optimizer",
    "GradientDescent",
    "LBFGS",
    "NormalEquations",
    "make_run",
    "make_step",
    "run_mini_batch_sgd",
]
