from tpu_sgd.optimize.optimizer import Optimizer
from tpu_sgd.optimize.gradient_descent import (
    GradientDescent,
    make_run,
    make_step,
    run_mini_batch_sgd,
)

__all__ = [
    "Optimizer",
    "GradientDescent",
    "make_run",
    "make_step",
    "run_mini_batch_sgd",
]
