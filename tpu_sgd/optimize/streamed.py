"""Host-streamed SGD for datasets larger than device HBM.

SURVEY.md §7 (phase 6, hard parts): config 4's full 10M x 1000 f32 dataset is
40 GB — it cannot be device-resident on a 16 GB chip.  The TPU-idiomatic
answer is to keep the dataset in host RAM, sample each iteration's
mini-batch host-side (the per-iteration seeded sample, same determinism
contract: ``default_rng(seed + i)``), and overlap iteration ``i``'s device
compute with iteration ``i+1``'s host-side batch assembly + transfer: the
sample sequence is deterministic in ``(seed, i)``, so the shared ingest
prefetcher (``tpu_sgd/io``) assembles and ``device_put``s iteration
``i+1``'s batch on a worker thread while iteration ``i``'s dispatched step
computes — only the final ``block_until_ready`` waits on the device — the
analogue of the reference's executors reading partitions while the driver
schedules the next job (SURVEY.md §3.1), without the per-iteration
scheduling cost.  An opt-in bf16 wire format (``wire_dtype``) halves the
transferred bytes on the feed-bound paths.

The device-side step is the SAME ``make_step`` the resident paths use
(frac=1.0 over the transferred batch; normalization by the realized batch
size is preserved because the host sampler marks exactly the sampled rows
valid).  All three sampling modes (bernoulli / indexed / sliced) are
honored host-side.  Bernoulli and indexed match the resident path's
distribution; sliced draws ONE global contiguous window that is then
sharded, whereas the resident mesh path draws an independent window per
shard — both are single-window-per-sampler designs, but the streamed batch
is globally contiguous where the resident mesh batch is a union of 8 local
windows.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.updaters import Updater


def sliced_window_rows(n: int, frac: float) -> int:
    """Rows per sliced-sampling window — THE definition shared by the
    sampler and by external consumers (bench's residency math), so they
    cannot silently desync on rounding."""
    return max(1, round(frac * n))


def resident_window_probability(n: int, frac: float, resident: int) -> float:
    """Probability a sliced window lies in the resident prefix: the sampler
    draws ``start ~ integers(0, n-m+1)`` and the window is resident iff
    ``start + m <= resident`` — shared with bench's recorded
    ``expected_transfer_fraction`` so the artifact cannot desync from the
    sampler's actual accept set."""
    m = sliced_window_rows(n, frac)
    return min(1.0, max(0.0, (resident - m + 1) / max(n - m + 1, 1)))


#: whole-run resident-loop memo for the streamed path — the stepwise
#: driver memoizes its loops per-optimizer (``_run_cache``), but this is
#: a free function, so the memo lives here: ``TrainingSupervisor``
#: resume attempts and repeated runs with an unchanged ``(gradient,
#: updater, config, K, C, feed)`` reuse the ONE compiled while-loop
#: program instead of re-tracing the largest program in the codebase
#: per call.  Bounded FIFO so a long-lived process cycling configs
#: doesn't pin dead programs (and their gradient objects) forever.
_RESIDENT_LOOPS: OrderedDict = OrderedDict()
_RESIDENT_LOOPS_MAX = 8

#: memo-key contract (checked by graftlint's memo-key rule): the cache
#: key must be built from exactly these roots, and every program-
#: affecting value the stored loop derives from must be covered by them
GRAFTLINT_MEMO = {
    # the loop key's locals (K, C, comp_frac, m_fixed,
    # shared_full_batch) decompose to these roots: the optimizer
    # plugins, the config, the superstep / cadence / wire knobs, and
    # the feed geometry through X
    "_RESIDENT_LOOPS": ("gradient", "updater", "config", "superstep_k",
                        "resident_cadence", "wire_compress", "X"),
}


def optimize_host_streamed(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    X: np.ndarray,
    y: np.ndarray,
    initial_weights,
    device=None,
    mesh=None,
    listener=None,
    checkpoint_manager=None,
    checkpoint_every: int = 10,
    resident_rows: int = 0,
    wire_dtype=None,
    prefetch_depth: int = 2,
    retry_policy=None,
    stop_signal=None,
    superstep_k: int = 1,
    resident_cadence: int = 0,
    wire_compress=None,
) -> Tuple[jax.Array, np.ndarray]:
    """Run mini-batch SGD with the dataset resident on the HOST.

    Returns ``(weights, loss_history)`` with the same semantics as the
    resident path: per-iteration sample of ``mini_batch_fraction`` honoring
    ``config.sampling`` (host-side, seeded ``seed + i``), loss history
    including the previous iteration's reg value, convergence tolerance
    early exit.

    ``mesh``: a 1-D data mesh combines the two scaling axes — each streamed
    batch is ``device_put`` row-sharded across cores and the step runs under
    ``shard_map`` with the ICI gradient all-reduce, so datasets beyond one
    chip's HBM still use every core (SURVEY.md §7 phase 6).

    ``resident_rows``: partial residency for datasets only somewhat beyond
    HBM (the 10M x 1000 bf16 north star is 20 GB vs a 16 GB chip): rows
    ``[0, resident_rows)`` are placed on the device ONCE, and any sliced
    window falling inside that prefix is sliced on-device — zero
    host->device traffic for a ``resident_rows/n`` fraction of iterations,
    cutting per-epoch feed bytes by the same factor while drawing the
    identical window sequence (the sampler's RNG stream is unchanged).
    Sliced sampling, single device (``mesh=None``) only.

    Ingest pipeline (``tpu_sgd/io``; README "Ingestion pipeline"): the
    window/index sequence is deterministic in ``(seed, i)``, so iteration
    ``i+1``'s whole host-side assembly — the sliced window copy, the
    INDEXED row gather, the bernoulli mask + gather, padding, wire cast,
    and the ``device_put`` dispatch — runs on a prefetch worker thread
    while iteration ``i`` computes on the device (``prefetch_depth=2`` =
    double buffer; ``0`` = the legacy inline assembly, bitwise the same
    trajectory).  ``wire_dtype="bfloat16"`` (opt-in) halves the bytes of
    every transferred batch; the step then consumes bf16 rows, which is
    exactly the north-star host dtype (see the wire-safety notes in
    ``tpu_sgd/io/wire.py``).

    Reliability (``tpu_sgd/reliability``): ``retry_policy`` re-runs a
    failed host-side sample/transfer with seeded backoff (transient
    ``device_put`` faults heal in place).  ``stop_signal`` is a zero-arg
    callable polled once per iteration — the ``TrainingSupervisor``'s
    cooperative preemption hook: when it returns True the CURRENT state
    is checkpointed and ``TrainingPreempted`` unwinds cleanly; a later
    run with the same checkpoint manager resumes and, because every
    iteration is deterministic in ``(seed, i)``, finishes with
    bitwise-identical final weights (f32 wire).  The iteration body and
    the transfer pass the ``optimize.streamed.step`` /
    ``io.device_put`` failpoints.

    Superstep fusion (``superstep_k=K > 1``; README "Fused stepping"):
    K consecutive iterations run as ONE compiled ``lax.scan`` program,
    and the prefetch worker assembles a K-batch *superchunk*
    (``tpu_sgd.io.stack_superchunk``, the ``io.superstep`` failpoint)
    so ``device_put`` and program dispatch each fire once per K
    iterations instead of once per iteration — the per-iteration host
    dispatch tax drops ~K× (BENCH_SUPERSTEP.json).  Per-step math is
    the SAME ``make_step`` over the SAME deterministic sample sequence;
    per-step loss/norm/weights return as scan ys and replay host-side
    with the legacy bookkeeping, so the loss-history length, the
    detected convergence iteration, and the checkpoint cadence are
    exactly the K=1 loop's, and every same-program contract stays
    bitwise (fused runs replay, RESUME, and prefetch-A/B to identical
    weights, all three sampling modes).  Versus the K=1 loop the
    trajectories agree to reassociation noise — XLA lowers the batch
    dot differently inside the scanned program (~1 ulp/step; the same
    cross-program caveat as ``resident_step`` above — see
    ``make_superstep``).  ``stop_signal`` is polled at superstep
    BOUNDARIES
    (worst-case preemption latency: K iterations; the boundary
    iteration is checkpointed exactly).  Full-batch feeds
    (``mini_batch_fraction >= 1``) transfer the batch ONCE and scan
    over it.  A mesh shards the superchunk row-wise under the shared
    ``superchunk_specs`` layout (``dp_superstep_fn``), and
    ``resident_rows`` rides the same scan body with a per-step
    resident/transferred flag — both fuse since PR 6.
    ``resident_cadence >= 2`` additionally moves the WHOLE run loop on
    device for the full-batch and fully-resident-slab feeds (README
    "Device-resident training"); host-sampled feeds keep the superstep
    driver (warned — the host hop is the data feed).

    Compressed gradient wire (``wire_compress="topk:<frac>"``; README
    "Compressed wire"): the per-step gradient combine ships top-k
    ``(values, indices)`` segments with per-shard error-feedback state
    instead of a dense all-reduce (``make_compressed_step``).  The EF
    accumulator is optimizer state: it rides the superstep scan carry,
    is checkpointed (``extras={"ef": ...}``) at every save — cadence,
    convergence, and preemption — and restores on resume, so an
    interrupted+resumed compressed run is bitwise equal to its
    uninterrupted twin.  Composes with ``superstep_k`` AND with the
    whole-run resident driver (``resident_cadence >= 2`` on the
    full-batch or fully-resident-slab feed): the EF accumulator rides
    the while-loop carry with its per-step history on a ring leaf, so
    a compressed resident run is ONE dispatch per run like the dense
    one (tests/test_composition.py).  Only PARTIAL residency falls
    back to the dense wire with a warning (the mixed
    resident/transferred window step carries no EF state — the grid's
    recorded fallback cell).
    """
    import time as _time

    from tpu_sgd.io import (Prefetcher, parse_wire_compress,
                            resolve_wire_dtype, wire_cast)
    from tpu_sgd.io.integrity import seal, verify
    from tpu_sgd.obs.counters import record_wire
    from tpu_sgd.obs.spans import span
    from tpu_sgd.optimize.gradient_descent import (make_compressed_step,
                                                   make_step,
                                                   observed_loop_tail)
    from tpu_sgd.reliability.failpoints import corruptpoint, failpoint
    from tpu_sgd.utils.events import RunEvent

    cfg = config
    n = X.shape[0]
    w = jnp.asarray(initial_weights)
    if not jnp.issubdtype(w.dtype, jnp.inexact):
        w = w.astype(jnp.float32)
    if n == 0:
        return w, np.zeros((0,), np.float32)
    wd = resolve_wire_dtype(wire_dtype, X.dtype)
    comp_frac = parse_wire_compress(wire_compress)
    # frac applied host-side; the device step consumes the whole batch.
    step_cfg = cfg.replace(mini_batch_fraction=1.0)
    frac = cfg.mini_batch_fraction
    m_fixed = sliced_window_rows(n, frac)
    R = 0
    if resident_rows:
        if mesh is not None:
            raise NotImplementedError(
                "resident_rows composes with a single device; a mesh "
                "shards the resident slab with its own layout — use the "
                "fully-resident mesh path or plain streaming"
            )
        if cfg.sampling != "sliced" or frac >= 1.0:
            raise NotImplementedError(
                "resident_rows requires sampling='sliced' with "
                "mini_batch_fraction < 1 (contiguous windows are what can "
                "be sliced on-device)"
            )
        R = min(int(resident_rows), n)
        if R < m_fixed:
            raise ValueError(
                f"resident_rows={resident_rows} is smaller than one "
                f"window ({m_fixed} rows); no window can ever hit the "
                "resident prefix — raise it or use plain streaming"
            )
    K = max(1, int(superstep_k))
    C = max(0, int(resident_cadence))
    # fully-resident slab: R == n means EVERY sliced window lands in the
    # resident prefix — the feed is device-resident-sample and the
    # whole-run resident driver can take it (zero steady-state transfer)
    fully_resident = bool(R) and R >= n
    if C >= 2 and K <= 1:
        import warnings

        warnings.warn(
            "device residency rides the fused superstep executor; pass "
            "superstep_k >= 2 (or let the planner pick K) to engage it",
            RuntimeWarning, stacklevel=3,
        )
        C = 0
    if C >= 2 and (mesh is not None
                   or not (frac >= 1.0 or fully_resident)):
        import warnings

        warnings.warn(
            "device residency applies to the single-device full-batch "
            "and fully-resident-slab feeds (a host-sampled feed's host "
            "hop IS the data feed); running the fused superstep driver "
            "— the recorded composition-grid cell for this feed "
            "(tests/test_composition.py, feed=host-sampled x resident)",
            RuntimeWarning, stacklevel=3,
        )
        C = 0
    if comp_frac is not None and R and not (fully_resident and C >= 2):
        import warnings

        # a PARTIALLY-resident window feed mixes on-device and
        # transferred windows through steps that carry no EF state
        # (make_resident_window_superstep / resident_step) — the dense
        # wire runs instead, per the recorded composition-grid cell
        # (tests/test_composition.py, feed=slab-partial x compressed).
        # A FULLY-resident slab with resident_cadence >= 2 composes:
        # the EF accumulator rides the while-loop carry (the lifted
        # PR 9 DEVIATION — see resident_driver.ResidentLoop).
        warnings.warn(
            "wire_compress with a partially-resident window feed runs "
            "the dense gradient wire (the resident-window step has no "
            "EF carry; composition grid cell feed=slab-partial x "
            "compressed) — a fully resident slab with "
            "resident_cadence >= 2 carries EF in the while-loop ring",
            RuntimeWarning, stacklevel=3,
        )
        comp_frac = None
    if mesh is None:
        if device is None:
            device = jax.devices()[0]
        w_sharding = device
        base_step = make_step(gradient, updater, step_cfg)
        if comp_frac is not None:
            step = jax.jit(make_compressed_step(
                gradient, updater, step_cfg, comp_frac))
        else:
            step = jax.jit(base_step)
        row_sharding = mask_sharding = device
        super_row_sharding = super_mask_sharding = device
        ef_sharding = device
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_sgd.parallel.data_parallel import (dp_compressed_step_fn,
                                                    dp_step_fn)
        from tpu_sgd.parallel.mesh import DATA_AXIS, superchunk_specs

        if comp_frac is not None:
            step = dp_compressed_step_fn(
                gradient, updater, step_cfg, comp_frac, mesh,
                with_valid=True)
        else:
            step = dp_step_fn(gradient, updater, step_cfg, mesh,
                              with_valid=True)
        w_sharding = NamedSharding(mesh, P())
        row_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
        mask_sharding = NamedSharding(mesh, P(DATA_AXIS))
        spec_xs, spec_ys, _ = superchunk_specs()
        super_row_sharding = NamedSharding(mesh, spec_xs)
        super_mask_sharding = NamedSharding(mesh, spec_ys)
        ef_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    w = jax.device_put(w, w_sharding)

    _, reg_val = updater.compute(
        w, jnp.zeros_like(w), 0.0, jnp.asarray(1, jnp.int32), cfg.reg_param
    )

    # Fixed row cap so the device step compiles once.  Bernoulli batches are
    # variable-size: cap at the binomial mean + 6 sigma + slack (overflow is
    # astronomically rare; a uniformly random subset is kept on overflow —
    # shuffle before truncation — so the estimate stays unbiased).  Indexed
    # and sliced batches are fixed-size by construction.
    if frac >= 1.0:
        cap = n
    elif cfg.sampling == "bernoulli":
        sigma = np.sqrt(n * frac * (1.0 - frac))
        cap = int(min(n, np.ceil(n * frac + 6.0 * sigma + 8)))
    else:  # indexed / sliced: same batch size as the device-resident path
        cap = m_fixed
    if mesh is not None:
        n_shards = mesh.shape[DATA_AXIS]
        cap += (-cap) % n_shards  # even shards; padding rows are invalid

    if R:
        # One-time placement of the resident prefix; windows inside it are
        # sliced on-device by the SAME step math (identical window sequence
        # and mask/count ops; the two compiled programs may fuse
        # differently, so trajectories agree to reassociation noise).  The
        # slab rides at the WIRE dtype so the resident and transferred
        # windows feed the same compiled step.
        Xres = jax.device_put(wire_cast(X[:R], wd), device)
        yres = jax.device_put(y[:R], device)
        ones_mask = jnp.ones((m_fixed,), bool)

        @jax.jit
        def resident_step(w, Xr, yr, start, i, reg_val):
            Xb = jax.lax.dynamic_slice_in_dim(Xr, start, m_fixed, 0)
            yb = jax.lax.dynamic_slice_in_dim(yr, start, m_fixed, 0)
            return base_step(w, Xb, yb, i, reg_val, ones_mask)

        # Prewarm BOTH compiled programs (dummy on-device inputs, no host
        # transfer): the window sequence decides per iteration which
        # program runs, so without this the OTHER program's first compile
        # would land mid-run at an RNG-dependent iteration — a multi-second
        # wall spike that corrupts steady-state timing.  The fused K > 1
        # drivers run ONE program for both window kinds and compile it on
        # their own first dispatch — no prewarm to do.
        if K == 1:
            i0 = jnp.asarray(1, jnp.int32)
            r0 = jnp.zeros((), jnp.float32)
            jax.block_until_ready(resident_step(
                w, Xres, yres, jnp.asarray(0, jnp.int32), i0, r0
            ))
            Xb0 = jnp.zeros((m_fixed,) + X.shape[1:], Xres.dtype)
            yb0 = jnp.zeros((m_fixed,), yres.dtype)
            v0 = jnp.ones((m_fixed,), bool)
            jax.block_until_ready(step(w, Xb0, yb0, i0, r0, v0))
            del Xb0, yb0, v0

    _gather = lambda A, idx: A[idx]
    if X.flags.c_contiguous:  # native gather requires contiguous rows
        try:  # multi-threaded row gather; X[idx] fallback
            from tpu_sgd.utils.native import gather_rows as _native_gather

            _native_gather(X[:1], np.zeros((1,), np.int64))  # probe once
            _gather = _native_gather
        except Exception:
            pass

    # frac >= 1: the "sample" is the whole dataset every iteration — the
    # host-side assembly is IDENTICAL across iterations and must be paid
    # once, not re-gathered per step (a full (n, d) memcpy that roughly
    # doubles the host feed cost the overlap exists to hide)
    _full_batch = [None]

    _wire_fmt = "bf16" if wd is not None else "dense-f32"

    def _put_batch(Xb, yb, valid):
        """The host→device hop of one assembled batch — THE transfer
        fault-injection site (``io.device_put``); retries, when
        configured, wrap the whole sample via the prefetcher.

        The chunk is a checksummed FRAME (tpu_sgd/io/integrity.py):
        sealed over the assembled host bytes, passed through the
        ``io.chunk`` corrupting failpoint (the modeled wire/DMA damage
        window), and verified at this consume boundary — the last host
        instant before the bytes become a device buffer.  A mismatch
        raises typed IntegrityError inside the prefetcher's retry
        scope, and the deterministic (seed, i) reassembly heals it
        BITWISE."""
        failpoint("io.device_put")
        ck = seal(Xb, yb, valid)
        Xb, yb, valid = corruptpoint("io.chunk", (Xb, yb, valid))
        verify("io.chunk", ck, Xb, yb, valid)
        record_wire(
            _wire_fmt,
            logical_nbytes=int(Xb.size * 4 + yb.nbytes + valid.nbytes),
            physical_nbytes=int(Xb.nbytes + yb.nbytes + valid.nbytes))
        return ("batch", (
            jax.device_put(Xb, row_sharding),
            jax.device_put(yb, mask_sharding),
            jax.device_put(valid, mask_sharding),
        ))

    def sample_host(i: int):
        """Per-iteration HOST-side sample honoring ``config.sampling`` —
        bernoulli (RDD.sample parity), indexed (fixed-size gather with
        replacement), or sliced (contiguous window) — deterministic in
        ``default_rng(seed + i)`` and padded to the fixed cap.  Pure
        host assembly (gather, pad, wire cast); the transfer belongs to
        the caller, so the SAME assembly feeds both the per-iteration
        feed (one ``_put_batch`` per batch) and the superstep feed (K
        batches stacked into one superchunk, one put).

        Returns a tagged pair: ``("resident", start)`` for an on-device
        window of the resident prefix, or ``("host", (Xb, yb, valid))``
        with cap-row host arrays — explicit dispatch, no
        type-sniffing."""
        rng = np.random.default_rng(cfg.seed + i)
        if frac < 1.0 and cfg.sampling == "sliced":
            # Contiguous window: a plain slice (zero-copy view on an f32
            # wire), never the row gather — sequential host I/O is this
            # mode's entire point.
            start = int(rng.integers(0, max(1, n - m_fixed + 1)))
            if start + m_fixed <= R:
                # window lies in the device-resident prefix: no transfer;
                # the RNG stream is identical either way, so residency
                # changes WHERE a window is read from, never WHICH windows
                # are drawn
                return ("resident", start)
            Xb = wire_cast(X[start:start + m_fixed], wd)
            yb = y[start:start + m_fixed]
            valid = np.ones((cap,), bool)
            if cap > m_fixed:  # mesh shard padding: one tail memcpy
                valid[m_fixed:] = False
                Xp = np.zeros((cap, X.shape[1]), Xb.dtype)
                Xp[:m_fixed] = Xb
                yp = np.zeros((cap,), y.dtype)
                yp[:m_fixed] = yb
                Xb, yb = Xp, yp
            return ("host", (Xb, yb, valid))
        if frac >= 1.0:
            if _full_batch[0] is None:
                Xw = wire_cast(X, wd)
                if cap == n:
                    # no shard padding: stream the rows as they are —
                    # no host copy at all (f32 wire; the bf16 wire cast
                    # above is the one host pass, paid once and cached)
                    _full_batch[0] = (Xw, y, np.ones((cap,), bool))
                else:
                    Xp = np.zeros((cap, X.shape[1]), Xw.dtype)
                    Xp[:n] = Xw
                    yp = np.zeros((cap,), y.dtype)
                    yp[:n] = y
                    valid = np.zeros((cap,), bool)
                    valid[:n] = True
                    _full_batch[0] = (Xp, yp, valid)
            return ("host", _full_batch[0])
        if cfg.sampling == "indexed":
            idx = rng.integers(0, n, size=m_fixed)
        else:  # bernoulli
            m = rng.random(n) < frac
            idx = np.nonzero(m)[0]
            if idx.shape[0] > cap:
                idx = rng.permutation(idx)[:cap]
        valid = np.zeros((cap,), bool)
        valid[: idx.shape[0]] = True
        pad = np.zeros((cap,), np.int64)
        pad[: idx.shape[0]] = idx
        # the gather itself rides the prefetch worker (the i+1 lookahead),
        # so this host pass overlaps iteration i's device step
        return ("host", (wire_cast(_gather(X, pad), wd), y[pad], valid))

    def sample(i: int):
        """``sample_host`` plus the transfer — the per-iteration
        producer the legacy (K=1) prefetch loop consumes."""
        kind, payload = sample_host(i)
        if kind == "resident":
            return (kind, payload)
        return _put_batch(*payload)

    def _put_super(Xs, Ys, Vs):
        """The host→device hop of one assembled K-step superchunk —
        the same ``io.device_put`` failpoint/retry scope as
        ``_put_batch``, with the ``(K, rows, ...)`` shardings from
        ``superchunk_specs`` (row axis sharded on a mesh, step axis
        replicated).  Same checksummed-frame contract as
        ``_put_batch`` — one seal/verify per superchunk, so the
        integrity plane's host cost amortizes with K exactly like the
        dispatch tax the superstep exists to amortize."""
        failpoint("io.device_put")
        ck = seal(Xs, Ys, Vs)
        Xs, Ys, Vs = corruptpoint("io.chunk", (Xs, Ys, Vs))
        verify("io.chunk", ck, Xs, Ys, Vs)
        record_wire(
            _wire_fmt,
            logical_nbytes=int(Xs.size * 4 + Ys.nbytes + Vs.nbytes),
            physical_nbytes=int(Xs.nbytes + Ys.nbytes + Vs.nbytes))
        return (jax.device_put(Xs, super_row_sharding),
                jax.device_put(Ys, super_mask_sharding),
                jax.device_put(Vs, super_mask_sharding))

    def sample_super(base: int):
        """Superstep producer: assemble the K per-iteration batches for
        iterations ``[base, base+K)`` into ONE ``(K, cap, ...)``
        superchunk (host numpy; ``tpu_sgd.io.stack_superchunk`` — the
        ``io.superstep`` failpoint) and transfer it with a single
        ``device_put`` per leaf (row-sharded over a mesh when one is
        set).  A tail superstep (fewer than K real
        iterations left) pads with zero rows and all-False valid masks,
        which the fused step turns into no-op updates — the fixed (K,
        cap) shape keeps the scan program compiled exactly once.  Runs
        on the prefetch worker, inside the retry scope, like every
        other producer."""
        from tpu_sgd.io import stack_superchunk

        steps = min(K, cfg.num_iterations - base + 1)
        parts = [sample_host(base + t)[1] for t in range(steps)]
        Xs, Ys, Vs = stack_superchunk(
            [p[0] for p in parts], [p[1] for p in parts],
            [p[2] for p in parts], k=K)
        return _put_super(Xs, Ys, Vs)

    def sample_super_resident(base: int):
        """Partial-residency superstep producer: a per-step window that
        lands in the resident prefix rides as a ``(start, True)`` flag
        pair with zero rows in the superchunk (the fixed shape still
        transfers — fusing trades those windows' transfer-byte savings
        for the K-fold dispatch cut, see
        ``make_resident_window_superstep``), while non-resident windows
        assemble and transfer exactly like ``sample_super``'s.  One put
        per superstep, same failpoint/retry scope as every producer."""
        from tpu_sgd.io import stack_superchunk

        steps = min(K, cfg.num_iterations - base + 1)
        starts = np.zeros((K,), np.int32)
        flags = np.zeros((K,), bool)
        xdt = np.dtype(wd) if wd is not None else X.dtype
        zeros = None
        parts = []
        for t in range(steps):
            kind, payload = sample_host(base + t)
            if kind == "resident":
                starts[t] = payload
                flags[t] = True
                if zeros is None:
                    zeros = (np.zeros((cap, X.shape[1]), xdt),
                             np.zeros((cap,), y.dtype),
                             np.ones((cap,), bool))
                parts.append(zeros)
            else:
                parts.append(payload)
        Xs, Ys, Vs = stack_superchunk(
            [p[0] for p in parts], [p[1] for p in parts],
            [p[2] for p in parts], k=K)
        Xd, Yd, Vd = _put_super(Xs, Ys, Vs)
        return (jax.device_put(starts, device),
                jax.device_put(flags, device), Xd, Yd, Vd)

    if listener is not None:
        listener.on_run_start(cfg)
    losses = []
    start_iter = 1
    config_key = repr((type(gradient).__name__, type(updater).__name__, cfg))
    ef_resume = None
    if checkpoint_manager is not None:
        state = checkpoint_manager.restore()
        if state is not None:
            if state["config_key"] and state["config_key"] != config_key:
                import warnings

                warnings.warn(
                    "checkpoint config differs from current config; resuming "
                    "anyway",
                    RuntimeWarning,
                    stacklevel=3,
                )
            w = jax.device_put(jnp.asarray(state["weights"]), w_sharding)
            reg_val = state["reg_val"]
            losses = list(np.asarray(state["loss_history"], np.float32))
            start_iter = state["iteration"] + 1
            ef_resume = state.get("extras", {}).get("ef")
    ef = None
    if comp_frac is not None:
        # error feedback is OPTIMIZER STATE (ADVICE.md): a fresh run
        # starts the accumulator at zero; a resumed compressed run MUST
        # restore the checkpointed accumulator or it stops being
        # bitwise vs its uninterrupted twin
        dim = int(w.shape[-1])
        if mesh is None:
            ef0 = np.zeros((dim,), np.float32)
        else:
            ef0 = np.zeros((mesh.shape[DATA_AXIS], dim), np.float32)
        if ef_resume is not None:
            ef0 = np.asarray(ef_resume, np.float32).reshape(ef0.shape)
        elif start_iter > 1:
            import warnings

            warnings.warn(
                "resuming a compressed run from a checkpoint without EF "
                "state (written by an uncompressed run?); the "
                "accumulator restarts at zero — the trajectory will not "
                "be bitwise vs an uninterrupted compressed run",
                RuntimeWarning, stacklevel=3,
            )
        ef = jax.device_put(jnp.asarray(ef0), ef_sharding)
    t_run = _time.perf_counter()
    converged = False

    # iteration-exact EF for mid-superstep checkpoint saves: the
    # replay's save_cb fires at iteration ii inside the CURRENT
    # superstep, whose per-step post-update accumulators sit in the
    # ys' seventh leaf (installed before each replay); the K=1 loop
    # never installs a window, so its saves read the live accumulator
    _ef_window = {"efs": None, "i0": start_iter}

    def _save(ii, w_np, rv):
        extras = None
        if comp_frac is not None:
            efs = _ef_window["efs"]
            extras = {"ef": (efs[ii - _ef_window["i0"]]
                             if efs is not None else np.asarray(ef))}
        checkpoint_manager.save(ii, np.asarray(w_np), rv,
                                np.asarray(losses), config_key,
                                extras=extras)

    if K > 1:
        # Superstep executor: ONE compiled lax.scan program advances K
        # iterations per dispatch; the prefetcher stages whole
        # superchunks, so device_put ALSO fires once per K iterations.
        # Per-step (weights, loss, reg, count, norms) return as scan ys
        # and replay host-side with the legacy loop's exact bookkeeping
        # (_replay_fused_steps) — same loss history, same convergence
        # iteration, same checkpoint bytes.  A mesh runs the same scan
        # under shard_map; partial residency runs the mixed
        # resident/transferred-window scan; and resident_cadence >= 2
        # on a device-resident-data feed escalates to the whole-run
        # resident driver below.
        from tpu_sgd.optimize.gradient_descent import (
            _replay_fused_steps,
            make_resident_window_superstep,
            make_shared_batch_superstep,
            make_superstep,
        )
        from tpu_sgd.reliability.supervisor import TrainingPreempted

        shared_full_batch = frac >= 1.0
        window_resident = bool(R) and not shared_full_batch

        def _full_batch_transfer():
            # THE one-time full-batch device_put, inside the ingest
            # retry scope (it runs outside a prefetcher, so a transient
            # fault must heal here exactly as on the per-iteration
            # feed) — shared by the resident and superstep drivers
            def _t():
                return sample(start_iter)

            _, put = (retry_policy.call(_t)
                      if retry_policy is not None else _t())
            return put

        if C >= 2:
            # Whole-run device-resident driver
            # (optimize/resident_driver.py): the per-iteration data is
            # already on device — the one-time full-batch transfer, or
            # the fully-resident slab plus a precomputed window-start
            # sequence — so the entire converged-or-budget-exhausted
            # run is ONE program dispatch; the host hops only at the
            # cadence io_callback, whose ring ys replay through the
            # same _replay_fused_steps as the superstep loop below
            # (bitwise-pinned in tests/test_resident.py).
            from tpu_sgd.optimize.resident_driver import (
                ResidentBookkeeper,
                ResidentLoop,
            )

            if start_iter <= cfg.num_iterations:
                # compressed wire on the resident driver: the EF
                # accumulator is a CARRY LEAF of the same while-loop
                # (with_extra) and its per-step history rides the ring,
                # exactly as make_compressed_superstep carries it in
                # the scan — one driver, many carries (ADVICE.md)
                comp_step = (make_compressed_step(
                    gradient, updater, step_cfg, comp_frac)
                    if comp_frac is not None else None)
                if shared_full_batch:
                    res_data = _full_batch_transfer()

                    if comp_frac is not None:
                        def _res_step(w_, e_, i_, rv_, Xr, yr, vr):
                            return comp_step(w_, e_, Xr, yr, i_, rv_,
                                             vr)
                    else:
                        def _res_step(w_, i_, rv_, Xr, yr, vr):
                            return base_step(w_, Xr, yr, i_, rv_, vr)
                else:
                    # fully-resident sliced slab: the window sequence
                    # is deterministic in (seed, i) — replay THE host
                    # sampler's draws up front (every window of a
                    # fully-resident slab returns ("resident", start),
                    # zero assembly) so the on-device run consumes the
                    # IDENTICAL windows from the one authoritative RNG
                    # rule (one tiny (N,) int32 transfer, once per run)
                    starts_np = np.empty((cfg.num_iterations,),
                                         np.int32)
                    for it in range(1, cfg.num_iterations + 1):
                        tag, start = sample_host(it)
                        assert tag == "resident", tag
                        starts_np[it - 1] = start
                    starts_d = jax.device_put(starts_np, device)
                    res_data = (Xres, yres, starts_d)

                    if comp_frac is not None:
                        def _res_step(w_, e_, i_, rv_, Xr, yr, st):
                            s0 = st[i_ - 1]
                            Xb = jax.lax.dynamic_slice_in_dim(
                                Xr, s0, m_fixed, 0)
                            yb = jax.lax.dynamic_slice_in_dim(
                                yr, s0, m_fixed, 0)
                            return comp_step(w_, e_, Xb, yb, i_, rv_,
                                             ones_mask)
                    else:
                        def _res_step(w_, i_, rv_, Xr, yr, st):
                            s0 = st[i_ - 1]
                            Xb = jax.lax.dynamic_slice_in_dim(
                                Xr, s0, m_fixed, 0)
                            yb = jax.lax.dynamic_slice_in_dim(
                                yr, s0, m_fixed, 0)
                            return base_step(w_, Xb, yb, i_, rv_,
                                             ones_mask)

                # the loop's program depends only on (step math, cfg,
                # K, C, wire) and the feed shape family — memo hit =
                # zero re-trace on resume/replay with the same
                # optimizer
                loop_key = (gradient, updater, cfg, K, C, comp_frac,
                            ("full",) if shared_full_batch
                            else ("slab", m_fixed))
                loop = _RESIDENT_LOOPS.get(loop_key)
                if loop is None:
                    loop = ResidentLoop(
                        _res_step, cfg, K, C,
                        with_extra=comp_frac is not None)
                    _RESIDENT_LOOPS[loop_key] = loop
                    while len(_RESIDENT_LOOPS) > _RESIDENT_LOOPS_MAX:
                        _RESIDENT_LOOPS.popitem(last=False)

                def _install_ef_window(i0w, exs):
                    # iteration-exact EF for checkpoint saves fired
                    # inside this window's replay (_save reads it)
                    _ef_window["efs"] = exs
                    _ef_window["i0"] = int(i0w)

                hooks = ResidentBookkeeper(
                    cfg, K, C, losses=losses, reg_val=reg_val,
                    start_iter=start_iter, listener=listener,
                    save_cb=(_save if checkpoint_manager is not None
                             else None),
                    save_every=checkpoint_every,
                    stop_signal=stop_signal,
                    retry_policy=retry_policy,
                    extras_cb=(_install_ef_window
                               if comp_frac is not None else None))
                # the iteration-body failpoint fires once per DISPATCH,
                # as on every other driver — one hit per resident run
                failpoint("optimize.streamed.step")
                if comp_frac is not None:
                    w_np, converged = loop.run(w, reg_val, start_iter,
                                               res_data, hooks,
                                               extra0=ef)
                else:
                    w_np, converged = loop.run(w, reg_val, start_iter,
                                               res_data, hooks)
                w = jax.device_put(jnp.asarray(w_np), w_sharding)
                reg_val = hooks.reg_val
            if listener is not None:
                listener.on_run_end(RunEvent(
                    event="run_completed",
                    num_iterations=len(losses),
                    final_loss=losses[-1] if losses else None,
                    converged_early=converged,
                    wall_time_s=_time.perf_counter() - t_run,
                ))
            return w, np.asarray(losses, np.float32)

        if mesh is not None:
            from tpu_sgd.parallel.data_parallel import (
                dp_compressed_shared_superstep_fn,
                dp_compressed_superstep_fn,
                dp_shared_superstep_fn,
                dp_superstep_fn,
            )

            if shared_full_batch:
                if comp_frac is not None:
                    fused = dp_compressed_shared_superstep_fn(
                        gradient, updater, step_cfg, comp_frac, K,
                        mesh, True)
                else:
                    fused = dp_shared_superstep_fn(
                        gradient, updater, step_cfg, K, mesh, True)
            elif comp_frac is not None:
                fused = dp_compressed_superstep_fn(
                    gradient, updater, step_cfg, comp_frac, mesh)
            else:
                fused = dp_superstep_fn(gradient, updater, step_cfg,
                                        mesh)
        elif shared_full_batch:
            # the full-batch "sample" is identical every iteration:
            # transfer it ONCE and let the scan reuse it — zero
            # per-iteration AND zero per-superstep transfer
            if comp_frac is not None:
                from tpu_sgd.optimize.gradient_descent import (
                    make_compressed_shared_superstep,
                )

                fused = jax.jit(make_compressed_shared_superstep(
                    gradient, updater, step_cfg, comp_frac, K))
            else:
                fused = jax.jit(make_shared_batch_superstep(
                    gradient, updater, step_cfg, K))
        elif window_resident:
            fused = jax.jit(make_resident_window_superstep(
                gradient, updater, step_cfg, m_fixed))
        elif comp_frac is not None:
            from tpu_sgd.optimize.gradient_descent import (
                make_compressed_superstep,
            )

            fused = jax.jit(make_compressed_superstep(
                gradient, updater, step_cfg, comp_frac))
        else:
            fused = jax.jit(make_superstep(gradient, updater, step_cfg))

        prefetch = None
        try:
            if shared_full_batch:
                if start_iter <= cfg.num_iterations:
                    Xd, yd, vd = _full_batch_transfer()
            else:
                producer = (sample_super_resident if window_resident
                            else sample_super)
                prefetch = Prefetcher(
                    producer,
                    range(start_iter, cfg.num_iterations + 1, K),
                    depth=prefetch_depth, retry_policy=retry_policy)
                nxt = (next(prefetch)
                       if start_iter <= cfg.num_iterations else None)
            i0 = start_iter
            while i0 <= cfg.num_iterations and not converged:
                steps = min(K, cfg.num_iterations - i0 + 1)
                t0 = _time.perf_counter()
                failpoint("optimize.streamed.step")
                # Dispatch the fused program FIRST (async), pull the
                # next superchunk while the device runs the K steps,
                # and only then block on the ys fetch.  The span times
                # dispatch -> ys-on-host; attrs are HOST ints, and the
                # ys fetch below is the driver's own documented
                # boundary, so tracing adds zero syncs (the acceptance
                # pin in tests/test_obs.py)
                with span("train.superstep", i0=i0, steps=steps):
                    if shared_full_batch:
                        if comp_frac is not None:
                            w_dev, ef, ys = fused(
                                w, ef, jnp.asarray(reg_val, jnp.float32),
                                jnp.asarray(i0, jnp.int32), Xd, yd, vd)
                        else:
                            w_dev, ys = fused(
                                w, jnp.asarray(reg_val, jnp.float32),
                                jnp.asarray(i0, jnp.int32), Xd, yd, vd)
                    elif window_resident:
                        w_dev, ys = fused(
                            w, jnp.asarray(reg_val, jnp.float32),
                            jnp.asarray(i0, jnp.int32), Xres, yres,
                            *nxt)
                        if i0 + K <= cfg.num_iterations:
                            nxt = next(prefetch)
                    else:
                        Xs, Ys, Vs = nxt
                        if comp_frac is not None:
                            w_dev, ef, ys = fused(
                                w, ef, jnp.asarray(reg_val, jnp.float32),
                                jnp.asarray(i0, jnp.int32), Xs, Ys, Vs)
                        else:
                            w_dev, ys = fused(
                                w, jnp.asarray(reg_val, jnp.float32),
                                jnp.asarray(i0, jnp.int32), Xs, Ys, Vs)
                        if i0 + K <= cfg.num_iterations:
                            nxt = next(prefetch)
                    ys_host = tuple(np.asarray(a) for a in ys)
                dt = _time.perf_counter() - t0
                efs_host = None
                if comp_frac is not None:
                    # seventh ys leaf = per-step post-update EF state
                    efs_host, ys_host = ys_host[6], ys_host[:6]
                    _ef_window["efs"] = efs_host
                    _ef_window["i0"] = i0
                t_last, reg_val, converged = _replay_fused_steps(
                    ys_host, i0, steps, losses, reg_val, cfg,
                    listener=listener, wall_dt=dt / steps,
                    save_cb=(_save if checkpoint_manager is not None
                             else None),
                    save_every=checkpoint_every,
                )
                if converged or steps < K:
                    # run ends mid-superstep: the true last iteration's
                    # weights ride the ys (per-batch tails are no-op
                    # padded, shared-batch tails overshoot — either
                    # way the carry is not the answer)
                    w = jax.device_put(jnp.asarray(ys_host[0][t_last]),
                                       w_sharding)
                else:
                    w = w_dev
                if (not converged and stop_signal is not None
                        and stop_signal()):
                    # cooperative preemption at the superstep BOUNDARY
                    # (the scan cannot poll mid-program): checkpoint
                    # the exact boundary iteration so a resumed run
                    # replays from precisely here, bitwise
                    boundary = i0 + steps - 1
                    if checkpoint_manager is not None:
                        checkpoint_manager.save(
                            # graftlint: disable=host-sync -- preemption save: fires once at the superstep boundary unwind, not per trip
                            boundary, np.asarray(w), reg_val,
                            np.asarray(losses), config_key,
                            extras=(
                                {"ef": efs_host[steps - 1]}
                                if comp_frac is not None else None))
                    raise TrainingPreempted(boundary)
                i0 += steps
        finally:
            if prefetch is not None:
                prefetch.close()
        if listener is not None:
            listener.on_run_end(
                RunEvent(
                    event="run_completed",
                    num_iterations=len(losses),
                    final_loss=losses[-1] if losses else None,
                    converged_early=converged,
                    wall_time_s=_time.perf_counter() - t_run,
                )
            )
        return w, np.asarray(losses, np.float32)
    # Lookahead prefetcher: the sample sequence is deterministic in
    # (seed, i), so sample(i+1) — gather/pad/cast/put, the whole host
    # side — runs on the worker thread while iteration i computes.
    # depth=0 degrades to the legacy inline assembly (same trajectory
    # either way; only WHERE the host work runs changes).
    prefetch = Prefetcher(sample, range(start_iter, cfg.num_iterations + 1),
                          depth=prefetch_depth, retry_policy=retry_policy)
    try:
        # a checkpoint restored at the final iteration leaves nothing to
        # sample — the loop below is skipped and the restored weights
        # return as-is
        nxt = (next(prefetch) if start_iter <= cfg.num_iterations
               else None)
        i = start_iter
        while i <= cfg.num_iterations and not converged:
            t0 = _time.perf_counter()
            # mid-iteration fault-injection site: a crash here loses the
            # iterations since the last checkpoint, which the supervised
            # resume replays deterministically (chaos-soak contract)
            failpoint("optimize.streamed.step")
            # Dispatch the device step FIRST (async), then pull the next
            # prefetched batch while the device computes — only the final
            # block_until_ready waits on the device.  The span times the
            # host region around an ALREADY-contractual barrier (this
            # driver's per-iteration hop IS the data feed); it adds no
            # sync of its own.
            with span("train.step", i=i):
                kind, payload = nxt
                if kind == "resident":
                    new_w, loss_i, new_reg, c = resident_step(
                        w, Xres, yres, jnp.asarray(payload, jnp.int32),
                        jnp.asarray(i, jnp.int32),
                        jnp.asarray(reg_val, jnp.float32),
                    )
                elif comp_frac is not None:
                    # compressed wire: the EF accumulator is carried
                    # across iterations like the weights (a skipped
                    # empty batch passes it through unchanged)
                    Xb, yb, valid = payload
                    new_w, ef, loss_i, new_reg, c = step(
                        w, ef, Xb, yb, jnp.asarray(i, jnp.int32),
                        jnp.asarray(reg_val, jnp.float32),
                        valid,
                    )
                else:
                    Xb, yb, valid = payload
                    new_w, loss_i, new_reg, c = step(
                        w, Xb, yb, jnp.asarray(i, jnp.int32),
                        jnp.asarray(reg_val, jnp.float32),
                        valid,
                    )
                if i < cfg.num_iterations:
                    nxt = next(prefetch)
                # observed streamed driver: the per-iteration host hop IS
                # the data feed and the bookkeeping contract — barrier
                # once per step, then fetch each scalar exactly once
                # graftlint: disable=host-sync -- observed driver: one barrier per step precedes the scalar reads below
                new_w = jax.block_until_ready(new_w)
            dt = _time.perf_counter() - t0
            # the shared observed-loop TAIL (one definition for this
            # driver and the sparse streamed driver — the PR 9 review's
            # flagged duplication, extracted to the observe_step home):
            # barrier above, then each scalar fetched exactly once, then
            # the cooperative-preemption check
            w, reg_val, converged = observed_loop_tail(  # graftlint: disable=host-sync -- observed driver: the per-step scalar fetches ARE the contract (one barrier above, each scalar fetched once inside the shared helper)
                i, w, new_w, loss_i, new_reg, c, losses, reg_val, cfg,
                listener=listener, wall_dt=dt,
                save_cb=(_save if checkpoint_manager is not None
                         else None),
                save_every=checkpoint_every, stop_signal=stop_signal,
            )
            i += 1
    finally:
        # convergence exits early: cancel the worker's queued lookahead —
        # nobody will consume those batches
        prefetch.close()
    if listener is not None:
        listener.on_run_end(
            RunEvent(
                event="run_completed",
                num_iterations=len(losses),
                final_loss=losses[-1] if losses else None,
                converged_early=converged,
                wall_time_s=_time.perf_counter() - t_run,
            )
        )
    return w, np.asarray(losses, np.float32)
