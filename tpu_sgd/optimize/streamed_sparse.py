"""Host-streamed SGD over SPARSE (BCOO) features — never densified.

The RCV1-shaped workload (~47k features, ~0.1% nnz) previously had two
executions: fully device-resident BCOO (tpu_sgd/ops/sparse.py) or
nothing — ``set_host_streaming`` raised, because the dense streamed
driver's whole feed is dense row buffers.  This driver closes that gap
END-TO-END sparse: the dataset stays host-resident as CSR entry arrays,
every sampled batch ships as fixed-shape BCOO *components* ``(data,
indices)`` staged in host numpy (``tpu_sgd.io.sparse_wire``), and the
device step reassembles the BCOO inside the compiled program — no dense
``(rows, d)`` chunk is ever materialized on host or device, so the
wire carries ~``nnz/(rows*d)`` of the dense bytes (>= 100x on RCV1
shapes; measured by the ``obs`` wire counters, README "Compressed
wire").

Shape discipline (the eager-op shape-compile trap): a sparse batch
varies in BOTH rows and nse, so the staging pads to ONE ``(row_cap,
nse_cap)`` shape per build — ``row_cap`` by the dense driver's
binomial-cap rule, ``nse_cap`` by a deterministic pre-pass over the
whole run's sample sequence (``io.sparse_wire.plan_sparse_batches``;
the sample is deterministic in ``(seed, i)``, so the cap — and the one
compiled body program — is identical across replays and resumes,
``assert_compile_count``-pinned in tests/test_sparse_wire.py).  Padding
entries are null entries (0.0 at (0, 0)) contributing exact zeros.

Same driver contracts as ``optimize/streamed.py``: bernoulli sampling
(the sparse support surface) or full batch, deterministic in
``default_rng(seed + i)`` and bitwise-identical to the dense streamed
driver's sampled row sequence; double-buffered prefetch
(``Prefetcher``, bitwise A/B vs depth 0); superstep fusion
(``superstep_k=K``: one ``lax.scan`` program over the K-batch sparse
superchunk, per-step ys replayed through the shared
``_replay_fused_steps`` — tail supersteps pad with all-False valid
rows); checkpoint/resume and cooperative preemption at superstep
boundaries, bitwise vs uninterrupted.  Full-batch feeds transfer the
components ONCE and scan over them; ``resident_cadence >= 2`` on that
feed escalates to the shared whole-run resident driver
(``optimize/resident_driver.py``) — the fixed-nse BCOO body becomes a
``step_fn`` feed variant of the ONE ``lax.while_loop`` program, one
dispatch per run instead of one per superstep.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.updaters import Updater

#: compiled sparse step/superstep memo — the sparse twin of
#: ``streamed._RESIDENT_LOOPS``: repeated runs / supervisor resume
#: attempts with an unchanged (plugin pair, config, K, feed geometry)
#: reuse the one compiled program instead of re-tracing per call.
#: Bounded FIFO so a long-lived process cycling configs doesn't pin
#: dead programs forever.
_SPARSE_PROGRAMS: OrderedDict = OrderedDict()
_SPARSE_PROGRAMS_MAX = 8

#: memo-key contract (graftlint memo-key rule): the cache key is built
#: from exactly these roots — the optimizer plugins, the config, the
#: superstep knob, and the feed geometry (``n``/``d`` and the derived
#: row/nse caps all come from X's host CSR relayout and the config's
#: sampling parameters)
GRAFTLINT_MEMO = {
    "_SPARSE_PROGRAMS": ("gradient", "updater", "config", "superstep_k",
                         "resident_cadence", "X", "n", "d"),
}


def _bcoo(data, idx, rows: int, d: int):
    from jax.experimental.sparse import BCOO

    return BCOO((data, idx), shape=(rows, d))


def _sparse_step_fn(gradient, updater, step_cfg, rows: int, d: int):
    """Jitted single sparse step: rebuild the batch BCOO from its
    transferred components inside the program, then the SAME
    ``make_step`` body as every other driver."""
    from tpu_sgd.optimize.gradient_descent import make_step

    base = make_step(gradient, updater, step_cfg)

    def fn(w, data, idx, yb, i, rv, valid):
        return base(w, _bcoo(data, idx, rows, d), yb, i, rv, valid)

    return jax.jit(fn)


def _sparse_superstep_fn(gradient, updater, step_cfg, rows: int, d: int):
    """Jitted K-fused sparse superstep: ``lax.scan`` over the sparse
    superchunk's leading step axis, one BCOO reassembly per step inside
    the one compiled program; ys per ``pack_step_ys``."""
    from tpu_sgd.optimize.gradient_descent import make_step, pack_step_ys

    step = make_step(gradient, updater, step_cfg)

    def fn(w, rv, i0, Ds, Is, Ys, Vs):
        idxs = i0 + jnp.arange(Ds.shape[0], dtype=jnp.int32)

        def body(carry, xs):
            cw, crv = carry
            i, dt, it, yt, vt = xs
            new_w, loss_i, new_rv, c = step(
                cw, _bcoo(dt, it, rows, d), yt, i, crv, vt)
            return (new_w, new_rv), pack_step_ys(cw, new_w, loss_i,
                                                 new_rv, c)

        (w, _), out = jax.lax.scan(body, (w, rv), (idxs, Ds, Is, Ys, Vs))
        return w, out

    return fn


def _sparse_resident_step_fn(gradient, updater, step_cfg, rows: int,
                             d: int):
    """Per-iteration unit for the whole-run resident driver over the
    ONE shared sparse batch: the fixed-nse BCOO reassembles from its
    once-transferred components inside the while-loop body — the
    sparse feed is just another ``step_fn`` variant of the single
    driver (``resident_driver.ResidentLoop``), not a second loop.
    UNJITTED: the loop owns the jit."""
    from tpu_sgd.optimize.gradient_descent import make_step

    base = make_step(gradient, updater, step_cfg)

    def fn(w, i, rv, data, idx, yb, valid):
        return base(w, _bcoo(data, idx, rows, d), yb, i, rv, valid)

    return fn


def _sparse_shared_superstep_fn(gradient, updater, step_cfg, rows: int,
                                d: int, k: int):
    """Jitted K-fused superstep over ONE shared sparse batch (the
    full-batch feed: components transferred once, the scan reuses
    them)."""
    from tpu_sgd.optimize.gradient_descent import make_step, pack_step_ys

    step = make_step(gradient, updater, step_cfg)
    K = int(k)

    def fn(w, rv, i0, data, idx, yb, valid):
        idxs = i0 + jnp.arange(K, dtype=jnp.int32)

        def body(carry, i):
            cw, crv = carry
            new_w, loss_i, new_rv, c = step(
                cw, _bcoo(data, idx, rows, d), yb, i, crv, valid)
            return (new_w, new_rv), pack_step_ys(cw, new_w, loss_i,
                                                 new_rv, c)

        (w, _), out = jax.lax.scan(body, (w, rv), idxs)
        return w, out

    return fn


def optimize_host_streamed_sparse(
    gradient: Gradient,
    updater: Updater,
    config: SGDConfig,
    X,
    y: np.ndarray,
    initial_weights,
    device=None,
    listener=None,
    checkpoint_manager=None,
    checkpoint_every: int = 10,
    prefetch_depth: int = 2,
    retry_policy=None,
    stop_signal=None,
    superstep_k: int = 1,
    resident_cadence: int = 0,
    wire_compress=None,
) -> Tuple[jax.Array, np.ndarray]:
    """Run mini-batch SGD with the SPARSE dataset resident on the host.

    ``X`` is a host-side BCOO (``tpu_sgd.ops.sparse``); see the module
    docstring for the staging/shape contracts.  Returns ``(weights,
    loss_history)`` with the dense streamed driver's exact bookkeeping
    semantics (loss history includes the previous iteration's reg
    value, convergence tolerance early exit, checkpoint cadence,
    boundary preemption).

    ``resident_cadence >= 2`` (with ``superstep_k >= 2``) on the
    FULL-BATCH feed moves the whole run loop on device: the fixed-nse
    BCOO components transfer once and the run is ONE
    ``lax.while_loop`` dispatch of the same resident driver the dense
    feeds use (``optimize/resident_driver.py``) — the sparse slab is a
    ``step_fn`` feed variant of that one program, with the cadence
    ``io_callback`` ring replaying through the shared
    ``_replay_fused_steps``.  Host-sampled (bernoulli) sparse
    streaming keeps the superstep driver with a warning (the per-batch
    host hop IS the data feed — the composition grid's recorded
    fallback cell)."""
    import time as _time

    from tpu_sgd.io import Prefetcher
    from tpu_sgd.io.integrity import seal, verify
    from tpu_sgd.io.sparse_wire import (bcoo_to_csr_host,
                                        plan_sparse_batches,
                                        stage_sparse_batch)
    from tpu_sgd.obs.counters import record_wire
    from tpu_sgd.obs.spans import span
    from tpu_sgd.optimize.gradient_descent import (_replay_fused_steps,
                                                   observed_loop_tail)
    from tpu_sgd.reliability.failpoints import corruptpoint
    from tpu_sgd.utils.events import RunEvent

    cfg = config
    if cfg.mini_batch_fraction < 1.0 and cfg.sampling != "bernoulli":
        raise NotImplementedError(
            "host-streamed sparse training supports bernoulli sampling "
            f"or full batch (got sampling={cfg.sampling!r}; sliced/"
            "indexed need a dense row layout)"
        )
    if wire_compress is not None:
        import warnings

        warnings.warn(
            "wire_compress applies to the update-shaped wires (gradient "
            "all-reduce, totals merge); the sparse FEED is already "
            "compressed — BCOO components are the wire format here",
            RuntimeWarning, stacklevel=3,
        )
    if device is None:
        device = jax.devices()[0]
    indptr, cols, vals, (n, d) = bcoo_to_csr_host(X)
    w = jnp.asarray(initial_weights)
    if not jnp.issubdtype(w.dtype, jnp.inexact):
        w = w.astype(jnp.float32)
    w = jax.device_put(w, device)
    if n == 0:
        return w, np.zeros((0,), np.float32)
    yh = np.asarray(y)
    if not np.issubdtype(yh.dtype, np.inexact):
        yh = yh.astype(np.float32)

    step_cfg = cfg.replace(mini_batch_fraction=1.0)
    frac = cfg.mini_batch_fraction
    full_batch = frac >= 1.0
    if full_batch:
        cap = n
    else:
        sigma = np.sqrt(n * frac * (1.0 - frac))
        cap = int(min(n, np.ceil(n * frac + 6.0 * sigma + 8)))

    def sample_rows(i: int) -> np.ndarray:
        """THE per-iteration sampled-row rule — identical to the dense
        streamed driver's bernoulli draw (``default_rng(seed + i)``
        mask, uniformly-truncated overflow), shared by the nse-cap
        pre-pass and the producer so the planned cap can never miss a
        batch."""
        if full_batch:
            return np.arange(n, dtype=np.int64)
        rng = np.random.default_rng(cfg.seed + i)
        m = rng.random(n) < frac
        idx = np.nonzero(m)[0]
        if idx.shape[0] > cap:
            idx = rng.permutation(idx)[:cap]
        return idx

    # fixed nse per staged batch, planned over the WHOLE run (the
    # sample sequence is deterministic, so a resumed run plans the
    # same cap and reuses the same compiled body)
    if full_batch:
        nse_cap = max(1, int(vals.shape[0]))
    else:
        nse_cap = plan_sparse_batches(indptr, sample_rows,
                                      cfg.num_iterations, cap)

    K = max(1, int(superstep_k))
    C = max(0, int(resident_cadence))
    if C >= 2 and K <= 1:
        import warnings

        warnings.warn(
            "device residency rides the fused superstep executor; pass "
            "superstep_k >= 2 (or let the planner pick K) to engage it",
            RuntimeWarning, stacklevel=3,
        )
        C = 0
    if C >= 2 and not full_batch:
        import warnings

        warnings.warn(
            "device residency applies to the full-batch sparse feed "
            "(components transfer once); a bernoulli-sampled sparse "
            "stream's per-batch host hop IS the data feed, so the "
            "fused superstep driver runs — the recorded "
            "composition-grid cell for this feed "
            "(tests/test_composition.py, feed=sparse-bernoulli x "
            "resident)",
            RuntimeWarning, stacklevel=3,
        )
        C = 0

    _, reg_val = updater.compute(
        w, jnp.zeros_like(w), 0.0, jnp.asarray(1, jnp.int32),
        cfg.reg_param
    )

    def stage(i: int):
        """One batch's host assembly: CSR row gather + fixed-shape pad
        (``io.sparse_wire`` failpoint) — pure host numpy."""
        rows = sample_rows(i)
        data, idx, valid = stage_sparse_batch(
            indptr, cols, vals, rows, cap, nse_cap)
        yb = np.zeros((cap,), yh.dtype)
        yb[: rows.shape[0]] = yh[rows]
        return data, idx, yb, valid

    def sample(i: int):
        """Stage + transfer — the per-iteration producer (runs on the
        prefetch worker inside the retry scope).  The staged components
        are a checksummed FRAME (tpu_sgd/io/integrity.py): sealed after
        assembly, passed through the ``io.sparse_chunk`` corrupting
        failpoint, verified here at the consume boundary — a damaged
        entry array, label, or mask raises typed IntegrityError inside
        the retry scope and the deterministic re-stage heals BITWISE."""
        data, idx, yb, valid = stage(i)
        ck = seal(data, idx, yb, valid)
        data, idx, yb, valid = corruptpoint(
            "io.sparse_chunk", (data, idx, yb, valid))
        verify("io.sparse_chunk", ck, data, idx, yb, valid)
        record_wire(
            "bcoo",
            logical_nbytes=int(cap * d * 4 + yb.nbytes + valid.nbytes),
            physical_nbytes=int(data.nbytes + idx.nbytes + yb.nbytes
                                + valid.nbytes))
        return (jax.device_put(data, device), jax.device_put(idx, device),
                jax.device_put(yb, device), jax.device_put(valid, device))

    def sample_super(base: int):
        """Superstep producer: K staged batches assembled into one
        ``(K, ...)`` sparse superchunk, one ``device_put`` per leaf; a
        tail superstep pads missing steps with null entries and
        all-False valid rows (no-op updates, fixed shape)."""
        steps = min(K, cfg.num_iterations - base + 1)
        Ds = np.zeros((K, nse_cap), vals.dtype)
        Is = np.zeros((K, nse_cap, 2), np.int32)
        Ys = np.zeros((K, cap), yh.dtype)
        Vs = np.zeros((K, cap), bool)
        for t in range(steps):
            Ds[t], Is[t], Ys[t], Vs[t] = stage(base + t)
        ck = seal(Ds, Is, Ys, Vs)
        Ds, Is, Ys, Vs = corruptpoint(
            "io.sparse_chunk", (Ds, Is, Ys, Vs))
        verify("io.sparse_chunk", ck, Ds, Is, Ys, Vs)
        record_wire(
            "bcoo",
            logical_nbytes=int(K * cap * d * 4 + Ys.nbytes + Vs.nbytes),
            physical_nbytes=int(Ds.nbytes + Is.nbytes + Ys.nbytes
                                + Vs.nbytes))
        return (jax.device_put(Ds, device), jax.device_put(Is, device),
                jax.device_put(Ys, device), jax.device_put(Vs, device))

    # -- compiled programs (memoized; see GRAFTLINT_MEMO) -------------------
    # kind stays at key index 4 (pinned in tests); the resident kind
    # appends its cadence, which the other kinds don't key on
    if K > 1 and C >= 2:
        kind = "resident"
        prog_key = (gradient, updater, cfg, K, kind, cap, nse_cap, d, C)
    else:
        if K > 1:
            kind = "shared_super" if full_batch else "super"
        else:
            kind = "step"
        prog_key = (gradient, updater, cfg, K, kind, cap, nse_cap, d)
    prog = _SPARSE_PROGRAMS.get(prog_key)
    if prog is None:
        if kind == "step":
            prog = _sparse_step_fn(gradient, updater, step_cfg, cap, d)
        elif kind == "super":
            prog = jax.jit(_sparse_superstep_fn(
                gradient, updater, step_cfg, cap, d))
        elif kind == "resident":
            # the ONE whole-run driver (optimize/resident_driver.py):
            # the sparse shared batch is a step_fn feed variant of the
            # same while-loop program the dense feeds dispatch
            from tpu_sgd.optimize.resident_driver import ResidentLoop

            prog = ResidentLoop(
                _sparse_resident_step_fn(gradient, updater, step_cfg,
                                         cap, d),
                cfg, K, C)
        else:
            prog = jax.jit(_sparse_shared_superstep_fn(
                gradient, updater, step_cfg, cap, d, K))
        _SPARSE_PROGRAMS[prog_key] = prog
        while len(_SPARSE_PROGRAMS) > _SPARSE_PROGRAMS_MAX:
            _SPARSE_PROGRAMS.popitem(last=False)

    # -- bookkeeping state (the dense streamed driver's exact recipe) -------
    if listener is not None:
        listener.on_run_start(cfg)
    losses = []
    start_iter = 1
    config_key = repr((type(gradient).__name__, type(updater).__name__,
                       cfg))
    if checkpoint_manager is not None:
        state = checkpoint_manager.restore()
        if state is not None:
            if state["config_key"] and state["config_key"] != config_key:
                import warnings

                warnings.warn(
                    "checkpoint config differs from current config; "
                    "resuming anyway",
                    RuntimeWarning, stacklevel=3,
                )
            w = jax.device_put(jnp.asarray(state["weights"]), device)
            reg_val = state["reg_val"]
            losses = list(np.asarray(state["loss_history"], np.float32))
            start_iter = state["iteration"] + 1
    t_run = _time.perf_counter()
    converged = False

    def _save(ii, w_np, rv):
        checkpoint_manager.save(ii, np.asarray(w_np), rv,
                                np.asarray(losses), config_key)

    def _end():
        if listener is not None:
            listener.on_run_end(RunEvent(
                event="run_completed",
                num_iterations=len(losses),
                final_loss=losses[-1] if losses else None,
                converged_early=converged,
                wall_time_s=_time.perf_counter() - t_run,
            ))

    if K > 1 and C >= 2:
        # Whole-run resident sparse driver: the shared fixed-nse BCOO
        # components transfer ONCE (inside the ingest retry scope,
        # like the dense full-batch transfer) and the entire
        # converged-or-budget-exhausted run is one dispatch of the
        # shared while-loop program; window rings replay through the
        # same ResidentBookkeeper/_replay_fused_steps bookkeeping as
        # every resident feed, so history, events, convergence, and
        # checkpoint bytes are exactly the superstep driver's.
        from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

        if start_iter <= cfg.num_iterations:
            def _t0():
                return sample(start_iter)

            shared = (retry_policy.call(_t0)
                      if retry_policy is not None else _t0())
            hooks = ResidentBookkeeper(
                cfg, K, C, losses=losses, reg_val=reg_val,
                start_iter=start_iter, listener=listener,
                save_cb=(_save if checkpoint_manager is not None
                         else None),
                save_every=checkpoint_every,
                stop_signal=stop_signal,
                retry_policy=retry_policy)
            w_np, converged = prog.run(w, reg_val, start_iter, shared,
                                       hooks)
            w = jax.device_put(jnp.asarray(w_np), device)
            reg_val = hooks.reg_val
        _end()
        return w, np.asarray(losses, np.float32)

    if K > 1:
        from tpu_sgd.reliability.supervisor import TrainingPreempted

        if full_batch:
            if start_iter <= cfg.num_iterations:
                def _t():
                    return sample(start_iter)

                shared = (retry_policy.call(_t)
                          if retry_policy is not None else _t())
            prefetch = None
        else:
            prefetch = Prefetcher(
                sample_super,
                range(start_iter, cfg.num_iterations + 1, K),
                depth=prefetch_depth, retry_policy=retry_policy)
            nxt = (next(prefetch)
                   if start_iter <= cfg.num_iterations else None)
        try:
            i0 = start_iter
            while i0 <= cfg.num_iterations and not converged:
                steps = min(K, cfg.num_iterations - i0 + 1)
                t0 = _time.perf_counter()
                with span("train.superstep", i0=i0, steps=steps):
                    if full_batch:
                        w_dev, ys = prog(
                            w, jnp.asarray(reg_val, jnp.float32),
                            jnp.asarray(i0, jnp.int32), *shared)
                    else:
                        Ds, Is, Ys, Vs = nxt
                        w_dev, ys = prog(
                            w, jnp.asarray(reg_val, jnp.float32),
                            jnp.asarray(i0, jnp.int32), Ds, Is, Ys, Vs)
                        if i0 + K <= cfg.num_iterations:
                            nxt = next(prefetch)
                    ys_host = tuple(np.asarray(a) for a in ys)
                dt = _time.perf_counter() - t0
                t_last, reg_val, converged = _replay_fused_steps(
                    ys_host, i0, steps, losses, reg_val, cfg,
                    listener=listener, wall_dt=dt / steps,
                    save_cb=(_save if checkpoint_manager is not None
                             else None),
                    save_every=checkpoint_every,
                )
                if converged or steps < K:
                    w = jax.device_put(jnp.asarray(ys_host[0][t_last]),
                                       device)
                else:
                    w = w_dev
                if (not converged and stop_signal is not None
                        and stop_signal()):
                    boundary = i0 + steps - 1
                    if checkpoint_manager is not None:
                        checkpoint_manager.save(
                            # graftlint: disable=host-sync -- preemption save: fires once at the superstep boundary unwind, not per trip
                            boundary, np.asarray(w), reg_val,
                            np.asarray(losses), config_key)
                    raise TrainingPreempted(boundary)
                i0 += steps
        finally:
            if prefetch is not None:
                prefetch.close()
        _end()
        return w, np.asarray(losses, np.float32)

    # -- K=1 per-iteration loop ---------------------------------------------
    if full_batch:
        shared = None
        if start_iter <= cfg.num_iterations:
            def _t1():
                return sample(start_iter)

            shared = (retry_policy.call(_t1)
                      if retry_policy is not None else _t1())
        prefetch = None
    else:
        prefetch = Prefetcher(sample,
                              range(start_iter, cfg.num_iterations + 1),
                              depth=prefetch_depth,
                              retry_policy=retry_policy)
    try:
        nxt = None
        if prefetch is not None and start_iter <= cfg.num_iterations:
            nxt = next(prefetch)
        i = start_iter
        while i <= cfg.num_iterations and not converged:
            t0 = _time.perf_counter()
            with span("train.step", i=i):
                data, idx, yb, valid = shared if full_batch else nxt
                new_w, loss_i, new_reg, c = prog(
                    w, data, idx, yb, jnp.asarray(i, jnp.int32),
                    jnp.asarray(reg_val, jnp.float32), valid)
                if prefetch is not None and i < cfg.num_iterations:
                    nxt = next(prefetch)
                # the observed sparse streamed driver shares the dense
                # driver's contract: one barrier per step, then each
                # scalar fetched exactly once
                # graftlint: disable=host-sync -- observed driver: one barrier per step precedes the scalar reads below
                new_w = jax.block_until_ready(new_w)
            dt = _time.perf_counter() - t0
            # the shared observed-loop TAIL (one definition for this
            # driver and the dense streamed driver — the PR 9 review's
            # flagged duplication, extracted to the observe_step home):
            # barrier above, then each scalar fetched exactly once,
            # then the cooperative-preemption check
            w, reg_val, converged = observed_loop_tail(  # graftlint: disable=host-sync -- observed driver: the per-step scalar fetches ARE the contract (one barrier above, each scalar fetched once inside the shared helper)
                i, w, new_w, loss_i, new_reg, c, losses, reg_val, cfg,
                listener=listener, wall_dt=dt,
                save_cb=(_save if checkpoint_manager is not None
                         else None),
                save_every=checkpoint_every, stop_signal=stop_signal,
            )
            i += 1
    finally:
        if prefetch is not None:
            prefetch.close()
    _end()
    return w, np.asarray(losses, np.float32)
