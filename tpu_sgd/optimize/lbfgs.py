"""L-BFGS optimizer behind the same plugin boundary.

Reference parity: [U] mllib/optimization/LBFGS.scala (SURVEY.md §2 #18):
``LBFGS(gradient, updater)`` is the alternative ``Optimizer`` that proves the
boundary is real.  Semantics mirrored: full-batch cost function
``loss_sum / n + regVal(w)`` (reg term and its gradient derived from the
updater family exactly as the reference's ``CostFun`` does for
``SquaredL2Updater``), ``num_corrections`` two-loop recursion, convergence on
relative loss improvement, loss history returned alongside weights.

TPU-first shape: the cost function is one fused batched matvec pass (the same
``Gradient.batch_sums`` the SGD path uses, so the MXU kernel is shared); the
two-loop recursion runs on-device over the correction history; only the
line-search control flow is host-side (it is data-dependent and tiny).

Distribution: ``set_mesh`` shards the cost function's batch sums row-wise
over a 1-D data mesh with one ``lax.psum`` over ICI — the analogue of the
reference's ``CostFun`` running through ``treeAggregate`` ([U]
mllib/optimization/LBFGS.scala, distributed by construction).  The whole
backtracking ladder is evaluated as ONE batched multi-weight loss sweep
(X is read once for all trial points; the host syncs once per iteration
instead of once per trial — crucial over a high-latency device link).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from tpu_sgd.ops.gradients import Gradient
from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS
from tpu_sgd.ops.sparse import is_sparse
from tpu_sgd.ops.updaters import (
    L1Updater,
    SimpleUpdater,
    SquaredL2Updater,
    Updater,
)
from tpu_sgd.optimize.optimizer import Dataset, Optimizer

Array = jax.Array


def _reg_terms(updater: Updater, reg_param: float):
    """(reg_value(w), reg_grad(w)) matching the reference's CostFun handling
    of each updater family."""
    if isinstance(updater, SquaredL2Updater):
        return (
            lambda w: 0.5 * reg_param * jnp.sum(w * w),
            lambda w: reg_param * w,
        )
    if isinstance(updater, L1Updater):
        # Subgradient; the reference steers L1 users to OWL-QN, but accepts
        # this for parity testing at small reg.
        return (
            lambda w: reg_param * jnp.sum(jnp.abs(w)),
            lambda w: reg_param * jnp.sign(w),
        )
    return (lambda w: jnp.zeros((), w.dtype), lambda w: jnp.zeros_like(w))


def _warn_sequential_line_search(gradient, n_trials):
    """Tell the user their gradient lacks the ``loss_sweep`` protocol, so
    the Armijo backtracking runs one device call + host sync PER TRIAL (up
    to ``n_trials`` per iteration) instead of one fused multi-weight pass
    with a single sync — ruinous over a high-latency device link.  Every
    shipped gradient implements the sweep; this fires only for
    user-supplied exotics (cf. [U] LBFGS.scala's one-treeAggregate-per-
    iteration CostFun economy, SURVEY.md §2 #18)."""
    import warnings

    warnings.warn(
        f"{type(gradient).__name__} has no loss_sweep(X, y, W, mask) "
        "method, so the line search falls back to SEQUENTIAL trials — up "
        f"to {n_trials} device calls + host syncs per iteration instead "
        "of one batched sweep.  Implement loss_sweep (losses of a (T, d) "
        "stack of trial weights in one pass — see "
        "tpu_sgd.ops.gradients.LeastSquaresGradient.loss_sweep) to fuse "
        "the ladder.",
        RuntimeWarning,
        stacklevel=3,
    )


def _coerce_inputs(X, y, w, defer_commit: bool = False):
    """Shared (X, y, w) -> inexact arrays coercion for the quasi-Newton
    optimizers.  BCOO feature matrices and GramData statistics bundles
    pass through untouched (the fused cost dispatches to the sparse
    lowering / the sufficient-stats totals respectively).

    ``defer_commit`` (meshed runs): leave dense host (X, y) as
    dtype-coerced NUMPY arrays — ``jnp.asarray`` would commit the whole
    matrix to the DEFAULT device first, which OOMs for data larger than
    one device's HBM, exactly the regime the mesh serves.  The sharded
    placement (``shard_dataset`` / the per-shard statistics builders)
    then transfers each shard straight to its own device.  Already-
    committed ``jax.Array`` inputs keep their placement either way."""
    import numpy as np

    from tpu_sgd.ops.gram import GramData

    def to_inexact(a):
        # ONE dtype policy for both namespaces: deferred host arrays
        # stay numpy, everything else commits via jnp
        xp = (np if defer_commit and not isinstance(a, jax.Array)
              else jnp)
        a = xp.asarray(a)
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            a = a.astype(xp.float32)
        return a

    if not is_sparse(X) and not isinstance(X, GramData):
        X = to_inexact(X)
    y = to_inexact(y)
    w = jnp.asarray(w)
    if not jnp.issubdtype(w.dtype, jnp.inexact):
        w = w.astype(jnp.float32)
    return X, y, w


def _wrap_mesh(mesh, body, n_weight_args, with_valid, n_outs,
               sparse=False):
    """Jit ``body`` — plain, or shard_mapped over the 1-D data mesh with
    the first ``n_weight_args`` args replicated and (X, y[, valid]) row-
    sharded; outputs replicated (the psum inside ``body`` makes them so).
    ``sparse``: X arrives as sharded BCOO component arrays ``(data, idx)``
    (see parallel/sparse_parallel.py) instead of a dense row block."""
    if mesh is None:
        return jax.jit(body)
    from jax.sharding import PartitionSpec as P

    from tpu_sgd.parallel.mesh import DATA_AXIS, shard_map_fn

    x_spec = (
        (P(DATA_AXIS), P(DATA_AXIS, None)) if sparse else P(DATA_AXIS, None)
    )
    in_specs = (P(),) * n_weight_args + (x_spec, P(DATA_AXIS))
    if with_valid:
        in_specs = in_specs + (P(DATA_AXIS),)
    out_specs = P() if n_outs == 1 else (P(),) * n_outs
    return jax.jit(shard_map_fn(mesh, body, in_specs, out_specs))


def _maybe_bcoo(X, sparse_shape):
    """Reassemble a shard's ``(data, idx)`` components into its local BCOO
    block inside the shard_map body; dense X passes through."""
    if sparse_shape is None:
        return X
    from tpu_sgd.parallel.sparse_parallel import local_bcoo

    return local_bcoo(X[0], X[1], *sparse_shape)


def _build_cost(gradient, reg_value, reg_grad, mesh, with_valid,
                sparse_shape=None):
    """``cost(w, X, y[, valid]) -> (f, g)``: full objective and gradient,
    one fused pass, psum'd per shard under a mesh (the treeAggregate-CostFun
    analogue)."""

    def body(w, X, y, valid=None):
        X = _maybe_bcoo(X, sparse_shape)
        g_sum, l_sum, c = gradient.batch_sums(X, y, w, mask=valid)
        if mesh is not None:
            from tpu_sgd.parallel.mesh import DATA_AXIS

            g_sum, l_sum, c = jax.lax.psum((g_sum, l_sum, c), DATA_AXIS)
        return l_sum / c + reg_value(w), g_sum / c + reg_grad(w)

    if not with_valid:  # fixed arity for shard_map specs
        full = body
        body = lambda w, X, y: full(w, X, y)
    return _wrap_mesh(mesh, body, 1, with_valid, 2,
                      sparse=sparse_shape is not None)


def _build_loss_only(gradient, reg_value, mesh, with_valid,
                     sparse_shape=None):
    """``loss(w, X, y[, valid]) -> f``: objective WITHOUT the gradient as a
    compiled output, so XLA dead-code-eliminates the ``coeffᵀ @ X`` matmul —
    half the HBM traffic of the fused cost.  Used for line-search trials of
    matrix-weight gradients (``cost(...)[0]`` would keep the matmul live)."""

    def body(w, X, y, valid=None):
        X = _maybe_bcoo(X, sparse_shape)
        _, l_sum, c = gradient.batch_sums(X, y, w, mask=valid)
        if mesh is not None:
            from tpu_sgd.parallel.mesh import DATA_AXIS

            l_sum, c = jax.lax.psum((l_sum, c), DATA_AXIS)
        return l_sum / c + reg_value(w)

    if not with_valid:
        full = body
        body = lambda w, X, y: full(w, X, y)
    return _wrap_mesh(mesh, body, 1, with_valid, 1,
                      sparse=sparse_shape is not None)


def _build_loss_sweep(gradient, reg_value, mesh, with_valid,
                      sparse_shape=None):
    """``sweep(W, X, y[, valid]) -> (T,)`` objective values of T trial
    weight vectors in ONE fused pass: the gradient's ``loss_sweep`` rule
    reads X once for the entire backtracking ladder (a single MXU matmul)
    vs T separate matvecs (and T host syncs) for a scalar line search.
    Covers vector weights (derived from ``pointwise``) AND matrix weights
    (``MultinomialLogisticGradient.loss_sweep``'s stacked-class matmul)."""

    def body(W, X, y, valid=None):
        X = _maybe_bcoo(X, sparse_shape)
        l_sum, c = gradient.loss_sweep(X, y, W, mask=valid)
        if mesh is not None:
            from tpu_sgd.parallel.mesh import DATA_AXIS

            l_sum, c = jax.lax.psum((l_sum, c), DATA_AXIS)
        return l_sum / c + jax.vmap(reg_value)(W)

    if not with_valid:
        full = body
        body = lambda W, X, y: full(W, X, y)
    return _wrap_mesh(mesh, body, 1, with_valid, 1,
                      sparse=sparse_shape is not None)


def _shard_for_mesh(mesh, X, y):
    """Shard (X, y) over the data mesh: dense rows via ``shard_dataset``,
    BCOO via equal-nse component blocks (``shard_bcoo``) — the distributed-
    sparse CostFun analogue.  Returns ``(X, y, valid, sparse_shape)`` where
    dense X keeps ``sparse_shape=None`` and sparse X becomes the component
    tuple ``(data, idx)``."""
    from tpu_sgd.ops.gram import GramData

    if isinstance(X, GramData):
        raise NotImplementedError(
            "GramData input supports unmeshed quasi-Newton runs (the "
            "statistics already live on one device); drop set_mesh"
        )
    if is_sparse(X):
        from tpu_sgd.parallel.sparse_parallel import shard_bcoo

        data, idx, y, valid, rows_local, d = shard_bcoo(mesh, X, y)
        return (data, idx), y, valid, (rows_local, d)
    from tpu_sgd.parallel.data_parallel import shard_dataset

    X, y, valid = shard_dataset(mesh, X, y)
    return X, y, valid, None


def _reject_model_axis(mesh, who: str):
    from tpu_sgd.parallel.mesh import has_model_axis

    if has_model_axis(mesh):
        raise ValueError(
            f"{who} shards rows over a 1-D 'data' mesh; a 2-D (data, "
            "model) mesh would silently replicate X across the model "
            "axis — use a data-only mesh"
        )


def _push_correction(s_stack, y_stack, rho, k, m, s, yv, sy):
    """Append a curvature pair to the fixed-size history (shift when full);
    shared by LBFGS and OWLQN.  Returns updated (s_stack, y_stack, rho, k)."""
    if k < m:
        return (
            s_stack.at[k].set(s),
            y_stack.at[k].set(yv),
            rho.at[k].set(1.0 / sy),
            k + 1,
        )
    return (
        jnp.roll(s_stack, -1, axis=0).at[m - 1].set(s),
        jnp.roll(y_stack, -1, axis=0).at[m - 1].set(yv),
        jnp.roll(rho, -1).at[m - 1].set(1.0 / sy),
        k,
    )


@jax.jit
def _two_loop(g, s_stack, y_stack, rho, k):
    """Standard L-BFGS two-loop recursion over a fixed-size history buffer
    holding ``k`` valid corrections (rows [0, k)).  Module-level jit: one
    compile per history/weight shape across every optimize() call (the
    streaming mode re-enters per micro-batch)."""
    m = s_stack.shape[0]

    def bwd(carry, idx):
        q, alphas = carry
        valid = idx < k
        alpha = jnp.where(valid, rho[idx] * jnp.dot(s_stack[idx], q), 0.0)
        q = q - alpha * y_stack[idx]
        return (q, alphas.at[idx].set(alpha)), None

    (q, alphas), _ = jax.lax.scan(
        bwd, (g, jnp.zeros((m,), g.dtype)), jnp.arange(m - 1, -1, -1)
    )
    # initial Hessian scaling gamma = s.y / y.y of newest correction
    newest = jnp.maximum(k - 1, 0)
    gamma = jnp.where(
        k > 0,
        jnp.dot(s_stack[newest], y_stack[newest])
        / jnp.maximum(jnp.dot(y_stack[newest], y_stack[newest]), 1e-10),
        1.0,
    )
    r = gamma * q

    def fwd(r, idx):
        valid = idx < k
        beta = jnp.where(valid, rho[idx] * jnp.dot(y_stack[idx], r), 0.0)
        r = r + (alphas[idx] - beta) * s_stack[idx]
        return r, None

    r, _ = jax.lax.scan(fwd, r, jnp.arange(m))
    return r


class LBFGS(Optimizer):
    """Limited-memory BFGS with backtracking Armijo line search."""

    def __init__(
        self,
        gradient: Gradient = None,
        updater: Updater = None,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_num_iterations: int = 100,
        reg_param: float = 0.0,
    ):
        from tpu_sgd.ops.gradients import LeastSquaresGradient

        self.gradient = gradient if gradient is not None else LeastSquaresGradient()
        self.updater = updater if updater is not None else SimpleUpdater()
        self.num_corrections = num_corrections
        self.convergence_tol = convergence_tol
        self.max_num_iterations = max_num_iterations
        self.reg_param = reg_param
        self.mesh = None
        self.sufficient_stats = False
        self.streamed_stats = False
        self.host_streaming = False
        self.stream_batch_rows = None
        self.gram_block_rows = DEFAULT_BLOCK_ROWS
        self.gram_batch_rows = None
        #: ingest-pipeline knobs (tpu_sgd/io; set_ingest_options) — the
        #: streamed statistics builds feed through the shared prefetcher
        self.ingest_wire_dtype = None
        self.ingest_prefetch_depth = 2
        self.ingest_pipeline = True
        self.ingest_retry_policy = None
        #: compressed update wire (tpu_sgd/io/sparse_wire): the meshed
        #: streamed totals MERGE ships top-k + error-feedback segments
        #: with one dense residual flush (README "Compressed wire")
        self.ingest_wire_compress = None
        #: gram-knob fields the USER set (planner preserves these; see
        #: GradientDescent._user_gram_opts)
        self._user_gram_opts = frozenset()
        self.last_plan = None
        self._plan_key = None
        self._gram_entry = None
        self._streamed_gram_entry = None
        self._stream_costfun_entry = None
        self._eval_cache = {}
        self._loss_history = None

    # fluent setters, reference parity
    def set_gradient(self, g):
        if g is not self.gradient:
            # a swapped-out gradient (e.g. a user-built gram bundle in a
            # dataset sweep) must not stay pinned through cached
            # evaluators keyed on it
            self._evict_eval_entries(self.gradient)
        self.gradient = g
        return self

    def set_updater(self, u):
        self.updater = u
        return self

    def set_num_corrections(self, m: int):
        self.num_corrections = int(m)
        return self

    def set_convergence_tol(self, t: float):
        self.convergence_tol = float(t)
        return self

    def set_max_num_iterations(self, n: int):
        self.max_num_iterations = int(n)
        return self

    def set_reg_param(self, r: float):
        self.reg_param = float(r)
        return self

    def _clear_planned_schedule(self):
        """A manual schedule setter taking the wheel AFTER an auto-planned
        run: the previous plan's sibling flags are the PLANNER's, not the
        user's — reset them so the mutual-exclusion guards never blame
        the user for a flag a plan set (user-set flags are untouched:
        they always come with ``last_plan is None``)."""
        if self.last_plan is not None:
            self.host_streaming = False
            self.sufficient_stats = False
            self.streamed_stats = False
            # ...and the plan's sizing knobs (see GradientDescent's
            # _clear_planned_schedule): a manual schedule on a new
            # dataset must not inherit the planned dataset's block size
            # or chunk caps
            from tpu_sgd.plan import reset_plan_owned_gram_knobs

            reset_plan_owned_gram_knobs(self)

    def set_sufficient_stats(self, flag: bool = True):
        """Run the least-squares CostFun and line-search sweep from
        precomputed block-prefix Gram statistics (``ops/gram.py``): each
        full-batch objective/gradient becomes an O(d²) matvec instead of
        two passes over X.  Applies when the gradient is exactly
        ``LeastSquaresGradient`` on dense unmeshed data; otherwise a
        no-op.

        The last built ``(X, y, GramData)`` is retained by identity so
        repeated calls on the same arrays (the streaming mode) never
        rebuild; call :meth:`release_sufficient_stats` to free the
        dataset plus its prefix stack from HBM after a one-shot run."""
        self._clear_planned_schedule()
        self.sufficient_stats = bool(flag)
        # user-set flags invalidate any auto-plan (see glm._auto_plan)
        self.last_plan = None
        self._plan_key = None
        return self

    def release_sufficient_stats(self):
        """Drop the cached sufficient-statistics bundle so the bound
        dataset plus the GB-scale prefix stack can be freed from HBM
        (``set_sufficient_stats``/``set_streamed_stats`` retain the last
        build by design).  Also drops the host-streamed CostFun entry
        (its compiled kernels and host array references)."""
        self._gram_entry = None
        self._streamed_gram_entry = None
        self._stream_costfun_entry = None
        self._eval_cache = {}  # entries close over the dropped gradients
        return self

    def _evict_eval_entries(self, gradient) -> None:
        """Drop cached evaluators that close over ``gradient``.  Called
        when a gram identity-cache slot is REPLACED (new dataset): the
        old single-slot behavior freed the prior GramData automatically,
        and the evaluator cache must not keep the displaced gradient —
        and its rows + GB-scale prefix stacks — pinned in HBM across a
        dataset sweep."""
        if gradient is None:
            return
        for k in [k for k in self._eval_cache if gradient in k]:
            del self._eval_cache[k]

    def _cached_eval(self, key, builder):
        """Instance-level evaluator cache.  The cost/sweep/loss builders
        create FRESH ``jax.jit`` wrappers, so without this every
        ``optimize()`` call retraced and recompiled the full-batch
        programs — seconds of compile per call on the streaming mode's
        repeated re-entries, where ``GradientDescent``'s cached runner
        pays it once.  ``key`` must capture everything the built closure
        BAKES IN (gradient/updater identity, reg params, mesh, masking,
        sparse shape — and for OWL-QN the reg vector's shape/dtype and
        intercept exemption); jit itself handles new data shapes within
        a cached wrapper."""
        fn = self._eval_cache.get(key)
        if fn is None:
            fn = builder()
            self._eval_cache[key] = fn
        return fn

    def set_gram_options(self, block_rows: int = None,
                         batch_rows: int = None):
        """Sufficient-statistics build knobs (set by the execution
        planner): ``block_rows`` sizes the prefix stack (memory vs edge
        traffic — see ``ops/gram.py``); ``batch_rows`` caps the streamed
        build's host→device chunk, co-resident with the stack."""
        from tpu_sgd.plan import apply_user_gram_knobs

        apply_user_gram_knobs(self, block_rows=block_rows,
                              batch_rows=batch_rows)
        return self

    def set_ingest_options(self, wire_dtype=None, prefetch_depth=None,
                           pipeline=None, retry=None, wire_compress=None):
        """Host→device ingest-pipeline knobs for the streamed builds
        (``tpu_sgd/io``; README "Ingestion pipeline"): opt-in bf16 wire
        (half the bytes per chunk, f32+ accumulation unchanged),
        prefetch lookahead (2 = double buffer), and the pipelined-feed
        master switch — same contract as
        ``GradientDescent.set_ingest_options``, including the ``retry``
        reliability knob (a ``tpu_sgd.reliability.RetryPolicy``; heals
        transient host-feed faults on the host-streamed schedules).
        ``wire_compress="topk:<frac>"`` compresses the MESHED streamed
        totals merge — per-shard top-k + error-feedback segments with
        one dense residual flush (README "Compressed wire")."""
        from tpu_sgd.plan import apply_user_ingest_options

        apply_user_ingest_options(self, wire_dtype=wire_dtype,
                                  prefetch_depth=prefetch_depth,
                                  pipeline=pipeline, retry=retry,
                                  wire_compress=wire_compress)
        return self

    def set_streamed_stats(self, flag: bool = True, block_rows: int = None):
        """Beyond-HBM quasi-Newton least squares: ONE host-streaming pass
        builds the block-prefix statistics on device
        (``GramLeastSquaresGradient.build_streamed``), after which every
        full-batch cost/gradient/sweep evaluation is an O(d²) statistics
        read — the rows never live on the device at all.  Full-batch
        sums are EXACT from the totals; the only deviation is the
        dropped ``n % block_rows`` tail rows (<0.1% at scale).  Applies
        to exactly ``LeastSquaresGradient`` on dense single-device data;
        the build is identity-cached per ``(X, y)``.  The build pass
        feeds through the shared double-buffered ingest pipeline
        (``tpu_sgd/io``; knobs via ``set_ingest_options``, bf16-wire
        safety in README "Ingestion pipeline")."""
        self._clear_planned_schedule()
        self.streamed_stats = bool(flag)
        if block_rows is not None:
            self.gram_block_rows = int(block_rows)
            self._user_gram_opts = self._user_gram_opts | {"block_rows"}
        self.last_plan = None
        self._plan_key = None
        return self

    def set_host_streaming(self, flag: bool = True,
                           batch_rows: int = None):
        """Beyond-HBM quasi-Newton for ANY loss: keep the dataset in host
        RAM and evaluate every full-batch cost/gradient/line-search sweep
        by streaming the rows through the device in fixed-size chunks
        with a device-resident accumulator — the chunked treeAggregate
        CostFun (``optimize/streamed_costfun.py``; [U]
        mllib/optimization/LBFGS.scala CostFun, SURVEY.md §2 #18).

        Unlike ``set_streamed_stats`` (least squares only, one build
        pass then O(d²) evaluations), this works for logistic, hinge,
        and multinomial losses — at the cost of re-reading the dataset
        through the host feed per evaluation (~3 reads per iteration).
        Composes with ``set_mesh``: each chunk is row-sharded across the
        data mesh and per-chunk sums psum over ICI.

        ``batch_rows`` caps the chunk size (default ~256 MB of rows;
        the execution planner sets it from the probed HBM budget).
        Note: the chunked CostFun keeps its own feed — the
        ``set_ingest_options`` knobs apply to the streamed STATISTICS
        builds (``set_streamed_stats``), not to this mode."""
        self._clear_planned_schedule()
        self.host_streaming = bool(flag)
        if batch_rows is not None:
            if int(batch_rows) < 1:
                raise ValueError(
                    f"batch_rows must be positive, got {batch_rows}"
                )
            self.stream_batch_rows = int(batch_rows)
            self._user_gram_opts = (
                self._user_gram_opts | {"stream_batch_rows"})
        self.last_plan = None
        self._plan_key = None
        return self

    def set_mesh(self, mesh):
        """Shard the cost function (and line-search sweep) row-wise over a
        1-D data mesh — the treeAggregate-CostFun analogue (SURVEY.md §2
        #18)."""
        _reject_model_axis(mesh, type(self).__name__)
        self.mesh = mesh
        return self

    @property
    def loss_history(self):
        return self._loss_history

    def optimize(self, data: Dataset, initial_weights: Array) -> Array:
        w, _ = self.optimize_with_history(data, initial_weights)
        return w

    def _maybe_streamed_reentry(self, X, y, initial_weights):
        """``set_streamed_stats`` front door, shared by LBFGS and the
        OWLQN override: build the virtual statistics once from the host
        rows BEFORE any device coercion, swap the gradient, and re-enter
        ``optimize_with_history`` with the virtual GramData as X (the
        flow the manual build_streamed + GramData-input path takes).
        Returns None when the flag is off or X is already statistics."""
        import numpy as np

        from tpu_sgd.ops.gram import GramData

        if self.streamed_stats and self.host_streaming:
            raise ValueError(
                "set_streamed_stats and set_host_streaming are "
                "alternative beyond-HBM schedules; enable exactly one"
            )
        if not self.streamed_stats or isinstance(X, GramData):
            return None
        g = self._maybe_streamed_gram(X, y)
        orig, self.gradient = self.gradient, g
        # The statistics are replicated/device-local after the build, so
        # the re-entered run executes UNMESHED — full-batch sums are the
        # exact totals; the mesh's job (dividing the rows) is done.
        orig_mesh, self.mesh = self.mesh, None
        try:
            return self.optimize_with_history(
                (g.data, np.asarray(y)[:g.data.shape[0]]),
                initial_weights,
            )
        finally:
            self.gradient = orig
            self.mesh = orig_mesh

    def _maybe_streamed_gram(self, X, y):
        """Guards + identity-cached build for ``set_streamed_stats``."""
        import numpy as np

        from tpu_sgd.ops.gradients import LeastSquaresGradient as _LS
        from tpu_sgd.ops.gram import GramLeastSquaresGradient
        from tpu_sgd.ops.sparse import is_sparse as _is_sp

        if _is_sp(X):
            raise NotImplementedError(
                "streamed statistics need dense rows; BCOO features are "
                "~1000x smaller and stay device-resident instead"
            )
        if type(self.gradient) is not _LS:
            raise NotImplementedError(
                "streamed statistics exist for least squares only (the "
                f"quadratic loss); got {type(self.gradient).__name__}; "
                "use set_host_streaming for beyond-HBM non-LS losses"
            )
        entry = self._streamed_gram_entry
        ingest = (self.ingest_wire_dtype, self.ingest_prefetch_depth,
                  self.ingest_pipeline, self.ingest_wire_compress)
        opts = (self.gram_block_rows, self.gram_batch_rows, self.mesh,
                ingest)
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[3] == opts):
            return entry[2]
        if self.mesh is not None:
            # Per-shard streamed TOTALS on each device, combined once:
            # the quasi-Newton CostFun reads only totals, so the mesh
            # matters only for the BUILD (each device digests its own
            # host row slice in parallel); evaluations then run O(d²)
            # from the replicated statistics — EXACT totals, no dropped
            # tail (parallel/gram_parallel.py).
            from tpu_sgd.parallel.gram_parallel import (
                build_streamed_total_stats,
            )

            data = build_streamed_total_stats(
                self.mesh, np.asarray(X), np.asarray(y),
                block_rows=self.gram_block_rows,
                batch_rows=self.gram_batch_rows,
                wire_dtype=self.ingest_wire_dtype,
                prefetch_depth=self.ingest_prefetch_depth,
                pipeline=self.ingest_pipeline,
                wire_compress=(self.ingest_wire_compress
                               if self.ingest_pipeline else None),
            )
            g = GramLeastSquaresGradient(data)
        else:
            g = GramLeastSquaresGradient.build_streamed(
                np.asarray(X), np.asarray(y),
                block_rows=self.gram_block_rows,
                batch_rows=self.gram_batch_rows,
                wire_dtype=self.ingest_wire_dtype,
                prefetch_depth=self.ingest_prefetch_depth,
                pipeline=self.ingest_pipeline,
            )
        if self._streamed_gram_entry is not None:
            # new dataset displaces the old bundle: drop evaluators
            # that would pin its statistics in HBM
            self._evict_eval_entries(self._streamed_gram_entry[2])
        self._streamed_gram_entry = (X, y, g, opts)
        return g

    #: backtracking ladder length (t = 1, 1/2, ..., 2^-(N-1))
    _LS_TRIALS = 25

    def _substitute_gram(self, gradient, X, y):
        """Apply ``set_sufficient_stats`` when it fits (exactly
        ``LeastSquaresGradient``, dense, unmeshed), identity-cached per
        ``(X, y)``.  Shared with OWLQN (Lasso least squares).  Returns
        ``(gradient, X)`` — on substitution, X becomes the ``GramData``
        bundle so the stats enter jit programs as argument buffers."""
        from tpu_sgd.ops.gradients import LeastSquaresGradient as _LS
        from tpu_sgd.ops.gram import GramData, GramLeastSquaresGradient
        from tpu_sgd.ops.sparse import is_sparse as _is_sp

        if isinstance(X, GramData) and not isinstance(
                gradient, GramLeastSquaresGradient):
            raise ValueError(
                "GramData input needs a GramLeastSquaresGradient "
                "(use GramLeastSquaresGradient.build/build_streamed and "
                "pass it as the gradient)"
            )
        if (self.mesh is None
                and isinstance(gradient, GramLeastSquaresGradient)
                and gradient.data is not None and gradient.data.X is X):
            # user-built gram gradient on exactly this matrix: route its
            # GramData through so the traced cost/sweep accelerate
            return gradient, gradient.data
        if not (self.sufficient_stats and not _is_sp(X)
                and type(gradient) is _LS
                and not isinstance(X, GramData)):
            return gradient, X
        entry = self._gram_entry
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[3:] == (self.gram_block_rows, self.mesh)):
            g = entry[2]
            return g, g.data
        if self.mesh is not None:
            # Meshed substitution: per-shard blockwise TOTALS + one psum
            # (the quasi-Newton CostFun reads only totals — no prefix
            # stacks), replicated; the caller then runs the iteration
            # loop unmeshed from the tiny (d, d) statistics.  EXACT for
            # any row count (padded rows are masked in the build).
            from tpu_sgd.parallel.gram_parallel import (
                build_sharded_total_stats,
            )

            data = build_sharded_total_stats(
                self.mesh, X, y, block_rows=self.gram_block_rows)
            g = GramLeastSquaresGradient(data)
        else:
            g = GramLeastSquaresGradient.build(
                X, y, block_rows=self.gram_block_rows)
            data = g.data
        if self._gram_entry is not None:
            self._evict_eval_entries(self._gram_entry[2])
        self._gram_entry = (X, y, g, self.gram_block_rows, self.mesh)
        return g, data

    def _mesh_spans_processes(self) -> bool:
        if self.mesh is None:
            return False
        from tpu_sgd.optimize.streamed_costfun import mesh_spans_processes

        return mesh_spans_processes(self.mesh)

    def _host_streamed_costfun(self, X, y):
        """Guards + identity-cached :class:`StreamedCostFun` for
        ``set_host_streaming`` (shared with the OWLQN override)."""
        from tpu_sgd.ops.gram import GramData
        from tpu_sgd.optimize.streamed_costfun import StreamedCostFun

        if isinstance(X, GramData):
            raise ValueError(
                "GramData input already runs beyond-HBM from its "
                "statistics; drop set_host_streaming"
            )
        if is_sparse(X):
            raise NotImplementedError(
                "host streaming needs dense rows; BCOO features are "
                "~1000x smaller and stay device-resident instead"
            )
        if self.streamed_stats:
            raise ValueError(
                "set_streamed_stats and set_host_streaming are "
                "alternative beyond-HBM schedules; enable exactly one"
            )
        if self.sufficient_stats:
            raise ValueError(
                "set_sufficient_stats needs device-resident data; it "
                "cannot combine with set_host_streaming"
            )
        entry = self._stream_costfun_entry
        opts = (self.stream_batch_rows, self.mesh)
        if (entry is not None and entry[0] is X and entry[1] is y
                and entry[3] == opts and entry[2].gradient is self.gradient):
            return entry[2]
        scf = StreamedCostFun(
            self.gradient, X, y,
            batch_rows=self.stream_batch_rows, mesh=self.mesh,
        )
        self._stream_costfun_entry = (X, y, scf, opts)
        return scf

    def _host_streamed_evaluators(self, X, y, initial_weights):
        """``(w0, cost1, sweep1, loss1)`` closures over the chunked
        streaming CostFun, in the exact shape :meth:`_qn_loop` consumes;
        None for empty input (the resident path's early return covers
        it)."""
        import numpy as np

        if int(np.shape(X)[0]) == 0 and not self._mesh_spans_processes():
            # single-host empty input: the resident path's early return
            # covers it.  A multihost process with ZERO local rows must
            # NOT bail here — it still joins every collective (allgather
            # + per-chunk psums), feeding all-invalid chunks; bailing
            # would deadlock its peers.
            return None
        scf = self._host_streamed_costfun(X, y)
        w = jnp.asarray(initial_weights)
        if not jnp.issubdtype(w.dtype, jnp.inexact):
            w = w.astype(jnp.float32)
        reg_value, reg_grad = _reg_terms(self.updater, self.reg_param)

        def _build_finishes():
            @jax.jit
            def _finish_cost(gs, ls, c, wv):
                return ls / c + reg_value(wv), gs / c + reg_grad(wv)

            @jax.jit
            def _finish_sweep(ls, c, W):
                return ls / c + jax.vmap(reg_value)(W)

            @jax.jit
            def _finish_loss(ls, c, wv):
                return ls / c + reg_value(wv)

            return _finish_cost, _finish_sweep, _finish_loss

        _finish_cost, _finish_sweep, _finish_loss = self._cached_eval(
            ("stream_finish", self.updater, float(self.reg_param)),
            _build_finishes)

        def cost1(wv):
            return _finish_cost(*scf.cost_sums(wv), wv)

        if hasattr(self.gradient, "loss_sweep"):
            def sweep1(W):
                return _finish_sweep(*scf.sweep_sums(W), W)

            return w, cost1, sweep1, None
        _warn_sequential_line_search(self.gradient, self._LS_TRIALS)

        def loss1(wv):
            return _finish_loss(*scf.loss_sums(wv), wv)

        return w, cost1, None, loss1

    def optimize_with_history(self, data: Dataset, initial_weights: Array):
        import numpy as np

        X, y = data
        streamed = self._maybe_streamed_reentry(X, y, initial_weights)
        if streamed is not None:
            return streamed
        if self.host_streaming:
            # BEFORE _coerce_inputs: jnp.asarray would commit the
            # beyond-HBM matrix to the device
            ev = self._host_streamed_evaluators(X, y, initial_weights)
            if ev is not None:
                return self._qn_loop(*ev)
        X, y, w = _coerce_inputs(X, y, initial_weights,
                                 defer_commit=self.mesh is not None)
        n = X.shape[0]
        if n == 0:
            self._loss_history = np.zeros((0,), np.float32)
            return w, self._loss_history
        from tpu_sgd.ops.gram import GramData as _GramData

        was_gram_input = isinstance(X, _GramData)
        gradient, X = self._substitute_gram(self.gradient, X, y)
        reg_value, reg_grad = _reg_terms(self.updater, self.reg_param)

        mesh = self.mesh
        if isinstance(X, _GramData) and not was_gram_input:
            # internally substituted statistics are replicated: the
            # iteration loop runs unmeshed from exact totals (user-passed
            # GramData with a mesh still raises in _shard_for_mesh)
            mesh = None
            if not isinstance(y, jnp.ndarray):
                # the statistics carry Xᵀy / yᵀy — the gram cost never
                # reads y, but the host numpy array defer_commit left
                # here would re-upload host→device on EVERY evaluation
                # (~3/iteration); swap in an empty device vector
                y = jnp.zeros((0,), jnp.float32)
        valid = None
        sparse_shape = None
        if mesh is not None:
            X, y, valid, sparse_shape = _shard_for_mesh(mesh, X, y)
        with_valid = valid is not None
        data_args = (X, y, valid) if with_valid else (X, y)

        eval_key = (gradient, self.updater, float(self.reg_param),
                    mesh, with_valid, sparse_shape)
        cost = self._cached_eval(
            ("cost",) + eval_key,
            lambda: _build_cost(gradient, reg_value, reg_grad, mesh,
                                with_valid, sparse_shape))

        def cost1(wv):
            return cost(wv, *data_args)

        if hasattr(gradient, "loss_sweep"):
            sweep = self._cached_eval(
                ("sweep",) + eval_key,
                lambda: _build_loss_sweep(gradient, reg_value, mesh,
                                          with_valid, sparse_shape))

            def sweep1(W):
                return sweep(W, *data_args)

            return self._qn_loop(w, cost1, sweep1, None)
        # exotic gradients without a sweep rule: sequential trials
        _warn_sequential_line_search(gradient, self._LS_TRIALS)
        loss_only = self._cached_eval(
            ("loss",) + eval_key,
            lambda: _build_loss_only(gradient, reg_value, mesh,
                                     with_valid, sparse_shape))

        def loss1(wv):
            return loss_only(wv, *data_args)

        return self._qn_loop(w, cost1, None, loss1)

    def _qn_loop(self, w, cost1, sweep1, loss1):
        """The L-BFGS iteration loop over abstract FULL-BATCH evaluators:
        ``cost1(w) -> (f, g)``, ``sweep1(W_trials) -> (T,)`` trial
        objectives (None for gradients without a sweep rule), ``loss1(w)
        -> f`` (the sequential fallback).  Both the device-resident and
        the host-streamed CostFun paths drive this same loop — the
        evaluators are the only thing that differs."""
        import numpy as np

        n_ls = self._LS_TRIALS
        ladder = jnp.asarray(
            0.5 ** np.arange(n_ls), jnp.float32
        )  # trial step sizes, largest first
        swept = sweep1 is not None
        if swept:
            @jax.jit
            def make_trials(w, direction):
                return w[None, :] + ladder[:, None] * direction[None, :]

        m = self.num_corrections
        d = w.shape[0]
        s_stack = jnp.zeros((m, d), w.dtype)
        y_stack = jnp.zeros((m, d), w.dtype)
        rho = jnp.zeros((m,), w.dtype)
        k = 0  # valid corrections

        f, g = cost1(w)
        losses: List[float] = [float(f)]
        for _ in range(self.max_num_iterations):
            direction = -_two_loop(g, s_stack, y_stack, rho, jnp.asarray(k))
            # Armijo backtracking; only the accept decision is host-side
            g_dot_d = float(jnp.dot(g, direction))
            if g_dot_d >= 0:  # not a descent direction: reset to -g
                direction = -g
                g_dot_d = float(jnp.dot(g, direction))
            f0 = float(f)
            if swept:
                # whole ladder in one device pass + ONE host sync
                f_trials = np.asarray(sweep1(make_trials(w, direction)))
                ok = f_trials <= f0 + 1e-4 * np.asarray(ladder) * g_dot_d
                j = int(np.argmax(ok)) if ok.any() else -1
                accepted = j >= 0
                if accepted:
                    t = float(ladder[j])
                    w_new = w + t * direction
            else:
                t = 1.0
                accepted = False
                for _ls in range(n_ls):
                    w_new = w + t * direction
                    f_new = loss1(w_new)
                    if float(f_new) <= f0 + 1e-4 * t * g_dot_d:
                        accepted = True
                        break
                    t *= 0.5
            if not accepted:
                break  # cannot make progress
            f_new, g_new = cost1(w_new)  # gradient at accepted pt
            s = w_new - w
            yv = g_new - g
            sy = float(jnp.dot(s, yv))
            if sy > 1e-10:  # curvature condition: keep correction
                s_stack, y_stack, rho, k = _push_correction(
                    s_stack, y_stack, rho, k, m, s, yv, sy
                )
            w, f, g = w_new, f_new, g_new
            losses.append(float(f))
            rel = abs(losses[-2] - losses[-1]) / max(
                abs(losses[-2]), abs(losses[-1]), 1.0
            )
            if rel < self.convergence_tol:
                break

        self._loss_history = np.asarray(losses, np.float32)
        return w, self._loss_history


def run_lbfgs(
    data: Dataset,
    gradient: Gradient,
    updater: Updater,
    num_corrections: int,
    convergence_tol: float,
    max_num_iterations: int,
    reg_param: float,
    initial_weights: Array,
    mesh=None,
):
    """Functional entry point, signature-parity with the reference's
    ``object LBFGS.runLBFGS`` ([U] mllib/optimization/LBFGS.scala,
    SURVEY.md §2 #18): same argument order, returns
    ``(weights, loss_history)``.
    """
    opt = LBFGS(
        gradient,
        updater,
        num_corrections=num_corrections,
        convergence_tol=convergence_tol,
        max_num_iterations=max_num_iterations,
        reg_param=reg_param,
    )
    if mesh is not None:
        opt.set_mesh(mesh)
    return opt.optimize_with_history(data, initial_weights)
