"""Opt-in wire format for the host→device hop.

The streamed paths are feed-bound (the 248 s statistics build moves
20 GB through a 0.03–0.16 GB/s link), so bytes-on-the-wire IS the
build time — SparCML's observation (arXiv:1802.08021) applied to the
host→HBM hop instead of the inter-node one.  ``wire_dtype="bfloat16"``
casts each chunk in HOST numpy, transfers half the bytes, and the
device-side consumers upcast to the f32 (or wider) stats dtype before
accumulating — accumulation precision is unchanged; only the INPUT
values are rounded to bf16 (~0.4% relative).

When that is safe: the north-star host dataset is already bf16 (zero
rounding — the wire matches the data), and SGD on f32 data tolerates
input rounding far below its own sampling noise.  When it is not: runs
that must be bit-reproducible against an f32 resident build, or data
whose information lives below bf16's 8 mantissa bits.  The default is
always OFF (``wire_dtype=None`` = transfer at the data dtype).

bf16 halves the bytes of EVERY element; the compressed sparse wire
(``tpu_sgd/io/sparse_wire.py``, ``wire_compress="topk:<frac>"``) goes
further for *update-shaped* data — ship only the top-k coordinates and
carry the rest in an error-feedback accumulator (README "Compressed
wire"; ADVICE.md "Error feedback is optimizer state, not a transport
detail").
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _as_dtype(name) -> np.dtype:
    """``np.dtype`` that also understands ``"bfloat16"`` (an ml_dtypes
    extension type plain numpy cannot name)."""
    if isinstance(name, str) and name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _is_floating(dt: np.dtype) -> bool:
    """Floating check that covers the ml_dtypes extension types (bf16 is
    not an ``np.floating`` subtype — ``np.issubdtype`` alone rejects
    exactly the dtype this module exists for)."""
    if np.issubdtype(dt, np.floating):
        return True
    try:
        import ml_dtypes

        ml_dtypes.finfo(dt)  # raises for non-float extension types
        return True
    except Exception:
        return False


def resolve_wire_dtype(wire_dtype, data_dtype) -> Optional[np.dtype]:
    """The host-side cast target for a streaming path, or None for
    "transfer as-is" (no cast, bit-identical wire).

    ``None`` passes through; a wire dtype equal to the data dtype also
    resolves to None (nothing to cast — e.g. bf16 wire on the bf16
    north-star host set is already free).  Non-floating wire dtypes
    raise: an int wire would silently truncate every element.
    """
    if wire_dtype is None:
        return None
    wd = _as_dtype(wire_dtype)
    if not _is_floating(wd):
        raise ValueError(
            f"wire_dtype must be a floating dtype, got {wd}; "
            "use 'bfloat16' (half the bytes) or None (data dtype)"
        )
    if wd == np.dtype(data_dtype):
        return None
    return wd


def wire_cast(a: np.ndarray, wire: Optional[np.dtype]) -> np.ndarray:
    """Host-numpy cast to the resolved wire dtype (identity when the
    wire is None or already matches — zero-copy)."""
    a = np.asarray(a)
    if wire is None or a.dtype == wire:
        return a
    return a.astype(wire)
