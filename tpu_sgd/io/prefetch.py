"""Bounded-lookahead background producer (the double buffer).

One worker thread runs ``producer(item)`` — host-side chunk assembly
(slice / gather / pad / wire cast; for the superstep executor the item
is a BASE iteration and the producer assembles the whole K-batch
superchunk, ``tpu_sgd.io.stack_superchunk``) plus the ``device_put``
dispatch — while the consumer thread runs the current chunk's device
kernel.  The
worker holds no JAX state of its own: ``device_put`` and jit dispatch
are thread-safe, and numpy releases the GIL for the bulk copies, so the
two genuinely overlap (measured on this repo's serving threads and in
``bench_ingest.py``).

Semantics the consumers rely on:

* ORDER — one worker thread, FIFO submission: results arrive in item
  order, always.
* EXCEPTIONS — a producer error re-raises at the consumer's ``next()``
  for exactly that item (not earlier, not swallowed); the prefetcher
  then closes itself, cancelling queued work.
* BOUNDED STAGING — at most ``depth`` chunks are materialized at once
  INCLUDING the one the consumer holds (the default 2 = one being
  consumed + one in flight — at most ``depth - 1`` staged ahead), so
  the device-side staging footprint is ``depth``× one chunk.
  ``plan.choose_streamed_build`` budgets for the default 2; deeper
  depths grow the footprint proportionally — shrink ``batch_rows``
  when raising depth on a tight device.
* ``depth<=1`` — synchronous passthrough (no thread): one chunk
  materialized at a time, the exact legacy serial loop, kept for
  bitwise A/B tests, debugging, and single-chunk memory budgets.

Reliability (``tpu_sgd/reliability``): every producer call passes the
``io.prefetch.produce`` failpoint (fault-injection hook for chaos
tests), an optional ``retry_policy`` re-runs a failed producer call
with seeded backoff before the error propagates (transient
``device_put``/disk faults heal without killing a 200-second build),
and an optional ``heartbeat`` ticks per produced chunk so a
``HealthMonitor`` can flag a wedged feed as a straggler.
"""

from __future__ import annotations

import collections
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, TypeVar

from tpu_sgd.obs.spans import span
from tpu_sgd.reliability.failpoints import failpoint

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose, and load-bearing as documentation.  The prefetcher owns no
#: lock because all mutable state (_pending, _items, _exhausted, _pool)
#: is touched ONLY from the consumer thread; the worker thread receives
#: work exclusively through executor submission and communicates back
#: exclusively through Futures.  Adding shared state to this module
#: means adding a lock AND declaring it here.
GRAFTLINT_LOCKS: dict = {}

T = TypeVar("T")
R = TypeVar("R")


class Prefetcher:
    """Iterate ``producer(item) for item in items`` with background
    lookahead.  Use as an iterator; call :meth:`close` (or leave a
    ``with`` block) to cancel outstanding work on early exit — a
    convergence break must not leave a worker streaming chunks nobody
    will consume."""

    def __init__(self, producer: Callable[[T], R], items: Iterable[T],
                 depth: int = 2, *, retry_policy=None, heartbeat=None):
        if int(depth) < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        self._producer = producer
        self._retry_policy = retry_policy
        self._heartbeat = heartbeat
        self._items = iter(items)
        self._depth = int(depth)
        self._pending = collections.deque()
        self._pool = None
        self._exhausted = False
        if self._depth > 1:  # <=1: serial — one chunk live at a time
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-sgd-ingest")
            self._fill()

    def _run_producer(self, item: T) -> R:
        """One produce, through the failpoint (inside the retry scope,
        so an injected one-shot fault is healed by the retry — the
        contract the reliability tests pin) and the heartbeat."""
        def attempt():
            failpoint("io.prefetch.produce")
            return self._producer(item)

        # spans are per-thread, so this one nests under whatever the
        # WORKER thread has open (nothing, usually) rather than under
        # the consumer's training span — which also tags the producer's
        # device_put bytes as `ingest`, not `train` (obs.counters)
        with span("ingest.produce"):
            if self._retry_policy is not None:
                out = self._retry_policy.call(attempt)
            else:
                out = attempt()
        if self._heartbeat is not None:
            self._heartbeat.beat()
        return out

    def _fill(self) -> None:
        # pending is capped at depth-1: the consumer's in-hand chunk plus
        # the pending window together stay within the depth-chunk staging
        # budget (a cap of depth here would materialize depth+1 chunks)
        cap = self._depth - 1
        while not self._exhausted and len(self._pending) < cap:
            try:
                item = next(self._items)
            except StopIteration:
                self._exhausted = True
                return
            self._pending.append(self._pool.submit(self._run_producer, item))

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> R:
        if self._depth <= 1:  # synchronous passthrough
            return self._run_producer(next(self._items))
        if self._pool is None:
            raise StopIteration  # closed
        if not self._pending:
            self.close()
            raise StopIteration
        fut = self._pending.popleft()
        self._fill()  # keep the lookahead window full while we wait
        try:
            return fut.result()
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        """Cancel queued work and release the worker.  Idempotent; the
        in-flight producer call (if any) is left to finish — its result
        is dropped."""
        pool, self._pool = self._pool, None
        self._pending.clear()
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
