"""Fixed-shape chunk planning for host→device streaming.

The planner's one job is shape discipline: every chunk it emits has the
SAME row count, so the per-chunk device programs (stats kernel, prefix
scan, donated accumulate) compile exactly once per build — the tail is
padded with zero rows in HOST numpy (never an eager device op; see the
eager-op shape-compile trap note in ``tpu_sgd/serve/engine.py``).  Zero
rows are exact for every consumer in this codebase: they contribute
exact zeros to Gram sums, and the prefix running sum repeats its carry
through zero blocks, so padded prefix rows hold the same value as the
last valid row.

``round_to`` aligns the fixed shape to the consumer's block size ``B``
so a padded tail is whole zero BLOCKS — the valid blocks then run
through bit-identical ``(B, d)`` matmuls and the f32-wire pipelined
build is bitwise equal to the legacy sync build (asserted in
``tests/test_io.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import numpy as np

from tpu_sgd.reliability.failpoints import failpoint


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One planned chunk: source rows ``[start, stop)`` materialized at
    the plan's fixed ``rows`` shape (``pad`` trailing zero rows)."""

    index: int
    start: int
    stop: int
    rows: int

    @property
    def valid(self) -> int:
        return self.stop - self.start

    @property
    def pad(self) -> int:
        return self.rows - self.valid


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Fixed-shape cover of host rows ``[offset, n)``.

    Every chunk is ``chunk_rows`` rows (a multiple of ``round_to``);
    only the LAST chunk may carry padding, always trailing, never
    interleaved with valid rows.  When the covered span is itself a
    multiple of ``round_to`` (the prefix builds: ``n_used = nbf · B``),
    the pad is whole zero groups — the bitwise-equality guarantee.  A
    ragged span (the totals builds, which count every row) leaves ONE
    group partially valid, zero-padded to the group boundary; consumers
    that truncate to whole groups (``valid // B``) must feed
    group-aligned spans.
    """

    n: int
    offset: int
    chunk_rows: int
    round_to: int

    @property
    def n_chunks(self) -> int:
        span = self.n - self.offset
        return -(-span // self.chunk_rows) if span > 0 else 0

    @property
    def pad_rows(self) -> int:
        """Zero rows appended to the final chunk."""
        span = self.n - self.offset
        return self.n_chunks * self.chunk_rows - span

    def __iter__(self) -> Iterator[Chunk]:
        for i in range(self.n_chunks):
            start = self.offset + i * self.chunk_rows
            yield Chunk(index=i, start=start,
                        stop=min(start + self.chunk_rows, self.n),
                        rows=self.chunk_rows)


def plan_chunks(n: int, chunk_rows: int, *, offset: int = 0,
                round_to: int = 1) -> ChunkPlan:
    """Plan fixed-shape chunks over rows ``[offset, n)``.

    ``chunk_rows`` is rounded down to a multiple of ``round_to`` (the
    consumer's block size), then CLAMPED so a dataset smaller than one
    requested chunk gets one right-sized chunk instead of a mostly-pad
    transfer (``streamed_totals_chunking``'s ``batch_rows`` caps flow in
    here unchanged — the cap bounds the fixed shape, the clamp keeps the
    shape tight).  ``offset`` supports resumed builds: checkpoints save
    at chunk boundaries, so a resumed plan's chunks land on the same
    rows as the uninterrupted plan's remaining chunks.
    """
    n = int(n)
    offset = int(offset)
    round_to = max(1, int(round_to))
    if not 0 <= offset <= n:
        raise ValueError(f"offset {offset} outside [0, {n}]")
    if offset % round_to:
        raise ValueError(
            f"offset {offset} is not a multiple of round_to={round_to} "
            "(resume checkpoints save at block boundaries)"
        )
    chunk_rows = max(round_to, (int(chunk_rows) // round_to) * round_to)
    span = n - offset
    span_rounded = -(-span // round_to) * round_to  # pad only to blocks
    chunk_rows = min(chunk_rows, max(span_rounded, round_to))
    return ChunkPlan(n=n, offset=offset, chunk_rows=chunk_rows,
                     round_to=round_to)


def stack_superchunk(xs: Sequence[np.ndarray], ys: Sequence[np.ndarray],
                     valids: Sequence[np.ndarray], k: Optional[int] = None):
    """Stack per-step host batches into ONE ``(K, ...)`` *superchunk*.

    The superstep executor's host stage (README "Fused stepping"): K
    consecutive iterations' cap-shaped batches become one contiguous
    buffer per leaf, so the host→device hop is ONE ``device_put`` per
    superstep instead of one per iteration.  All work is host numpy —
    one memcpy per batch, never an eager device op (the shape-trap
    rule) — and the output shape is FIXED at ``k`` steps: when fewer
    than ``k`` batches are passed (the tail superstep of a run whose
    iteration count ``k`` does not divide), the missing steps stay zero
    rows with all-False valid masks, which the fused step's empty-batch
    rule turns into no-op updates.  One shape → the fused scan program
    compiles exactly once per build.

    Passes the ``io.superstep`` failpoint (fault-injection site for the
    chaos/reliability tests); assembly runs on the prefetch worker
    inside the retry scope, so an armed fault here heals through the
    feed's ``RetryPolicy`` like any other producer fault.

    Returns ``(Xs, Ys, Vs)`` with shapes ``(k,) + batch.shape``.
    """
    failpoint("io.superstep")
    if not xs or len(xs) != len(ys) or len(xs) != len(valids):
        raise ValueError(
            f"need matching non-empty batch lists, got "
            f"{len(xs)}/{len(ys)}/{len(valids)}")
    k = len(xs) if k is None else int(k)
    if k < len(xs):
        raise ValueError(f"{len(xs)} batches do not fit k={k} steps")
    Xs = np.zeros((k,) + xs[0].shape, xs[0].dtype)
    Ys = np.zeros((k,) + ys[0].shape, ys[0].dtype)
    Vs = np.zeros((k,) + valids[0].shape, bool)
    for t, (Xb, yb, vb) in enumerate(zip(xs, ys, valids)):
        Xs[t] = Xb
        Ys[t] = yb
        Vs[t] = vb
    return Xs, Ys, Vs


def pad_rows(a: np.ndarray, rows: int,
             dtype: Optional[np.dtype] = None) -> np.ndarray:
    """Fixed-shape host-numpy padding (+ optional wire cast).

    Returns ``a`` itself (zero-copy) when it already has ``rows`` rows
    and the target dtype; otherwise allocates a ``rows``-row zero buffer
    of the target dtype and copies ``a`` in (numpy casts on assignment,
    so pad and wire cast are one host pass).  All shape-dependent work
    happens HERE, on host — the device only ever sees the fixed shape.
    """
    a = np.asarray(a)
    dt = np.dtype(dtype) if dtype is not None else a.dtype
    if a.shape[0] == rows and a.dtype == dt:
        return a
    if a.shape[0] > rows:
        raise ValueError(f"{a.shape[0]} rows do not fit a {rows}-row chunk")
    out = np.zeros((rows,) + a.shape[1:], dt)
    out[: a.shape[0]] = a
    return out
