"""End-to-end data integrity: checksummed frames, verified at consume.

Every failure this codebase handled before ISSUE 15 was *loud* — an
exception, a timeout, a fenced epoch.  A flipped bit in a prefetched
chunk, a NaN in a compressed push segment, or a truncated delta-log
record is *silent*: it passes straight into the weights and surfaces,
if ever, as unexplained loss divergence — and under bounded staleness
(arXiv:1505.04956) a single poisoned contribution admitted at τ>0
contaminates every subsequent version with no synchronous barrier to
catch it.  This module turns corruption into a **detected, typed,
healed** failure class (ADVICE.md "Corruption is a payload, not an
exception"):

* :func:`seal` computes a CRC-32 over a frame's host bytes (dtype and
  shape included, so a truncated segment can never alias a shorter
  valid one) at the PRODUCE site;
* :func:`verify` recomputes it at the CONSUME site — after the frame
  crossed whatever hop the caller distrusts (the corrupting failpoints
  in ``tpu_sgd/reliability/failpoints.py`` model that hop) — and a
  mismatch raises the typed :class:`IntegrityError` plus bumps the
  ``integrity.corrupt`` / ``integrity.corrupt.<site>`` counters the
  :class:`~tpu_sgd.obs.detect.IntegrityDetector` watches.

:class:`IntegrityError` subclasses ``RuntimeError`` ON PURPOSE: the
default :class:`~tpu_sgd.reliability.retry.RetryPolicy` retryable set
includes ``RuntimeError``, so every verified wire heals through the
retry machinery that already guards it — and because every producer in
this codebase is deterministic in ``(seed, iteration)``, the healed
retry reproduces the frame bit-for-bit (the chaos soak's
healed-run-is-BITWISE invariant, ``scripts/chaos_soak.py`` phase 1g).
The one consumer that must NOT retry — ``CheckpointManager.restore``'s
latest-default path — instead quarantines the proven-bad file and
falls back, composing with the existing corruption/transient
carve-outs (``tpu_sgd/utils/checkpoint.py``).

Checksums are pure HOST work over bytes the producers already hold, so
the integrity plane adds ZERO dispatches, compiles, or host syncs on
the warmed hot paths (the PR 8 pin discipline, re-asserted with
checksums on in ``tests/test_integrity.py``).  :func:`set_integrity`
exists for the bench A/B arm (``bench_integrity.py`` measures the
checksum wall in isolation), not as a production recommendation.
"""

from __future__ import annotations

import zlib
from typing import Optional

import numpy as np

from tpu_sgd.obs.counters import inc
from tpu_sgd.obs.spans import event

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose.  The only mutable module state is the ``_ENABLED`` bool —
#: a GIL-atomic reference flip read by hot paths and written only by
#: test/bench harnesses (the failpoints/obs gate idiom).
GRAFTLINT_LOCKS: dict = {}

#: fast-path gate: :func:`seal` reads this ONE module global and
#: returns None when falsy — frames then carry no checksum and
#: :func:`verify` skips (``expected is None``).  Default ON: the
#: checksum is host CRC-32 over bytes the producer already assembled.
_ENABLED = True


class IntegrityError(RuntimeError):
    """A frame failed its integrity check at ``site``.

    ``kind`` names the check that failed (``"checksum"`` today;
    ``"poison"`` is spelled as a typed ``PushResult.poisoned`` at the
    store's admission guard instead — a rejected push is a protocol
    answer, not an unwind).  Subclasses ``RuntimeError`` so the default
    ``RetryPolicy`` treats it as transient: the producers are
    deterministic in ``(seed, iteration)``, so the healing retry
    replays the exact frame and the healed run is bitwise the
    fault-free one."""

    def __init__(self, site: str, kind: str = "checksum",
                 detail: str = ""):
        self.site = site
        self.kind = kind
        msg = f"integrity violation at {site!r} ({kind})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def set_integrity(enabled: bool) -> None:
    """Bench/test switch for the checksummed-wire plane (see module
    docstring).  The poison-admission guard and the rollback controller
    are NOT gated here — they live in ``tpu_sgd/replica``."""
    global _ENABLED
    _ENABLED = bool(enabled)


def integrity_enabled() -> bool:
    return _ENABLED


def checksum_arrays(*arrays) -> int:
    """CRC-32 over the concatenated ``(dtype, shape, bytes)`` of every
    array (None leaves hash a sentinel so positional structure is
    covered too).  Dtype and shape ride the digest ON PURPOSE: a
    truncated frame must fail even when its surviving bytes are intact,
    and a bf16 frame must never verify against its f32 twin."""
    c = 0
    for a in arrays:
        if a is None:
            c = zlib.crc32(b"<none>", c)
            continue
        a = np.ascontiguousarray(a)
        c = zlib.crc32(repr((a.dtype.str, a.shape)).encode(), c)
        try:
            c = zlib.crc32(a.data, c)  # zero-copy buffer view
        except (ValueError, BufferError):
            # extension dtypes (ml_dtypes bf16) refuse the buffer
            # protocol: digest their raw bytes instead (one copy)
            c = zlib.crc32(a.tobytes(), c)
    return c


def seal(*arrays) -> Optional[int]:
    """Produce-site checksum of a frame, or None when the integrity
    plane is disabled (the bench A/B arm) — a None seal makes the
    matching :func:`verify` a no-op, so the two sides always agree on
    whether the wire is checksummed."""
    if not _ENABLED:
        return None
    return checksum_arrays(*arrays)


def verify(site: str, expected: Optional[int], *arrays) -> None:
    """Consume-site check: recompute the frame's checksum and compare.

    A mismatch is a DETECTED corruption: the ``integrity.corrupt`` /
    ``integrity.corrupt.<site>`` counters bump (the window series the
    ``IntegrityDetector`` trips on), one typed ``integrity.corrupt_frame``
    event lands on the trace, and the typed :class:`IntegrityError`
    raises for the site's retry machinery to heal.  ``expected=None``
    (unsealed frame — integrity disabled, or a legacy producer) skips.
    """
    if expected is None:
        return
    actual = checksum_arrays(*arrays)
    if actual != expected:
        inc("integrity.corrupt")
        inc(f"integrity.corrupt.{site}")
        event("integrity.corrupt_frame", site=site, kind="checksum")
        raise IntegrityError(
            site, "checksum",
            f"crc {actual:#010x} != sealed {expected:#010x}")
    inc(f"integrity.verified.{site}")
