"""Shared host→device ingestion layer.

Every streaming path in this codebase ultimately does the same three
things: cut a host-resident dataset into chunks, move each chunk to a
device, and hand it to a compiled consumer.  Before this package each
path hand-rolled that loop — synchronous full-width ``device_put`` with
zero transfer/compute overlap, and a differently-shaped tail chunk that
recompiled the per-chunk kernels (the eager-op shape-compile trap).
``BENCH_LAST_TPU.json`` puts the cost on the record: the streamed
statistics build is feed-bound at ``build_s=248.2 s`` while the compute
side idles at 0.024 ms/iter.

Three pieces, composed by the streaming consumers (``ops/gram.py``
builders, ``parallel/gram_parallel.py`` meshed builders,
``optimize/streamed.py`` host-streamed SGD):

* :mod:`tpu_sgd.io.chunking` — a chunk planner that emits FIXED-SHAPE
  chunks; the tail is padded in host numpy so the device-side consumer
  compiles exactly one body program (MLlib keeps the pipeline full
  between stages, arXiv:1505.06807 — our stage boundary is the host
  link).
* :mod:`tpu_sgd.io.prefetch` — a bounded-lookahead background producer:
  chunk ``k+1``'s host assembly + ``device_put`` runs on a worker
  thread while chunk ``k``'s kernel executes.  ``depth=2`` is the
  classic double buffer (one chunk being consumed + one in flight), so
  the staging footprint is ~2× one chunk — size ``batch_rows``
  accordingly (``plan.choose_streamed_build`` does).
* :mod:`tpu_sgd.io.wire` — an opt-in bf16 wire format: cast on host,
  transfer half the bytes, upcast/accumulate in f32 on device (the
  SparCML shrink-bytes-on-the-wire move, arXiv:1802.08021, applied to
  the host→HBM hop).
* :mod:`tpu_sgd.io.sparse_wire` — the compressed sparse wire: top-k +
  error-feedback ``(indices, values)`` segments for update-shaped data
  (``wire_compress="topk:<frac>"``; the dropped mass is carried, never
  lost) and fixed-nse BCOO chunk staging for the host-streamed sparse
  feed — see README "Compressed wire".

The superstep executor (``GradientDescent.set_superstep``; README
"Fused stepping") composes with all three: ``stack_superchunk``
(:mod:`tpu_sgd.io.chunking`) bundles K per-iteration batches into one
fixed-shape *superchunk* on the prefetch worker, so both the transfer
count AND the program-dispatch count drop K-fold — the AdaBatch
aggregation lever (arXiv:1711.01761) applied to the dispatch tax.

See README "Ingestion pipeline" for when the bf16 wire is safe and how
``batch_rows`` interacts with the double buffer's 2× staging footprint.
"""

from tpu_sgd.io.chunking import (Chunk, ChunkPlan, pad_rows, plan_chunks,
                                 stack_superchunk)
from tpu_sgd.io.prefetch import Prefetcher
from tpu_sgd.io.sparse_wire import (ErrorFeedback, parse_wire_compress,
                                    plan_sparse_batches, stage_sparse_batch,
                                    topk_nnz, topk_select)
from tpu_sgd.io.wire import resolve_wire_dtype, wire_cast

#: default lookahead of every pipelined streaming path (double buffer)
DEFAULT_PREFETCH_DEPTH = 2

__all__ = [
    "Chunk",
    "ChunkPlan",
    "DEFAULT_PREFETCH_DEPTH",
    "ErrorFeedback",
    "Prefetcher",
    "pad_rows",
    "parse_wire_compress",
    "plan_chunks",
    "plan_sparse_batches",
    "resolve_wire_dtype",
    "stack_superchunk",
    "stage_sparse_batch",
    "topk_nnz",
    "topk_select",
    "wire_cast",
]
