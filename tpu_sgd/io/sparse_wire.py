"""Compressed sparse wire: top-k + error feedback, and fixed-nse BCOO chunks.

The bf16 wire (``tpu_sgd/io/wire.py``) halved the bytes on the
host→device hop; this module moves the SparCML lever (arXiv:1802.08021)
the rest of the way for the two wires that carry *update-shaped* data —
only the bytes that matter cross the link:

* **top-k + error feedback** — a gradient/update vector is reduced to
  its ``k`` largest-magnitude entries as ``(indices, values)`` segments;
  the dropped mass is NOT lost but carried in a persistent
  *error-feedback accumulator* that is added back before the next
  selection, so every coordinate's contribution eventually ships
  (EF-SGD: convergent at matched final loss where plain top-k is not).
  The host-side selection (:class:`ErrorFeedback`, the per-shard
  Gram/totals merge wire in ``parallel/gram_parallel.py``) runs in HOST
  numpy — an eager ``jnp.argsort``/gather here would compile one
  program per novel shape, the eager-op shape-compile trap.  The
  device-side selection (``make_compressed_step`` in
  ``optimize/gradient_descent.py``, the data-parallel all-reduce wire)
  uses ``jax.lax.top_k`` with a STATIC ``k`` inside the traced step, so
  it is shape-stable by construction and the EF state rides the
  superstep scan carry.

* **fixed-nse BCOO chunk staging** — the host-streamed sparse feed
  (``optimize/streamed_sparse.py``) moves batches as ``(data, indices)``
  component arrays padded to ONE fixed ``(rows, nse)`` shape per build
  (:func:`plan_sparse_batches` + :func:`stage_sparse_batch`), so the
  device consumer compiles exactly one body program and a ~0.1%-nnz
  RCV1-shaped batch ships ~100-1000x fewer bytes than its dense-f32
  chunk.  Padding entries are *null entries* — value 0.0 at local
  (0, 0), the same construction as ``parallel/sparse_parallel.py`` —
  which contribute exactly zero to both matvecs; no chunk is ever
  densified anywhere on the path.

Error feedback is OPTIMIZER STATE, not a transport detail: the
accumulator changes which update reaches the weights, so it must live
in the checkpoint (the drivers persist it through
``CheckpointManager.save(extras={"ef": ...})``) and in the scan carry —
see ADVICE.md "Error feedback is optimizer state, not a transport
detail" and README "Compressed wire".

``wire_compress`` spec format: ``"topk:<frac>"`` — keep the top
``frac`` fraction of coordinates (e.g. ``"topk:0.01"`` ships ~1% of
the entries; physical bytes are ``2 * frac`` of the dense wire since
each entry carries an int32 index alongside its f32 value).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from tpu_sgd.io.integrity import seal, verify
from tpu_sgd.obs.counters import record_wire
from tpu_sgd.reliability.failpoints import corruptpoint, failpoint


def parse_wire_compress(spec) -> Optional[float]:
    """Validate a ``wire_compress`` spec; returns the top-k fraction or
    None (no compression).  Accepted: ``None``, ``"topk:<frac>"`` with
    ``0 < frac <= 1``.  Raises on anything else — a typo must fail at
    ``set_ingest_options`` time, not mid-build."""
    if spec is None:
        return None
    if not isinstance(spec, str) or not spec.startswith("topk:"):
        raise ValueError(
            f"wire_compress must be 'topk:<frac>' or None, got {spec!r}"
        )
    try:
        frac = float(spec[len("topk:"):])
    except ValueError:
        raise ValueError(
            f"wire_compress fraction is not a number: {spec!r}"
        ) from None
    if not 0.0 < frac <= 1.0:
        raise ValueError(
            f"wire_compress fraction must be in (0, 1], got {frac}"
        )
    return frac


def topk_nnz(dim: int, frac: float) -> int:
    """Entries kept per compressed update: ``ceil(frac * dim)``, at
    least 1, at most ``dim`` — ONE definition shared by the host wire,
    the traced step, and the byte accounting."""
    return int(max(1, min(int(dim), int(np.ceil(int(dim) * float(frac))))))


def topk_select(v: np.ndarray, k: int) -> np.ndarray:
    """Host-numpy indices of the ``k`` largest-|v| entries (int32,
    unordered — scatter-add is order-free).  ``argpartition`` keeps the
    selection O(dim), not O(dim log dim)."""
    v = np.asarray(v)
    k = int(min(k, v.shape[0]))
    if k >= v.shape[0]:
        return np.arange(v.shape[0], dtype=np.int32)
    return np.argpartition(np.abs(v), -k)[-k:].astype(np.int32)


class ErrorFeedback:
    """Persistent host-side error-feedback accumulator for one wire.

    ``compress(update)`` folds the update into the accumulator, extracts
    the top-k ``(indices, values)`` segment, and KEEPS the rest — the
    dropped mass is carried into the next selection, never lost.
    ``residual()`` surfaces what is still unsent (the merge wires flush
    it as one dense add at the end, making the merged total exact up to
    f.p. reassociation).  ``state()``/``load_state()`` round-trip the
    accumulator through a checkpoint: error feedback is optimizer
    state, and a resumed compressed run must select from the same
    accumulator to stay bitwise.
    """

    def __init__(self, dim: int, frac: float, dtype=np.float32):
        self.dim = int(dim)
        self.frac = float(frac)
        self.k = topk_nnz(self.dim, self.frac)
        self.acc = np.zeros((self.dim,), dtype)

    def compress(self, update: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """``(indices int32, values)`` of the top-k of accumulator +
        update; the selected coordinates are zeroed in the accumulator
        (their mass ships), the rest stays.  All host numpy.  Passes the
        ``io.sparse_wire`` failpoint — THE compress/stage fault-injection
        site — and ships the segment as a checksummed FRAME through the
        ``io.segment`` corrupting failpoint, verified here at the
        extraction boundary (tpu_sgd/io/integrity.py).  Both heal under
        the caller's retry machinery: NOTHING mutates (accumulator
        included) until every check passes, so a healed retry replays
        nothing twice and the reselected segment is bit-identical."""
        failpoint("io.sparse_wire")
        update = np.asarray(update).reshape(-1)
        if update.shape[0] != self.dim:
            raise ValueError(
                f"update has {update.shape[0]} entries, accumulator has "
                f"{self.dim}"
            )
        # NOT in place (see docstring); the explicit compute-then-cast
        # matches the old ``acc += update`` bits for any update dtype
        folded = np.add(self.acc, update).astype(self.acc.dtype,
                                                 copy=False)
        idx = topk_select(folded, self.k)
        vals = folded[idx].copy()
        ck = seal(idx, vals)
        idx, vals = corruptpoint("io.segment", (idx, vals))
        verify("io.segment", ck, idx, vals)
        self.acc = folded
        self.acc[idx] = 0.0
        record_wire("topk", logical_nbytes=int(update.nbytes),
                    physical_nbytes=int(vals.nbytes + idx.nbytes))
        return idx, vals

    def restore_segment(self, idx: np.ndarray, vals: np.ndarray) -> None:
        """Fold an extracted-but-NOT-delivered segment back into the
        accumulator — the rejected-push path of the bounded-staleness
        wire (``tpu_sgd/replica``): a stale push is discarded whole, and
        discarding must return the selected mass to the accumulator or
        the rejection silently drops gradient.  Scatter-ADD, not set:
        later updates may have deposited new mass on the same
        coordinates since the extraction."""
        np.add.at(self.acc, np.asarray(idx, np.int64),
                  np.asarray(vals, self.acc.dtype))

    def residual(self) -> np.ndarray:
        """Copy of the still-unsent mass (the merge wires' final dense
        flush; does NOT clear — call :meth:`clear` after flushing)."""
        return self.acc.copy()

    def clear(self) -> None:
        self.acc[:] = 0.0

    def state(self) -> np.ndarray:
        """Checkpointable accumulator state (see class docstring)."""
        return self.acc.copy()

    def load_state(self, acc: np.ndarray) -> None:
        acc = np.asarray(acc).reshape(-1)
        if acc.shape[0] != self.dim:
            raise ValueError(
                f"checkpointed accumulator has {acc.shape[0]} entries, "
                f"this wire needs {self.dim}"
            )
        self.acc = acc.astype(self.acc.dtype, copy=True)


# -- SparCML stream aggregation (arXiv:1802.08021) ---------------------------


def _merge_pair(a, b):
    """Merge two sparse ``(indices, values)`` segments into one
    deduplicated segment: concatenate, stable-sort by index, and
    reduce runs of equal indices with ``np.add.reduceat`` — within a
    run the summation order is the concatenation order (stable sort),
    so the merge is a deterministic function of its inputs."""
    idx = np.concatenate([a[0], b[0]])
    vals = np.concatenate([a[1], b[1]])
    order = np.argsort(idx, kind="stable")
    idx = idx[order]
    vals = vals[order]
    starts = np.flatnonzero(np.r_[True, idx[1:] != idx[:-1]])
    return idx[starts], np.add.reduceat(vals, starts)


def merge_sparse_segments(segments, dim: int,
                          density_crossover: float = 0.25) -> np.ndarray:
    """SparCML stream aggregation (arXiv:1802.08021) of top-k
    ``(indices, values)`` contributions: merge segments PAIRWISE up a
    tree — each round halves the segment count while the merged
    segments stay sparse — and switch to a DENSE accumulator the
    moment any merged segment's density (nnz / dim) crosses
    ``density_crossover``, scatter-adding the remaining segments into
    it.  The crossover is the paper's representation switch: a sparse
    merge costs O(nnz log nnz) per pair and re-pays only while the
    union stays sparse; once contributions overlap enough that the
    union approaches dense, the O(dim) dense add is strictly cheaper.
    The threshold is a cost-model knob
    (``plan.CostModel.sparse_merge_density``) plumbed next to
    ``wire_compress_frac``.

    Returns the DENSE f32 sum vector of shape ``(dim,)`` — the sharded
    store's apply consumes a dense accumulator either way
    (``tpu_sgd/replica/shard.py``).  Deterministic given the segment
    ORDER (the caller passes payloads in shard order), which is what
    keeps a primary and its standby bitwise against each other: both
    replay the identical segment list through this identical tree.
    Segments may be empty; duplicate indices WITHIN a segment are
    summed (scatter-add semantics, matching the dense scatter the flat
    gather used)."""
    dim = int(dim)
    segs = []
    for si, sv in segments:
        si = np.asarray(si, np.int64).reshape(-1)
        sv = np.asarray(sv, np.float32).reshape(-1)
        if si.size:
            segs.append((si, sv))
    if not segs:
        return np.zeros((dim,), np.float32)
    nnz_cap = max(1, int(np.ceil(float(density_crossover) * dim)))
    while len(segs) > 1:
        merged = []
        for j in range(0, len(segs) - 1, 2):
            merged.append(_merge_pair(segs[j], segs[j + 1]))
        if len(segs) % 2:
            merged.append(segs[-1])
        segs = merged
        if any(si.size > nnz_cap for si, _ in segs):
            # density crossover: the unions stopped being sparse —
            # finish with one dense accumulator, remaining segments
            # scatter-added in list order (still deterministic)
            out = np.zeros((dim,), np.float32)
            for si, sv in segs:
                np.add.at(out, si, sv)
            return out
    out = np.zeros((dim,), np.float32)
    si, sv = segs[0]
    np.add.at(out, si, sv)
    return out


# -- fixed-nse sparse chunk planning / staging -------------------------------


def plan_sparse_batches(indptr: np.ndarray, sample_rows, num_iterations: int,
                        row_cap: int) -> int:
    """Fixed nse cap covering EVERY batch of a deterministic sampled
    run — the sparse chunk planner's shape-discipline moment.

    The dense chunk planner (``io/chunking.py``) fixes the ROW shape;
    a sparse batch additionally varies in nse, and a per-batch nse
    would compile one device program per novel sparsity (the shape
    trap).  The sample sequence is deterministic in ``(seed, i)``, so
    one cheap host pre-pass over ``sample_rows(i)`` computes the max
    batch nse of the whole run; every staged batch then pads to that
    ONE ``(row_cap, nse_cap)`` shape and the fused body compiles
    exactly once per build (``assert_compile_count``-pinned in
    tests/test_sparse_wire.py).  A resumed run re-plans over the SAME
    full iteration range, so its cap — and its compiled program —
    match the uninterrupted run's.

    ``indptr``: CSR row pointers of the host matrix; ``sample_rows(i)``
    returns iteration ``i``'s row ids (truncated to ``row_cap``
    exactly as the producer truncates).  Returns ``nse_cap >= 1``.
    """
    row_nnz = np.diff(np.asarray(indptr)).astype(np.int64)
    cap = 1
    for i in range(1, int(num_iterations) + 1):
        rows = np.asarray(sample_rows(i))[:row_cap]
        nse = int(row_nnz[rows].sum())
        if nse > cap:
            cap = nse
    return cap


def gather_csr_rows(indptr: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                    rows: np.ndarray):
    """Host-numpy CSR row gather: entries of ``rows`` (in order) with
    LOCAL row ids ``0..len(rows)-1``.  Returns ``(lrows, lcols, lvals)``
    flat entry arrays.  Vectorized — one ``np.repeat`` + ranged index,
    no per-row Python loop."""
    rows = np.asarray(rows)
    starts = indptr[rows]
    counts = (indptr[rows + 1] - starts).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return (np.zeros((0,), np.int32), np.zeros((0,), np.int32),
                np.zeros((0,), vals.dtype))
    # flat positions: for each selected row r, the range
    # [indptr[r], indptr[r+1]) — built as offsets into a repeat
    base = np.repeat(starts, counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts)
    pos = base + within
    lrows = np.repeat(np.arange(rows.shape[0], dtype=np.int32), counts)
    return lrows, cols[pos].astype(np.int32), vals[pos]


def stage_sparse_batch(indptr: np.ndarray, cols: np.ndarray,
                       vals: np.ndarray, rows: np.ndarray, row_cap: int,
                       nse_cap: int):
    """Assemble one fixed-shape sparse batch in HOST numpy.

    Returns ``(data (nse_cap,), idx (nse_cap, 2) int32, valid
    (row_cap,) bool)``: the entries of ``rows`` at local row ids, padded
    with *null entries* (0.0 at (0, 0) — exact zero contribution to
    both matvecs, the ``sparse_parallel`` construction) to the planned
    ``nse_cap`` and ``row_cap``.  Passes the ``io.sparse_wire``
    failpoint (the stage site; runs on the prefetch worker inside the
    retry scope like every producer).  The wire-byte accounting lives
    at the TRANSFER site (the streamed driver's producer), which sees
    every leaf that actually crosses — components, labels, and mask —
    so the recorded ratio compares like payloads."""
    failpoint("io.sparse_wire")
    lrows, lcols, lvals = gather_csr_rows(indptr, cols, vals, rows)
    nse = lvals.shape[0]
    if nse > nse_cap:
        raise ValueError(
            f"batch carries {nse} entries but the plan capped nse at "
            f"{nse_cap} (the pre-pass and the producer must share one "
            "sample rule)"
        )
    data = np.zeros((nse_cap,), vals.dtype)
    idx = np.zeros((nse_cap, 2), np.int32)
    data[:nse] = lvals
    idx[:nse, 0] = lrows
    idx[:nse, 1] = lcols
    valid = np.zeros((row_cap,), bool)
    valid[: rows.shape[0]] = True
    return data, idx, valid


def bcoo_to_csr_host(X):
    """Host CSR view ``(indptr, cols, vals, (n, d))`` of a BCOO matrix —
    the one-time relayout the streamed sparse feed samples from
    (``host_entries`` drops jax's out-of-bounds padding sentinels and
    establishes row-major order, so ``searchsorted`` yields exact row
    pointers)."""
    from tpu_sgd.ops.sparse import host_entries

    n, d = X.shape
    rows, cols, vals = host_entries(X)
    indptr = np.searchsorted(rows, np.arange(int(n) + 1)).astype(np.int64)
    return indptr, np.asarray(cols, np.int32), np.asarray(vals), \
        (int(n), int(d))
