"""Summary statistics.

Reference parity: [U] mllib/stat/Statistics.scala (``colStats``, ``corr``)
and [U] mllib/stat/MultivariateOnlineSummarizer.scala — the column-summary
surface the reference's users run over ``RDD[Vector]`` before training
(SURVEY.md §2 #12's MLUtils sits next to it in the same util tier).

TPU-first design: the reference folds a treeAggregate of summarizer objects
(one JVM merge per partition); here ``col_stats`` is ONE jitted fused
reduction over the device-resident matrix, and the correlation matrix is a
single MXU Gram pass (``Xc^T @ Xc`` on centered columns) instead of the
reference's pairwise column cogroup — O(n d^2) FLOPs the systolic array
eats, with no shuffle.  Sparse (BCOO) inputs get the same statistics from
scatter-adds over ``data``/``indices`` without densifying.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sgd.ops.sparse import is_sparse


class MultivariateStatisticalSummary:
    """Value object mirroring [U] MultivariateStatisticalSummary: ``mean``,
    ``variance`` (sample, n-1), ``count``, ``num_nonzeros``, ``max``,
    ``min``, ``norm_l1``, ``norm_l2`` — all per column, host numpy."""

    def __init__(self, mean, variance, count, num_nonzeros, mx, mn, l1, l2):
        self.mean = np.asarray(mean)
        self.variance = np.asarray(variance)
        self.count = int(count)
        self.num_nonzeros = np.asarray(num_nonzeros)
        self.max = np.asarray(mx)
        self.min = np.asarray(mn)
        self.norm_l1 = np.asarray(l1)
        self.norm_l2 = np.asarray(l2)


@jax.jit
def _dense_col_stats(X):
    n = X.shape[0]
    mean = jnp.mean(X, axis=0)
    var = jnp.sum((X - mean) ** 2, axis=0) / jnp.maximum(n - 1, 1)
    nnz = jnp.sum(X != 0, axis=0)
    return (
        mean,
        var,
        nnz,
        jnp.max(X, axis=0),
        jnp.min(X, axis=0),
        jnp.sum(jnp.abs(X), axis=0),
        jnp.sqrt(jnp.sum(X * X, axis=0)),
    )


def _bcoo_col_stats(X):
    """Same statistics without densifying.  Implicit zeros participate in
    mean/variance/min/max exactly as the reference's summarizer counts them
    (a column whose stored values are all positive still has min 0 when any
    row lacks an entry)."""
    n, d = X.shape
    cols = X.indices[:, 1]
    vals = X.data.astype(jnp.float32)
    # jax's out-of-bounds nse sentinels (the ops/sparse.host_entries
    # invariant) can be bad in EITHER coordinate; scatter mode='drop' only
    # catches a bad destination column, so mask on both explicitly.
    valid = (X.indices[:, 0] < n) & (cols < d)
    vals = jnp.where(valid, vals, 0.0)
    s1 = jnp.zeros((d,), jnp.float32).at[cols].add(vals, mode="drop")
    s2 = jnp.zeros((d,), jnp.float32).at[cols].add(vals * vals, mode="drop")
    l1 = jnp.zeros((d,), jnp.float32).at[cols].add(jnp.abs(vals), mode="drop")
    nnz = (
        jnp.zeros((d,), jnp.int32)
        .at[cols]
        .add(jnp.where((vals != 0) & valid, 1, 0), mode="drop")
    )
    # Stored-entry extrema; fold the implicit zeros in afterwards.
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    mx = jnp.full((d,), -big).at[cols].max(
        jnp.where(valid, vals, -big), mode="drop"
    )
    mn = jnp.full((d,), big).at[cols].min(
        jnp.where(valid, vals, big), mode="drop"
    )
    stored = jnp.zeros((d,), jnp.int32).at[cols].add(
        jnp.where(valid, 1, 0), mode="drop"
    )
    has_zero = stored < n
    mx = jnp.where(has_zero, jnp.maximum(mx, 0.0), mx)
    mn = jnp.where(has_zero, jnp.minimum(mn, 0.0), mn)
    mean = s1 / n
    var = jnp.maximum((s2 - n * mean * mean) / max(n - 1, 1), 0.0)
    return mean, var, nnz, mx, mn, l1, jnp.sqrt(s2)


def column_mean_variance(X):
    """(mean, sample variance) per column, dense or BCOO — the shared
    summarizer kernel ``StandardScaler.fit`` and ``col_stats`` both use, so
    the BCOO sentinel masking lives in exactly one place."""
    if is_sparse(X):
        if X.shape[0] == 0:
            raise ValueError("empty input")
        mean, var = _bcoo_col_stats(X)[:2]
        return mean, var
    X = jnp.asarray(X)
    if X.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got {X.shape}")
    if X.shape[0] == 0:
        raise ValueError("empty input")
    return _dense_col_stats(X)[:2]


def col_stats(X) -> MultivariateStatisticalSummary:
    """[U] ``Statistics.colStats(rdd)`` over a dense or BCOO matrix."""
    if is_sparse(X):
        n = X.shape[0]
        if n == 0:
            raise ValueError("empty input")
        parts = _bcoo_col_stats(X)
    else:
        X = jnp.asarray(X)
        if X.ndim != 2:
            raise ValueError(f"col_stats expects a 2-D matrix, got {X.shape}")
        n = X.shape[0]
        if n == 0:
            raise ValueError("empty input")
        parts = _dense_col_stats(X)
    mean, var, nnz, mx, mn, l1, l2 = parts
    return MultivariateStatisticalSummary(mean, var, n, nnz, mx, mn, l1, l2)


@jax.jit
def _pearson(X):
    n = X.shape[0]
    Xc = X - jnp.mean(X, axis=0)
    # One MXU Gram pass replaces the reference's pairwise column cogroup.
    # HIGHEST precision: the TPU default runs bf16 passes and puts ~5e-4
    # absolute error into every correlation entry (measured), while the
    # sparse path computes at 1e-7 — the two corr() paths must agree.
    cov = jnp.dot(Xc.T, Xc,
                  precision=jax.lax.Precision.HIGHEST) / jnp.maximum(n - 1, 1)
    sd = jnp.sqrt(jnp.diag(cov))
    denom = jnp.outer(sd, sd)
    corr = jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-38), jnp.nan)
    # Exact ones on the diagonal (the reference returns 1.0 there even for
    # near-constant columns with defined variance).
    eye = jnp.eye(X.shape[1], dtype=bool)
    return jnp.where(eye & (sd > 0)[None, :], 1.0, corr)


def _ranks(X):
    """Average-tie column ranks (1-based), the Spearman prerequisite."""
    X = np.asarray(X, np.float64)
    n, d = X.shape
    out = np.empty_like(X)
    for j in range(d):  # host-side; ranking is a one-time O(n log n) per col
        col = X[:, j]
        order = np.argsort(col, kind="stable")
        ranks = np.empty(n, np.float64)
        ranks[order] = np.arange(1, n + 1, dtype=np.float64)
        # average ties
        uniq, inv, counts = np.unique(
            col, return_inverse=True, return_counts=True
        )
        sums = np.zeros(uniq.size, np.float64)
        np.add.at(sums, inv, ranks)
        out[:, j] = sums[inv] / counts[inv]
    return out


def _pearson_bcoo(X):
    """Pearson for BCOO without materializing the dense n x d matrix: the
    raw Gram comes from a sparse-sparse ``X^T @ X`` (only the d x d result —
    which IS the output size — goes dense), and centering folds in
    analytically: cov = (G - n * outer(mean, mean)) / (n - 1)."""
    n, d = X.shape
    G = jnp.asarray((X.T @ X).todense(), jnp.float32)
    mean, _ = column_mean_variance(X)
    cov = (G - n * jnp.outer(mean, mean)) / max(n - 1, 1)
    sd = jnp.sqrt(jnp.maximum(jnp.diag(cov), 0.0))
    denom = jnp.outer(sd, sd)
    corr_m = jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-38), jnp.nan)
    eye = jnp.eye(d, dtype=bool)
    return jnp.where(eye & (sd > 0)[None, :], 1.0, corr_m)


def corr(X, method: str = "pearson") -> np.ndarray:
    """[U] ``Statistics.corr(rdd, method)``: full correlation matrix.

    ``pearson`` is one jitted MXU Gram pass (sparse inputs use a
    sparse-sparse Gram — only the d x d result, i.e. the output itself, is
    ever dense); ``spearman`` ranks columns host-side (average ties, the
    reference's convention) then reuses the same device pass on the ranks.
    Spearman over BCOO would densify through the rank transform (implicit
    zeros all get the same mid-rank), so it asks for an explicit dense
    matrix instead of silently allocating one.
    """
    if is_sparse(X):
        if method == "pearson":
            return np.asarray(_pearson_bcoo(X))
        if method == "spearman":
            raise ValueError(
                "spearman over sparse features requires the dense rank "
                "transform; pass X.todense() explicitly if n x d fits"
            )
        raise ValueError(f"unknown correlation method {method!r}")
    X = np.asarray(X, np.float32)
    if X.ndim != 2:
        raise ValueError(f"corr expects a 2-D matrix, got {X.shape}")
    if method == "pearson":
        return np.asarray(_pearson(jnp.asarray(X)))
    if method == "spearman":
        return np.asarray(_pearson(jnp.asarray(_ranks(X), dtype=jnp.float32)))
    raise ValueError(f"unknown correlation method {method!r}")
