"""tpu_sgd: a TPU-native framework with the capabilities of
``Patrickgsheng/spark-parallelized-sgd`` (Spark-MLlib-style parallelized
mini-batch SGD for generalized linear models).

The reference's capability contract is preserved — the
Optimizer × Gradient × Updater plugin boundary, the model families
(Linear/Lasso/Ridge regression, logistic regression, linear SVM, streaming
variants), seeded mini-batch sampling, loss history, convergence tolerance,
and sparse (BCOO) feature training that never densifies — re-designed
TPU-first: fused XLA matvec gradient steps, a whole-run ``lax.while_loop``
driver, and ``shard_map`` + ``lax.psum`` data parallelism over ICI for
dense rows and equal-nse sparse blocks alike.  See SURVEY.md for the
reference analysis this build follows.
"""

from tpu_sgd.config import MeshConfig, SGDConfig
from tpu_sgd.evaluation import (BinaryClassificationMetrics,
                                MulticlassMetrics, RegressionMetrics)
from tpu_sgd.feature import Normalizer, StandardScaler, StandardScalerModel
from tpu_sgd.linalg import BLAS, DenseVector, SparseVector, Vectors
from tpu_sgd.models import *  # noqa: F401,F403
from tpu_sgd.models import __all__ as _models_all
from tpu_sgd.ops import *  # noqa: F401,F403
from tpu_sgd.ops import __all__ as _ops_all
from tpu_sgd.optimize import (GradientDescent, LBFGS, NormalEquations,
                              OWLQN, Optimizer, run_lbfgs,
                              run_mini_batch_sgd)
from tpu_sgd.parallel import data_mesh, make_mesh
# NOTE: the bare `plan` FUNCTION is deliberately not re-exported here —
# `from tpu_sgd.plan import x` would still work, but the package attribute
# `tpu_sgd.plan` must keep naming the MODULE (an `import tpu_sgd.plan as m`
# resolves the package attribute and would get the function instead).
from tpu_sgd.plan import (CostModel, Plan, device_budget, plan_for,
                          plan_quasi_newton)
from tpu_sgd.stat import MultivariateStatisticalSummary, col_stats, corr
# serving subsystem (imported last: it builds on models + utils above)
from tpu_sgd.serve import (BackpressureError, ModelRegistry, PredictEngine,
                           Server)

__version__ = "0.1.0"

__all__ = (
    ["SGDConfig", "MeshConfig", "Vectors", "DenseVector", "SparseVector", "BLAS"]
    + list(_models_all)
    + list(_ops_all)
    + ["GradientDescent", "LBFGS", "NormalEquations", "OWLQN", "Optimizer",
       "run_mini_batch_sgd", "run_lbfgs",
       "data_mesh", "make_mesh",
       "CostModel", "Plan", "device_budget", "plan_for",
       "plan_quasi_newton",
       "Normalizer", "StandardScaler", "StandardScalerModel",
       "RegressionMetrics", "BinaryClassificationMetrics",
       "MulticlassMetrics",
       "col_stats", "corr", "MultivariateStatisticalSummary",
       "Server", "ModelRegistry", "PredictEngine", "BackpressureError"]
)
