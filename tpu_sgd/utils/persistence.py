"""Model persistence: save/load with versioned metadata.

Reference parity: [U] mllib/regression/impl/GLMRegressionModel.scala and the
``Saveable``/``Loader`` contract (SURVEY.md §2 #19, §5.4): weights +
intercept + metadata (class name, format version, numFeatures) persisted
durably.  The reference writes Parquet through Spark SQL; the TPU-native
equivalent is an ``.npz`` of arrays plus a JSON metadata sidecar — same
contract, no JVM.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

FORMAT_VERSION = "1.0"


def save_glm_model(path: str, model) -> None:
    """Persist a GLM model directory: ``metadata.json`` + ``data.npz``."""
    import glob as _glob
    import uuid

    os.makedirs(path, exist_ok=True)
    for stale in _glob.glob(os.path.join(path, ".*.tmp")):
        try:  # a crash mid-save orphaned these; sweep before writing
            os.remove(stale)
        except OSError:
            pass
    weights = np.asarray(model.weights)
    save_id = uuid.uuid4().hex
    meta = {
        "class": type(model).__name__,
        "version": FORMAT_VERSION,
        "numFeatures": int(getattr(model, "num_features", weights.shape[-1])),
        "intercept": float(model.intercept),
        "threshold": getattr(model, "threshold", None),
        "saveId": save_id,
    }
    if hasattr(model, "num_classes"):
        meta["numClasses"] = int(model.num_classes)
        meta["hasInterceptColumn"] = bool(
            getattr(model, "has_intercept_column", False)
        )
    # tmp + fsync + atomic rename per file (the checkpoint manager's
    # durability pattern), with a shared saveId as the cross-file
    # transaction marker: each file is torn-proof on its own, and a
    # crash BETWEEN the two replaces (new weights + stale metadata)
    # surfaces as a clear mismatch error at load instead of silently
    # returning the wrong intercept/threshold with the new weights
    def _durable_write(name, writer):
        final = os.path.join(path, name)
        tmp = os.path.join(path, "." + name + ".tmp")
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    _durable_write(
        "data.npz",
        lambda f: np.savez(f, weights=weights,
                           save_id=np.asarray(save_id)),
    )
    _durable_write(
        "metadata.json", lambda f: f.write(json.dumps(meta).encode())
    )


def load_glm_model(path: str, cls, strict_class: bool = True):
    """Load a model saved by :func:`save_glm_model` as an instance of
    ``cls``; validates class name and format version like the reference's
    ``Loader.load``."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    if meta["version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported model format version {meta['version']}")
    if strict_class and meta["class"] != cls.__name__:
        raise ValueError(
            f"model at {path} is a {meta['class']}, expected {cls.__name__}"
        )
    data = np.load(os.path.join(path, "data.npz"))
    if "save_id" in data.files and "saveId" in meta:
        if str(data["save_id"]) != meta["saveId"]:
            raise ValueError(
                f"model directory {path!r} is torn: metadata.json and "
                "data.npz come from different saves (a crash interrupted "
                "an overwrite) — re-save the model"
            )
    import inspect

    accepts_classes = "num_classes" in inspect.signature(cls.__init__).parameters
    if "numClasses" in meta and accepts_classes:
        model = cls(
            data["weights"],
            meta["intercept"],
            num_classes=meta["numClasses"],
            num_features=meta["numFeatures"],
            has_intercept_column=meta.get("hasInterceptColumn", False),
        )
    else:
        model = cls(data["weights"], meta["intercept"])
    thr: Optional[float] = meta.get("threshold")
    if hasattr(model, "threshold"):
        model.threshold = thr
    return model
