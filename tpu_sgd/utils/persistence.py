"""Model persistence: save/load with versioned metadata.

Reference parity: [U] mllib/regression/impl/GLMRegressionModel.scala and the
``Saveable``/``Loader`` contract (SURVEY.md §2 #19, §5.4): weights +
intercept + metadata (class name, format version, numFeatures) persisted
durably.  The reference writes Parquet through Spark SQL; the TPU-native
equivalent is an ``.npz`` of arrays plus a JSON metadata sidecar — same
contract, no JVM.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

FORMAT_VERSION = "1.0"


def save_glm_model(path: str, model) -> None:
    """Persist a GLM model directory: ``metadata.json`` + ``data.npz``."""
    os.makedirs(path, exist_ok=True)
    weights = np.asarray(model.weights)
    meta = {
        "class": type(model).__name__,
        "version": FORMAT_VERSION,
        "numFeatures": int(getattr(model, "num_features", weights.shape[-1])),
        "intercept": float(model.intercept),
        "threshold": getattr(model, "threshold", None),
    }
    if hasattr(model, "num_classes"):
        meta["numClasses"] = int(model.num_classes)
        meta["hasInterceptColumn"] = bool(
            getattr(model, "has_intercept_column", False)
        )
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f)
    np.savez(os.path.join(path, "data.npz"), weights=weights)


def load_glm_model(path: str, cls, strict_class: bool = True):
    """Load a model saved by :func:`save_glm_model` as an instance of
    ``cls``; validates class name and format version like the reference's
    ``Loader.load``."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    if meta["version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported model format version {meta['version']}")
    if strict_class and meta["class"] != cls.__name__:
        raise ValueError(
            f"model at {path} is a {meta['class']}, expected {cls.__name__}"
        )
    data = np.load(os.path.join(path, "data.npz"))
    import inspect

    accepts_classes = "num_classes" in inspect.signature(cls.__init__).parameters
    if "numClasses" in meta and accepts_classes:
        model = cls(
            data["weights"],
            meta["intercept"],
            num_classes=meta["numClasses"],
            num_features=meta["numFeatures"],
            has_intercept_column=meta.get("hasInterceptColumn", False),
        )
    else:
        model = cls(data["weights"], meta["intercept"])
    thr: Optional[float] = meta.get("threshold")
    if hasattr(model, "threshold"):
        model.threshold = thr
    return model
