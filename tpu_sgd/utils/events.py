"""Tracing / metrics / observability.

Reference parity: SURVEY.md §5.1 and §5.5 — Spark's event-bus
(``SparkListenerEvent`` per job/stage/task, JSON event log, per-task
``TaskMetrics``) and the ``Logging`` trait.  The TPU-native analogues:

  * :class:`SGDListener` — per-iteration callbacks (the analogue of listener
    events; each reference iteration is a visible Spark job).  Attaching a
    listener switches the optimizer to its step-wise traced path, trading the
    single fused XLA program for full per-iteration host observability.
  * :class:`JsonLinesEventLog` — the analogue of ``spark.eventLog.enabled``:
    append-only JSONL of run/iteration events.
  * :func:`profile_trace` — wraps ``jax.profiler`` (TensorBoard/Perfetto),
    the analogue of the Spark web UI's task-level timeline.
  * :class:`StepTimer` — wall-clock per-call timing harness built on
    ``block_until_ready`` (SURVEY.md §5.1 "step-time log").
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import asdict, dataclass
from typing import List, Optional


@dataclass
class IterationEvent:
    """One optimizer iteration (the analogue of a Spark job for one
    treeAggregate round)."""

    iteration: int
    loss: float
    weight_delta_norm: float
    mini_batch_size: int
    wall_time_s: float


@dataclass
class RunEvent:
    """Run-level summary (the analogue of SparkListenerJobEnd + logged
    loss history, SURVEY.md §5.5)."""

    event: str  # "run_started" | "run_completed"
    num_iterations: int = 0
    final_loss: Optional[float] = None
    converged_early: bool = False
    wall_time_s: float = 0.0


class SGDListener:
    """Override any subset; attached via ``GradientDescent.set_listener``."""

    def on_run_start(self, config) -> None: ...

    def on_iteration(self, event: IterationEvent) -> None: ...

    def on_run_end(self, event: RunEvent) -> None: ...


class CollectingListener(SGDListener):
    """Buffers every event in memory (test/introspection helper)."""

    def __init__(self):
        self.iterations: List[IterationEvent] = []
        self.runs: List[RunEvent] = []

    def on_run_start(self, config):
        self.runs.append(RunEvent(event="run_started"))

    def on_iteration(self, event):
        self.iterations.append(event)

    def on_run_end(self, event):
        self.runs.append(event)


class JsonLinesEventLog(SGDListener):
    """Append-only JSONL event log (the ``spark.eventLog`` analogue)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")

    def _write(self, kind: str, payload: dict):
        self._f.write(json.dumps({"kind": kind, "ts": time.time(),
                                  **payload}, default=float) + "\n")
        self._f.flush()

    def on_run_start(self, config):
        self._write("run_started", {"config": asdict(config)})

    def on_iteration(self, event: IterationEvent):
        self._write("iteration", asdict(event))

    def on_run_end(self, event: RunEvent):
        self._write("run_completed", asdict(event))

    def close(self):
        self._f.close()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``jax.profiler`` trace context — open the result in TensorBoard or
    Perfetto (SURVEY.md §5.1 TPU equivalent of the Spark web UI)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Step-time harness.  Use :meth:`timed_call` for jitted functions —
    it blocks on the result (``jax.block_until_ready``) so device work is
    included; the raw :meth:`time` context manager measures plain wall clock
    of the enclosed block (async dispatch is NOT awaited)."""

    def __init__(self):
        self.times: List[float] = []

    def timed_call(self, fn, *args, **kwargs):
        """Call ``fn``, block until its outputs are ready, record the time."""
        import jax

        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
            out = jax.block_until_ready(out)
        finally:
            # same contract as time(): failed work still spent the clock
            self.times.append(time.perf_counter() - t0)
        return out

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # a raising timed block still spent the wall clock; dropping
            # it would skew mean_s optimistic
            self.times.append(time.perf_counter() - t0)

    @property
    def mean_s(self) -> float:
        return sum(self.times) / max(len(self.times), 1)
