"""Tracing / metrics / observability.

Reference parity: SURVEY.md §5.1 and §5.5 — Spark's event-bus
(``SparkListenerEvent`` per job/stage/task, JSON event log, per-task
``TaskMetrics``) and the ``Logging`` trait.  The TPU-native analogues:

  * :class:`SGDListener` — per-iteration callbacks (the analogue of listener
    events; each reference iteration is a visible Spark job).  Attaching a
    listener switches the optimizer to its step-wise traced path, trading the
    single fused XLA program for full per-iteration host observability.
  * :class:`JsonLinesEventLog` — the analogue of ``spark.eventLog.enabled``:
    append-only JSONL of run/iteration events.
  * :func:`profile_trace` — wraps ``jax.profiler`` (TensorBoard/Perfetto),
    the analogue of the Spark web UI's task-level timeline.
  * :class:`StepTimer` — wall-clock per-call timing harness built on
    ``block_until_ready`` (SURVEY.md §5.1 "step-time log").
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import asdict, dataclass
from typing import List, Optional


#: graftlint lock-discipline declaration (tpu_sgd/analysis): the JSONL
#: file handle is shared by the serving flush thread, user threads, and
#: close() — every write/flush/close must hold the write lock so lines
#: stay whole and close never races a writer.
GRAFTLINT_LOCKS = {
    "JsonLinesEventLog": {
        "_f": "_write_lock",
    },
}


@dataclass
class IterationEvent:
    """One optimizer iteration (the analogue of a Spark job for one
    treeAggregate round)."""

    iteration: int
    loss: float
    weight_delta_norm: float
    mini_batch_size: int
    wall_time_s: float


@dataclass
class RunEvent:
    """Run-level summary (the analogue of SparkListenerJobEnd + logged
    loss history, SURVEY.md §5.5)."""

    event: str  # "run_started" | "run_completed"
    num_iterations: int = 0
    final_loss: Optional[float] = None
    converged_early: bool = False
    wall_time_s: float = 0.0


@dataclass
class ServeBatchEvent:
    """One coalesced serving batch (tpu_sgd/serve) — the observability
    record for the micro-batching path: how deep the queue ran, how many
    requests coalesced, the padded bucket actually compiled against, the
    oldest request's end-to-end latency, cumulative rejects, and which
    model version answered.

    ``enqueue_depth`` is the queue depth the batch's OLDEST request saw
    at its own enqueue, and ``deadline_slack_s`` is how much of the
    flush deadline was left when the batch actually flushed (negative =
    the deadline was missed by that much) — the two admission-control
    inputs: sustained high enqueue depth says shed earlier, sustained
    negative slack says the deadline is unkeepable at this load.

    ``lanes`` (ISSUE 12) is the batch's priority-lane composition:
    ``{lane: {"n": rows, "max_latency_s": worst end-to-end latency of
    that lane's rows in this batch}}`` — what the per-lane p99 SLOs in
    ``obs.report`` evaluate over (a per-batch lane MAX, so the offline
    p99 is a conservative upper estimate of the per-request p99).

    All extras default (old readers of the JSONL stream and positional
    constructors keep working; new records simply carry more keys).
    """

    queue_depth: int
    batch_size: int
    padded_size: int
    latency_s: float
    reject_count: int
    model_version: int
    enqueue_depth: int = 0
    deadline_slack_s: float = 0.0
    lanes: Optional[dict] = None


@dataclass
class ServeReloadEvent:
    """A serving model hot-reload attempt (serve/registry.py): either a
    successful atomic swap to ``version`` or a rejected load (corrupt /
    unreadable checkpoint) with the retained previous-good version."""

    event: str  # "reloaded" | "load_failed"
    version: int
    previous_version: Optional[int] = None
    error: Optional[str] = None


@dataclass
class ReliabilityEvent:
    """One reliability observation (tpu_sgd/reliability): a component
    heartbeat, a flagged straggler, a queue-depth sample, a supervisor
    retry/preemption/resume, or a quarantined checkpoint.  Logged as
    ``reliability_<kind>`` JSONL records so an incident replay can
    filter them with one prefix match."""

    kind: str    # "heartbeat" | "straggler" | "queue_depth" | "retry" | ...
    source: str  # emitting component, e.g. "prefetcher" | "supervisor"
    value: float = 0.0
    detail: str = ""


class SGDListener:
    """Override any subset; attached via ``GradientDescent.set_listener``."""

    def on_run_start(self, config) -> None: ...

    def on_iteration(self, event: IterationEvent) -> None: ...

    def on_run_end(self, event: RunEvent) -> None: ...

    def on_serve_batch(self, event: ServeBatchEvent) -> None: ...

    def on_serve_reload(self, event: ServeReloadEvent) -> None: ...

    def on_reliability(self, event: ReliabilityEvent) -> None: ...


class CollectingListener(SGDListener):
    """Buffers every event in memory (test/introspection helper)."""

    def __init__(self):
        self.iterations: List[IterationEvent] = []
        self.runs: List[RunEvent] = []
        self.serve_batches: List[ServeBatchEvent] = []
        self.serve_reloads: List[ServeReloadEvent] = []
        self.reliability: List[ReliabilityEvent] = []

    def on_run_start(self, config):
        self.runs.append(RunEvent(event="run_started"))

    def on_iteration(self, event):
        self.iterations.append(event)

    def on_run_end(self, event):
        self.runs.append(event)

    def on_serve_batch(self, event):
        self.serve_batches.append(event)

    def on_serve_reload(self, event):
        self.serve_reloads.append(event)

    def on_reliability(self, event):
        self.reliability.append(event)


class JsonLinesEventLog(SGDListener):
    """Append-only JSONL event log (the ``spark.eventLog`` analogue).

    ``fsync=True`` forces each record to stable storage before the
    write returns — the durability knob for post-mortem forensics (a
    host preemption must not eat the events explaining it).  Default
    off: an fsync per event is an O(ms) tax the serving flush thread
    cannot afford in steady state.
    """

    def __init__(self, path: str, fsync: bool = False):
        import threading

        self.path = path
        self.fsync = bool(fsync)
        self._f = open(path, "a")
        # the serving subsystem logs from its flush thread while user
        # threads log reloads/bulk scores through the same instance; the
        # lock keeps every JSONL line whole (a torn line breaks replay)
        self._write_lock = threading.Lock()

    def _write(self, kind: str, payload: dict):
        line = json.dumps({"kind": kind, "ts": time.time(),
                           **payload}, default=float) + "\n"
        with self._write_lock:
            if self._f.closed:
                return  # closed mid-shutdown: drop, don't raise in servers
            self._f.write(line)
            self._f.flush()
            if self.fsync:
                import os

                os.fsync(self._f.fileno())

    def emit(self, kind: str, payload: dict) -> None:
        """Public record-writer for EXTERNAL producers on this log's
        contract — the observability layer (``tpu_sgd.obs``) emits its
        ``trace_span``/``trace_event``/``metric_counters`` records
        through here, so traces interleave with the listener events on
        one lock-serialized, torn-tail-tolerant JSONL stream that
        ``read()`` (and ``obs.report``) replays whole.  ``payload``'s
        own ``ts`` (the producer's timestamp) wins over the write-time
        default."""
        self._write(kind, payload)

    def on_run_start(self, config):
        self._write("run_started", {"config": asdict(config)})

    def on_iteration(self, event: IterationEvent):
        self._write("iteration", asdict(event))

    def on_run_end(self, event: RunEvent):
        self._write("run_completed", asdict(event))

    def on_serve_batch(self, event: ServeBatchEvent):
        self._write("serve_batch", asdict(event))

    def on_serve_reload(self, event: ServeReloadEvent):
        self._write("serve_reload", asdict(event))

    def on_reliability(self, event: ReliabilityEvent):
        payload = asdict(event)
        # the record's kind IS the prefixed form; the raw sub-kind field
        # would otherwise win the dict merge in _write and erase the
        # reliability_ prefix replay filters key on
        del payload["kind"]
        self._write(f"reliability_{event.kind}", payload)

    def close(self):
        with self._write_lock:  # never close out from under a writer
            self._f.close()

    @staticmethod
    def read(path: str):
        """Parse an event log back into a list of dicts.

        A crash (or preemption, without ``fsync=True``) can leave the
        final line torn mid-record; that trailing partial line is
        SKIPPED — losing the last event is the expected cost of a crash,
        not corruption.  Every record is written as one line ending in
        ``\\n``, so a torn tail is recognizable by the MISSING final
        newline; a malformed line that IS newline-terminated (anywhere,
        including last) still raises: that is real corruption replay
        must not paper over."""
        events = []
        with open(path) as f:
            content = f.read()
        lines = [ln for ln in content.split("\n") if ln.strip()]
        unterminated_tail = bool(content) and not content.endswith("\n")
        for i, ln in enumerate(lines):
            try:
                events.append(json.loads(ln))
            except json.JSONDecodeError:
                if i == len(lines) - 1 and unterminated_tail:
                    break  # crash-truncated tail: tolerate
                raise
        return events


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``jax.profiler`` trace context — open the result in TensorBoard or
    Perfetto (SURVEY.md §5.1 TPU equivalent of the Spark web UI)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Step-time harness.  Use :meth:`timed_call` for jitted functions —
    it blocks on the result (``jax.block_until_ready``) so device work is
    included; the raw :meth:`time` context manager measures plain wall clock
    of the enclosed block (async dispatch is NOT awaited)."""

    def __init__(self):
        self.times: List[float] = []

    def timed_call(self, fn, *args, **kwargs):
        """Call ``fn``, block until its outputs are ready, record the time."""
        import jax

        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
            out = jax.block_until_ready(out)
        finally:
            # same contract as time(): failed work still spent the clock
            self.times.append(time.perf_counter() - t0)
        return out

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # a raising timed block still spent the wall clock; dropping
            # it would skew mean_s optimistic
            self.times.append(time.perf_counter() - t0)

    @property
    def mean_s(self) -> float:
        return sum(self.times) / max(len(self.times), 1)
