"""Data loading / generation utilities.

Reference parity: [U] mllib/util/MLUtils.scala (SURVEY.md §2 #12, §3.4):
``loadLibSVMFile`` parses 1-based-indexed sparse text into labeled points;
``saveAsLibSVMFile`` writes it back; ``appendBias`` appends a 1.0 feature.
Also mirrors the reference's synthetic data generators
([U] mllib/util/{Linear,LogisticRegression,SVM}DataGenerator.scala), which
the reference's test suites and the benchmark configs rely on.

A native C++ fast path for the LIBSVM parser (the analogue of the
reference's executor-side parsing throughput) lives in
``tpu_sgd/utils/native``; this module transparently uses it when built.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def append_bias(X: np.ndarray) -> np.ndarray:
    """Append a 1.0 bias column (parity with ``MLUtils.appendBias``)."""
    X = np.asarray(X)
    return np.concatenate([X, np.ones((X.shape[0], 1), X.dtype)], axis=1)


def _parse_libsvm_python(path: str):
    labels, rows, cols, vals = [], [], [], []
    max_idx = 0
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            r = len(labels) - 1
            for tok in parts[1:]:
                idx, val = tok.split(":")
                j = int(idx) - 1  # 1-based on disk
                if j < 0:
                    raise ValueError(f"invalid 0 index in libsvm file {path}")
                rows.append(r)
                cols.append(j)
                vals.append(float(val))
                max_idx = max(max_idx, j + 1)
    return (
        np.asarray(labels, np.float32),
        np.asarray(rows, np.int64),
        np.asarray(cols, np.int64),
        np.asarray(vals, np.float32),
        max_idx,
    )


def _resolve_input_paths(path: str):
    """Expand ``path`` the way the reference's ``sc.textFile`` does
    ([U] MLUtils.loadLibSVMFile over HDFS paths, SURVEY.md §3.4): a
    directory reads its part files (sorted; Hadoop markers like _SUCCESS
    and hidden files skipped), a glob pattern expands, a plain path is one
    file.  Raises FileNotFoundError when nothing matches."""
    import glob as _glob

    def _is_data_file(p):
        base = os.path.basename(p)
        return (not base.startswith((".", "_"))) and os.path.isfile(p)

    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if _is_data_file(os.path.join(path, f))
        )
    elif os.path.exists(path):
        # literal path wins over glob interpretation — a filename that
        # merely CONTAINS glob chars ("a9a[train].txt") must never be
        # shadowed by whatever its pattern-reading matches; existence (not
        # isfile) so FIFOs / /dev/stdin / process substitutions still load
        files = [path]
    elif any(c in path for c in "*?["):
        files = sorted(p for p in _glob.glob(path) if _is_data_file(p))
    else:
        files = []
    if not files:
        raise FileNotFoundError(f"no input files match {path!r}")
    return files


def _parse_one(path):
    try:
        from tpu_sgd.utils.native import parse_libsvm as _native

        return _native(path)
    except Exception:
        return _parse_libsvm_python(path)


def load_libsvm_file(
    path: str,
    num_features: Optional[int] = None,
    dense: bool = True,
    dtype=np.float32,
):
    """Load LIBSVM-format data into ``(X, y)``.

    ``path`` may be one file, a directory of part files, or a glob — the
    reference reads all three through ``sc.textFile`` (SURVEY.md §3.4);
    rows concatenate in sorted-filename order.  ``num_features`` discovery
    scans for the max index, exactly like the reference's one extra reduce
    job.  ``dense=True`` densifies (the TPU-resident layout; config 3's
    "sparse->densified", BASELINE.json:9); ``dense=False`` returns a
    scipy-free CSR triple ``((data, indices, indptr), y, num_features)``.
    """
    files = _resolve_input_paths(path)
    if len(files) == 1:
        labels, rows, cols, vals, max_idx = _parse_one(files[0])
    else:
        parts = [_parse_one(f) for f in files]
        offsets = np.cumsum([0] + [p[0].shape[0] for p in parts[:-1]])
        labels = np.concatenate([p[0] for p in parts])
        rows = np.concatenate(
            [p[1] + off for p, off in zip(parts, offsets)]
        )
        cols = np.concatenate([p[2] for p in parts])
        vals = np.concatenate([p[3] for p in parts])
        max_idx = max(p[4] for p in parts)
    d = num_features if num_features is not None else max_idx
    n = labels.shape[0]
    if rows.size:
        order0 = np.lexsort((cols, rows))
        rs, cs = rows[order0], cols[order0]
        dup = (rs[1:] == rs[:-1]) & (cs[1:] == cs[:-1])
        if dup.any():
            # the dense path would silently last-win while the CSR/BCOO
            # path kept BOTH entries (summing in matvecs) — one file,
            # three different matrices; the reference rejects it too
            j = int(np.nonzero(dup)[0][0])
            raise ValueError(
                f"duplicate feature index {int(cs[j]) + 1} on data line "
                f"{int(rs[j]) + 1} (LIBSVM rows need unique indices)"
            )
    if dense:
        X = np.zeros((n, d), dtype)
        X[rows, cols] = vals
        return X, labels
    # CSR without scipy (order0 computed by the duplicate check above;
    # rows/cols are unchanged since)
    order = order0 if rows.size else np.zeros((0,), np.int64)
    rows, cols, vals = rows[order], cols[order], vals[order]
    indptr = np.zeros((n + 1,), np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    return (vals.astype(dtype), cols, indptr), labels, d


def _save_partitioned(path: str, n_items: int, num_partitions: int,
                      write_slice) -> None:
    """Shared ``saveAsTextFile`` directory layout: refuse an existing
    output path, write ``part-NNNNN`` slices by even row bounds, then the
    ``_SUCCESS`` marker.  ``write_slice(part_path, lo, hi)`` writes one
    part file."""
    if os.path.exists(path):
        # Spark's saveAsTextFile refuses an existing output path: a
        # rewrite with fewer partitions would otherwise leave stale part
        # files that the directory loader silently mixes in.
        raise FileExistsError(
            f"output path {path!r} already exists; remove it first "
            "(saveAsTextFile semantics)"
        )
    os.makedirs(path)
    bounds = np.linspace(0, n_items, num_partitions + 1).astype(int)
    for p in range(num_partitions):
        write_slice(
            os.path.join(path, f"part-{p:05d}"),
            int(bounds[p]), int(bounds[p + 1]),
        )
    open(os.path.join(path, "_SUCCESS"), "w").close()


def save_as_libsvm_file(path: str, X, y: np.ndarray,
                        num_partitions: int = 1) -> None:
    """Write ``(X, y)`` in 1-based LIBSVM text (parity with
    ``MLUtils.saveAsLibSVMFile``, which serves sparse and dense RDDs
    alike); zero entries are dropped.  ``X`` may be a dense array or a
    BCOO matrix — sparse rows are written straight from the entry lists,
    never densified.

    ``num_partitions > 1`` writes ``path`` as a DIRECTORY of part-NNNNN
    files plus a ``_SUCCESS`` marker — the reference's ``saveAsTextFile``
    output layout, read back by ``load_libsvm_file(path)``."""
    from tpu_sgd.ops.sparse import host_entries, is_sparse

    y = np.asarray(y)
    if num_partitions > 1:
        _save_partitioned(
            path, y.shape[0], num_partitions,
            lambda p, lo, hi: save_as_libsvm_file(p, X[lo:hi], y[lo:hi]),
        )
        return
    if is_sparse(X):
        rows, cols, vals = host_entries(X)  # row-major sorted
        n, d = X.shape
        # Coalesce duplicate (i, j) entries (BCOO semantics: values sum —
        # writing them verbatim would be invalid LIBSVM and reload
        # last-wins) and drop stored zeros, matching the dense branch.
        key = rows.astype(np.int64) * d + cols
        uniq, inv = np.unique(key, return_inverse=True)
        summed = np.zeros(uniq.shape, np.float64)
        np.add.at(summed, inv, vals)
        keep = summed != 0.0
        uniq, summed = uniq[keep], summed[keep]
        rows, cols = uniq // d, (uniq % d).astype(np.int64)
        starts = np.searchsorted(rows, np.arange(n))
        ends = np.searchsorted(rows, np.arange(n), side="right")
        cols_l, vals_l = cols.tolist(), summed.tolist()
        y_l = y.tolist()
        with open(path, "w") as f:
            for i in range(n):
                feats = " ".join(
                    f"{cols_l[k] + 1}:{vals_l[k]:.9g}"
                    for k in range(starts[i], ends[i])
                )
                f.write(f"{y_l[i]:.9g} {feats}\n")
        return
    X = np.asarray(X)
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            nz = np.nonzero(X[i])[0]
            feats = " ".join(f"{j + 1}:{X[i, j]:.9g}" for j in nz)
            f.write(f"{y[i]:.9g} {feats}\n")


def load_labeled_points(path: str):
    """Read ``LabeledPoint`` text lines — the reference's OTHER text
    ingestion path ([U] MLUtils.loadLabeledPoints, reading the
    ``LabeledPoint.toString`` forms ``(label,[f0,f1,...])`` and
    ``(label,(size,[indices],[values]))``).  ``path`` may be one file, a
    directory of part files, or a glob, exactly like ``load_libsvm_file``.
    Returns a list of ``LabeledPoint`` (the ``RDD[LabeledPoint]``
    analogue); feed it to ``models.to_arrays`` / any ``train()`` for
    arrays."""
    from tpu_sgd.models.labeled_point import LabeledPoint

    points = []
    for p in _resolve_input_paths(path):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    points.append(LabeledPoint.parse(line))
    return points


def save_labeled_points(path: str, points, num_partitions: int = 1) -> None:
    """Write ``LabeledPoint``s in the reference's text form (the
    ``RDD.saveAsTextFile(points.map(_.toString))`` counterpart that
    ``loadLabeledPoints`` reads back): dense ``(label,[f0,f1,...])``,
    sparse ``(label,(size,[i0,...],[v0,...]))``.  ``num_partitions > 1``
    writes the part-file directory layout like ``save_as_libsvm_file``."""
    from tpu_sgd.linalg import SparseVector

    points = list(points)
    if num_partitions > 1:
        _save_partitioned(
            path, len(points), num_partitions,
            lambda p, lo, hi: save_labeled_points(p, points[lo:hi]),
        )
        return
    with open(path, "w") as f:
        for lp in points:
            feats = lp.features
            if isinstance(feats, SparseVector):
                idx = ",".join(str(int(i)) for i in feats.indices)
                val = ",".join(f"{float(v):.9g}" for v in feats.values)
                f.write(f"({lp.label:.9g},({feats.size},[{idx}],[{val}]))\n")
            else:
                arr = np.asarray(
                    feats.to_array() if hasattr(feats, "to_array") else feats
                ).ravel()
                body = ",".join(f"{float(v):.9g}" for v in arr)
                f.write(f"({lp.label:.9g},[{body}])\n")


def _take_rows(X, idx):
    """Row-select helper shared by the fold utilities: fancy indexing for
    dense arrays, host-side relayout for sparse (BCOO) features.  Bounds
    are validated for BOTH layouts — numpy would resolve a negative
    index to the tail row and the split would silently train on the
    wrong rows (the sparse path raises the same error inside
    ``take_rows_bcoo``)."""
    from tpu_sgd.ops.sparse import is_sparse, take_rows_bcoo

    if is_sparse(X):
        return take_rows_bcoo(X, idx)
    idx = np.asarray(idx)
    n = np.shape(X)[0]  # no device->host copy just to read a shape
    if idx.size and (idx.min() < 0 or idx.max() >= n):
        raise IndexError(
            f"row indices must lie in [0, {n}); got range "
            f"[{idx.min()}, {idx.max()}]"
        )
    return X[idx]


def _num_rows(X) -> int:
    from tpu_sgd.ops.sparse import is_sparse

    return int(X.shape[0]) if is_sparse(X) else int(np.asarray(X).shape[0])


def k_fold(X, y, num_folds: int, seed: int = 42):
    """Yield ``(train, validation)`` splits (parity with ``MLUtils.kFold``,
    which serves sparse and dense RDDs alike): a seeded shuffle partitioned
    into ``num_folds`` disjoint validation folds, each paired with the
    complement as training data.  Accepts dense arrays or BCOO features."""
    n = _num_rows(X)
    if num_folds < 2:
        raise ValueError("num_folds must be >= 2")
    perm = np.random.default_rng(seed).permutation(n)
    folds = np.array_split(perm, num_folds)
    y = np.asarray(y)
    for i in range(num_folds):
        val_idx = folds[i]
        train_idx = np.concatenate([folds[j] for j in range(num_folds) if j != i])
        yield (
            (_take_rows(X, train_idx), y[train_idx]),
            (_take_rows(X, val_idx), y[val_idx]),
        )


def train_test_split(X, y, test_fraction: float = 0.2, seed: int = 42):
    """Seeded shuffle split (the common analogue of ``RDD.randomSplit``);
    accepts dense arrays or BCOO features."""
    n = _num_rows(X)
    perm = np.random.default_rng(seed).permutation(n)
    n_test = int(round(test_fraction * n))
    te, tr = perm[:n_test], perm[n_test:]
    y = np.asarray(y)
    return (_take_rows(X, tr), y[tr]), (_take_rows(X, te), y[te])


# ---------------------------------------------------------------------------
# Synthetic data generators (reference: mllib/util/*DataGenerator.scala)
# ---------------------------------------------------------------------------

def linear_data(
    n: int,
    d: int,
    intercept: float = 0.0,
    weights: Optional[np.ndarray] = None,
    eps: float = 0.1,
    seed: int = 42,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """y = X.w + b + N(0, eps); returns (X, y, true_weights)."""
    rng = np.random.default_rng(seed)
    w = (
        np.asarray(weights, dtype)
        if weights is not None
        else rng.uniform(-1.0, 1.0, size=(d,)).astype(dtype)
    )
    X = rng.normal(size=(n, d)).astype(dtype)
    y = (X @ w + intercept + eps * rng.normal(size=(n,))).astype(dtype)
    return X, y, w


def logistic_data(
    n: int,
    d: int,
    weights: Optional[np.ndarray] = None,
    intercept: float = 0.0,
    seed: int = 42,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Labels in {0,1} drawn from sigmoid(X.w + b); returns (X, y, w)."""
    rng = np.random.default_rng(seed)
    w = (
        np.asarray(weights, dtype)
        if weights is not None
        else rng.uniform(-1.0, 1.0, size=(d,)).astype(dtype)
    )
    X = rng.normal(size=(n, d)).astype(dtype)
    p = 1.0 / (1.0 + np.exp(-(X @ w + intercept)))
    y = (rng.uniform(size=(n,)) < p).astype(dtype)
    return X, y, w


def a9a_like_data(
    n: int,
    seed: int = 42,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Synthetic stand-in matching the REAL a9a's structure (no network in
    this environment, so the genuine LIBSVM file cannot be fetched —
    data/README.md): 123 binary features arranged as the Adult dataset's
    one-hot encoded categorical groups, exactly 14 active features per row
    (one per group), labels from a logistic model over the binary design.
    The result is genuinely sparse (14/123 ≈ 11% density) and binary-valued
    like the original, unlike a dense Gaussian draw.

    Returns ``(X, y, w_true)`` with X dense {0,1} — pass through
    ``save_as_libsvm_file``/``load_libsvm_file`` (or BCOO) as needed."""
    # Adult/a9a one-hot group sizes (workclass, education, marital-status,
    # occupation, relationship, race, sex, native-country, plus the six
    # binned continuous features); sums to 123
    groups = [8, 16, 7, 14, 6, 5, 2, 41, 5, 5, 4, 4, 3, 3]
    assert sum(groups) == 123
    rng = np.random.default_rng(seed)
    d = 123
    w = rng.normal(scale=0.8, size=(d,)).astype(dtype)
    X = np.zeros((n, d), dtype)
    offset = 0
    for g in groups:
        # skewed category frequencies, like real census categories
        probs = rng.dirichlet(np.full((g,), 0.5))
        choice = rng.choice(g, size=(n,), p=probs)
        X[np.arange(n), offset + choice] = 1.0
        offset += g
    margin = X @ w - float(np.mean(X @ w))  # roughly balanced classes
    p_pos = 1.0 / (1.0 + np.exp(-margin))
    y = (rng.uniform(size=(n,)) < p_pos).astype(dtype)
    return X, y, w


def rcv1_like_data(
    n: int,
    d: int = 47_236,
    nnz_per_row: int = 75,
    seed: int = 42,
):
    """Synthetic stand-in matching the REAL RCV1's structure: ``d`` default
    47,236 features with power-law (Zipf) document frequencies, ~75
    nonzeros per row, positive log-tfidf-like values, L2-normalized rows,
    labels from a sparse linear model.  Returns ``(X: BCOO, y, w_true)`` —
    at this width the matrix cannot be densified (18.8 GB at n=100k), which
    is the point of the sparse training path."""
    import jax.numpy as jnp
    from jax.experimental.sparse import BCOO

    rng = np.random.default_rng(seed)
    # Zipf-ish feature popularity: common terms get picked far more often
    pop = 1.0 / np.arange(1, d + 1) ** 0.9
    pop /= pop.sum()
    w = np.zeros((d,), np.float32)
    active = rng.choice(d, size=max(8, d // 100), replace=False, p=pop)
    w[active] = rng.normal(scale=1.5, size=active.shape).astype(np.float32)

    # Per-row weighted sampling WITHOUT replacement, vectorized via
    # Gumbel-top-k (argpartition of log(pop) + Gumbel noise) — a Python
    # loop of rng.choice(..., p=pop) would be O(n*d) and take minutes at
    # the full 47k width; chunking bounds the noise matrix's memory.
    log_pop = np.log(pop).astype(np.float32)
    cols = np.empty((n, nnz_per_row), np.int32)
    vals = np.empty((n, nnz_per_row), np.float32)
    chunk = max(1, min(n, (1 << 27) // max(d, 1)))  # ~512 MB f32 noise cap
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        # dtype=f32 draws directly (uniform() would materialize an f64
        # buffer ~3x the intended cap before the cast)
        u = rng.random(size=(hi - lo, d), dtype=np.float32)
        # guard both logs: u=0 breaks the inner, u=1 the outer
        np.clip(u, np.finfo(np.float32).tiny, 1.0 - 1e-7, out=u)
        gumbel = -np.log(-np.log(u))
        keys = log_pop[None, :] + gumbel
        top = np.argpartition(keys, d - nnz_per_row, axis=1)[:, -nnz_per_row:]
        cols[lo:hi] = np.sort(top, axis=1).astype(np.int32)
        v = rng.lognormal(
            mean=0.0, sigma=0.5, size=(hi - lo, nnz_per_row)
        ).astype(np.float32)
        vals[lo:hi] = v / np.linalg.norm(v, axis=1, keepdims=True)
    rows = np.repeat(np.arange(n, dtype=np.int32), nnz_per_row)
    idx = np.stack([rows, cols.reshape(-1)], axis=1)
    X = BCOO(
        (jnp.asarray(vals.reshape(-1)), jnp.asarray(idx)), shape=(n, d),
        indices_sorted=True, unique_indices=True,
    )
    margins = np.einsum("ij,ij->i", vals, w[cols])
    y = (margins + 0.05 * rng.normal(size=n) > np.median(margins)).astype(
        np.float32
    )
    return X, y, w


def svm_data(
    n: int,
    d: int,
    weights: Optional[np.ndarray] = None,
    intercept: float = 0.0,
    noise: float = 0.1,
    seed: int = 42,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Labels in {0,1} by sign of noisy margin (parity with
    SVMDataGenerator's sign(x.w + noise))."""
    rng = np.random.default_rng(seed)
    w = (
        np.asarray(weights, dtype)
        if weights is not None
        else rng.uniform(-1.0, 1.0, size=(d,)).astype(dtype)
    )
    X = rng.normal(size=(n, d)).astype(dtype)
    margin = X @ w + intercept + noise * rng.normal(size=(n,))
    y = (margin > 0).astype(dtype)
    return X, y, w
