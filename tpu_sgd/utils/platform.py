"""Platform selection helpers for this TPU environment."""

from __future__ import annotations

import os


def honor_cpu_env() -> bool:
    """Re-assert ``JAX_PLATFORMS=cpu`` against site hooks.

    This environment's sitecustomize registers a remote-TPU PJRT plugin and
    force-sets ``jax_platforms="axon,cpu"`` via ``jax.config``, trampling the
    ``JAX_PLATFORMS`` env var; when the TPU tunnel is down any backend init
    then stalls for minutes.  Call this before the first ``jax.devices()`` to
    honor an explicit CPU request.  Returns True when CPU was forced.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
