"""ctypes loader for the native C++ LIBSVM parser.

The reference's data path runs parsing inside executor JVMs (SURVEY.md §3.4);
the TPU framework's native analogue is a small C++ shared library
(``libsvm_parser.cpp``) loaded via ctypes — no pybind11 dependency.  Build it
with ``python -m tpu_sgd.utils.native.build`` (uses g++); all callers fall
back to the pure-Python parser when the library is absent.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libsvm_parser.so")
_lib = None


def _load():
    global _lib
    if _lib is None:
        if not os.path.exists(_LIB_PATH):
            raise ImportError(f"native parser not built at {_LIB_PATH}")
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.parse_libsvm_count.restype = ctypes.c_int64
        _lib.parse_libsvm_count.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),  # n_rows out
            ctypes.POINTER(ctypes.c_int64),  # n_nz out
        ]
        _lib.parse_libsvm_fill.restype = ctypes.c_int64
        _lib.parse_libsvm_fill.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
    return _lib


_SAMPLER_PATH = os.path.join(os.path.dirname(__file__), "batch_sampler.so")
_sampler_lib = None


def _load_sampler():
    global _sampler_lib
    if _sampler_lib is None:
        if not os.path.exists(_SAMPLER_PATH):
            raise ImportError(f"native sampler not built at {_SAMPLER_PATH}")
        _sampler_lib = ctypes.CDLL(_SAMPLER_PATH)
        _sampler_lib.gather_rows.restype = ctypes.c_int64
        _sampler_lib.gather_rows.argtypes = [
            ctypes.c_void_p,  # X
            ctypes.c_int64,   # n_rows
            ctypes.c_int64,   # row_bytes
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,   # m
            ctypes.c_void_p,  # out
            ctypes.c_int64,   # n_threads
        ]
    return _sampler_lib


def gather_rows(
    X: np.ndarray, idx: np.ndarray, out: np.ndarray = None,
    n_threads: int = 8,
) -> np.ndarray:
    """Multi-threaded ``X[idx]`` for 1-D/2-D C-contiguous arrays.

    The host-streamed training path's batch assembly (memcpy-bound; NumPy
    fancy indexing is single-threaded).  ``out`` may be a preallocated
    destination to avoid per-iteration allocation.  Raises ImportError when
    the library is not built — callers fall back to ``X[idx]``.
    """
    lib = _load_sampler()
    if not X.flags.c_contiguous:
        # Copying the whole dataset per call would defeat the point on the
        # >HBM streamed workload; the caller's X[idx] fallback is cheaper.
        raise ValueError(
            "gather_rows needs a C-contiguous X; use X[idx] or "
            "np.ascontiguousarray(X) once at load time"
        )
    idx = np.ascontiguousarray(idx, np.int64)
    row_shape = X.shape[1:]
    row_bytes = int(np.prod(row_shape, dtype=np.int64)) * X.itemsize
    out_shape = (idx.shape[0],) + row_shape
    if out is None:
        out = np.empty(out_shape, X.dtype)
    elif (out.shape != out_shape or out.dtype != X.dtype
          or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous {out_shape} {X.dtype}, got "
            f"{out.shape} {out.dtype} contiguous={out.flags.c_contiguous}"
        )
    rc = lib.gather_rows(
        X.ctypes.data_as(ctypes.c_void_p),
        X.shape[0],
        row_bytes,
        idx,
        idx.shape[0],
        out.ctypes.data_as(ctypes.c_void_p),
        n_threads,
    )
    if rc != 0:
        raise IndexError("gather_rows: index out of range")
    return out


def parse_libsvm(path: str):
    """Parse a LIBSVM file natively -> (labels, rows, cols, vals, max_index)."""
    lib = _load()
    n_rows = ctypes.c_int64()
    n_nz = ctypes.c_int64()
    rc = lib.parse_libsvm_count(path.encode(), ctypes.byref(n_rows), ctypes.byref(n_nz))
    if rc != 0:
        raise IOError(f"native parser failed to open/scan {path} (rc={rc})")
    labels = np.empty((n_rows.value,), np.float32)
    rows = np.empty((n_nz.value,), np.int64)
    cols = np.empty((n_nz.value,), np.int64)
    vals = np.empty((n_nz.value,), np.float32)
    max_idx = lib.parse_libsvm_fill(path.encode(), labels, rows, cols, vals)
    if max_idx < 0:
        raise IOError(f"native parser failed to parse {path} (rc={max_idx})")
    return labels, rows, cols, vals, int(max_idx)
