"""ctypes loader for the native C++ LIBSVM parser.

The reference's data path runs parsing inside executor JVMs (SURVEY.md §3.4);
the TPU framework's native analogue is a small C++ shared library
(``libsvm_parser.cpp``) loaded via ctypes — no pybind11 dependency.  Build it
with ``python -m tpu_sgd.utils.native.build`` (uses g++); all callers fall
back to the pure-Python parser when the library is absent.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libsvm_parser.so")
_lib = None


def _load():
    global _lib
    if _lib is None:
        if not os.path.exists(_LIB_PATH):
            raise ImportError(f"native parser not built at {_LIB_PATH}")
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.parse_libsvm_count.restype = ctypes.c_int64
        _lib.parse_libsvm_count.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),  # n_rows out
            ctypes.POINTER(ctypes.c_int64),  # n_nz out
        ]
        _lib.parse_libsvm_fill.restype = ctypes.c_int64
        _lib.parse_libsvm_fill.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
    return _lib


def parse_libsvm(path: str):
    """Parse a LIBSVM file natively -> (labels, rows, cols, vals, max_index)."""
    lib = _load()
    n_rows = ctypes.c_int64()
    n_nz = ctypes.c_int64()
    rc = lib.parse_libsvm_count(path.encode(), ctypes.byref(n_rows), ctypes.byref(n_nz))
    if rc != 0:
        raise IOError(f"native parser failed to open/scan {path} (rc={rc})")
    labels = np.empty((n_rows.value,), np.float32)
    rows = np.empty((n_nz.value,), np.int64)
    cols = np.empty((n_nz.value,), np.int64)
    vals = np.empty((n_nz.value,), np.float32)
    max_idx = lib.parse_libsvm_fill(path.encode(), labels, rows, cols, vals)
    if max_idx < 0:
        raise IOError(f"native parser failed to parse {path} (rc={max_idx})")
    return labels, rows, cols, vals, int(max_idx)
