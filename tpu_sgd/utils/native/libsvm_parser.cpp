// Native LIBSVM parser for tpu_sgd.
//
// The reference parses LIBSVM text inside executor JVMs (SURVEY.md §3.4,
// [U] MLUtils.loadLibSVMFile); this is the TPU framework's native-speed
// analogue of that data-loader path.  Two-pass design: pass 1 counts rows and
// nonzeros so Python can allocate exact numpy buffers; pass 2 fills them.
// Exposed as a plain C ABI consumed via ctypes (no pybind11).
//
// Build: python -m tpu_sgd.utils.native.build  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

// Read a whole file into a buffer; returns false on failure.
bool read_file(const char* path, std::vector<char>& buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(size) + 1);
  size_t got = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  if (got != static_cast<size_t>(size)) return false;
  buf[got] = '\0';
  return true;
}

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

inline const char* line_end(const char* p) {
  while (*p && *p != '\n' && *p != '#') ++p;
  return p;
}

}  // namespace

extern "C" {

// Pass 1: count rows and nonzeros. Returns 0 on success, negative on error.
int64_t parse_libsvm_count(const char* path, int64_t* n_rows, int64_t* n_nz) {
  std::vector<char> buf;
  if (!read_file(path, buf)) return -1;
  int64_t rows = 0, nz = 0;
  const char* p = buf.data();
  while (*p) {
    const char* q = skip_ws(p);
    const char* end = line_end(q);
    if (end != q) {  // non-empty line (before any comment)
      ++rows;
      for (const char* c = q; c < end; ++c)
        if (*c == ':') ++nz;
    }
    p = end;
    while (*p && *p != '\n') ++p;  // skip comment tail
    if (*p == '\n') ++p;
  }
  *n_rows = rows;
  *n_nz = nz;
  return 0;
}

// Pass 2: fill pre-allocated buffers. Returns max feature index (1-based
// count == densified feature dim) on success, negative on parse error.
int64_t parse_libsvm_fill(const char* path, float* labels, int64_t* rows,
                          int64_t* cols, float* vals) {
  std::vector<char> buf;
  if (!read_file(path, buf)) return -1;
  int64_t row = 0, k = 0, max_idx = 0;
  char* p = buf.data();
  while (*p) {
    char* q = const_cast<char*>(skip_ws(p));
    const char* end = line_end(q);
    if (end != q) {
      char* cur = q;
      labels[row] = std::strtof(cur, &cur);
      while (cur < end) {
        cur = const_cast<char*>(skip_ws(cur));
        if (cur >= end) break;
        char* after = nullptr;
        long long idx = std::strtoll(cur, &after, 10);
        if (after == cur || *after != ':') return -2;  // malformed token
        if (idx < 1) return -3;                        // 1-based on disk
        cur = after + 1;
        char* vstart = cur;
        if (*vstart == ' ' || *vstart == '\t') return -2;  // "5: 2.0" —
                                       // strtof would skip the space and
                                       // eat the NEXT token
        float v = std::strtof(cur, &cur);
        if (cur == vstart) return -2;  // empty value token ("5:"): the
                                       // Python parser raises; accepting
                                       // 0.0 here would make corrupt
                                       // files load only when the .so
                                       // happens to be built
        rows[k] = row;
        cols[k] = idx - 1;
        vals[k] = v;
        ++k;
        if (idx > max_idx) max_idx = idx;
      }
      ++row;
    }
    p = const_cast<char*>(end);
    while (*p && *p != '\n') ++p;
    if (*p == '\n') ++p;
  }
  return max_idx;
}

}  // extern "C"
