// Native multi-threaded mini-batch row gather for tpu_sgd.
//
// The host-streamed training path (SURVEY.md §7 phase 6: datasets larger
// than HBM) assembles each iteration's sampled batch on the host before the
// device transfer.  The reference's analogue is the executor-side partition
// iterator feeding the per-example loop (SURVEY.md §3.1); here batch
// assembly is a pure row gather — memcpy-bound — and NumPy's fancy
// indexing runs it on one core.  This library splits the gather across a
// small thread pool so batch assembly keeps up with the device and the
// double-buffered overlap in optimize_host_streamed stays compute-bound.
//
// Dtype-agnostic: rows are opaque byte ranges (row_bytes = d * itemsize),
// so f32, bf16, f64 and label vectors all go through the same entry point.
// Plain C ABI consumed via ctypes (no pybind11).
//
// Build: python -m tpu_sgd.utils.native.build  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void gather_range(const char* X, int64_t row_bytes, const int64_t* idx,
                  int64_t begin, int64_t end, char* out) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(out + i * row_bytes, X + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

}  // namespace

extern "C" {

// Gather m rows (row_bytes each) of X at positions idx into out.
// idx values must be in [0, n_rows).  Returns 0 on success, -1 on a
// detected out-of-range index (checked up front; no partial writes of
// invalid rows).
int64_t gather_rows(const void* X, int64_t n_rows, int64_t row_bytes,
                    const int64_t* idx, int64_t m, void* out,
                    int64_t n_threads) {
  if (row_bytes <= 0 || m < 0) return -1;
  for (int64_t i = 0; i < m; ++i) {
    if (idx[i] < 0 || idx[i] >= n_rows) return -1;
  }
  const char* src = static_cast<const char*>(X);
  char* dst = static_cast<char*>(out);
  if (n_threads <= 1 || m < 4096) {
    gather_range(src, row_bytes, idx, 0, m, dst);
    return 0;
  }
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 4;
  int64_t t = n_threads < hw ? n_threads : hw;
  std::vector<std::thread> pool;
  pool.reserve(static_cast<size_t>(t));
  int64_t chunk = (m + t - 1) / t;
  for (int64_t k = 0; k < t; ++k) {
    int64_t b = k * chunk;
    int64_t e = b + chunk < m ? b + chunk : m;
    if (b >= e) break;
    pool.emplace_back(gather_range, src, row_bytes, idx, b, e, dst);
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
