"""Build the native libraries: ``python -m tpu_sgd.utils.native.build``.

Targets: the LIBSVM parser and the multi-threaded batch-row gather.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
TARGETS = {
    "libsvm_parser": [],
    "batch_sampler": ["-pthread"],
}


def build(verbose: bool = True) -> list:
    outs = []
    for name, extra in TARGETS.items():
        src = os.path.join(HERE, f"{name}.cpp")
        out = os.path.join(HERE, f"{name}.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *extra,
               src, "-o", out]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True)
        outs.append(out)
    return outs


if __name__ == "__main__":
    for path in build():
        print(f"built {path}")
    sys.exit(0)
