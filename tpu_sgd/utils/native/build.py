"""Build the native LIBSVM parser: ``python -m tpu_sgd.utils.native.build``."""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "libsvm_parser.cpp")
OUT = os.path.join(HERE, "libsvm_parser.so")


def build(verbose: bool = True) -> str:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", SRC, "-o", OUT]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    path = build()
    print(f"built {path}")
    sys.exit(0)
