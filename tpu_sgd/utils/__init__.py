from tpu_sgd.utils.mlutils import (
    append_bias,
    k_fold,
    a9a_like_data,
    linear_data,
    load_libsvm_file,
    logistic_data,
    rcv1_like_data,
    save_as_libsvm_file,
    svm_data,
    train_test_split,
)
from tpu_sgd.utils.persistence import load_glm_model, save_glm_model
from tpu_sgd.utils.checkpoint import CheckpointManager
from tpu_sgd.utils.events import (
    CollectingListener,
    IterationEvent,
    JsonLinesEventLog,
    RunEvent,
    SGDListener,
    StepTimer,
    profile_trace,
)

__all__ = [
    "k_fold",
    "train_test_split",
    "CheckpointManager",
    "SGDListener",
    "CollectingListener",
    "JsonLinesEventLog",
    "IterationEvent",
    "RunEvent",
    "StepTimer",
    "profile_trace",
    "append_bias",
    "load_libsvm_file",
    "save_as_libsvm_file",
    "linear_data",
    "logistic_data",
    "svm_data",
    "a9a_like_data",
    "rcv1_like_data",
    "save_glm_model",
    "load_glm_model",
]
