"""Optimizer-state checkpoint / resume.

Reference parity: SURVEY.md §5.3-§5.4.  The reference's recovery story is
RDD lineage + model ``save``/``load``; mid-training optimizer state is NOT
checkpointed — resume granularity is "the model so far".  The TPU build
matches model persistence (tpu_sgd.utils.persistence) and, as §5.4 suggests,
cheaply exceeds the reference by checkpointing the full optimizer state
``(weights, iteration, reg_val, loss_history)`` every K steps — which
restores the reference's any-iteration replay property without lineage
(SURVEY.md §5.3: each iteration is deterministic in (seed, iteration)).
"""

from __future__ import annotations

import glob
import logging
import os
from typing import Callable, Optional

import numpy as np

from tpu_sgd.reliability.failpoints import FaultInjected, failpoint

logger = logging.getLogger("tpu_sgd.checkpoint")

FORMAT_VERSION = "1.0"


class CheckpointVersionError(ValueError):
    """The checkpoint is intact but from an incompatible format version —
    a real incompatibility, never skipped by the corruption fallback."""


class CheckpointManager:
    """Numbered npz checkpoints in a directory, pruned to ``keep`` newest.

    ``on_corruption(path, quarantined_path, error)`` (optional) fires
    whenever the latest-default :meth:`restore` skips an unreadable
    checkpoint — the hook an ops pipeline uses to page on silent data
    loss instead of discovering it in a post-mortem (wire it to a
    ``ReliabilityEvent`` on your event log; ``scripts/chaos_soak.py``
    audits quarantines through it)."""

    def __init__(self, directory: str, keep: int = 3,
                 on_corruption: Optional[Callable] = None):
        self.directory = directory
        self.keep = keep
        self.on_corruption = on_corruption
        os.makedirs(directory, exist_ok=True)
        # a crash mid-save leaves .tmp_ckpt_* orphans (invisible to the
        # ckpt_*.npz glob but full model-sized files); sweep the STALE
        # ones here so a flaky job cannot leak disk indefinitely — but
        # only files old enough that no live writer (another process
        # sharing this directory, mid-save) can plausibly own them
        import time as _time

        cutoff = _time.time() - 3600
        for stale in glob.glob(os.path.join(directory, ".tmp_ckpt_*.npz")):
            try:
                if os.path.getmtime(stale) < cutoff:
                    os.remove(stale)
            except OSError:
                pass
        # quarantined corrupt files (.bad_ckpt_*, restore()'s fallback)
        # are kept for forensics but BOUNDED — a flaky job must not leak
        # one model-sized file per torn checkpoint forever
        def _mtime(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0  # vanished concurrently: sorts first, skipped

        bad = sorted(glob.glob(os.path.join(directory, ".bad_ckpt_*.npz")),
                     key=_mtime)
        for p in bad[:-max(1, keep)]:
            try:
                os.remove(p)
            except OSError:
                pass

    def _path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ckpt_{iteration:08d}.npz")

    @staticmethod
    def _iteration_of(path: str):
        """Parsed iteration, or None for a hand-named ckpt_*.npz file
        (e.g. a user's 'ckpt_best.npz' copy) — those are ignored rather
        than crashing every save/restore in the directory."""
        stem = os.path.basename(path)[5:-4]
        return int(stem) if stem.isdigit() else None

    def _paths_by_iteration(self):
        # sort by the PARSED iteration, not lexicographically: at
        # iteration 10^8 the name grows a digit and 'ckpt_100000000'
        # sorts before 'ckpt_99999999', which would make latest_path
        # return stale state and _prune delete every NEW checkpoint
        paths = glob.glob(os.path.join(self.directory, "ckpt_*.npz"))
        numbered = [p for p in paths if self._iteration_of(p) is not None]
        return sorted(numbered, key=self._iteration_of)

    def save(
        self,
        iteration: int,
        weights,
        reg_val: float,
        loss_history,
        config_key: str = "",
        extras: Optional[dict] = None,
    ) -> str:
        """``extras``: optional named arrays saved alongside the core
        state (``x_``-prefixed in the npz so they can never collide with
        the versioned schema) — the streaming driver persists its
        ``intercept`` through this (its stream position rides the core
        ``iteration`` field)."""
        from tpu_sgd.obs.spans import span

        # the span's ``iteration`` attr is the join key obs.report's
        # served-weight staleness metric uses: reload ts minus the ts of
        # the checkpoint.save span that wrote that version
        with span("checkpoint.save", iteration=int(iteration)):
            failpoint("checkpoint.save")  # injected BEFORE any byte is
            # written: a save fault never leaves a partial file behind
            path = self._path(iteration)
            # Temp prefix must NOT match the ckpt_*.npz glob, or a
            # truncated file left by a crash mid-write would be picked
            # up by latest_path.
            tmp = os.path.join(self.directory,
                               f".tmp_ckpt_{iteration:08d}.npz")
            with open(tmp, "wb") as f:
                np.savez(
                    f,
                    version=FORMAT_VERSION,
                    iteration=np.asarray(iteration, np.int64),
                    weights=np.asarray(weights),
                    reg_val=np.asarray(reg_val, np.float64),
                    loss_history=np.asarray(loss_history, np.float64),
                    config_key=np.asarray(config_key),
                    **{f"x_{k}": np.asarray(v)
                       for k, v in (extras or {}).items()},
                )
                # fsync BEFORE the rename: os.replace is atomic for the
                # directory entry, but on a writeback mount a power loss
                # can journal the rename while the data blocks are still
                # dirty — a durable name pointing at truncated bytes
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._prune()
            return path

    def _prune(self):
        for p in self._paths_by_iteration()[: -self.keep]:
            os.remove(p)

    def latest_path(self) -> Optional[str]:
        paths = self._paths_by_iteration()
        return paths[-1] if paths else None

    def versions(self):
        """Retained checkpoint iterations, ascending — the serving
        registry's load-by-version surface (serve/registry.py)."""
        return [self._iteration_of(p) for p in self._paths_by_iteration()]

    def latest_version(self) -> Optional[int]:
        p = self.latest_path()
        return None if p is None else self._iteration_of(p)

    def restore_version(self, iteration: int) -> dict:
        """Load exactly the checkpoint written at ``iteration``.  Explicit
        version requests raise on a missing or corrupt file (the caller
        named a specific version, so silently serving another would be
        wrong) — the latest-default :meth:`restore` keeps its fallback."""
        path = self._path(int(iteration))
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no checkpoint for iteration {iteration} in "
                f"{self.directory!r} (retained: {self.versions()})"
            )
        return self._load(path)

    def restore(self, path: Optional[str] = None) -> Optional[dict]:
        """Load a checkpoint dict or ``None`` when the directory is empty.

        An explicitly requested ``path`` raises on corruption; the
        latest-checkpoint default FALLS BACK through the older retained
        checkpoints instead — ``keep > 1`` exists precisely so one
        torn/truncated newest file cannot permanently break resume."""
        if path is not None:
            return self._load(path)
        candidates = self._paths_by_iteration()
        for p in reversed(candidates):
            try:
                return self._load(p)
            except CheckpointVersionError:
                raise  # intact but incompatible: not corruption
            except (OSError, FaultInjected) as e:
                # transient I/O (EMFILE, NFS hiccup, vanished file) or an
                # injected chaos fault: NOT corruption — fall back to an
                # older checkpoint for THIS restore but leave the file in
                # place (same carve-out as serve/registry.maybe_reload;
                # quarantining here would let a one-off hiccup destroy a
                # finished run's final, fully valid checkpoint)
                logger.warning(
                    "checkpoint %s hit a transient I/O error (%s: %s); "
                    "falling back to the previous retained checkpoint "
                    "without quarantining", p, type(e).__name__, e)
            except Exception as e:  # truncated/torn file: try older
                # QUARANTINE the proven-bad file out of the numbered
                # namespace: left in place, _prune would keep treating
                # it as 'newest' and delete every VALID checkpoint the
                # resumed run writes below its iteration
                quarantined = os.path.join(
                    os.path.dirname(p), ".bad_" + os.path.basename(p))
                try:
                    os.replace(p, quarantined)
                except OSError:
                    quarantined = None  # left in place (e.g. perms)
                logger.warning(
                    "checkpoint %s unreadable (%s: %s); quarantined as %s, "
                    "falling back to the previous retained checkpoint", p,
                    type(e).__name__, e, quarantined or "<unmoved>")
                if self.on_corruption is not None:
                    try:
                        self.on_corruption(p, quarantined, e)
                    except Exception:  # observer must not break resume
                        logger.warning(
                            "on_corruption hook raised; continuing",
                            exc_info=True)
        return None

    @staticmethod
    def _load(path: str) -> dict:
        from tpu_sgd.obs.spans import span

        with span("checkpoint.restore"):
            failpoint("checkpoint.load")
            return CheckpointManager._parse(path)

    @staticmethod
    def _parse(path: str) -> dict:
        with np.load(path, allow_pickle=False) as z:
            if str(z["version"]) != FORMAT_VERSION:
                raise CheckpointVersionError(
                    f"unsupported checkpoint version {z['version']}"
                )
            return {
                "iteration": int(z["iteration"]),
                "weights": z["weights"],
                "reg_val": float(z["reg_val"]),
                "loss_history": z["loss_history"],
                "config_key": str(z["config_key"]),
                "extras": {
                    k[2:]: z[k] for k in z.files if k.startswith("x_")
                },
            }
