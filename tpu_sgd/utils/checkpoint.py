"""Optimizer-state checkpoint / resume.

Reference parity: SURVEY.md §5.3-§5.4.  The reference's recovery story is
RDD lineage + model ``save``/``load``; mid-training optimizer state is NOT
checkpointed — resume granularity is "the model so far".  The TPU build
matches model persistence (tpu_sgd.utils.persistence) and, as §5.4 suggests,
cheaply exceeds the reference by checkpointing the full optimizer state
``(weights, iteration, reg_val, loss_history)`` every K steps — which
restores the reference's any-iteration replay property without lineage
(SURVEY.md §5.3: each iteration is deterministic in (seed, iteration)).
"""

from __future__ import annotations

import glob
import os
from typing import Optional

import numpy as np

FORMAT_VERSION = "1.0"


class CheckpointManager:
    """Numbered npz checkpoints in a directory, pruned to ``keep`` newest."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ckpt_{iteration:08d}.npz")

    def save(
        self,
        iteration: int,
        weights,
        reg_val: float,
        loss_history,
        config_key: str = "",
        extras: Optional[dict] = None,
    ) -> str:
        """``extras``: optional named arrays saved alongside the core
        state (``x_``-prefixed in the npz so they can never collide with
        the versioned schema) — the streaming driver persists its
        ``intercept`` through this (its stream position rides the core
        ``iteration`` field)."""
        path = self._path(iteration)
        # Temp prefix must NOT match the ckpt_*.npz glob, or a truncated
        # file left by a crash mid-write would be picked up by latest_path.
        tmp = os.path.join(self.directory, f".tmp_ckpt_{iteration:08d}.npz")
        np.savez(
            tmp,
            version=FORMAT_VERSION,
            iteration=np.asarray(iteration, np.int64),
            weights=np.asarray(weights),
            reg_val=np.asarray(reg_val, np.float64),
            loss_history=np.asarray(loss_history, np.float64),
            config_key=np.asarray(config_key),
            **{f"x_{k}": np.asarray(v) for k, v in (extras or {}).items()},
        )
        os.replace(tmp, path)
        self._prune()
        return path

    def _prune(self):
        paths = sorted(glob.glob(os.path.join(self.directory, "ckpt_*.npz")))
        for p in paths[: -self.keep]:
            os.remove(p)

    def latest_path(self) -> Optional[str]:
        paths = sorted(glob.glob(os.path.join(self.directory, "ckpt_*.npz")))
        return paths[-1] if paths else None

    def restore(self, path: Optional[str] = None) -> Optional[dict]:
        """Load a checkpoint dict or None when the directory is empty."""
        path = path or self.latest_path()
        if path is None:
            return None
        with np.load(path, allow_pickle=False) as z:
            if str(z["version"]) != FORMAT_VERSION:
                raise ValueError(f"unsupported checkpoint version {z['version']}")
            return {
                "iteration": int(z["iteration"]),
                "weights": z["weights"],
                "reg_val": float(z["reg_val"]),
                "loss_history": z["loss_history"],
                "config_key": str(z["config_key"]),
                "extras": {
                    k[2:]: z[k] for k in z.files if k.startswith("x_")
                },
            }
