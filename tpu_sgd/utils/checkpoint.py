"""Optimizer-state checkpoint / resume.

Reference parity: SURVEY.md §5.3-§5.4.  The reference's recovery story is
RDD lineage + model ``save``/``load``; mid-training optimizer state is NOT
checkpointed — resume granularity is "the model so far".  The TPU build
matches model persistence (tpu_sgd.utils.persistence) and, as §5.4 suggests,
cheaply exceeds the reference by checkpointing the full optimizer state
``(weights, iteration, reg_val, loss_history)`` every K steps — which
restores the reference's any-iteration replay property without lineage
(SURVEY.md §5.3: each iteration is deterministic in (seed, iteration)).
"""

from __future__ import annotations

import glob
import logging
import os
import re
from typing import Callable, Optional

import numpy as np

from tpu_sgd.io.integrity import (IntegrityError, checksum_arrays,
                                  integrity_enabled)
from tpu_sgd.reliability.failpoints import FaultInjected, failpoint

logger = logging.getLogger("tpu_sgd.checkpoint")


def _content_checksum(entries: dict) -> int:
    """CRC-32 over every npz entry's name and bytes, in sorted-name
    order — ONE definition shared by :meth:`CheckpointManager.save`
    (sealing) and :meth:`CheckpointManager._parse` (verifying), so a
    flipped bit, a truncated array, or a silently dropped field in ANY
    entry fails the restore-time check."""
    leaves = []
    for k in sorted(entries):
        leaves.append(np.frombuffer(k.encode(), np.uint8))
        leaves.append(np.asarray(entries[k]))
    return checksum_arrays(*leaves)

FORMAT_VERSION = "1.0"

#: checkpoint file names: the legacy ``ckpt_<iteration>.npz`` (epoch 0)
#: and the failover-stamped ``ckpt_e<epoch>_<iteration>.npz`` — the
#: replicated store (tpu_sgd/replica/ha.py) saves under the epoch of
#: its failover generation, and ordering/restore prefer the highest
#: ``(epoch, iteration)``, so a fenced old primary's late save can
#: never shadow the promoted store's state.
_CKPT_NAME = re.compile(r"^ckpt_(?:e(?P<epoch>\d+)_)?(?P<iter>\d+)\.npz$")


class CheckpointVersionError(ValueError):
    """The checkpoint is intact but from an incompatible format version —
    a real incompatibility, never skipped by the corruption fallback."""


class CheckpointManager:
    """Numbered npz checkpoints in a directory, pruned to ``keep`` newest.

    ``on_corruption(path, quarantined_path, error)`` (optional) fires
    whenever the latest-default :meth:`restore` skips an unreadable
    checkpoint — the hook an ops pipeline uses to page on silent data
    loss instead of discovering it in a post-mortem (wire it to a
    ``ReliabilityEvent`` on your event log; ``scripts/chaos_soak.py``
    audits quarantines through it)."""

    def __init__(self, directory: str, keep: int = 3,
                 on_corruption: Optional[Callable] = None):
        self.directory = directory
        self.keep = keep
        self.on_corruption = on_corruption
        os.makedirs(directory, exist_ok=True)
        # a crash mid-save leaves .tmp_ckpt_* orphans (invisible to the
        # ckpt_*.npz glob but full model-sized files); sweep the STALE
        # ones here so a flaky job cannot leak disk indefinitely — but
        # only files old enough that no live writer (another process
        # sharing this directory, mid-save) can plausibly own them
        import time as _time

        cutoff = _time.time() - 3600
        for stale in glob.glob(os.path.join(directory, ".tmp_ckpt_*.npz")):
            try:
                if os.path.getmtime(stale) < cutoff:
                    os.remove(stale)
            except OSError:
                pass
        # quarantined corrupt files (.bad_ckpt_*, restore()'s fallback)
        # are kept for forensics but BOUNDED — a flaky job must not leak
        # one model-sized file per torn checkpoint forever
        def _mtime(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0  # vanished concurrently: sorts first, skipped

        bad = sorted(glob.glob(os.path.join(directory, ".bad_ckpt_*.npz")),
                     key=_mtime)
        for p in bad[:-max(1, keep)]:
            try:
                os.remove(p)
            except OSError:
                pass

    def _path(self, iteration: int, epoch: int = 0) -> str:
        if epoch:
            return os.path.join(
                self.directory, f"ckpt_e{epoch:04d}_{iteration:08d}.npz")
        return os.path.join(self.directory, f"ckpt_{iteration:08d}.npz")

    @staticmethod
    def _key_of(path: str):
        """Parsed ``(epoch, iteration)``, or None for a hand-named
        ckpt_*.npz file (e.g. a user's 'ckpt_best.npz' copy) — those
        are ignored rather than crashing every save/restore in the
        directory."""
        m = _CKPT_NAME.match(os.path.basename(path))
        if m is None:
            return None
        return (int(m.group("epoch") or 0), int(m.group("iter")))

    @staticmethod
    def _iteration_of(path: str):
        key = CheckpointManager._key_of(path)
        return None if key is None else key[1]

    def _paths_by_iteration(self):
        # sort by the PARSED (epoch, iteration), not lexicographically:
        # at iteration 10^8 the name grows a digit and 'ckpt_100000000'
        # sorts before 'ckpt_99999999', which would make latest_path
        # return stale state and _prune delete every NEW checkpoint.
        # Epoch is the MAJOR key: after a store failover, the promoted
        # epoch's saves outrank a fenced old primary's late save even
        # when that save carries a higher iteration number.
        paths = glob.glob(os.path.join(self.directory, "ckpt_*.npz"))
        numbered = [p for p in paths if self._key_of(p) is not None]
        return sorted(numbered, key=self._key_of)

    def save(
        self,
        iteration: int,
        weights,
        reg_val: float,
        loss_history,
        config_key: str = "",
        extras: Optional[dict] = None,
        epoch: int = 0,
    ) -> str:
        """``extras``: optional named arrays saved alongside the core
        state (``x_``-prefixed in the npz so they can never collide with
        the versioned schema) — the streaming driver persists its
        ``intercept`` through this (its stream position rides the core
        ``iteration`` field).  ``epoch``: the store failover generation
        (``tpu_sgd/replica/ha.py``); stamped into the file NAME so
        ordering and :meth:`restore` prefer the highest ``(epoch,
        iteration)`` without opening every file."""
        from tpu_sgd.obs.spans import span

        # the span's ``iteration`` attr is the join key obs.report's
        # served-weight staleness metric uses: reload ts minus the ts of
        # the checkpoint.save span that wrote that version
        with span("checkpoint.save", iteration=int(iteration)):
            failpoint("checkpoint.save")  # injected BEFORE any byte is
            # written: a save fault never leaves a partial file behind
            path = self._path(iteration, epoch)
            # Temp prefix must NOT match the ckpt_*.npz glob, or a
            # truncated file left by a crash mid-write would be picked
            # up by latest_path.
            tmp = os.path.join(self.directory,
                               ".tmp_" + os.path.basename(path))
            entries = {
                "version": np.asarray(FORMAT_VERSION),
                "iteration": np.asarray(iteration, np.int64),
                "epoch": np.asarray(epoch, np.int64),
                "weights": np.asarray(weights),
                "reg_val": np.asarray(reg_val, np.float64),
                "loss_history": np.asarray(loss_history, np.float64),
                "config_key": np.asarray(config_key),
                **{f"x_{k}": np.asarray(v)
                   for k, v in (extras or {}).items()},
            }
            if integrity_enabled():
                # content checksum over every entry (ISSUE 15):
                # verified at restore, so a bit flipped at rest — in
                # bytes npz's own zip CRC does not cover end-to-end, or
                # after a tool rewrote the archive — is a typed,
                # quarantined corruption instead of poisoned weights
                entries["checksum"] = np.asarray(
                    _content_checksum(entries), np.uint32)
            with open(tmp, "wb") as f:
                np.savez(f, **entries)
                # fsync BEFORE the rename: os.replace is atomic for the
                # directory entry, but on a writeback mount a power loss
                # can journal the rename while the data blocks are still
                # dirty — a durable name pointing at truncated bytes
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._prune()
            return path

    def _prune(self):
        for p in self._paths_by_iteration()[: -self.keep]:
            os.remove(p)

    def latest_path(self) -> Optional[str]:
        paths = self._paths_by_iteration()
        return paths[-1] if paths else None

    def versions(self):
        """Retained checkpoint iterations in ``(epoch, iteration)``
        order, deduplicated — the serving registry's load-by-version
        surface (serve/registry.py).  After a store failover the list
        may be non-monotone in the iteration number alone: the promoted
        epoch's saves rank last (= newest) even when a fenced old
        primary left a higher-numbered save behind."""
        out, seen = [], set()
        for p in self._paths_by_iteration():
            it = self._iteration_of(p)
            if it not in seen:
                seen.add(it)
                out.append(it)
        return out

    def latest_version(self) -> Optional[int]:
        p = self.latest_path()
        return None if p is None else self._iteration_of(p)

    def restore_version(self, iteration: int) -> dict:
        """Load exactly the checkpoint written at ``iteration`` — the
        HIGHEST-epoch save of that iteration when a failover wrote it
        twice (the fenced old primary's copy never wins).  Explicit
        version requests raise on a missing or corrupt file (the caller
        named a specific version, so silently serving another would be
        wrong) — the latest-default :meth:`restore` keeps its fallback."""
        matches = [p for p in self._paths_by_iteration()
                   if self._iteration_of(p) == int(iteration)]
        if not matches:
            raise FileNotFoundError(
                f"no checkpoint for iteration {iteration} in "
                f"{self.directory!r} (retained: {self.versions()})"
            )
        return self._load(matches[-1])

    def restore(self, path: Optional[str] = None) -> Optional[dict]:
        """Load a checkpoint dict or ``None`` when the directory is empty.

        An explicitly requested ``path`` raises on corruption; the
        latest-checkpoint default FALLS BACK through the older retained
        checkpoints instead — ``keep > 1`` exists precisely so one
        torn/truncated newest file cannot permanently break resume."""
        if path is not None:
            return self._load(path)
        candidates = self._paths_by_iteration()
        for p in reversed(candidates):
            try:
                return self._load(p)
            except CheckpointVersionError:
                raise  # intact but incompatible: not corruption
            except (OSError, FaultInjected) as e:
                # transient I/O (EMFILE, NFS hiccup, vanished file) or an
                # injected chaos fault: NOT corruption — fall back to an
                # older checkpoint for THIS restore but leave the file in
                # place (same carve-out as serve/registry.maybe_reload;
                # quarantining here would let a one-off hiccup destroy a
                # finished run's final, fully valid checkpoint)
                logger.warning(
                    "checkpoint %s hit a transient I/O error (%s: %s); "
                    "falling back to the previous retained checkpoint "
                    "without quarantining", p, type(e).__name__, e)
            except Exception as e:  # truncated/torn file: try older
                # QUARANTINE the proven-bad file out of the numbered
                # namespace: left in place, _prune would keep treating
                # it as 'newest' and delete every VALID checkpoint the
                # resumed run writes below its iteration
                quarantined = os.path.join(
                    os.path.dirname(p), ".bad_" + os.path.basename(p))
                try:
                    os.replace(p, quarantined)
                except OSError:
                    quarantined = None  # left in place (e.g. perms)
                logger.warning(
                    "checkpoint %s unreadable (%s: %s); quarantined as %s, "
                    "falling back to the previous retained checkpoint", p,
                    type(e).__name__, e, quarantined or "<unmoved>")
                if self.on_corruption is not None:
                    try:
                        self.on_corruption(p, quarantined, e)
                    except Exception:  # observer must not break resume
                        logger.warning(
                            "on_corruption hook raised; continuing",
                            exc_info=True)
        return None

    @staticmethod
    def _load(path: str) -> dict:
        from tpu_sgd.obs.spans import span

        with span("checkpoint.restore"):
            failpoint("checkpoint.load")
            return CheckpointManager._parse(path)

    @staticmethod
    def _parse(path: str) -> dict:
        with np.load(path, allow_pickle=False) as z:
            if str(z["version"]) != FORMAT_VERSION:
                raise CheckpointVersionError(
                    f"unsupported checkpoint version {z['version']}"
                )
            if "checksum" in z.files:
                # the content-checksum verify (ISSUE 15).  Raising
                # IntegrityError here composes with restore()'s
                # existing carve-outs: the latest-default path
                # QUARANTINES this file and falls back to an older
                # retained checkpoint (it is proven corrupt, not a
                # transient hiccup), explicit path/version requests
                # raise to the caller, and the serve registry marks
                # the version bad.  Legacy checksum-less files load
                # as before.
                expected = int(z["checksum"])
                actual = _content_checksum(
                    {k: z[k] for k in z.files if k != "checksum"})
                if actual != expected:
                    from tpu_sgd.obs.counters import inc
                    from tpu_sgd.obs.spans import event

                    inc("integrity.corrupt")
                    inc("integrity.corrupt.checkpoint")
                    event("integrity.corrupt_frame", site="checkpoint",
                          kind="checksum", path=path)
                    raise IntegrityError(
                        "checkpoint", "checksum",
                        f"{path}: crc {actual:#010x} != sealed "
                        f"{expected:#010x}")
                from tpu_sgd.obs.counters import inc

                inc("integrity.verified.checkpoint")
            return {
                "iteration": int(z["iteration"]),
                "epoch": (int(z["epoch"]) if "epoch" in z.files else 0),
                "weights": z["weights"],
                "reg_val": float(z["reg_val"]),
                "loss_history": z["loss_history"],
                "config_key": str(z["config_key"]),
                "extras": {
                    k[2:]: z[k] for k in z.files if k.startswith("x_")
                },
            }
