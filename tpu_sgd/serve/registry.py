"""Model registry: hot-reload trained checkpoints into the serving path.

The training side continuously publishes through the existing
``CheckpointManager`` (a ``StreamingLinearAlgorithm`` with
``set_checkpoint`` writes one numbered, atomically-renamed npz per K
micro-batches); the registry is the consuming half: it watches the
checkpoint directory, loads any newer version, and atomically swaps the
serving model under a lock — prediction threads only ever observe the
old model or the new one, never a half-built one.

Failure containment is the point of the design: a corrupt or truncated
newest checkpoint must never take down the endpoint.  ``maybe_reload``
walks candidate versions newest-first, and a version that fails to load
is recorded as bad (never retried) while the endpoint keeps serving the
previous-good model — rollback is the *absence* of the swap.  An
explicitly pinned version (``pin``) disables auto-reload entirely, the
version-pinning escape hatch for incident response.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional

from tpu_sgd.reliability.failpoints import FaultInjected, failpoint
from tpu_sgd.utils.checkpoint import CheckpointManager

logger = logging.getLogger("tpu_sgd.serve.registry")

#: graftlint lock-discipline declaration (tpu_sgd/analysis).  The
#: serving model is an atomic-reference swap: prediction threads READ
#: ``_model``/``_version`` lock-free (old model or new, never torn — the
#: documented design), so those are ``:w`` — only mutations serialize.
#: ``bad_versions`` is a plain dict mutated during reload walks; copying
#: or iterating it concurrently with an insert can raise, so both sides
#: hold the lock.
GRAFTLINT_LOCKS = {
    "ModelRegistry": {
        "_model": "_lock:w",
        "_version": "_lock:w",
        "_previous_version": "_lock:w",
        "_pinned": "_lock:w",
        "bad_versions": "_lock",
        "load_failed_count": "_lock:w",
    },
}


class NoModelError(RuntimeError):
    """No loadable checkpoint exists yet in the registry's directory."""


class ModelRegistry:
    """Versioned, hot-reloadable model source over a checkpoint directory.

    ``model_factory(weights, intercept)`` builds the servable model from
    checkpoint state — typically ``algorithm.create_model`` of the family
    that trains into the directory (a streaming checkpoint's version
    number is its stream position, i.e. micro-batches consumed).
    """

    def __init__(
        self,
        manager_or_directory,
        model_factory: Callable,
        *,
        metrics=None,
        breaker=None,
    ):
        if isinstance(manager_or_directory, (str, os.PathLike)):
            manager_or_directory = CheckpointManager(str(manager_or_directory))
        self.manager: CheckpointManager = manager_or_directory
        self.model_factory = model_factory
        self.metrics = metrics
        #: optional tpu_sgd.reliability.CircuitBreaker: consecutive
        #: reload failures OPEN it and maybe_reload short-circuits (no
        #: directory scan, no load attempt) until the cooldown probe —
        #: serving keeps degrading gracefully to the current/pinned
        #: model instead of hammering a sick checkpoint directory
        self.breaker = breaker
        self._lock = threading.Lock()
        self._model = None
        self._version: Optional[int] = None
        self._previous_version: Optional[int] = None
        self._pinned = False
        #: versions that failed to load, with the error string — never
        #: retried, so one corrupt file cannot wedge reload in a loop
        self.bad_versions: Dict[int, str] = {}
        self.reload_count = 0
        #: cumulative failed load ATTEMPTS (transient + corrupt) — the
        #: registry-side rejection counter healthz surfaces next to the
        #: serving tier's admit/shed/reject tallies (ISSUE 12)
        self.load_failed_count = 0

    # -- read side ---------------------------------------------------------
    @property
    def current_version(self) -> Optional[int]:
        return self._version

    @property
    def previous_version(self) -> Optional[int]:
        return self._previous_version

    def model(self):
        """The current serving model; loads the newest checkpoint on first
        use.  Raises :class:`NoModelError` when nothing is loadable."""
        m = self._model
        if m is None:
            self.maybe_reload()
            m = self._model
            if m is None:
                raise NoModelError(
                    f"no loadable checkpoint in {self.manager.directory!r}"
                )
        return m

    # -- pinning -----------------------------------------------------------
    def pin(self, version: int):
        """Serve exactly ``version`` and disable auto-reload.  An
        explicitly pinned version raises on load failure (same contract as
        ``CheckpointManager.restore(path=...)``): pinning a bad version is
        an operator error, not something to paper over."""
        ck = self.manager.restore_version(int(version))
        with self._lock:
            self._swap(int(version), self._build(ck))
            self._pinned = True
        self._emit_reload("reloaded", int(version), None)
        return self

    def unpin(self):
        """Re-enable auto-reload (the next ``maybe_reload`` catches up)."""
        with self._lock:
            # under the lock like pin(): an unpin racing a maybe_reload
            # must order against the pinned-check inside the reload's
            # critical section (found by graftlint's lock-discipline rule)
            self._pinned = False
        return self

    @property
    def pinned(self) -> bool:
        return self._pinned

    # -- reload ------------------------------------------------------------
    def maybe_reload(self) -> bool:
        """Load the newest loadable version newer than the current one;
        returns True when the serving model was swapped.  Corrupt versions
        are logged, marked bad, and skipped — the previous-good model
        keeps serving (rollback)."""
        # listener events collected here and emitted AFTER the lock is
        # released: a listener that calls back into the registry (pin,
        # clear_bad_versions, another reload) must not deadlock on the
        # non-reentrant lock the emitting thread still holds
        from tpu_sgd.obs.spans import span

        if self.breaker is not None and not self.breaker.allow():
            # OPEN breaker: the directory has been failing repeatedly —
            # skip the scan entirely and keep serving the current model
            # until the cooldown lets one probe through (HALF_OPEN)
            return False
        emits = []
        swapped = False
        sp = span("serve.reload")
        with sp, self._lock:
            if self._pinned:
                # checked INSIDE the lock: a pin() that completed while
                # this reload waited must win, not be silently undone
                return False
            current = self._version if self._version is not None else -1
            for v in reversed(self.manager.versions()):
                if v <= current:
                    break
                if v in self.bad_versions:
                    continue
                try:
                    failpoint("serve.registry.reload")
                    ck = self.manager.restore_version(v)
                    model = self._build(ck)
                except FileNotFoundError:
                    continue  # pruned between listing and load: no error
                except (OSError, FaultInjected) as e:
                    # transient I/O (EMFILE, NFS hiccup) or an injected
                    # chaos fault: NOT corruption — retry on the next
                    # reload attempt instead of permanently blacklisting
                    # what may be the last checkpoint a finished
                    # training run ever writes
                    logger.warning(
                        "transient I/O error loading checkpoint version "
                        "%d (%s: %s); will retry", v, type(e).__name__, e,
                    )
                    self.load_failed_count += 1
                    emits.append(("load_failed", v, str(e)))
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    continue
                except Exception as e:
                    # incl. IntegrityError (ISSUE 15): a checkpoint
                    # whose content checksum fails at restore is PROVEN
                    # corrupt — blacklist the version (the breaker
                    # counts it, repeated corruption stops the disk
                    # scan) and keep serving the previous good model;
                    # the ``integrity.corrupt.checkpoint`` counter and
                    # its detector alert already fired at the verify
                    self.bad_versions[v] = f"{type(e).__name__}: {e}"
                    self.load_failed_count += 1
                    logger.warning(
                        "serving reload of checkpoint version %d failed "
                        "(%s: %s); keeping version %s",
                        v, type(e).__name__, e, self._version,
                    )
                    emits.append(("load_failed", v, str(e)))
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    continue
                self._swap(v, model)
                emits.append(("reloaded", v, None))
                swapped = True
                if self.breaker is not None:
                    self.breaker.record_success()
                break
            sp.set(swapped=swapped,
                   version=self._version if swapped else None)
        for kind, v, err in emits:
            self._emit_reload(kind, v, err)
        return swapped

    def healthz(self) -> dict:
        """Ops-probe snapshot: what is serving, is it pinned, what has
        been rejected, and the breaker state (``Server.healthz`` wraps
        this with the queue-side numbers)."""
        with self._lock:
            # the dict() copy of bad_versions iterates it — concurrent
            # with a reload-walk insert that raises RuntimeError, so the
            # snapshot takes the lock (found by graftlint's
            # lock-discipline rule); the scalar reads ride along free
            bad = dict(self.bad_versions)
        return {
            "current_version": self._version,
            "previous_version": self._previous_version,
            "pinned": self._pinned,
            "bad_versions": bad,
            "reload_count": self.reload_count,
            "load_failed_count": self.load_failed_count,
            "breaker": (None if self.breaker is None
                        else self.breaker.snapshot()),
        }

    def clear_bad_versions(self):
        """Forget recorded-bad versions so the next reload retries them —
        the operator escape hatch after repairing a checkpoint file."""
        with self._lock:
            self.bad_versions.clear()
        return self

    def on_model_update(self, model=None, batch_index=None):
        """`StreamingLinearAlgorithm.add_model_update_listener` adapter:
        the trainer publishes, the registry picks up whatever checkpoint
        the publish produced (the in-memory model argument is ignored —
        serving state must round-trip through the durable checkpoint)."""
        del model, batch_index
        self.maybe_reload()

    # -- internals ---------------------------------------------------------
    def _build(self, ck: dict):
        if "intercept" not in ck["extras"]:
            # a non-streaming (optimizer-state) checkpoint: intercept 0.0
            # is correct for intercept=False training but silently WRONG
            # for an intercept=True batch run whose bias still rides the
            # weight vector — say so instead of guessing quietly
            logger.warning(
                "checkpoint (config_key=%r) carries no intercept extra; "
                "serving with intercept=0.0 — for an intercept-trained "
                "batch checkpoint split the bias out via a custom "
                "model_factory", ck.get("config_key", ""),
            )
        intercept = float(ck["extras"].get("intercept", 0.0))
        return self.model_factory(ck["weights"], intercept)

    def _swap(self, version: int, model):
        """Caller holds ``self._lock`` and is responsible for emitting the
        'reloaded' event AFTER releasing it (re-entrant listeners).
        graftlint v2 proves the contract through the call graph (every
        in-class call site of this private helper is under the lock),
        so the accesses below need no suppressions; the runtime twin in
        tests/test_analysis.py validates it dynamically too."""
        if self._version is not None and version != self._version:
            self._previous_version = self._version
        self._model = model  # atomic reference swap: readers see old or new
        self._version = version
        self.reload_count += 1
        logger.info("serving model hot-swapped to version %d", version)

    def _emit_reload(self, kind: str, version: int, error: Optional[str]):
        if self.metrics is None:
            return
        from tpu_sgd.utils.events import ServeReloadEvent

        try:
            self.metrics.record_reload(ServeReloadEvent(
                event=kind,
                version=int(version),
                previous_version=self._previous_version
                if kind == "reloaded" else self._version,
                error=error,
            ))
        except Exception:  # observability must never kill serving
            logger.warning(
                "serve_reload listener raised; event dropped", exc_info=True
            )
