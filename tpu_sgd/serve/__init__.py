"""tpu_sgd.serve: online model serving for trained GLM families.

The inference half of the stack (ROADMAP north star: "serves heavy
traffic"): a trained or streaming-trained model becomes a low-latency
endpoint with

  * dynamic micro-batching — single-row requests coalesce into
    bucket-padded TPU batches under a max-latency deadline, with bounded
    queueing and explicit backpressure (:mod:`tpu_sgd.serve.batcher`);
  * a jit-compiled, shape-bucketed predict path shared by the dense and
    sparse feature layouts of all GLM families
    (:mod:`tpu_sgd.serve.engine`);
  * hot model reload — a ``StreamingLinearAlgorithm`` training loop
    publishes checkpoints and the serving side atomically swaps to the
    newest loadable version, rolling back past corrupt files
    (:mod:`tpu_sgd.serve.registry`);
  * observability into the shared event-log contract
    (:mod:`tpu_sgd.serve.metrics`).

Quickstart::

    from tpu_sgd.serve import Server

    server = Server(model, max_latency_s=0.002)     # static model
    with server:
        y = server.predict(x_row)

    registry = ModelRegistry(ckpt_dir, algorithm.create_model)
    with Server(registry=registry) as server:        # hot-reloading
        fut = server.submit(x_row)                   # async handle
        y = fut.result()
"""

from __future__ import annotations

from typing import Optional, Tuple

from tpu_sgd.obs import timeseries as obs_timeseries
from tpu_sgd.serve.batcher import (LANES, BackpressureError, MicroBatcher,
                                   Overloaded)
from tpu_sgd.serve.engine import DEFAULT_BUCKETS, PredictEngine, stack_rows
from tpu_sgd.serve.metrics import ServingMetrics
from tpu_sgd.serve.registry import ModelRegistry, NoModelError


class Server:
    """Facade wiring engine + batcher + registry + metrics into one
    endpoint.  Exactly one of ``model`` (static) or ``registry``
    (hot-reloading) must be given.

    Overload (README "Overload behavior"; ADVICE.md "Reject at
    admission, never at completion"): :meth:`submit` takes a priority
    ``lane`` and an optional ``deadline_s`` budget, and every admission
    rejection — queue-full, unmeetable deadline, utilization shed, or
    displacement by a higher lane — is a typed
    :class:`~tpu_sgd.serve.batcher.Overloaded` answer, never a silent
    drop; ``shed_utilization`` tunes (or with ``{}`` disables) the
    per-lane shed thresholds.

    Reliability (README "Reliability"; ``tpu_sgd/reliability``): pass
    the registry a ``CircuitBreaker`` (``ModelRegistry(...,
    breaker=...)``) so repeated corrupt/unreadable reloads stop
    hammering disk and serving degrades to the current (or pinned)
    model; :meth:`healthz` is the ops-probe snapshot (version, pinned?,
    queue depth, breaker state), and the batcher's ``heartbeat`` plugs
    into a ``reliability.HealthMonitor`` for straggler detection.
    Retry/backoff policy for the surrounding training feed lives on
    ``GradientDescent.set_ingest_options(retry=...)``."""

    def __init__(
        self,
        model=None,
        *,
        registry: Optional[ModelRegistry] = None,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        max_batch: Optional[int] = None,
        max_latency_s: float = 0.005,
        max_queue: int = 1024,
        event_log=None,
        auto_reload: bool = True,
        reload_interval_s: float = 0.1,
        shed_utilization=None,
    ):
        if (model is None) == (registry is None):
            raise ValueError("pass exactly one of model= or registry=")
        self._model = model
        self.registry = registry
        self.auto_reload = bool(auto_reload) and registry is not None
        self.reload_interval_s = float(reload_interval_s)
        self._last_reload_check = float("-inf")
        self.engine = PredictEngine(buckets)
        if max_batch is None:
            max_batch = self.engine.max_batch
        elif max_batch > self.engine.max_batch:
            # a coalesced batch beyond the largest bucket would fall off
            # the compiled-program cache onto the per-size eager path
            # (and the padded_size metric would lie)
            raise ValueError(
                f"max_batch={max_batch} exceeds the largest engine "
                f"bucket ({self.engine.max_batch}); raise buckets= or "
                "lower max_batch"
            )
        self.metrics = ServingMetrics(listener=event_log)
        if registry is not None:
            if registry.metrics is None:
                # adopt the registry into this server's metrics stream;
                # a metrics object the user attached themselves (or a
                # previous server's) is left in place — reload events
                # keep flowing wherever they already flow
                registry.metrics = self.metrics
            self.metrics.version_source = lambda: (
                -1 if registry.current_version is None
                else registry.current_version
            )
        self.batcher = MicroBatcher(
            self._predict_batch,
            max_batch=max_batch,
            max_latency_s=max_latency_s,
            max_queue=max_queue,
            metrics=self.metrics,
            padded_size_fn=self.engine.bucket_for,
            shed_utilization=shed_utilization,
        )

    # -- model access ------------------------------------------------------
    @property
    def model(self):
        if self.registry is not None:
            return self.registry.model()
        return self._model

    @property
    def model_version(self) -> Optional[int]:
        return None if self.registry is None else self.registry.current_version

    def reload(self) -> bool:
        """Explicitly check for and swap to a newer checkpoint version."""
        if self.registry is None:
            return False
        return self.registry.maybe_reload()

    def _predict_batch(self, X):
        if self.auto_reload:
            # throttled directory scan (not per batch, never per request):
            # a slow/hung filesystem listing must not sit on the serving
            # critical path, and ``reload_interval_s`` bounds staleness;
            # a trainer wired through add_model_update_listener ->
            # registry.on_model_update swaps immediately regardless
            import time

            now = time.monotonic()
            if now - self._last_reload_check >= self.reload_interval_s:
                self._last_reload_check = now
                self.registry.maybe_reload()
        return self.engine.predict_batch(self.model, X)

    # -- request path ------------------------------------------------------
    def submit(self, x, lane: str = "interactive",
               deadline_s: Optional[float] = None):
        """Async single-row predict; returns a ``concurrent.futures.Future``.

        ``lane`` picks the priority lane (``serve.LANES``:
        interactive > batch > shadow) and ``deadline_s`` the request's
        remaining latency budget — see README "Overload behavior".
        Raises :class:`Overloaded` (a :class:`BackpressureError`) on any
        typed admission rejection."""
        return self.batcher.submit(x, lane=lane, deadline_s=deadline_s)

    def predict(self, x, timeout: Optional[float] = None, *,
                lane: str = "interactive",
                deadline_s: Optional[float] = None):
        """Blocking single-row predict through the micro-batching path."""
        return self.batcher.predict(x, timeout, lane=lane,
                                    deadline_s=deadline_s)

    def predict_batch(self, X):
        """Direct batch predict through the bucketed compiled path,
        bypassing the queue (bulk/offline scoring against the same
        serving model)."""
        return self._predict_batch(X)

    def healthz(self) -> dict:
        """Liveness/readiness snapshot for ops probes: the serving
        version and pin state, queue pressure, flush-thread liveness,
        and (when a registry is attached) the reload/breaker picture.
        Cheap enough to scrape per second — no locks beyond the
        registry's own, no model access, never raises."""
        lanes = self.batcher.lane_snapshot()
        h = {
            "serving": self.batcher._thread is not None,
            "model_version": self.model_version,
            "queue_depth": self.batcher.queue_depth,
            "reject_count": self.batcher.reject_count,
            "batch_count": self.batcher.batch_count,
            "flush_heartbeat_age_s": self.batcher.heartbeat.age_s(),
            # admission-control picture (ISSUE 12): per-lane
            # admit/shed/reject tallies + depth, the aggregate counts,
            # and the p99 batch wall the deadline rule prices against
            "lanes": lanes,
            "admit_count": sum(s["admitted"] for s in lanes.values()),
            "shed_count": sum(s["shed"] + s["displaced"]
                              for s in lanes.values()),
            "p99_batch_wall_s": self.batcher.p99_batch_wall_s(),
            # admission lock amortization (ISSUE 18): lock rounds taken
            # vs requests priced — rounds << priced means the burst
            # path is doing its job
            "admission": self.batcher.admission_snapshot(),
            # the live windowed time-series for the serve subsystem
            # (ISSUE 13): per-window span/counter aggregates from the
            # bounded obs.timeseries ring, or None when the layer is
            # off — pure host dict reads, still cheap enough to scrape
            "windows": obs_timeseries.snapshot(prefix="serve", last=8),
        }
        if self.registry is not None:
            h["registry"] = self.registry.healthz()
            # the breaker state, surfaced at the top level too — the
            # one field an overload dashboard alerts on
            h["breaker"] = h["registry"]["breaker"]
        return h

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.batcher.start()
        return self

    def stop(self, drain: bool = True):
        self.batcher.stop(drain=drain)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


__all__ = [
    "BackpressureError",
    "DEFAULT_BUCKETS",
    "LANES",
    "MicroBatcher",
    "ModelRegistry",
    "NoModelError",
    "Overloaded",
    "PredictEngine",
    "Server",
    "ServingMetrics",
    "stack_rows",
]
